"""Autoregressive decode for the flagship model: KV cache + one-token steps.

The serving counterpart of models/transformer.py (no reference analogue —
the reference has no model at all, SURVEY.md section 2.5). Training
measures the compute-bound regime; decode is the OTHER TPU regime: one
query token against a long cache is HBM-bandwidth-bound (every step re-reads
the whole K/V cache and every weight), so tokens/s tracks bytes/token,
not FLOPs. The module provides:

- ``init_cache`` — the sharded K/V cache pytree ``[L, B, S_max, H, dh]``
  (heads sharded over ``tp``, batch over ``dp``).
- ``make_prefill_fn`` — the full-sequence forward that fills the cache
  for a prompt and returns the last position's logits (compute-bound
  phase).
- ``make_decode_fn`` — one token per sequence against the cache
  (bandwidth-bound phase); functionally pure (cache in, cache out) so
  the step jits and re-runs under the benchmark loop.
- ``reference_logits`` — single-device oracle: teacher-forced full
  forward whose logits the incremental cache path must reproduce (the
  prefill/decode consistency check is real — the two code paths share no
  attention code).

Topology: decode shards batch over ``dp`` and heads+experts over ``tp``
(the standard serving layout); pipeline stages don't apply to a
single-token step (``pp=1``). MoE routing at decode groups the batch's
sequences into ``tp`` balanced blocks — sequence ``i`` uses expert
``i // (B/(dp*tp))`` at every position — mirroring the family's
capacity-balanced philosophy with a per-sequence-stable assignment both
code paths reproduce exactly. The MLP kernel axis (bf16 / int8 STE /
int8_weights) is the shared ``_moe_ffn``; decode takes no gradients, so
all three are valid here.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ddlb_tpu.models.transformer import (
    TransformerConfig,
    _moe_ffn,
    _rms_norm,
)


def _ffn_scales(params, l, e, cfg):
    if cfg.mlp_kernel != "int8_weights":
        return None
    return (
        params["moe_w1_scale"][0, l, e],
        params["moe_w2_scale"][0, l, e],
    )


def init_cache(
    cfg: TransformerConfig, batch: int, max_len: int, mesh=None
) -> Dict[str, jax.Array]:
    """Zeroed K/V cache ``[L, B, S_max, H, dh]`` (+ sharded when a mesh is
    given: batch over dp, heads over tp)."""
    shape = (
        cfg.layers_per_stage,
        batch,
        max_len,
        cfg.n_heads,
        cfg.head_dim,
    )
    k = jnp.zeros(shape, cfg.dtype)
    v = jnp.zeros(shape, cfg.dtype)
    if mesh is not None:
        sh = NamedSharding(mesh, P(None, "dp", None, "tp", None))
        k, v = jax.device_put(k, sh), jax.device_put(v, sh)
    return {"k": k, "v": v}


def cache_specs() -> Dict[str, P]:
    return {
        "k": P(None, "dp", None, "tp", None),
        "v": P(None, "dp", None, "tp", None),
    }


def _project_qkv(h, w_qkv_l, b, t, h_loc, dh, dtype):
    """[b, t, D] -> three [b, t, h_loc, dh] local-head projections."""
    return (
        jnp.matmul(h, w_qkv_l[i], preferred_element_type=jnp.float32)
        .astype(dtype)
        .reshape(b, t, h_loc, dh)
        for i in range(3)
    )


def _routed_moe(h2d, params, cfg, l, B, dp, tp):
    """Per-sequence-stable balanced routing on a FULL-width row-major
    slab ``[B * per_seq, D]``: block ``e`` of each dp shard's sequences
    through expert ``e`` — the single-program formulation shared by the
    oracle and the GSPMD member (the shard_map path implements the same
    assignment positionally in ``_block_moe``)."""
    rows, _ = h2d.shape
    per_seq = rows // B
    b_dp = B // dp
    g = b_dp // tp
    u = jnp.zeros_like(h2d)
    for i0 in range(0, B, b_dp):
        for e in range(tp):
            sl = slice((i0 + e * g) * per_seq, (i0 + (e + 1) * g) * per_seq)
            z = _moe_ffn(
                h2d[sl],
                params["moe_w1"][0, l, e],
                params["moe_w2"][0, l, e],
                cfg.mlp_kernel,
                h2d.dtype,
                scales=_ffn_scales(params, l, e, cfg),
            )
            u = u.at[sl].set(z)
    return u


def _block_moe(h2d, params, l, cfg, tp):
    """Balanced per-sequence MoE on a tp-replicated ``[rows, D]`` slab:
    activations are replicated over ``tp`` at decode (tensor-parallel
    serving layout), so each rank slices ITS sequence block locally,
    applies the resident expert, and an all-gather reassembles the batch
    — the EP exchange degenerates from all-to-all to gather when the
    dispatch side is replicated."""
    rows, D = h2d.shape
    g = rows // tp
    my = jax.lax.axis_index("tp")
    blk = jax.lax.dynamic_slice_in_dim(h2d, my * g, g, 0)  # [g, D]
    z = _moe_ffn(
        blk,
        params["moe_w1"][0, l, 0],
        params["moe_w2"][0, l, 0],
        cfg.mlp_kernel,
        h2d.dtype,
        scales=_ffn_scales(params, l, 0, cfg),
    )
    return jax.lax.all_gather(z, "tp", axis=0, tiled=True)  # [rows, D]


def make_decode_fn(mesh, cfg: TransformerConfig):
    """One-token decode step over a ``('dp', 'tp')`` mesh.

    Returns ``(decode_step, shardings)``: ``decode_step(params, cache,
    tokens, pos) -> (logits, cache)`` with ``tokens [B]`` (this step's
    token per sequence), ``pos`` a scalar int32 position, ``logits
    [B, vocab]``; jit at the call site (cache threads through
    functionally, so the step re-runs under a measurement loop).
    """

    tp = mesh.shape["tp"]
    if cfg.attention != "gathered":
        raise ValueError(
            "decode supports attention='gathered' (heads sharded over tp); "
            "ring/context-parallel decode is a training-side construction"
        )
    if cfg.router != "block":
        raise ValueError(
            "serving paths use the per-sequence-stable block router; "
            "router='topk' is a training-side construction"
        )
    if cfg.n_heads % tp != 0:
        raise ValueError(f"n_heads={cfg.n_heads} not divisible by tp={tp}")
    L = cfg.layers_per_stage
    h_loc = cfg.n_heads // tp
    dh = cfg.head_dim

    def body(params, ck, cv, tokens, pos):
        b = tokens.shape[0]  # local batch (B/dp)
        if b % tp != 0:
            raise ValueError(f"per-dp batch {b} not divisible by tp={tp}")
        S_max = ck.shape[2]
        x = params["embed"][tokens][:, None, :]  # [b, 1, D]
        for l in range(L):
            h = _rms_norm(x, params["ln1"][0, l])
            q, k, v = _project_qkv(
                h, params["w_qkv"][0, l], b, 1, h_loc, dh, x.dtype
            )
            ck = jax.lax.dynamic_update_slice(
                ck, k[None], (l, 0, pos, 0, 0)
            )
            cv = jax.lax.dynamic_update_slice(
                cv, v[None], (l, 0, pos, 0, 0)
            )
            # q [b, 1, h, dh] against the whole cache row; positions past
            # ``pos`` are masked (zeros in the cache never win anyway, but
            # the mask keeps softmax exact)
            s = jnp.einsum(
                "bqhd,bkhd->bhqk",
                q.astype(jnp.float32) / np.sqrt(dh),
                ck[l].astype(jnp.float32),
            )  # [b, h, 1, S_max]
            live = (
                jax.lax.broadcasted_iota(jnp.int32, (S_max,), 0) <= pos
            )
            s = jnp.where(live[None, None, None], s, -1e30)
            p = jax.nn.softmax(s, axis=-1)
            attn = jnp.einsum(
                "bhqk,bkhd->bqhd", p, cv[l].astype(jnp.float32)
            ).astype(x.dtype)
            part = jnp.matmul(
                attn.reshape(b, 1, h_loc * dh),
                params["w_o"][0, l],
                preferred_element_type=jnp.float32,
            )
            y = jax.lax.psum(part, "tp").astype(x.dtype)  # heads partial
            x = x + y
            h2 = _rms_norm(x, params["ln2"][0, l])
            u = _block_moe(h2.reshape(b, -1), params, l, cfg, tp)
            x = x + u[:, None, :]
        h = _rms_norm(x, params["ln_f"])
        logits = jnp.matmul(
            h[:, 0], params["head"], preferred_element_type=jnp.float32
        )
        return logits, ck, cv

    from ddlb_tpu.models.transformer import param_specs

    specs = dict(param_specs(cfg))
    # decode topology: no pp axis in the mesh, heads over tp; the stage
    # axis of the param stacks is size pp=1 and stays unsharded
    specs = {
        name: P(*[None if ax == "pp" else ax for ax in spec])
        for name, spec in specs.items()
    }
    cspecs = cache_specs()

    def step(params, cache, tokens, pos):
        logits, ck, cv = jax.shard_map(
            body,
            mesh=mesh,
            in_specs=(specs, cspecs["k"], cspecs["v"], P("dp"), P()),
            out_specs=(P("dp", None), cspecs["k"], cspecs["v"]),
            check_vma=False,
        )(params, cache["k"], cache["v"], tokens, pos)
        return logits, {"k": ck, "v": cv}

    shardings = {k: NamedSharding(mesh, s) for k, s in specs.items()}
    shardings["cache_k"] = NamedSharding(mesh, cspecs["k"])
    shardings["cache_v"] = NamedSharding(mesh, cspecs["v"])
    shardings["tokens"] = NamedSharding(mesh, P("dp"))
    return step, shardings


def make_prefill_fn(mesh, cfg: TransformerConfig):
    """Full-sequence prompt pass over a ``('dp', 'tp')`` mesh: fills the
    cache for positions ``[0, S)`` and returns the last position's logits.

    Returns ``(prefill, shardings)``: ``prefill(params, cache, tokens) ->
    (logits, cache)`` with ``tokens [B, S]``. The compute-bound serving
    phase — so ``cfg.attn_kernel='flash'`` (the default) runs the prompt
    attention on the Pallas flash kernels, exactly the long-S regime they
    exist for; ``'einsum'`` keeps the HBM-score-matrix form for A/B.
    """

    tp = mesh.shape["tp"]
    if cfg.attention != "gathered":
        raise ValueError("decode/prefill support attention='gathered' only")
    if cfg.router != "block":
        raise ValueError(
            "serving paths use the per-sequence-stable block router; "
            "router='topk' is a training-side construction"
        )
    if cfg.attn_kernel not in ("flash", "einsum"):
        raise ValueError(f"unknown attn_kernel '{cfg.attn_kernel}'")
    L = cfg.layers_per_stage
    h_loc = cfg.n_heads // tp
    dh = cfg.head_dim

    from ddlb_tpu.models.transformer import _causal_attention, _flash_full

    interpret = jax.default_backend() != "tpu"

    def body(params, ck, cv, tokens):
        b, S = tokens.shape
        if b % tp != 0:
            raise ValueError(f"per-dp batch {b} not divisible by tp={tp}")
        x = params["embed"][tokens]  # [b, S, D]
        for l in range(L):
            h = _rms_norm(x, params["ln1"][0, l])
            q, k, v = _project_qkv(
                h, params["w_qkv"][0, l], b, S, h_loc, dh, x.dtype
            )
            ck = jax.lax.dynamic_update_slice(
                ck, k[None], (l, 0, 0, 0, 0)
            )
            cv = jax.lax.dynamic_update_slice(
                cv, v[None], (l, 0, 0, 0, 0)
            )
            if cfg.attn_kernel == "flash":
                attn = _flash_full(q, k, v, interpret).reshape(
                    b, S, h_loc * dh
                )
            else:
                attn = _causal_attention(q, k, v).reshape(b, S, h_loc * dh)
            part = jnp.matmul(
                attn, params["w_o"][0, l], preferred_element_type=jnp.float32
            )
            x = x + jax.lax.psum(part, "tp").astype(x.dtype)
            h2 = _rms_norm(x, params["ln2"][0, l])
            # per-sequence expert assignment, identical to the decode step
            # (rows are sequence-major, so each rank's block is its g
            # whole sequences)
            D = x.shape[-1]
            u = _block_moe(h2.reshape(b * S, D), params, l, cfg, tp)
            x = x + u.reshape(b, S, D)
        h = _rms_norm(x, params["ln_f"])
        logits = jnp.matmul(
            h[:, -1], params["head"], preferred_element_type=jnp.float32
        )
        return logits, ck, cv

    from ddlb_tpu.models.transformer import param_specs

    specs = dict(param_specs(cfg))
    specs = {
        name: P(*[None if ax == "pp" else ax for ax in spec])
        for name, spec in specs.items()
    }
    cspecs = cache_specs()

    def prefill(params, cache, tokens):
        logits, ck, cv = jax.shard_map(
            body,
            mesh=mesh,
            in_specs=(specs, cspecs["k"], cspecs["v"], P("dp", None)),
            out_specs=(P("dp", None), cspecs["k"], cspecs["v"]),
            check_vma=False,
        )(params, cache["k"], cache["v"], tokens)
        return logits, {"k": ck, "v": cv}

    shardings = {k: NamedSharding(mesh, s) for k, s in specs.items()}
    shardings["tokens"] = NamedSharding(mesh, P("dp", None))
    return prefill, shardings


def make_full_width_fns(cfg: TransformerConfig, batch: int, dp: int, tp: int):
    """Single-program (no shard_map) decode and prefill formulations:
    full-head attention, ``_routed_moe`` expert blocks, cache threading.

    These carry no collectives — GSPMD inserts them from sharding
    annotations when the returned callables are jitted over a mesh (the
    transformer_decode xla_gspmd member), and they double as the oracle
    building blocks. Returns ``(decode_fwd, prefill_fwd)`` with
    ``decode_fwd(params, ck, cv, tokens, pos) -> logits`` and
    ``prefill_fwd(params, ck, cv, tokens) -> (logits, ck, cv)``.
    """
    from ddlb_tpu.models.transformer import _causal_attention

    B = batch
    L, H, dh = cfg.layers_per_stage, cfg.n_heads, cfg.head_dim

    def decode_fwd(params, ck, cv, tokens, pos):
        x = params["embed"][tokens][:, None, :]  # [B, 1, D]
        for l in range(L):
            h = _rms_norm(x, params["ln1"][0, l])
            q, k, v = _project_qkv(
                h, params["w_qkv"][0, l], B, 1, H, dh, x.dtype
            )
            ck = jax.lax.dynamic_update_slice(ck, k[None], (l, 0, pos, 0, 0))
            cv = jax.lax.dynamic_update_slice(cv, v[None], (l, 0, pos, 0, 0))
            S_max = ck.shape[2]
            s = jnp.einsum(
                "bqhd,bkhd->bhqk",
                q.astype(jnp.float32) / np.sqrt(dh),
                ck[l].astype(jnp.float32),
            )
            live = jax.lax.broadcasted_iota(jnp.int32, (S_max,), 0) <= pos
            s = jnp.where(live[None, None, None], s, -1e30)
            attn = jnp.einsum(
                "bhqk,bkhd->bqhd",
                jax.nn.softmax(s, axis=-1),
                cv[l].astype(jnp.float32),
            ).astype(x.dtype)
            x = x + jnp.matmul(
                attn.reshape(B, 1, H * dh),
                params["w_o"][0, l],
                preferred_element_type=jnp.float32,
            ).astype(x.dtype)
            h2 = _rms_norm(x, params["ln2"][0, l])
            u = _routed_moe(h2.reshape(B, -1), params, cfg, l, B, dp, tp)
            x = x + u[:, None, :]
        h = _rms_norm(x, params["ln_f"])
        return jnp.matmul(
            h[:, 0], params["head"], preferred_element_type=jnp.float32
        )

    def prefill_fwd(params, ck, cv, tokens):
        B_, S = tokens.shape
        x = params["embed"][tokens]
        for l in range(L):
            h = _rms_norm(x, params["ln1"][0, l])
            q, k, v = _project_qkv(
                h, params["w_qkv"][0, l], B_, S, H, dh, x.dtype
            )
            ck = jax.lax.dynamic_update_slice(ck, k[None], (l, 0, 0, 0, 0))
            cv = jax.lax.dynamic_update_slice(cv, v[None], (l, 0, 0, 0, 0))
            attn = _causal_attention(q, k, v).reshape(B_, S, H * dh)
            x = x + jnp.matmul(
                attn, params["w_o"][0, l], preferred_element_type=jnp.float32
            ).astype(x.dtype)
            h2 = _rms_norm(x, params["ln2"][0, l])
            u = _routed_moe(h2.reshape(B_ * S, -1), params, cfg, l, B, dp, tp)
            x = x + u.reshape(B_, S, -1)
        h = _rms_norm(x, params["ln_f"])
        logits = jnp.matmul(
            h[:, -1], params["head"], preferred_element_type=jnp.float32
        )
        return logits, ck, cv

    return decode_fwd, prefill_fwd


def make_generate_fn(
    mesh, cfg: TransformerConfig, n_new: int, temperature: float = 0.0
):
    """Autoregressive generation, one jitted program.

    Returns ``(generate, shardings)``: ``generate(params, cache, prompt
    [, key])  -> tokens [B, S0 + n_new]`` — prefill the prompt, then
    ``n_new`` decode steps under ``lax.fori_loop`` (the whole loop
    compiles once; the cache and the sampled token thread the carry).
    ``temperature=0`` samples the argmax (greedy, no key needed);
    ``temperature>0`` draws from ``softmax(logits / temperature)`` with a
    per-step fold of the caller's PRNG key. The cache must hold
    ``S0 + n_new`` positions.
    """
    if n_new < 1:
        # n_new=0 would write the post-loop sample at column S0-1,
        # silently overwriting the last prompt token
        raise ValueError(f"n_new must be >= 1, got {n_new}")
    decode, shardings = make_decode_fn(mesh, cfg)
    prefill, _ = make_prefill_fn(mesh, cfg)

    def sample(logits, key, step):
        if temperature <= 0.0:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return jax.random.categorical(
            jax.random.fold_in(key, step), logits / temperature, axis=-1
        ).astype(jnp.int32)

    def generate(params, cache, prompt, key=None):
        if temperature > 0.0 and key is None:
            raise ValueError("temperature > 0 sampling needs a PRNG key")
        B, S0 = prompt.shape
        S_max = cache["k"].shape[2]
        if S0 + n_new > S_max:
            # OOB dynamic_update_slice CLAMPS: without this check later
            # steps would silently overwrite the last cache slot and
            # return plausible wrong tokens
            raise ValueError(
                f"cache holds {S_max} positions < prompt {S0} + "
                f"n_new {n_new}"
            )
        dp_rows = NamedSharding(mesh, P("dp", None))
        # one explicit layout for the token buffer, the prompt and each
        # sampled column: dynamic_update_slice requires operand and
        # update shardings to agree (reshard: the serving meshes carry
        # Explicit axis types, where with_sharding_constraint is a no-op)
        prompt = jax.sharding.reshard(prompt, dp_rows)
        logits, cache = prefill(params, cache, prompt)
        tokens = jax.sharding.reshard(
            jnp.zeros((B, S0 + n_new), jnp.int32), dp_rows
        )
        tokens = jax.lax.dynamic_update_slice(tokens, prompt, (0, 0))

        def body(i, carry):
            tokens, cache, logits = carry
            nxt = sample(logits, key, i)  # [B]
            tokens = jax.lax.dynamic_update_slice(
                tokens, nxt[:, None], (0, S0 + i)
            )
            logits, cache = decode(params, cache, nxt, S0 + i)
            return tokens, cache, logits

        # n_new - 1 looped steps; the LAST token comes from the carried
        # logits after the loop — a final decode would produce logits
        # nothing consumes, and each decode step is a full cache+weights
        # HBM re-read
        tokens, cache, logits = jax.lax.fori_loop(
            0, n_new - 1, body, (tokens, cache, logits)
        )
        last = sample(logits, key, n_new - 1)
        return jax.lax.dynamic_update_slice(
            tokens, last[:, None], (0, S0 + n_new - 1)
        )

    return generate, shardings


def reference_logits(
    params, tokens, cfg: TransformerConfig, tp: int, dp: int
) -> jax.Array:
    """Single-device oracle: teacher-forced full forward, logits at the
    LAST position ``[B, vocab]``.

    Reproduces the decode semantics exactly: per-sequence-stable expert
    assignment (sequence ``i`` of a dp shard uses expert
    ``i // (B/(dp*tp))``), full-precision causal attention, the shared
    ``_moe_ffn`` MLP kernels. The incremental cache path must match this
    non-incremental formulation — the real consistency check.
    """
    from ddlb_tpu.models.transformer import _causal_attention

    B, S = tokens.shape
    L = cfg.layers_per_stage
    x = params["embed"][tokens]  # [B, S, D]
    D = cfg.d_model
    for l in range(L):
        h = _rms_norm(x, params["ln1"][0, l])
        q, k, v = (
            jnp.matmul(
                h, params["w_qkv"][0, l][i], preferred_element_type=jnp.float32
            )
            .astype(x.dtype)
            .reshape(B, S, cfg.n_heads, cfg.head_dim)
            for i in range(3)
        )
        attn = _causal_attention(q, k, v).reshape(B, S, D)
        x = x + jnp.matmul(
            attn, params["w_o"][0, l], preferred_element_type=jnp.float32
        ).astype(x.dtype)
        h2 = _rms_norm(x, params["ln2"][0, l])
        u = _routed_moe(h2.reshape(B * S, D), params, cfg, l, B, dp, tp)
        x = x + u.reshape(B, S, D)
    h = _rms_norm(x, params["ln_f"])
    return jnp.matmul(
        h[:, -1], params["head"], preferred_element_type=jnp.float32
    )
