"""Autoregressive decode for the flagship model: KV cache + one-token steps.

The serving counterpart of models/transformer.py (no reference analogue —
the reference has no model at all, SURVEY.md section 2.5). Training
measures the compute-bound regime; decode is the OTHER TPU regime: one
query token against a long cache is HBM-bandwidth-bound (every step re-reads
the whole K/V cache and every weight), so tokens/s tracks bytes/token,
not FLOPs. The module provides:

- ``init_cache`` — the sharded K/V cache pytree ``[L, B, S_max, H_kv,
  dh]`` (kv heads sharded over ``tp``, batch over ``dp``; ``H_kv <
  n_heads`` under GQA, and ``kv_cache='int8'`` stores int8 payloads +
  per-(position, head) scales at half the bytes — the two cache-read
  levers of the bandwidth-bound regime).
- ``make_prefill_fn`` — the full-sequence forward that fills the cache
  for a prompt and returns the last position's logits (compute-bound
  phase).
- ``make_decode_fn`` — one token per sequence against the cache
  (bandwidth-bound phase); functionally pure (cache in, cache out) so
  the step jits and re-runs under the benchmark loop.
- ``reference_logits`` — single-device oracle: teacher-forced full
  forward whose logits the incremental cache path must reproduce (the
  prefill/decode consistency check is real — the two code paths share no
  attention code).

Topology: decode shards batch over ``dp`` and heads+experts over ``tp``
(the standard serving layout); pipeline stages don't apply to a
single-token step (``pp=1``). MoE routing at decode groups the batch's
sequences into ``tp`` balanced blocks — sequence ``i`` uses expert
``i // (B/(dp*tp))`` at every position — mirroring the family's
capacity-balanced philosophy with a per-sequence-stable assignment both
code paths reproduce exactly. The MLP kernel axis (bf16 / int8 STE /
int8_weights) is the shared ``_moe_ffn``; decode takes no gradients, so
all three are valid here.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ddlb_tpu.runtime import reshard_compat, shard_map_compat
from ddlb_tpu.models.transformer import (
    TransformerConfig,
    _moe_ffn,
    _rms_norm,
    apply_rope,
)


def _ffn_scales(params, l, e, cfg):
    if cfg.mlp_kernel != "int8_weights":
        return None
    return (
        params["moe_w1_scale"][0, l, e],
        params["moe_w2_scale"][0, l, e],
    )


_KV_QMAX = 127.0


def _quantize_kv(x):
    """Symmetric per-(position, head) int8 over the feature axis:
    ``x [..., dh] ~ q * s`` with ``q`` int8 and ``s [..., 1]`` f32."""
    xf = x.astype(jnp.float32)
    s = jnp.max(jnp.abs(xf), axis=-1, keepdims=True) / _KV_QMAX
    s = jnp.maximum(s, jnp.float32(1e-30))  # all-zero row guard
    q = jnp.clip(jnp.round(xf / s), -_KV_QMAX, _KV_QMAX).astype(jnp.int8)
    return q, s


def _kv_roundtrip(x):
    """Quantize-dequantize in one step — the value every cache READ sees
    under ``kv_cache='int8'``; shared by the serving paths and the oracle
    so their numerics agree bitwise."""
    q, s = _quantize_kv(x)
    return (q.astype(jnp.float32) * s).astype(x.dtype)


def init_cache(
    cfg: TransformerConfig, batch: int, max_len: int, mesh=None
) -> Dict[str, jax.Array]:
    """Zeroed K/V cache ``[L, B, S_max, H_kv, dh]`` (+ sharded when a mesh
    is given: batch over dp, heads over tp). Under GQA the cache carries
    ``n_kv_heads`` heads — the whole point: per-token HBM read shrinks by
    the group factor. ``cfg.kv_cache='int8'`` stores int8 payloads plus
    f32 per-(position, head) scales — half the bytes again."""
    shape = (
        cfg.layers_per_stage,
        batch,
        max_len,
        cfg.kv_heads,
        cfg.head_dim,
    )
    if cfg.kv_cache == "int8":
        cache = {
            "k": jnp.zeros(shape, jnp.int8),
            "v": jnp.zeros(shape, jnp.int8),
            "k_scale": jnp.zeros(shape[:-1] + (1,), jnp.float32),
            "v_scale": jnp.zeros(shape[:-1] + (1,), jnp.float32),
        }
    elif cfg.kv_cache == "bf16":
        cache = {
            "k": jnp.zeros(shape, cfg.dtype),
            "v": jnp.zeros(shape, cfg.dtype),
        }
    else:
        raise ValueError(f"unknown kv_cache '{cfg.kv_cache}'")
    if mesh is not None:
        specs = cache_specs(cfg)
        cache = {
            name: jax.device_put(arr, NamedSharding(mesh, specs[name]))
            for name, arr in cache.items()
        }
    return cache


def cache_specs(cfg: TransformerConfig) -> Dict[str, P]:
    if cfg.cache_layout == "paged":
        # pool [L, P, ps, H_kv, dh]: pages shard over heads only (the
        # pool is shared across the slot axis, which is why the paged
        # serving engine requires dp == 1); the table is replicated
        spec = P(None, None, None, "tp", None)
        specs = {"k": spec, "v": spec, "table": P(None, None)}
    else:
        spec = P(None, "dp", None, "tp", None)
        specs = {"k": spec, "v": spec}
    if cfg.kv_cache == "int8":
        specs["k_scale"] = spec
        specs["v_scale"] = spec
    return specs


def init_paged_cache(
    cfg: TransformerConfig,
    batch: int,
    max_len: int,
    num_pages: int,
    mesh=None,
) -> Dict[str, jax.Array]:
    """Paged K/V cache: pool ``[L, num_pages, page_size, H_kv, dh]`` plus
    a per-slot page table ``[batch, max_len // page_size]`` of page ids.

    The SENTINEL id ``num_pages`` marks an unmapped table entry: reads
    through it yield zeros (``mode='fill'``) — indistinguishable from the
    contiguous layout's zero-initialized rows — and writes through it
    drop (``mode='drop'``), which is also how a parked lane (pos =
    max_len) idles without corrupting anything, exactly the contiguous
    ragged contract (ADVICE r3).
    """
    if max_len % cfg.page_size:
        raise ValueError(
            f"max_len={max_len} not divisible by page_size={cfg.page_size}"
        )
    max_pages = max_len // cfg.page_size
    shape = (
        cfg.layers_per_stage,
        num_pages,
        cfg.page_size,
        cfg.kv_heads,
        cfg.head_dim,
    )
    if cfg.kv_cache == "int8":
        cache = {
            "k": jnp.zeros(shape, jnp.int8),
            "v": jnp.zeros(shape, jnp.int8),
            "k_scale": jnp.zeros(shape[:-1] + (1,), jnp.float32),
            "v_scale": jnp.zeros(shape[:-1] + (1,), jnp.float32),
        }
    elif cfg.kv_cache == "bf16":
        cache = {
            "k": jnp.zeros(shape, cfg.dtype),
            "v": jnp.zeros(shape, cfg.dtype),
        }
    else:
        raise ValueError(f"unknown kv_cache '{cfg.kv_cache}'")
    cache["table"] = jnp.full((batch, max_pages), num_pages, jnp.int32)
    if mesh is not None:
        specs = cache_specs(cfg)
        cache = {
            name: jax.device_put(arr, NamedSharding(mesh, specs[name]))
            for name, arr in cache.items()
        }
    return cache


def _cache_max_len(cache) -> int:
    """S_max of either layout (pages x page_size, or the row axis)."""
    if "table" in cache:
        return cache["table"].shape[1] * cache["k"].shape[2]
    return cache["k"].shape[2]


def _page_coords(cache, pos):
    """Map absolute positions (scalar or ``[b]``) to ``(pages [b],
    rows [b])`` through the table. Out-of-range positions and unmapped
    table entries both resolve to the sentinel page id (OOB for the
    pool), so downstream reads fill zeros and writes drop — the paged
    form of the contiguous layout's drop-on-overflow contract."""
    table = cache["table"]
    num_pages = cache["k"].shape[1]
    ps = cache["k"].shape[2]
    b = table.shape[0]
    pos = jnp.broadcast_to(jnp.asarray(pos, jnp.int32), (b,))
    page_idx = pos // ps
    oob = (page_idx < 0) | (page_idx >= table.shape[1])
    safe = jnp.clip(page_idx, 0, table.shape[1] - 1)
    pages = jnp.take_along_axis(table, safe[:, None], axis=1)[:, 0]
    pages = jnp.where(oob, num_pages, pages)
    return pages, pos % ps


def _project_qkv(h, params, l, b, t, h_loc, kv_loc, dh, dtype):
    """[b, t, D] -> ``q [b, t, h_loc, dh]`` and ``k, v [b, t, kv_loc, dh]``
    local-head projections, from either the fused MHA stack (``w_qkv``)
    or the split GQA pair (``w_q``/``w_kv``)."""
    if "w_qkv" in params:
        w = params["w_qkv"][0, l]
        q, k, v = (
            jnp.matmul(h, w[i], preferred_element_type=jnp.float32)
            .astype(dtype)
            for i in range(3)
        )
    else:
        q = jnp.matmul(
            h, params["w_q"][0, l], preferred_element_type=jnp.float32
        ).astype(dtype)
        k, v = (
            jnp.matmul(
                h, params["w_kv"][0, l, i], preferred_element_type=jnp.float32
            ).astype(dtype)
            for i in range(2)
        )
    return (
        q.reshape(b, t, h_loc, dh),
        k.reshape(b, t, kv_loc, dh),
        v.reshape(b, t, kv_loc, dh),
    )


def _grouped_scores(q, ck_l, dh):
    """Decode scores against the kv-head cache: ``q [b, 1, h, dh]``
    grouped as ``[b, 1, h_kv, G, dh]`` -> ``[b, h_kv, G, 1, S]`` f32."""
    b, t, h, _ = q.shape
    h_kv = ck_l.shape[2]
    G = h // h_kv
    q5 = q.astype(jnp.float32).reshape(b, t, h_kv, G, dh) / np.sqrt(dh)
    return jnp.einsum("bqhgd,bkhd->bhgqk", q5, ck_l.astype(jnp.float32))


def _grouped_attend(p, cv_l, b, t, dtype):
    """``p [b, h_kv, G, 1, S]`` x cache values -> ``[b, t, h, dh]``
    (query-head order hq = kvh * G + g, matching the kernels)."""
    attn = jnp.einsum("bhgqk,bkhd->bqhgd", p, cv_l.astype(jnp.float32))
    return attn.reshape(b, t, -1).astype(dtype)


def _cache_write(cache, l, pos, k, v, int8):
    """Store this step's ``k``/``v [b, t, h_kv, dh]`` at ``(l, :, pos)``
    (quantizing first in int8 mode).

    ``pos`` may be a scalar (the whole batch at one position) or a
    ``[b]`` vector — per-sequence positions, the ragged/continuous-
    batching form: sequence ``i``'s single new row lands at ``pos[i]``.
    """
    ragged = jnp.ndim(pos) == 1
    paged = "table" in cache

    def upd(name, val):
        if paged:
            # rows land at (page, row) through the slot's table; sentinel
            # coords (parked lane, unmapped page, overflow) drop — the
            # same contract as the contiguous ragged branch below
            b, t = val.shape[0], val.shape[1]
            if t == 1:
                pages, rows = _page_coords(cache, pos)  # [b], [b]
                cache[name] = (
                    cache[name]
                    .at[l, pages, rows]
                    .set(val[:, 0], mode="drop")
                )
            else:
                # verify chunk: rows j at scalar start pos + j, batchwide
                ps = cache["k"].shape[2]
                num_pages = cache["k"].shape[1]
                table = cache["table"]
                rowpos = jnp.asarray(pos, jnp.int32) + jnp.arange(
                    t, dtype=jnp.int32
                )
                page_idx = rowpos // ps                      # [t]
                oob = page_idx >= table.shape[1]
                safe = jnp.clip(page_idx, 0, table.shape[1] - 1)
                pages = table[:, safe]                       # [b, t]
                pages = jnp.where(oob[None, :], num_pages, pages)
                rows = jnp.broadcast_to(rowpos % ps, (b, t))
                cache[name] = (
                    cache[name].at[l, pages, rows].set(val, mode="drop")
                )
        elif ragged:
            # val [b, 1, h_kv, dh] -> row i at (l, i, pos[i]). A position
            # past the cache is DROPPED (mode="drop"), not clamped: a
            # continuous-batching caller that overflows a sequence loses
            # that write instead of silently corrupting the last cache row
            # for every other consumer of it (ADVICE r3). make_generate_fn
            # sizes the cache so its positions are always in bounds.
            b = val.shape[0]
            cache[name] = (
                cache[name]
                .at[l, jnp.arange(b), pos]
                .set(val[:, 0], mode="drop")
            )
        else:
            cache[name] = jax.lax.dynamic_update_slice(
                cache[name], val[None], (l, 0, pos, 0, 0)
            )

    if int8:
        qk, sk = _quantize_kv(k)
        qv, sv = _quantize_kv(v)
        upd("k", qk)
        upd("k_scale", sk)
        upd("v", qv)
        upd("v_scale", sv)
    else:
        upd("k", k)
        upd("v", v)
    return cache


def _cache_read(cache, name, l, dtype):
    """Cache layer ``l`` as the linear ``[B, S_max, H_kv, dh]`` view,
    dequantized in int8 mode. The convert+scale is an elementwise
    producer XLA fuses into the consuming einsum, so HBM still reads the
    int8 payload; rounding to ``dtype`` reproduces ``_kv_roundtrip``
    bit-for-bit — scale-folding into the scores instead would introduce
    1e-7 f32 skew that flips int8 round() buckets at the NEXT layer's
    cache write (observed: 2e-3 logits drift at 2 layers).

    Paged layout: the view is assembled by gathering each slot's pages
    (sentinel entries fill zeros — identical to the contiguous layout's
    zero-initialized rows); this is the one extra HBM pass per decode
    step that pages cost on the einsum path.
    """
    if "table" in cache:
        table = cache["table"]                       # [B, max_pages]
        b, mp = table.shape
        ps = cache[name].shape[2]

        def lin(arr):
            pages = arr[l].at[table].get(
                mode="fill", fill_value=0
            )                                        # [B, mp, ps, ...]
            return pages.reshape((b, mp * ps) + arr.shape[3:])

        view = lin(cache[name])
        scale = cache.get(f"{name}_scale")
        if scale is None:
            return view
        return (
            view.astype(jnp.float32) * lin(scale)
        ).astype(dtype)
    arr = cache[name][l]
    scale = cache.get(f"{name}_scale")
    if scale is None:
        return arr
    return (arr.astype(jnp.float32) * scale[l]).astype(dtype)


def _cache_attend(q, cache, l, dh, pos, dtype, window: int = 0):
    """Query rows against cache layer ``l``: grouped scores,
    live-position mask, softmax, value read.

    ``q [b, t, h, dh]``. For ``t == 1``, ``pos`` is a scalar (the whole
    batch at one position) or ``[b]`` per-sequence positions (each
    sequence attends only its own prefix). For ``t > 1`` (the
    speculative-verify chunk), ``pos`` is the scalar START: chunk row
    ``j`` sits at absolute position ``pos + j`` and attends causally up
    to itself. ``window > 0`` additionally drops positions behind the
    sliding window."""
    b, t = q.shape[0], q.shape[1]
    S_max = _cache_max_len(cache)
    s = _grouped_scores(q, _cache_read(cache, "k", l, dtype), dh)
    iota = jax.lax.broadcasted_iota(jnp.int32, (S_max,), 0)
    if t > 1:
        if jnp.ndim(pos) != 0:
            raise ValueError("chunk attention takes a scalar start position")
        rowpos = jnp.asarray(pos, jnp.int32) + jnp.arange(t, dtype=jnp.int32)
        live = iota[None, :] <= rowpos[:, None]       # [t, S]
        if window:
            live &= iota[None, :] > rowpos[:, None] - window
        s = jnp.where(live[None, None, None, :, :], s, -1e30)
    elif jnp.ndim(pos) == 1:
        live = iota[None, :] <= pos[:, None]          # [b, S]
        if window:
            live &= iota[None, :] > pos[:, None] - window
        s = jnp.where(live[:, None, None, None, :], s, -1e30)
    else:
        live = iota <= pos
        if window:
            live &= iota > pos - window
        s = jnp.where(live[None, None, None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return _grouped_attend(p, _cache_read(cache, "v", l, dtype), b, t, dtype)


def _routed_moe(h2d, params, cfg, l, B, dp, tp):
    """Per-sequence-stable balanced routing on a FULL-width row-major
    slab ``[B * per_seq, D]``: block ``e`` of each dp shard's sequences
    through expert ``e`` — the single-program formulation shared by the
    oracle and the GSPMD member (the shard_map path implements the same
    assignment positionally in ``_block_moe``)."""
    rows, _ = h2d.shape
    per_seq = rows // B
    b_dp = B // dp
    g = b_dp // tp
    u = jnp.zeros_like(h2d)
    for i0 in range(0, B, b_dp):
        for e in range(tp):
            sl = slice((i0 + e * g) * per_seq, (i0 + (e + 1) * g) * per_seq)
            z = _moe_ffn(
                h2d[sl],
                params["moe_w1"][0, l, e],
                params["moe_w2"][0, l, e],
                cfg.mlp_kernel,
                h2d.dtype,
                scales=_ffn_scales(params, l, e, cfg),
            )
            u = u.at[sl].set(z)
    return u


def _block_moe(h2d, params, l, cfg, tp):
    """Balanced per-sequence MoE on a tp-replicated ``[rows, D]`` slab:
    activations are replicated over ``tp`` at decode (tensor-parallel
    serving layout), so each rank slices ITS sequence block locally,
    applies the resident expert, and an all-gather reassembles the batch
    — the EP exchange degenerates from all-to-all to gather when the
    dispatch side is replicated."""
    rows, D = h2d.shape
    g = rows // tp
    my = jax.lax.axis_index("tp")
    blk = jax.lax.dynamic_slice_in_dim(h2d, my * g, g, 0)  # [g, D]
    z = _moe_ffn(
        blk,
        params["moe_w1"][0, l, 0],
        params["moe_w2"][0, l, 0],
        cfg.mlp_kernel,
        h2d.dtype,
        scales=_ffn_scales(params, l, 0, cfg),
    )
    return jax.lax.all_gather(z, "tp", axis=0, tiled=True)  # [rows, D]


def _cache_attend_pallas(q, cache, l, pos, dtype, cfg):
    """The fused decode-attention engine (ops/decode_attention.py):
    ``q [b, 1, h, dh]`` against cache layer ``l`` with NO HBM score
    round-trip; int8 payloads + scales are read as-is and dequantized
    in-kernel. Same mask/window/dequant semantics as ``_cache_attend``
    (pinned to float tolerance in tests/test_decode_attention.py).

    Paged caches route to ``paged_decode_attention``: the kernel reads
    the page table directly and streams only mapped pages — the fused
    alternative to the einsum path's gather of the whole linear view.
    """
    from ddlb_tpu.ops.decode_attention import (
        decode_attention,
        paged_decode_attention,
    )

    b = q.shape[0]
    interpret = jax.default_backend() != "tpu"
    if "table" in cache:
        out = paged_decode_attention(
            q[:, 0],
            cache["k"][l],
            cache["v"][l],
            cache["table"],
            pos,
            k_scale=(cache["k_scale"][l] if "k_scale" in cache else None),
            v_scale=(cache["v_scale"][l] if "v_scale" in cache else None),
            window=cfg.attn_window,
            interpret=interpret,
        )
        return out.reshape(b, 1, -1).astype(dtype)
    out = decode_attention(
        q[:, 0],
        cache["k"][l],
        cache["v"][l],
        pos,
        k_scale=(cache["k_scale"][l] if "k_scale" in cache else None),
        v_scale=(cache["v_scale"][l] if "v_scale" in cache else None),
        window=cfg.attn_window,
        interpret=interpret,
    )
    return out.reshape(b, 1, -1).astype(dtype)


def _serving_body(params, cache, tokens, pos, cfg, tp, h_loc, kv_loc, dh):
    """The shared cached serving forward: ``tokens [b, t]`` consumed at
    positions derived from ``pos``, attending through the cache.

    ONE implementation serves both cadences — ``make_decode_fn`` is the
    ``t=1`` case (``pos`` scalar, or ``[b]`` ragged per-sequence) and
    ``make_chunk_decode_fn`` the ``t>1`` speculative-verify chunk
    (``pos`` = scalar start; row ``j`` sits at ``pos + j``) — so a new
    serving lever cannot diverge the decode and verify paths.

    Returns ``(logits [b, t, vocab], cache)``: one logits row per
    consumed token.
    """
    b, t = tokens.shape
    if b % tp != 0:
        raise ValueError(f"per-dp batch {b} not divisible by tp={tp}")
    int8_cache = cfg.kv_cache == "int8"
    x = params["embed"][tokens]  # [b, t, D]
    if cfg.rope:
        posb = (
            pos[:, None]  # ragged: each sequence at its own position
            if jnp.ndim(pos) == 1
            else (
                jnp.asarray(pos, jnp.int32)
                + jnp.arange(t, dtype=jnp.int32)
            )[None]
        )
    for l in range(cfg.layers_per_stage):
        h = _rms_norm(x, params["ln1"][0, l])
        q, k, v = _project_qkv(
            h, params, l, b, t, h_loc, kv_loc, dh, x.dtype
        )
        if cfg.rope:
            q = apply_rope(q, posb, cfg.rope_theta)
            k = apply_rope(k, posb, cfg.rope_theta)
        cache = _cache_write(cache, l, pos, k, v, int8_cache)
        # grouped against the kv-head cache rows; positions past each
        # query's own position are masked (zeros in the cache never win
        # anyway, but the mask keeps softmax exact)
        if t == 1 and cfg.decode_kernel == "pallas":
            attn = _cache_attend_pallas(q, cache, l, pos, x.dtype, cfg)
        else:
            attn = _cache_attend(
                q, cache, l, dh, pos, x.dtype, window=cfg.attn_window
            )
        part = jnp.matmul(
            attn,
            params["w_o"][0, l],
            preferred_element_type=jnp.float32,
        )
        x = x + jax.lax.psum(part, "tp").astype(x.dtype)
        h2 = _rms_norm(x, params["ln2"][0, l])
        D = x.shape[-1]
        # rows sequence-major: each rank's block is whole sequences
        u = _block_moe(h2.reshape(b * t, D), params, l, cfg, tp)
        x = x + u.reshape(b, t, D)
    h = _rms_norm(x, params["ln_f"])
    logits = jnp.matmul(
        h, params["head"], preferred_element_type=jnp.float32
    )
    return logits, cache


def make_decode_fn(mesh, cfg: TransformerConfig, ragged: bool = False):
    """One-token decode step over a ``('dp', 'tp')`` mesh.

    Returns ``(decode_step, shardings)``: ``decode_step(params, cache,
    tokens, pos) -> (logits, cache)`` with ``tokens [B]`` (this step's
    token per sequence), ``pos`` a scalar int32 position, ``logits
    [B, vocab]``; jit at the call site (cache threads through
    functionally, so the step re-runs under a measurement loop).

    ``ragged=True`` is the continuous-batching form: ``pos`` is a
    ``[B]`` int32 vector (sharded over ``dp`` with its sequences) and
    every sequence decodes at ITS OWN cache position — the write lands
    at ``pos[i]`` and the attention mask ends there, so one compiled
    step serves a batch whose members are at different generation
    depths.
    """

    tp = mesh.shape["tp"]
    if cfg.attention != "gathered":
        raise ValueError(
            "decode supports attention='gathered' (heads sharded over tp); "
            "ring/context-parallel decode is a training-side construction"
        )
    if cfg.router != "block":
        raise ValueError(
            "serving paths use the per-sequence-stable block router; "
            f"router='{cfg.router}' is a training-side construction"
        )
    if cfg.n_heads % tp != 0:
        raise ValueError(f"n_heads={cfg.n_heads} not divisible by tp={tp}")
    if cfg.kv_heads % tp != 0:
        raise ValueError(
            f"n_kv_heads={cfg.kv_heads} not divisible by tp={tp}"
        )
    if cfg.cache_layout == "paged" and mesh.shape.get("dp", 1) != 1:
        raise ValueError(
            "cache_layout='paged' shares one page pool across the slot "
            "axis and requires dp=1 (run one engine per dp shard)"
        )
    h_loc = cfg.n_heads // tp
    kv_loc = cfg.kv_heads // tp
    dh = cfg.head_dim

    def body(params, cache, tokens, pos):
        logits, cache = _serving_body(
            params, cache, tokens[:, None], pos, cfg, tp, h_loc, kv_loc, dh
        )
        return logits[:, 0], cache

    from ddlb_tpu.models.transformer import param_specs

    specs = dict(param_specs(cfg))
    # decode topology: no pp axis in the mesh, heads over tp; the stage
    # axis of the param stacks is size pp=1 and stays unsharded
    specs = {
        name: P(*[None if ax == "pp" else ax for ax in spec])
        for name, spec in specs.items()
    }
    cspecs = cache_specs(cfg)
    pos_spec = P("dp") if ragged else P()

    def step(params, cache, tokens, pos):
        return shard_map_compat(
            body,
            mesh=mesh,
            in_specs=(specs, cspecs, P("dp"), pos_spec),
            out_specs=(P("dp", None), cspecs),
            check_vma=False,
        )(params, cache, tokens, pos)

    shardings = {k: NamedSharding(mesh, s) for k, s in specs.items()}
    # every cache leaf (incl. the int8 scale entries), prefixed to avoid
    # param-name collisions
    for name, spec in cspecs.items():
        shardings[f"cache_{name}"] = NamedSharding(mesh, spec)
    shardings["tokens"] = NamedSharding(mesh, P("dp"))
    return step, shardings


def make_chunk_decode_fn(mesh, cfg: TransformerConfig):
    """Multi-token cached step over a ``('dp', 'tp')`` mesh — the
    speculative-verify engine: ``chunk(params, cache, tokens, start) ->
    (logits, cache)`` with ``tokens [B, t]`` consumed at absolute
    positions ``[start, start + t)`` and ``logits [B, t, vocab]`` (one
    row per consumed token, each attending causally through the cache up
    to itself).

    The t-token generalization of ``make_decode_fn`` (both run the same
    ``_serving_body``): cache rows ``[start, start + t)`` are written in
    one block, attention reads THE CACHE (so int8 quantization numerics
    are identical to plain decode), and the MoE block routing is
    per-sequence exactly as decode/prefill. One target-model call
    verifies t draft proposals — turning t bandwidth-bound cache+weight
    re-reads into one.

    PRECONDITION: ``start + t <= S_max``. The block write is a
    ``dynamic_update_slice``, whose out-of-bounds semantics CLAMP the
    start — an overflowing chunk would shift onto and overwrite live
    prefix rows with no error (the ragged t=1 path drops instead; a
    block write has no drop mode). ``make_speculate_fn`` sizes both
    caches so this holds; size yours the same way.
    """

    tp = mesh.shape["tp"]
    if cfg.attention != "gathered":
        raise ValueError("chunk decode supports attention='gathered' only")
    if cfg.router != "block":
        raise ValueError(
            "serving paths use the per-sequence-stable block router; "
            f"router='{cfg.router}' is a training-side construction"
        )
    if cfg.n_heads % tp != 0:
        raise ValueError(f"n_heads={cfg.n_heads} not divisible by tp={tp}")
    if cfg.kv_heads % tp != 0:
        raise ValueError(
            f"n_kv_heads={cfg.kv_heads} not divisible by tp={tp}"
        )
    h_loc = cfg.n_heads // tp
    kv_loc = cfg.kv_heads // tp
    dh = cfg.head_dim

    def body(params, cache, tokens, start):
        if jnp.ndim(start) != 0:
            raise ValueError(
                "chunk decode takes a scalar start position (the batch-"
                "uniform speculative form; ragged is the t=1 decode path)"
            )
        return _serving_body(
            params, cache, tokens, start, cfg, tp, h_loc, kv_loc, dh
        )

    from ddlb_tpu.models.transformer import param_specs

    specs = dict(param_specs(cfg))
    specs = {
        name: P(*[None if ax == "pp" else ax for ax in spec])
        for name, spec in specs.items()
    }
    cspecs = cache_specs(cfg)

    def chunk(params, cache, tokens, start):
        return shard_map_compat(
            body,
            mesh=mesh,
            in_specs=(specs, cspecs, P("dp", None), P()),
            out_specs=(P("dp", None, None), cspecs),
            check_vma=False,
        )(params, cache, tokens, start)

    shardings = {k: NamedSharding(mesh, s) for k, s in specs.items()}
    for name, spec in cspecs.items():
        shardings[f"cache_{name}"] = NamedSharding(mesh, spec)
    shardings["tokens"] = NamedSharding(mesh, P("dp", None))
    return chunk, shardings


def make_prefill_fn(mesh, cfg: TransformerConfig, dynamic_last: bool = False):
    """Full-sequence prompt pass over a ``('dp', 'tp')`` mesh: fills the
    cache for positions ``[0, S)`` and returns the last position's logits.

    Returns ``(prefill, shardings)``: ``prefill(params, cache, tokens) ->
    (logits, cache)`` with ``tokens [B, S]``. The compute-bound serving
    phase — so ``cfg.attn_kernel='flash'`` (the default) runs the prompt
    attention on the Pallas flash kernels, exactly the long-S regime they
    exist for; ``'einsum'`` keeps the HBM-score-matrix form for A/B.

    ``dynamic_last=True`` is the bucketed-prompt form: the returned fn
    takes a fourth TRACED scalar ``last`` and emits the logits at that
    position instead of ``S - 1`` — the serving engine pads prompts to
    power-of-two buckets so compile count is O(log S), and reads each
    prompt's true last row (the pad tail is causally downstream and
    never influences it).
    """

    tp = mesh.shape["tp"]
    if cfg.attention != "gathered":
        raise ValueError("decode/prefill support attention='gathered' only")
    if cfg.router != "block":
        raise ValueError(
            "serving paths use the per-sequence-stable block router; "
            f"router='{cfg.router}' is a training-side construction"
        )
    if cfg.attn_kernel not in ("flash", "einsum"):
        raise ValueError(f"unknown attn_kernel '{cfg.attn_kernel}'")
    if cfg.kv_heads % tp != 0:
        raise ValueError(
            f"n_kv_heads={cfg.kv_heads} not divisible by tp={tp}"
        )
    L = cfg.layers_per_stage
    h_loc = cfg.n_heads // tp
    kv_loc = cfg.kv_heads // tp
    dh = cfg.head_dim

    from ddlb_tpu.models.transformer import _causal_attention, _flash_full

    interpret = jax.default_backend() != "tpu"

    int8_cache = cfg.kv_cache == "int8"

    def body(params, cache, tokens, last):
        b, S = tokens.shape
        if b % tp != 0:
            raise ValueError(f"per-dp batch {b} not divisible by tp={tp}")
        x = params["embed"][tokens]  # [b, S, D]
        for l in range(L):
            h = _rms_norm(x, params["ln1"][0, l])
            q, k, v = _project_qkv(
                h, params, l, b, S, h_loc, kv_loc, dh, x.dtype
            )
            if cfg.rope:
                pos = jnp.arange(S, dtype=jnp.int32)[None]
                q = apply_rope(q, pos, cfg.rope_theta)
                k = apply_rope(k, pos, cfg.rope_theta)
            cache = _cache_write(cache, l, 0, k, v, int8_cache)
            if int8_cache:
                # prompt attention reads the same dequantized values the
                # later decode steps will — one consistent serving
                # numerics, exactly reproducible by the oracle
                k = _kv_roundtrip(k)
                v = _kv_roundtrip(v)
            if cfg.attn_kernel == "flash":
                attn = _flash_full(
                    q, k, v, interpret, window=cfg.attn_window
                ).reshape(
                    b, S, h_loc * dh
                )
            else:
                attn = _causal_attention(
                    q, k, v, window=cfg.attn_window
                ).reshape(b, S, h_loc * dh)
            part = jnp.matmul(
                attn, params["w_o"][0, l], preferred_element_type=jnp.float32
            )
            x = x + jax.lax.psum(part, "tp").astype(x.dtype)
            h2 = _rms_norm(x, params["ln2"][0, l])
            # per-sequence expert assignment, identical to the decode step
            # (rows are sequence-major, so each rank's block is its g
            # whole sequences)
            D = x.shape[-1]
            u = _block_moe(h2.reshape(b * S, D), params, l, cfg, tp)
            x = x + u.reshape(b, S, D)
        h = _rms_norm(x, params["ln_f"])
        # ``last`` (dynamic_last=True) indexes the logits position so a
        # BUCKETED prompt — padded past its real length — reads its own
        # last row: K/V row j and hidden row i depend only on tokens
        # <= themselves under the causal mask, so pad-tail garbage never
        # reaches rows [0, last]. The index is a traced scalar: bucket
        # length, not prompt length, drives compiles.
        h_last = (
            h[:, -1]
            if last is None
            else jax.lax.dynamic_index_in_dim(h, last, axis=1, keepdims=False)
        )
        logits = jnp.matmul(
            h_last, params["head"], preferred_element_type=jnp.float32
        )
        return logits, cache

    from ddlb_tpu.models.transformer import param_specs

    specs = dict(param_specs(cfg))
    specs = {
        name: P(*[None if ax == "pp" else ax for ax in spec])
        for name, spec in specs.items()
    }
    cspecs = cache_specs(cfg)

    if dynamic_last:

        def prefill(params, cache, tokens, last):
            return shard_map_compat(
                body,
                mesh=mesh,
                in_specs=(specs, cspecs, P("dp", None), P()),
                out_specs=(P("dp", None), cspecs),
                check_vma=False,
            )(params, cache, tokens, last)

    else:

        def prefill(params, cache, tokens):
            return shard_map_compat(
                functools.partial(body, last=None),
                mesh=mesh,
                in_specs=(specs, cspecs, P("dp", None)),
                out_specs=(P("dp", None), cspecs),
                check_vma=False,
            )(params, cache, tokens)

    shardings = {k: NamedSharding(mesh, s) for k, s in specs.items()}
    shardings["tokens"] = NamedSharding(mesh, P("dp", None))
    return prefill, shardings


def make_full_width_fns(cfg: TransformerConfig, batch: int, dp: int, tp: int):
    """Single-program (no shard_map) decode and prefill formulations:
    full-head attention, ``_routed_moe`` expert blocks, cache threading.

    These carry no collectives — GSPMD inserts them from sharding
    annotations when the returned callables are jitted over a mesh (the
    transformer_decode xla_gspmd member), and they double as the oracle
    building blocks. Returns ``(decode_fwd, prefill_fwd)`` with
    ``decode_fwd(params, cache, tokens, pos) -> logits`` and
    ``prefill_fwd(params, cache, tokens) -> (logits, cache)``.
    """
    from ddlb_tpu.models.transformer import _causal_attention

    B = batch
    L, H, dh = cfg.layers_per_stage, cfg.n_heads, cfg.head_dim
    H_kv = cfg.kv_heads
    int8_cache = cfg.kv_cache == "int8"

    def decode_fwd(params, cache, tokens, pos):
        cache = dict(cache)
        x = params["embed"][tokens][:, None, :]  # [B, 1, D]
        if cfg.rope:
            posb = (
                pos[:, None]
                if jnp.ndim(pos) == 1
                else jnp.broadcast_to(jnp.asarray(pos, jnp.int32), (B, 1))
            )
        for l in range(L):
            h = _rms_norm(x, params["ln1"][0, l])
            q, k, v = _project_qkv(
                h, params, l, B, 1, H, H_kv, dh, x.dtype
            )
            if cfg.rope:
                q = apply_rope(q, posb, cfg.rope_theta)
                k = apply_rope(k, posb, cfg.rope_theta)
            cache = _cache_write(cache, l, pos, k, v, int8_cache)
            attn = _cache_attend(
                q, cache, l, dh, pos, x.dtype, window=cfg.attn_window
            )
            x = x + jnp.matmul(
                attn,
                params["w_o"][0, l],
                preferred_element_type=jnp.float32,
            ).astype(x.dtype)
            h2 = _rms_norm(x, params["ln2"][0, l])
            u = _routed_moe(h2.reshape(B, -1), params, cfg, l, B, dp, tp)
            x = x + u[:, None, :]
        h = _rms_norm(x, params["ln_f"])
        return jnp.matmul(
            h[:, 0], params["head"], preferred_element_type=jnp.float32
        )

    def prefill_fwd(params, cache, tokens):
        cache = dict(cache)
        B_, S = tokens.shape
        x = params["embed"][tokens]
        for l in range(L):
            h = _rms_norm(x, params["ln1"][0, l])
            q, k, v = _project_qkv(
                h, params, l, B_, S, H, H_kv, dh, x.dtype
            )
            if cfg.rope:
                pos = jnp.arange(S, dtype=jnp.int32)[None]
                q = apply_rope(q, pos, cfg.rope_theta)
                k = apply_rope(k, pos, cfg.rope_theta)
            cache = _cache_write(cache, l, 0, k, v, int8_cache)
            if int8_cache:
                k = _kv_roundtrip(k)
                v = _kv_roundtrip(v)
            attn = _causal_attention(
                q, k, v, window=cfg.attn_window
            ).reshape(B_, S, H * dh)
            x = x + jnp.matmul(
                attn, params["w_o"][0, l], preferred_element_type=jnp.float32
            ).astype(x.dtype)
            h2 = _rms_norm(x, params["ln2"][0, l])
            u = _routed_moe(h2.reshape(B_ * S, -1), params, cfg, l, B, dp, tp)
            x = x + u.reshape(B_, S, -1)
        h = _rms_norm(x, params["ln_f"])
        logits = jnp.matmul(
            h[:, -1], params["head"], preferred_element_type=jnp.float32
        )
        return logits, cache

    return decode_fwd, prefill_fwd


def make_generate_fn(
    mesh,
    cfg: TransformerConfig,
    n_new: int,
    temperature: float = 0.0,
    top_k: int = 0,
    top_p: float = 1.0,
):
    """Autoregressive generation, one jitted program.

    Returns ``(generate, shardings)``: ``generate(params, cache, prompt
    [, key])  -> tokens [B, S0 + n_new]`` — prefill the prompt, then
    ``n_new`` decode steps under ``lax.fori_loop`` (the whole loop
    compiles once; the cache and the sampled token thread the carry).
    ``temperature=0`` samples the argmax (greedy, no key needed);
    ``temperature>0`` draws from ``softmax(logits / temperature)`` with a
    per-step fold of the caller's PRNG key, optionally restricted to the
    ``top_k`` highest logits and/or the smallest set of tokens whose
    cumulative probability reaches ``top_p`` (nucleus sampling; the
    first-past-the-threshold token is always kept, so the set is never
    empty). The cache must hold ``S0 + n_new`` positions.
    """
    if n_new < 1:
        # n_new=0 would write the post-loop sample at column S0-1,
        # silently overwriting the last prompt token
        raise ValueError(f"n_new must be >= 1, got {n_new}")
    if not 0.0 < top_p <= 1.0:
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")
    if top_k < 0 or top_k > cfg.vocab:
        raise ValueError(f"top_k must be in [0, vocab], got {top_k}")
    decode, shardings = make_decode_fn(mesh, cfg)
    prefill, _ = make_prefill_fn(mesh, cfg)

    def _restrict(logits):
        """Mask logits outside the top-k set / the top-p nucleus."""
        if top_k:
            kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
            logits = jnp.where(logits < kth, -jnp.inf, logits)
        if top_p < 1.0:
            srt = jnp.sort(logits, axis=-1)[..., ::-1]
            probs = jax.nn.softmax(srt, axis=-1)
            # exclusive cumulative mass BEFORE each token: a token enters
            # the nucleus iff the mass before it is < top_p (the
            # first-past-the-threshold token stays)
            before = jnp.cumsum(probs, axis=-1) - probs
            kept = before < top_p
            # smallest kept logit = the acceptance threshold
            thr = jnp.min(
                jnp.where(kept, srt, jnp.inf), axis=-1, keepdims=True
            )
            logits = jnp.where(logits < thr, -jnp.inf, logits)
        return logits

    def sample(logits, key, step):
        if temperature <= 0.0:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        logits = _restrict(logits.astype(jnp.float32) / temperature)
        return jax.random.categorical(
            jax.random.fold_in(key, step), logits, axis=-1
        ).astype(jnp.int32)

    def generate(params, cache, prompt, key=None):
        if temperature > 0.0 and key is None:
            raise ValueError("temperature > 0 sampling needs a PRNG key")
        B, S0 = prompt.shape
        S_max = _cache_max_len(cache)
        if S0 + n_new > S_max:
            # OOB dynamic_update_slice CLAMPS: without this check later
            # steps would silently overwrite the last cache slot and
            # return plausible wrong tokens
            raise ValueError(
                f"cache holds {S_max} positions < prompt {S0} + "
                f"n_new {n_new}"
            )
        dp_rows = NamedSharding(mesh, P("dp", None))
        # one explicit layout for the token buffer, the prompt and each
        # sampled column: dynamic_update_slice requires operand and
        # update shardings to agree (reshard: the serving meshes carry
        # Explicit axis types, where with_sharding_constraint is a no-op)
        prompt = reshard_compat(prompt, dp_rows)
        logits, cache = prefill(params, cache, prompt)
        tokens = reshard_compat(
            jnp.zeros((B, S0 + n_new), jnp.int32), dp_rows
        )
        tokens = jax.lax.dynamic_update_slice(tokens, prompt, (0, 0))

        def body(i, carry):
            tokens, cache, logits = carry
            nxt = sample(logits, key, i)  # [B]
            tokens = jax.lax.dynamic_update_slice(
                tokens, nxt[:, None], (0, S0 + i)
            )
            logits, cache = decode(params, cache, nxt, S0 + i)
            return tokens, cache, logits

        # n_new - 1 looped steps; the LAST token comes from the carried
        # logits after the loop — a final decode would produce logits
        # nothing consumes, and each decode step is a full cache+weights
        # HBM re-read
        tokens, cache, logits = jax.lax.fori_loop(
            0, n_new - 1, body, (tokens, cache, logits)
        )
        last = sample(logits, key, n_new - 1)
        return jax.lax.dynamic_update_slice(
            tokens, last[:, None], (0, S0 + n_new - 1)
        )

    return generate, shardings


def make_speculate_fn(
    mesh,
    cfg: TransformerConfig,
    cfg_draft: TransformerConfig,
    n_new: int,
    spec_k: int = 4,
    with_stats: bool = False,
):
    """Greedy speculative decoding, one jitted program — LOSSLESS: the
    output is exactly the target model's own greedy chain, for ANY draft
    model (the draft only changes how fast the chain is produced).

    Each round: the draft autoregressively proposes ``spec_k`` tokens
    (cheap decode steps), the target verifies all of them in ONE chunk
    forward (``make_chunk_decode_fn`` — one cache+weights HBM re-read
    instead of ``spec_k``), and the batch advances by ``a + 1`` tokens
    where ``a`` is the count of leading proposals every sequence's target
    argmax agrees with (batch-uniform: the minimum across sequences, so
    one scalar position serves the whole batch — the ragged form would
    use per-sequence positions). The ``+1`` is the target's own next
    token at the first disagreement (or the bonus token when everything
    matched), so every emitted token is the target's argmax given the
    tokens before it — greedy speculative decoding's losslessness,
    pinned by test_speculative.py against ``make_generate_fn``.

    int8-cache caveat: the verify chunk quantizes K/V computed by a
    batched projection whose f32 accumulation order can differ from the
    t=1 decode path's by one int8 bucket, so under ``kv_cache='int8'``
    exactness holds up to quantization near-ties (an argmax whose top-2
    gap is below the ~1e-2 drift may flip); the bf16 cache is exact.

    Greedy only (``temperature=0``): lossless acceptance for sampled
    generation needs the rejection-sampling scheme (Leviathan et al.
    2023), whose verdict depends on the draft's full distribution —
    out of scope for the benchmark family this serves.

    Returns ``(generate, (shardings, shardings_draft))``:
    ``generate(params, params_draft, cache, cache_draft, prompt) ->
    tokens [B, S0 + n_new]``. Both caches must hold at least
    ``S0 + n_new + spec_k`` positions (the verify chunk writes up to
    ``spec_k`` provisional rows past the accepted prefix; they are
    masked by position until overwritten).

    ``with_stats=True`` returns ``(tokens, {"rounds", "accepted",
    "proposals"})`` instead, so the benchmark row can report the
    MEASURED acceptance rate ``accepted / proposals`` next to the
    tokens/s the ~1.3x speculation model predicts. Both counters are
    clipped to the requested ``n_new``: a final round that overshoots
    has its surplus sliced from the output, so neither the surplus
    acceptances nor the proposal slots that could never land inside
    ``n_new`` are counted (``proposals`` adds ``min(spec_k,
    remaining - 1)`` per round). This keeps the rate unbiased — a
    draft identical to the target reports exactly 1.0 — and the
    invariant ``rounds + accepted == n_new - 1`` exact in every
    acceptance regime.
    """
    if n_new < 1:
        raise ValueError(f"n_new must be >= 1, got {n_new}")
    if spec_k < 1:
        raise ValueError(f"spec_k must be >= 1, got {spec_k}")
    if cfg_draft.vocab != cfg.vocab:
        raise ValueError(
            f"draft vocab {cfg_draft.vocab} != target vocab {cfg.vocab}"
        )
    decode_d, sh_d = make_decode_fn(mesh, cfg_draft)
    chunk_t, _ = make_chunk_decode_fn(mesh, cfg)
    prefill_t, sh_t = make_prefill_fn(mesh, cfg)
    prefill_d, _ = make_prefill_fn(mesh, cfg_draft)
    k = spec_k

    def generate(params, params_draft, cache, cache_draft, prompt):
        B, S0 = prompt.shape
        need = S0 + n_new + k
        for name, c in (("target", cache), ("draft", cache_draft)):
            S_max = c["k"].shape[2]
            if S_max < need:
                raise ValueError(
                    f"{name} cache holds {S_max} positions < prompt {S0} "
                    f"+ n_new {n_new} + spec_k {k}"
                )
        dp_rows = NamedSharding(mesh, P("dp", None))
        prompt = reshard_compat(prompt, dp_rows)
        logits, cache = prefill_t(params, cache, prompt)
        _, cache_draft = prefill_d(params_draft, cache_draft, prompt)
        # token buffer wide enough for a full provisional block written
        # at the last in-range position; final slice trims it
        width = S0 + n_new + k + 1
        tokens = reshard_compat(
            jnp.zeros((B, width), jnp.int32), dp_rows
        )
        tokens = jax.lax.dynamic_update_slice(tokens, prompt, (0, 0))
        first = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        tokens = jax.lax.dynamic_update_slice(
            tokens, first[:, None], (0, S0)
        )

        def cond(carry):
            return carry[3] < S0 + n_new

        def body(carry):
            tokens, cache, cache_draft, ntok, rounds, accepted, props_n = (
                carry
            )
            # tokens[:, :ntok] are final; the last one is not yet in
            # either model's cache — both consume it first
            last = jax.lax.dynamic_slice(
                tokens, (0, ntok - 1), (B, 1)
            )[:, 0]

            def dstep(j, dc):
                cache_draft, tok, props = dc
                lg, cache_draft = decode_d(
                    params_draft, cache_draft, tok, ntok - 1 + j
                )
                nxt = jnp.argmax(lg, axis=-1).astype(jnp.int32)
                props = jax.lax.dynamic_update_slice(
                    props, nxt[:, None], (0, j)
                )
                return cache_draft, nxt, props

            props = reshard_compat(jnp.zeros((B, k), jnp.int32), dp_rows)
            cache_draft, last_prop, props = jax.lax.fori_loop(
                0, k, dstep, (cache_draft, last, props)
            )
            # consume the final proposal too: when every proposal is
            # accepted, the next round's draft attends its cache row
            _, cache_draft = decode_d(
                params_draft, cache_draft, last_prop, ntok - 1 + k
            )

            # ONE target forward verifies the whole proposal chain:
            # g[:, j] is the target argmax after [.., last, p_1..p_j]
            chunk_in = jnp.concatenate([last[:, None], props], axis=1)
            lg, cache = chunk_t(params, cache, chunk_in, ntok - 1)
            g = jnp.argmax(lg, axis=-1).astype(jnp.int32)  # [B, k+1]
            # write the target's whole greedy block: cols < a repeat the
            # accepted proposals (equal by construction), col a is the
            # correction/bonus, cols > a are provisional — the next
            # round's block starts at ntok + a + 1 and overwrites them
            tokens = jax.lax.dynamic_update_slice(tokens, g, (0, ntok))
            match = (props == g[:, :k]).astype(jnp.int32)
            a = jnp.min(jnp.sum(jnp.cumprod(match, axis=1), axis=1))
            # stats count only work inside the requested n_new: the
            # final round can overshoot (ntok + a + 1 past the target)
            # and its surplus tokens are sliced away below, so neither
            # the surplus acceptances nor the proposal slots that could
            # never land count — acceptance stays unbiased (identical
            # draft and target measure exactly 1.0) and
            # rounds + accepted == n_new - 1 holds in every regime
            remaining = S0 + n_new - ntok
            emit = jnp.minimum(a + 1, remaining)
            return (
                tokens, cache, cache_draft, ntok + a + 1,
                rounds + 1, accepted + emit - 1,
                props_n + jnp.minimum(jnp.int32(k), remaining - 1),
            )

        (tokens, cache, cache_draft, _, rounds, accepted, props_n) = (
            jax.lax.while_loop(
                cond, body,
                (
                    tokens, cache, cache_draft, jnp.int32(S0 + 1),
                    jnp.int32(0), jnp.int32(0), jnp.int32(0),
                ),
            )
        )
        out = jax.lax.dynamic_slice(tokens, (0, 0), (B, S0 + n_new))
        if with_stats:
            return out, {
                "rounds": rounds,
                "accepted": accepted,
                "proposals": props_n,
            }
        return out

    return generate, (sh_t, sh_d)


@functools.partial(jax.jit, static_argnames=("window",))
def _oracle_attn_block(qc, q0, k, v, window):
    """One query-chunk of the oracle attention: rows ``[q0, q0+C)``
    softmaxed over the full key range. Module-level jit so the graph
    compiles once and is reused across layers and validation forwards
    (k/v are arguments, not trace-time closure constants)."""
    S = k.shape[1]
    C = qc.shape[1]
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", qc, k, preferred_element_type=jnp.float32
    )
    s = s * (1.0 / np.sqrt(qc.shape[-1]))
    rows = q0 + jax.lax.broadcasted_iota(jnp.int32, (C, S), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (C, S), 1)
    mask = rows >= cols
    if window:
        mask &= cols > rows - window
    s = jnp.where(mask[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum(
        "bhqk,bkhd->bqhd", p, v, preferred_element_type=jnp.float32
    )
    return out.astype(qc.dtype)


def _oracle_attention(q, k, v, window: int = 0):
    """Exact causal attention for the oracle without the ``[B, H, S, S]``
    score matrix: query rows are processed in chunks, each chunk's rows
    softmaxed over the full key range (query chunking is exact — no
    online-softmax accumulator needed; a ragged final chunk is fine, and
    matters: the decode oracle's teacher-forced length is m+1, odd for
    every power-of-two context).

    Same math as ``models.transformer._causal_attention``: operands stay
    bf16 with an f32 MXU accumulator (bf16 products are exact in f32, so
    the only difference from the upcast-first einsum is the accumulation
    order, far below the validation atol). The full matrix OOMs the v5e
    past ctx≈4k — observed RESOURCE_EXHAUSTED in the first live serving
    batch — while the chunked rows keep oracle scratch around 1 GB even
    at 64k context.
    """
    B, S, H, dh = q.shape
    if k.shape[2] != H:
        G = H // k.shape[2]
        k = jnp.repeat(k, G, axis=2)
        v = jnp.repeat(v, G, axis=2)
    # chunk sized so the [B, H, chunk, S] f32 score block stays ~1 GB
    chunk = S
    while B * H * chunk * S * 4 > (1 << 30) and chunk > 1:
        chunk = (chunk + 1) // 2
    outs = [
        _oracle_attn_block(q[:, q0 : q0 + chunk], jnp.int32(q0), k, v, window)
        for q0 in range(0, S, chunk)
    ]
    return jnp.concatenate(outs, axis=1) if len(outs) > 1 else outs[0]


def reference_logits(
    params, tokens, cfg: TransformerConfig, tp: int, dp: int
) -> jax.Array:
    """Single-device oracle: teacher-forced full forward, logits at the
    LAST position ``[B, vocab]``.

    Reproduces the decode semantics exactly: per-sequence-stable expert
    assignment (sequence ``i`` of a dp shard uses expert
    ``i // (B/(dp*tp))``), q-chunked causal attention with an f32
    accumulator over bf16 operands (``_oracle_attention`` — bf16
    products are exact in f32, so this differs from a full-f32 einsum
    only in accumulation order), the shared ``_moe_ffn`` MLP kernels.
    The incremental cache path must match this non-incremental
    formulation — the real consistency check.
    """
    B, S = tokens.shape
    L = cfg.layers_per_stage
    x = params["embed"][tokens]  # [B, S, D]
    D = cfg.d_model
    for l in range(L):
        h = _rms_norm(x, params["ln1"][0, l])
        q, k, v = _project_qkv(
            h, params, l, B, S, cfg.n_heads, cfg.kv_heads,
            cfg.head_dim, x.dtype,
        )
        if cfg.rope:
            pos = jnp.arange(S, dtype=jnp.int32)[None]
            q = apply_rope(q, pos, cfg.rope_theta)
            k = apply_rope(k, pos, cfg.rope_theta)
        if cfg.kv_cache == "int8":
            # the serving paths attend dequantized cache entries; the
            # oracle applies the identical per-(position, head) rounding
            k = _kv_roundtrip(k)
            v = _kv_roundtrip(v)
        attn = _oracle_attention(
            q, k, v, window=cfg.attn_window
        ).reshape(B, S, D)
        x = x + jnp.matmul(
            attn, params["w_o"][0, l], preferred_element_type=jnp.float32
        ).astype(x.dtype)
        h2 = _rms_norm(x, params["ln2"][0, l])
        u = _routed_moe(h2.reshape(B * S, D), params, cfg, l, B, dp, tp)
        x = x + u.reshape(B, S, D)
    h = _rms_norm(x, params["ln_f"])
    return jnp.matmul(
        h[:, -1], params["head"], preferred_element_type=jnp.float32
    )
