"""Model compositions built from the framework's parallel primitives."""

from ddlb_tpu.models.tp_mlp import (  # noqa: F401
    example_batch,
    init_params,
    make_train_step,
    mlp_block,
    mlp_forward,
)
