"""Flagship model: MoE transformer LM over a (dp, tp, pp) mesh.

The reference has no model at all — its workloads are bare GEMM primitives
(SURVEY.md section 2.5). This module composes every primitive family the
framework benchmarks into the training step they exist to accelerate, all
five parallelism axes at once:

- **dp**: batch sharded over the ``dp`` mesh axis; gradient all-reduce is
  the ``dp_allreduce`` pattern (inserted by autodiff through the psums).
- **tp + sp**: Megatron-style sequence-parallel attention/MLP — activations
  sequence-sharded over ``tp`` outside the matmuls; the QKV projection is
  the ``tp_columnwise`` pattern (all-gather + column-sharded GEMM), the
  output projection the ``tp_rowwise`` pattern (row-sharded GEMM +
  psum_scatter).
- **ep**: MoE FFN with one expert resident per ``tp`` coordinate, balanced
  block routing over mirrored ``lax.all_to_all`` — the ``ep_alltoall``
  pattern.
- **pp**: layers split into stages resident per ``pp`` coordinate,
  GPipe-microbatched with activations hopping neighbor-to-neighbor over
  ``ppermute`` — the ``pp_pipeline`` pattern (loss is a scalar, so the
  drain is a trivial psum instead of the ring drain).
- long-context attention itself is head-parallel over ``tp`` after the
  sequence all-gather (the ``cp_ring_attention`` family benchmarks the
  ring alternative).

Everything is hand-scheduled manual SPMD under one ``shard_map`` — the
whole train step (forward, backward through every collective, optimizer)
jits to a single XLA program per device.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ddlb_tpu.runtime import set_mesh_compat, shard_map_compat

LN_EPS = 1e-6


@dataclass(frozen=True)
class TransformerConfig:
    vocab: int = 64
    d_model: int = 32
    n_heads: int = 4
    #: 0 = MHA (n_kv_heads == n_heads). Smaller = grouped-query attention:
    #: K/V carry n_kv_heads heads shared by groups of n_heads/n_kv_heads
    #: query heads — the flash kernels read the shared KV tile straight
    #: from the head index map and the decode cache shrinks by the group
    #: factor (ops/flash_attention.py GQA support).
    n_kv_heads: int = 0
    d_ff: int = 64
    layers_per_stage: int = 1
    microbatches: int = 2
    #: "gathered": Megatron sp — all-gather the sequence, attend, scatter
    #: back. "ring": context parallelism — K/V chunks circulate the tp
    #: ring with an online-softmax accumulator, so no device ever holds
    #: the full sequence (the long-context mode; same math, exact).
    attention: str = "gathered"
    #: "flash": the Pallas flash kernels (custom_vjp forward+backward,
    #: ops/flash_attention.py) — the training path's compute engine.
    #: "einsum": XLA einsum attention (HBM-resident scores; the oracle's
    #: formulation), kept selectable for A/B measurement.
    attn_kernel: str = "flash"
    #: "int8": the MoE FFN GEMMs (the FLOPs-dominant block) run on the
    #: int8 MXU path via the straight-through estimator
    #: (ops/quantized_matmul.int8_ste_matmul) — real int8 compute forward,
    #: full-precision gradients; per-token/per-feature scales make the
    #: sharded forward bit-identical to the oracle's. Attention
    #: projections stay in the operand dtype (head sharding would need
    #: per-shard scale bookkeeping for marginal FLOPs share).
    #: "int8_weights": the inference serving form — expert weights are
    #: quantized ONCE at init (init_params emits int8 weights + scale
    #: leaves) so the step pays no per-call weight quantization, only the
    #: dynamic per-token activation quant. Forward-only: int8 weight
    #: leaves have no gradients.
    mlp_kernel: str = "bf16"
    #: sliding-window (local) attention span: each position attends only
    #: the ``attn_window`` most recent positions including itself
    #: (0 = full causal). All paths: gathered and serving (the flash and
    #: decode kernels skip tiles entirely behind the band) AND ring —
    #: a windowed ring skips whole hops' compute (chunks entirely behind
    #: the band; the ppermute chain still circulates every chunk, since
    #: hop liveness differs per device).
    attn_window: int = 0
    #: rotary position embeddings (RoPE, rotate-half form) applied to
    #: q/k after projection. Position source per path: global sequence
    #: index (gathered), chunk offset + local index (ring), cache
    #: position (decode — scalar or ragged per-sequence). The K cache
    #: stores POST-rotation keys, so decode reads need no re-rotation.
    #: False keeps the family's established benchmark numbers comparable.
    rope: bool = False
    rope_theta: float = 10000.0
    #: single-token cache attention engine for the decode step:
    #: "einsum" materializes the [b, h_kv, G, 1, S] scores in HBM (the
    #: oracle's formulation); "pallas" streams the cache through the
    #: fused online-softmax kernel (ops/decode_attention.py) — no score
    #: round-trip, int8 dequant in-kernel. The t>1 verify chunk and the
    #: full-width oracle always use einsum.
    decode_kernel: str = "einsum"
    #: "block": balanced block routing — sequence i's tokens use expert
    #: i-block (deterministic, perfectly balanced; the benchmark default,
    #: isolating the all-to-all traffic pattern from routing dynamics).
    #: "topk": learned top-k gating (GShard/Switch style) — per-token
    #: router logits, top-k expert choice, per-(shard, expert) capacity
    #: with first-come slot assignment, overflow dropped to the residual
    #: stream, Switch load-balance aux loss weighted ``router_aux``.
    #: "expert_choice": the dual (Zhou et al.) — each EXPERT picks its
    #: top-C tokens, so load is perfectly balanced by construction (no
    #: aux loss needed; aux reports 0); tokens chosen by no expert pass
    #: through on the residual stream, tokens chosen by several get a
    #: gate-weighted sum.
    router: str = "block"
    router_topk: int = 2
    #: capacity factor: each (source shard, expert) pair gets
    #: ceil(capacity_factor * k * T_loc / E) slots
    capacity_factor: float = 1.25
    router_aux: float = 0.01
    #: K/V cache precision for the serving paths (models/decode.py):
    #: "bf16" stores the operand dtype; "int8" stores symmetric per-
    #: (position, head) int8 with f32 scales — halves the cache bytes the
    #: bandwidth-bound decode step re-reads every token, dequantized on
    #: the fly inside the score/value einsums. Training paths ignore it.
    kv_cache: str = "bf16"
    #: cache memory layout for the serving engine (models/decode.py):
    #: "contiguous" — per-slot [B, S_max] rows, the benchmark members'
    #: layout. "paged" — a shared pool of fixed-size pages indexed by a
    #: per-slot page table (the vLLM pattern, TPU-first: static pool and
    #: table shapes, gather/scatter by page id). Pages let a mixed-length
    #: workload share HBM that a contiguous layout strands at B*S_max,
    #: and full prefix pages are shared across slots instead of copied.
    #: Decode-step cost: the einsum path gathers the linear cache view
    #: per step (one extra HBM pass over live pages vs contiguous);
    #: decode_kernel='pallas' instead streams mapped pages directly
    #: through the fused kernel's table-reading block index map
    #: (ops/decode_attention.paged_decode_attention). Serving-engine
    #: paths only.
    cache_layout: str = "contiguous"
    #: tokens per page under cache_layout='paged'
    page_size: int = 128
    dtype: Any = jnp.float32

    def __post_init__(self):
        # config-construction-time validation so BOTH kernels (and the
        # serving paths) fail identically: a negative window makes the
        # einsum mask all-False — silently uniform attention
        if self.attn_window < 0:
            raise ValueError(
                f"attn_window must be >= 0, got {self.attn_window}"
            )
        if self.cache_layout not in ("contiguous", "paged"):
            raise ValueError(
                f"unknown cache_layout '{self.cache_layout}'"
            )
        if self.cache_layout == "paged" and self.page_size < 1:
            raise ValueError(
                f"page_size must be >= 1, got {self.page_size}"
            )

    @property
    def head_dim(self) -> int:
        assert self.d_model % self.n_heads == 0
        return self.d_model // self.n_heads

    @property
    def kv_heads(self) -> int:
        h_kv = self.n_kv_heads or self.n_heads
        assert self.n_heads % h_kv == 0, (
            f"n_heads={self.n_heads} not divisible by n_kv_heads={h_kv}"
        )
        return h_kv

    @property
    def kv_dim(self) -> int:
        return self.kv_heads * self.head_dim


def init_params(
    cfg: TransformerConfig, pp: int, n_experts: int, seed: int = 0
) -> Dict[str, jax.Array]:
    """Seeded host-side parameters, stage-stacked on a leading ``pp`` axis
    (deterministic across hosts, like the primitive operands)."""
    rng = np.random.default_rng(seed)
    D, F, L, V = cfg.d_model, cfg.d_ff, cfg.layers_per_stage, cfg.vocab

    def normal(shape, scale):
        return jnp.asarray(rng.normal(0.0, scale, shape), cfg.dtype)

    s_in = (1.0 / D) ** 0.5
    s_ff = (1.0 / F) ** 0.5
    params = {
        "embed": normal((V, D), 1.0),
        "w_o": normal((pp, L, D, D), s_in),
        "moe_w1": normal((pp, L, n_experts, D, F), s_in),
        "moe_w2": normal((pp, L, n_experts, F, D), s_ff),
        "ln1": jnp.ones((pp, L, D), cfg.dtype),
        "ln2": jnp.ones((pp, L, D), cfg.dtype),
        "ln_f": jnp.ones((D,), cfg.dtype),
        "head": normal((D, V), s_in),
    }
    if cfg.kv_heads == cfg.n_heads:
        # leading 3 = Q/K/V so a tp column-shard is per-projection heads,
        # not a contiguous slice across the fused [D, 3D] layout
        params["w_qkv"] = normal((pp, L, 3, D, D), s_in)
    else:
        # GQA: K/V project to n_kv_heads * head_dim columns
        params["w_q"] = normal((pp, L, D, D), s_in)
        params["w_kv"] = normal((pp, L, 2, D, cfg.kv_dim), s_in)
    if cfg.router in ("topk", "expert_choice"):
        # learned gate, one logit per expert; kept in float32 so the
        # softmax/top-k selection is bit-identical between the sharded
        # step and the oracle whatever the activation dtype
        params["router"] = jnp.asarray(
            rng.normal(0.0, s_in, (pp, L, D, n_experts)), jnp.float32
        )
    if cfg.mlp_kernel == "int8_weights":
        # inference serving form: the expert weights ship pre-quantized,
        # so the step never re-quantizes them (deterministic: both the
        # distributed step and the oracle consume THESE leaves)
        from ddlb_tpu.ops.quantized_matmul import quantize_weight_stack

        for name in ("moe_w1", "moe_w2"):
            q, s = quantize_weight_stack(params[name])
            params[name] = q
            params[f"{name}_scale"] = s
    return params


def param_specs(cfg: TransformerConfig) -> Dict[str, P]:
    """PartitionSpecs: stage axis on ``pp``; QKV columns / output-proj rows
    / experts on ``tp``; embedding, head and norms replicated.

    ``attention='ring'`` replicates the attention projections instead:
    sequence and heads cannot shard on the same axis (a ringed K/V chunk
    would have been projected with the source's head-group weights), so
    in ring mode ``tp`` acts purely as the context-parallel axis for
    attention — K/V chunks move, weights don't — while the MoE FFN still
    uses it as the expert axis."""
    attn_o = (
        P("pp", None, None, None)
        if cfg.attention == "ring"
        else P("pp", None, "tp", None)
    )
    specs = {
        "embed": P(None, None),
        "w_o": attn_o,
        "moe_w1": P("pp", None, "tp", None, None),
        "moe_w2": P("pp", None, "tp", None, None),
        "ln1": P("pp", None, None),
        "ln2": P("pp", None, None),
        "ln_f": P(None),
        "head": P(None, None),
    }
    if cfg.kv_heads == cfg.n_heads:
        specs["w_qkv"] = (
            P("pp", None, None, None, None)
            if cfg.attention == "ring"
            else P("pp", None, None, None, "tp")
        )
    elif cfg.attention == "ring":
        # ring mode replicates the attention projections (tp is the
        # context axis); GQA just shrinks the replicated K/V columns —
        # and the ringed chunks with them
        specs["w_q"] = P("pp", None, None, None)
        specs["w_kv"] = P("pp", None, None, None, None)
    else:
        specs["w_q"] = P("pp", None, None, "tp")
        specs["w_kv"] = P("pp", None, None, None, "tp")
    if cfg.router in ("topk", "expert_choice"):
        # every rank routes its own token shard: gate replicated over tp
        specs["router"] = P("pp", None, None, None)
    if cfg.mlp_kernel == "int8_weights":
        # scale leaves ride with their weights: expert axis on tp
        specs["moe_w1_scale"] = P("pp", None, "tp", None, None)
        specs["moe_w2_scale"] = P("pp", None, "tp", None, None)
    return specs


def apply_rope(x, positions, theta: float):
    """Rotate-half rotary embedding: ``x [..., s, h, dh]`` with
    ``positions`` broadcastable to ``x.shape[:-2]`` (int32 absolute
    positions per row). Pairs dimension i with i + dh/2 (the rotate-half
    convention); computed in f32 and cast back, so the sharded paths and
    the oracle agree bitwise. Shared by train (global/chunk positions),
    prefill (0..S), and decode (cache position, scalar or ragged).
    """
    dh = x.shape[-1]
    assert dh % 2 == 0, f"RoPE needs an even head_dim, got {dh}"
    half = dh // 2
    freqs = theta ** (-jnp.arange(half, dtype=jnp.float32) / half)
    ang = positions[..., None, None].astype(jnp.float32) * freqs  # [..., 1, half]
    cos, sin = jnp.cos(ang), jnp.sin(ang)
    xf = x.astype(jnp.float32)
    x1, x2 = xf[..., :half], xf[..., half:]
    out = jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1
    )
    return out.astype(x.dtype)


def _rms_norm(x, scale):
    h = x.astype(jnp.float32)
    h = h * jax.lax.rsqrt(jnp.mean(h * h, axis=-1, keepdims=True) + LN_EPS)
    return (h * scale.astype(jnp.float32)).astype(x.dtype)


def _causal_attention(q, k, v, window: int = 0):
    """[b, S, h, dh] f32 causal softmax attention (full gathered sequence,
    local heads). ``k``/``v`` may carry fewer (grouped/GQA) heads — they
    are repeated up to the query head count (exact: repetition and
    grouped attention compute identical dot products). ``window > 0``
    restricts each query to its sliding window."""
    if k.shape[2] != q.shape[2]:
        G = q.shape[2] // k.shape[2]
        k = jnp.repeat(k, G, axis=2)
        v = jnp.repeat(v, G, axis=2)
    scale = 1.0 / np.sqrt(q.shape[-1])
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32))
    s = s * scale
    S = s.shape[-1]
    rows = jax.lax.broadcasted_iota(jnp.int32, (S, S), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (S, S), 1)
    mask = rows >= cols
    if window:
        mask &= cols > rows - window
    s = jnp.where(mask[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)


def _ring_attention(q, k, v, d, axis_name="tp", window: int = 0):
    """Context-parallel causal attention inside the train step: K/V chunks
    circulate the ``axis_name`` ring while a running (max, sum, output)
    accumulator folds each arriving chunk — exact online softmax, no
    device ever materializes the full sequence, and every op (including
    ``ppermute``) is differentiable, so autodiff derives the backward
    ring. The cp_ring_attention primitive family benchmarks this pattern
    standalone.

    ``q``/``k``/``v``: [b, s_loc, h_loc, dh] (local sequence chunk, local
    heads; ``k``/``v`` may carry fewer GQA heads — each arriving chunk is
    repeated up to the query head count before its fold, so the ring
    still ships the small kv chunks). Returns [b, s_loc, h_loc, dh].
    """
    G = q.shape[2] // k.shape[2]
    my = jax.lax.axis_index(axis_name)
    s_loc, dh = q.shape[1], q.shape[3]
    scale = 1.0 / np.sqrt(dh)
    fwd = [(i, (i + 1) % d) for i in range(d)]
    qh = q.astype(jnp.float32).transpose(0, 2, 1, 3) * scale  # [b, h, s, d]
    acc = jnp.zeros(qh.shape, jnp.float32)
    m_run = jnp.full(qh.shape[:3] + (1,), -1e30, jnp.float32)
    l_run = jnp.zeros_like(m_run)
    rows = jax.lax.broadcasted_iota(jnp.int32, (s_loc, s_loc), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (s_loc, s_loc), 1)
    k_cur, v_cur = k, v
    from ddlb_tpu.ops.flash_attention import _ring_chunk_live

    for t in range(d):
        src = (my - t) % d  # the chunk held after t hops came from src

        def fold(carry, k_blk=k_cur, v_blk=v_cur, src_=src):
            acc, m_run, l_run = carry
            k_use = jnp.repeat(k_blk, G, axis=2) if G > 1 else k_blk
            v_use = jnp.repeat(v_blk, G, axis=2) if G > 1 else v_blk
            s = jnp.einsum(
                "bhqd,bkhd->bhqk", qh, k_use.astype(jnp.float32)
            )
            mask = (my * s_loc + rows) >= (src_ * s_loc + cols)
            if window:
                # sliding window: keys more than window-1 behind the
                # query drop out (global coordinates — the band crosses
                # chunk boundaries)
                mask &= (src_ * s_loc + cols) > (
                    my * s_loc + rows - window
                )
            s = jnp.where(mask[None, None], s, -1e30)
            m_new = jnp.maximum(m_run, s.max(-1, keepdims=True))
            alpha = jnp.exp(m_run - m_new)
            p = jnp.exp(s - m_new)
            if window:
                # a fully-masked score row would make exp(s - m_new) = 1
                # per column — zero masked entries (a partially-banded
                # chunk can fully mask some rows)
                p = jnp.where(mask[None, None], p, 0.0)
            l_new = l_run * alpha + p.sum(-1, keepdims=True)
            acc_new = acc * alpha + jnp.einsum(
                "bhqk,bkhd->bhqd", p, v_use.astype(jnp.float32)
            )
            return acc_new, m_new, l_new

        # skip chunks entirely outside the live band — strictly future,
        # or (windowed) entirely behind it (same predicate as the flash
        # ring: dead hops cost no FLOPs on any ring path)
        acc, m_run, l_run = jax.lax.cond(
            _ring_chunk_live(src, my, s_loc, window),
            fold, lambda c: c, (acc, m_run, l_run),
        )
        if t + 1 < d:
            k_cur = jax.lax.ppermute(k_cur, axis_name, perm=fwd)
            v_cur = jax.lax.ppermute(v_cur, axis_name, perm=fwd)
    out = acc / l_run  # diagonal chunk guarantees every row attended
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


def _flash_block(S: int) -> int:
    """Largest usable flash tile for sequence length ``S``: the whole
    sequence when it fits one tile, else the largest power-of-two divisor
    up to 1024 (the kernel requires the grid to divide S — an S like 1536
    under a fixed min(1024, S) would fail deep in tracing)."""
    if S <= 1024:
        return S
    b = 1
    while b < 1024 and S % (b * 2) == 0:
        b *= 2
    return b


def _flash_full(q, k, v, interpret, window: int = 0):
    """Batched causal flash attention: [b, S, h, dh] -> [b, S, h, dh].

    The batch dim merges into the kernel's head grid (heads are
    independent and the causal mask depends only on sequence position),
    so no vmap of the pallas call is needed. ``k``/``v`` may carry fewer
    (GQA) heads: the merged layouts stay group-aligned because
    ``(b_idx*h + qh) // G == b_idx*h_kv + qh // G`` exactly.
    """
    from ddlb_tpu.ops.flash_attention import flash_attention

    b, S, h, dh = q.shape
    merge = lambda x: x.transpose(1, 0, 2, 3).reshape(S, b * x.shape[2], dh)
    o = flash_attention(
        merge(q), merge(k), merge(v),
        scale=1.0 / np.sqrt(dh),
        block_q=_flash_block(S),
        block_kv=_flash_block(S),
        interpret=interpret,
        window=window,
    )
    return o.reshape(S, b, h, dh).transpose(1, 0, 2, 3)


def _ring_flash(q, k, v, d, interpret, axis_name="tp", window: int = 0):
    """Batched context-parallel flash attention on the local sequence
    chunk: [b, s_loc, h, dh] -> [b, s_loc, h, dh]; K/V (and, in the
    backward, their gradient accumulators) ride the ``axis_name`` ring —
    at the kv-head width, so GQA shrinks the ring traffic."""
    from ddlb_tpu.ops.flash_attention import ring_flash_attention

    b, s_loc, h, dh = q.shape
    merge = lambda x: x.transpose(1, 0, 2, 3).reshape(
        s_loc, b * x.shape[2], dh
    )
    o = ring_flash_attention(
        merge(q), merge(k), merge(v),
        axis_name=axis_name,
        axis_size=d,
        scale=1.0 / np.sqrt(dh),
        block_q=_flash_block(s_loc),
        block_kv=_flash_block(s_loc),
        interpret=interpret,
        window=window,
    )
    return o.reshape(s_loc, b, h, dh).transpose(1, 0, 2, 3)


def _moe_ffn(tokens2d, w1, w2, mlp_kernel, out_dtype, scales=None):
    """One expert's FFN on a ``[T, D]`` token slab -> ``[T, D]``.

    Shared verbatim by the sharded stage body and the single-device
    oracle: per-token/per-feature int8 scales are row/column-local, so
    the two call sites produce bit-identical values whatever the token
    batching — which is what keeps the oracle pinning exact under the
    int8 kernels. ``scales`` is the ``(w1_scale, w2_scale)`` pair in
    ``int8_weights`` mode (w1/w2 are then the pre-quantized int8 leaves).
    """
    if mlp_kernel == "int8":
        from ddlb_tpu.ops.quantized_matmul import int8_ste_matmul

        z = jax.nn.gelu(int8_ste_matmul(tokens2d, w1)).astype(out_dtype)
        return int8_ste_matmul(z, w2).astype(out_dtype)
    if mlp_kernel == "int8_weights":
        from ddlb_tpu.ops.quantized_matmul import int8_matmul, quantize_rowwise

        if scales is None:
            raise ValueError(
                "mlp_kernel='int8_weights' needs the (w1_scale, w2_scale) "
                "pair emitted by init_params alongside the int8 weights"
            )
        s1, s2 = scales
        qx, sx = quantize_rowwise(tokens2d)
        z = jax.nn.gelu(
            int8_matmul(qx, w1, sx, s1, out_dtype=jnp.float32)
        ).astype(out_dtype)
        qz, sz = quantize_rowwise(z)
        return int8_matmul(qz, w2, sz, s2, out_dtype=out_dtype)
    if mlp_kernel != "bf16":
        # the shared choke point fails fast for every entry path —
        # make_loss_fn validates, but reference_loss/library callers
        # must not silently measure the full-precision kernel
        raise ValueError(f"unknown mlp_kernel '{mlp_kernel}'")
    z = jax.nn.gelu(
        jnp.matmul(tokens2d, w1, preferred_element_type=jnp.float32)
    ).astype(out_dtype)
    return jnp.matmul(
        z, w2, preferred_element_type=jnp.float32
    ).astype(out_dtype)


def _ce_loss(logits, targets):
    """Mean token cross-entropy in f32."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    picked = jnp.take_along_axis(logp, targets[..., None], axis=-1)
    return -jnp.mean(picked)


# -- learned top-k router (GShard/Switch style) -------------------------------
#
# Shared verbatim by the sharded stage body and the single-device oracle:
# every op below is per-token-slab deterministic (softmax, top_k, cumsum
# slot assignment), so identical slabs produce identical dispatch — which
# is what keeps the oracle pinning exact. The EP exchange itself (an
# all_to_all of the fixed-capacity dispatch buffer) lives only in the
# sharded caller; the oracle applies the experts to the same buffer
# directly.


def router_capacity(t_loc: int, n_experts: int, k: int, factor: float) -> int:
    """Static per-(source shard, expert) slot count."""
    return max(1, int(np.ceil(factor * k * t_loc / n_experts)))


def _router_probs(tokens2d, gate):
    """f32 gate probabilities for one token slab — the parity-critical
    prologue shared by the token-choice and expert-choice routers (the
    float32 cast keeps selection bit-identical across activation
    dtypes)."""
    logits = jnp.matmul(
        tokens2d.astype(jnp.float32), gate.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )
    return jax.nn.softmax(logits, axis=-1)


def _router_assign(tokens2d, gate, k: int, capacity: int):
    """Route one token slab: top-k choice, slot assignment, aux loss.

    Returns ``(tope [T,k] int32, topv [T,k] f32, slot [T,k] int32,
    kept [T,k] bool, aux f32 scalar)``. Slots are first-come in
    (selection-rank-major, token-order) priority — GShard's assignment —
    via a cumsum over the one-hot dispatch mask; a token whose slot
    overflows ``capacity`` is dropped (``kept=False``) and its residual
    stream passes through unchanged. ``aux`` is the Switch load-balance
    loss ``E * sum_e f_e * P_e`` (f_e: top-1 dispatch fraction, P_e: mean
    router probability), minimized at uniform load.
    """
    T = tokens2d.shape[0]
    E = gate.shape[-1]
    probs = _router_probs(tokens2d, gate)    # [T, E]
    topv, tope = jax.lax.top_k(probs, k)     # [T, k]
    sel = jax.nn.one_hot(tope, E, dtype=jnp.float32)  # [T, k, E]
    # selection-rank-major flattening: all rank-0 choices get slots before
    # any rank-1 choice, matching GShard's priority
    flat = sel.transpose(1, 0, 2).reshape(k * T, E)
    pos = jnp.cumsum(flat, axis=0) - flat
    slot = jnp.sum(
        sel * pos.reshape(k, T, E).transpose(1, 0, 2), axis=-1
    ).astype(jnp.int32)
    kept = slot < capacity
    f = jnp.mean(jax.nn.one_hot(tope[:, 0], E, dtype=jnp.float32), axis=0)
    P = jnp.mean(probs, axis=0)
    aux = E * jnp.sum(f * P)
    return tope, topv, slot, kept, aux


def _expert_choice_assign(tokens2d, gate, capacity: int):
    """Expert-choice routing on one token slab: every expert picks its
    ``capacity`` highest-scoring tokens.

    Returns ``(idx [E, C] int32 token indices, w [E, C] f32 gate
    weights)``. Dispatch is a GATHER (``tokens2d[idx]``), combine a
    gate-weighted scatter-add back to token rows — per-slab
    deterministic, so the sharded step and the oracle agree exactly.
    Load is perfectly balanced by construction; unchosen tokens ride the
    residual stream.
    """
    # scores normalized over experts per token (the Zhou et al. form),
    # then each expert takes its top-C column entries
    probs = _router_probs(tokens2d, gate)
    w, idx = jax.lax.top_k(probs.T, capacity)  # [E, C] each
    return idx.astype(jnp.int32), w


def _expert_choice_combine(buf_out, idx, w, T, out_dtype):
    """Scatter each expert's ``[C, D]`` outputs back to their token rows,
    weighted by the gate: ``u[t] = sum_e w[e, c] * buf_out[e, c]`` over
    the slots that picked token ``t``."""
    D = buf_out.shape[-1]
    u = jnp.zeros((T, D), jnp.float32)
    vals = buf_out.astype(jnp.float32) * w[..., None]
    u = u.at[idx.reshape(-1)].add(vals.reshape(-1, D))
    return u.astype(out_dtype)


def _router_dispatch(tokens2d, tope, slot, kept, n_experts, capacity):
    """Scatter the slab into the fixed-capacity buffer ``[E, C, D]``
    (dropped selections scatter zeros at a clamped slot)."""
    vals = tokens2d[:, None, :] * kept[..., None].astype(tokens2d.dtype)
    buf = jnp.zeros((n_experts, capacity, tokens2d.shape[-1]), tokens2d.dtype)
    return buf.at[tope, jnp.minimum(slot, capacity - 1)].add(vals)


def _router_combine(buf_out, tope, slot, topv, kept, capacity, out_dtype):
    """Gather each token's k expert outputs and mix by its (raw, un-
    renormalized) router probabilities; dropped selections weigh 0."""
    gathered = buf_out[tope, jnp.minimum(slot, capacity - 1)]  # [T, k, D]
    w = (topv * kept.astype(jnp.float32))[..., None]
    return jnp.sum(gathered.astype(jnp.float32) * w, axis=1).astype(out_dtype)


def make_stage_fn(cfg: TransformerConfig, tp: int, interpret: bool):
    """Build the per-stage block body ``stage_fn(x, sp) -> x`` shared by
    the GPipe loss loop and the 1F1B manual-vjp loop (models/pipeline.py):
    this stage's L transformer blocks on a local activation slab
    ``[b, S/tp, d_model]`` with the tp/sp/ep collectives inside. Wrapped
    in ``jax.checkpoint`` (PP-standard per-stage remat) so a backward
    through it stashes only the stage INPUT — which is exactly the
    quantity 1F1B's memory story counts."""
    L = cfg.layers_per_stage
    if cfg.attn_kernel not in ("flash", "einsum"):
        raise ValueError(f"unknown attn_kernel '{cfg.attn_kernel}'")
    if cfg.mlp_kernel not in ("bf16", "int8", "int8_weights"):
        raise ValueError(f"unknown mlp_kernel '{cfg.mlp_kernel}'")
    if cfg.router not in ("block", "topk", "expert_choice"):
        raise ValueError(f"unknown router '{cfg.router}'")

    def stage_fn(x, sp):
        """Apply this stage's L transformer blocks to a local activation
        slab ``[b, S/tp, d_model]``; tp/sp/ep collectives inside. Returns
        ``(x, aux)`` — aux is the stage's mean-over-layers router
        load-balance loss (0 under block routing)."""
        aux = jnp.zeros((), jnp.float32)
        b, s_loc, D = x.shape
        h_heads = cfg.n_heads // tp
        if sp["moe_w1"].shape[2] != 1:
            # the body indexes the single resident expert ([0, l, 0]);
            # more experts per rank would silently use only every tp-th one
            raise ValueError(
                f"n_experts must equal tp={tp} (one resident expert per "
                f"rank); got {sp['moe_w1'].shape[2] * tp}"
            )
        for l in range(L):
            h = _rms_norm(x, sp["ln1"][0, l])
            if cfg.attention == "ring":
                # -- context-parallel attention (cp_ring_attention
                # pattern): full-head QKV projected on the LOCAL sequence
                # chunk (replicated weights — see param_specs), K/V chunks
                # ring the tp axis, local out-proj, no collective. Under
                # GQA the ringed chunks carry only kv heads — the wire
                # bytes shrink by the group factor --
                if cfg.kv_heads == cfg.n_heads:
                    wq = sp["w_qkv"][0, l]  # [3, D, D]: replicated heads
                    q, k, v = (
                        jnp.matmul(
                            h, wq[i], preferred_element_type=jnp.float32
                        )
                        .astype(x.dtype)
                        .reshape(b, s_loc, cfg.n_heads, cfg.head_dim)
                        for i in range(3)
                    )
                else:
                    q = (
                        jnp.matmul(
                            h, sp["w_q"][0, l],
                            preferred_element_type=jnp.float32,
                        )
                        .astype(x.dtype)
                        .reshape(b, s_loc, cfg.n_heads, cfg.head_dim)
                    )
                    k, v = (
                        jnp.matmul(
                            h, sp["w_kv"][0, l, i],
                            preferred_element_type=jnp.float32,
                        )
                        .astype(x.dtype)
                        .reshape(b, s_loc, cfg.kv_heads, cfg.head_dim)
                        for i in range(2)
                    )
                if cfg.rope:
                    # global positions of this rank's sequence chunk —
                    # rotation happens BEFORE the chunks ring, so every
                    # arriving K block already carries its true positions
                    pos = (
                        jax.lax.axis_index("tp") * s_loc
                        + jnp.arange(s_loc, dtype=jnp.int32)
                    )[None]
                    q = apply_rope(q, pos, cfg.rope_theta)
                    k = apply_rope(k, pos, cfg.rope_theta)
                if cfg.attn_kernel == "flash":
                    attn = _ring_flash(
                        q, k, v, tp, interpret, window=cfg.attn_window
                    ).reshape(b, s_loc, -1)
                else:
                    attn = _ring_attention(
                        q, k, v, tp, window=cfg.attn_window
                    ).reshape(b, s_loc, -1)
                y = jnp.matmul(
                    attn, sp["w_o"][0, l], preferred_element_type=jnp.float32
                ).astype(x.dtype)  # [b, s_loc, D], complete (all heads)
            else:
                # -- Megatron sp (tp_columnwise -> heads-local ->
                # tp_rowwise) --
                h_full = jax.lax.all_gather(h, "tp", axis=1, tiled=True)
                if cfg.kv_heads == cfg.n_heads:
                    wq = sp["w_qkv"][0, l]  # [3, D, D/tp]: local heads
                    q, k, v = (
                        jnp.matmul(
                            h_full, wq[i], preferred_element_type=jnp.float32
                        ).astype(x.dtype)
                        for i in range(3)
                    )
                else:
                    # GQA: K/V project to the rank's kv-head columns
                    q = jnp.matmul(
                        h_full, sp["w_q"][0, l],
                        preferred_element_type=jnp.float32,
                    ).astype(x.dtype)
                    k, v = (
                        jnp.matmul(
                            h_full, sp["w_kv"][0, l, i],
                            preferred_element_type=jnp.float32,
                        ).astype(x.dtype)
                        for i in range(2)
                    )
                S = q.shape[1]
                kv_loc = cfg.kv_heads // tp
                shape = (b, S, h_heads, cfg.head_dim)
                kshape = (b, S, kv_loc, cfg.head_dim)
                q4, k4, v4 = (
                    q.reshape(shape), k.reshape(kshape), v.reshape(kshape)
                )
                if cfg.rope:
                    pos = jnp.arange(S, dtype=jnp.int32)[None]
                    q4 = apply_rope(q4, pos, cfg.rope_theta)
                    k4 = apply_rope(k4, pos, cfg.rope_theta)
                if cfg.attn_kernel == "flash":
                    attn = _flash_full(
                        q4, k4, v4, interpret, window=cfg.attn_window
                    ).reshape(b, S, -1)  # [b, S, D/tp]
                else:
                    attn = _causal_attention(
                        q4, k4, v4, window=cfg.attn_window
                    ).reshape(b, S, -1)  # [b, S, D/tp]
                part = jnp.matmul(
                    attn, sp["w_o"][0, l], preferred_element_type=jnp.float32
                )  # [b, S, D] partial over tp
                y = jax.lax.psum_scatter(
                    part, "tp", scatter_dimension=1, tiled=True
                ).astype(x.dtype)
            x = x + y
            # -- MoE FFN (ep_alltoall over the tp axis) --
            h = _rms_norm(x, sp["ln2"][0, l])
            T = b * s_loc
            scales = (
                (sp["moe_w1_scale"][0, l, 0], sp["moe_w2_scale"][0, l, 0])
                if cfg.mlp_kernel == "int8_weights"
                else None
            )
            if cfg.router == "topk":
                # learned routing: fixed-capacity dispatch buffers ride
                # the same mirrored all_to_all as the block path, so the
                # EP traffic pattern is identical — only the (data-
                # dependent) buffer CONTENTS differ
                C = router_capacity(
                    T, tp, cfg.router_topk, cfg.capacity_factor
                )
                h2d = h.reshape(T, D)
                tope, topv, slot, kept, aux_l = _router_assign(
                    h2d, sp["router"][0, l], cfg.router_topk, C
                )
                buf = _router_dispatch(h2d, tope, slot, kept, tp, C)
                buf = jax.lax.all_to_all(
                    buf, "tp", split_axis=0, concat_axis=0, tiled=True
                )  # [src_rank, C, D] at the resident expert
                z = _moe_ffn(
                    buf.reshape(tp * C, D),
                    sp["moe_w1"][0, l, 0],
                    sp["moe_w2"][0, l, 0],
                    cfg.mlp_kernel,
                    x.dtype,
                    scales=scales,
                )
                z = jax.lax.all_to_all(
                    z.reshape(tp, C, D),
                    "tp", split_axis=0, concat_axis=0, tiled=True,
                )  # [expert, C, D] back at the source
                u2d = _router_combine(
                    z, tope, slot, topv, kept, C, x.dtype
                )
                x = x + u2d.reshape(b, s_loc, D)
                aux = aux + aux_l / L
                continue
            if cfg.router == "expert_choice":
                # each resident expert picks its top-C tokens: dispatch
                # is a gather, combine a gate-weighted scatter-add; load
                # is balanced by construction (aux stays 0) and the
                # buffers ride the same mirrored all_to_all
                C = min(
                    router_capacity(T, tp, 1, cfg.capacity_factor), T
                )
                h2d = h.reshape(T, D)
                idx, wgt = _expert_choice_assign(
                    h2d, sp["router"][0, l], C
                )
                buf = h2d[idx]  # [E, C, D]
                buf = jax.lax.all_to_all(
                    buf, "tp", split_axis=0, concat_axis=0, tiled=True
                )
                z = _moe_ffn(
                    buf.reshape(tp * C, D),
                    sp["moe_w1"][0, l, 0],
                    sp["moe_w2"][0, l, 0],
                    cfg.mlp_kernel,
                    x.dtype,
                    scales=scales,
                )
                z = jax.lax.all_to_all(
                    z.reshape(tp, C, D),
                    "tp", split_axis=0, concat_axis=0, tiled=True,
                )
                u2d = _expert_choice_combine(z, idx, wgt, T, x.dtype)
                x = x + u2d.reshape(b, s_loc, D)
                continue
            t3 = h.reshape(tp, T // tp, D)  # balanced block routing
            t3 = jax.lax.all_to_all(
                t3, "tp", split_axis=0, concat_axis=0, tiled=True
            )
            u = _moe_ffn(
                t3.reshape(T, D),
                sp["moe_w1"][0, l, 0],
                sp["moe_w2"][0, l, 0],
                cfg.mlp_kernel,
                x.dtype,
                scales=scales,
            )
            u = jax.lax.all_to_all(
                u.reshape(tp, T // tp, D),
                "tp",
                split_axis=0,
                concat_axis=0,
                tiled=True,
            )
            x = x + u.reshape(b, s_loc, D)
        return x, aux

    return jax.checkpoint(stage_fn)  # PP-standard per-stage remat


def make_loss_fn(mesh, cfg: TransformerConfig):
    """Build the shard_mapped loss of the flagship model over a
    ``('dp', 'tp', 'pp')`` mesh.

    Returns ``(loss_fn, shardings)``: ``loss_fn(params, tokens, targets) ->
    scalar`` (differentiable; jit at the call site) and ``shardings`` maps
    param names plus ``'data'`` to ``NamedSharding``s for ``device_put``.
    """
    dp = mesh.shape["dp"]
    tp = mesh.shape["tp"]
    pp = mesh.shape["pp"]
    mb = cfg.microbatches
    specs = param_specs(cfg)
    # pallas kernels run compiled on TPU, interpreted elsewhere (CPU sim)
    interpret = jax.default_backend() != "tpu"
    stage_fn = make_stage_fn(cfg, tp, interpret)

    def loss_body(params, tokens, targets):
        """shard_map body. tokens/targets: [B/dp, S] int32 (dp-sharded,
        replicated over tp and pp)."""
        p_tp = jax.lax.axis_index("tp")
        p_pp = jax.lax.axis_index("pp")
        B_loc, S = tokens.shape
        # static-shape contract, checked at trace time: silent truncation
        # here would diverge from the oracle instead of failing fast
        if B_loc % mb != 0:
            raise ValueError(
                f"per-dp-rank batch {B_loc} not divisible by "
                f"microbatches={mb}"
            )
        if S % tp != 0:
            raise ValueError(f"sequence {S} not divisible by tp={tp}")
        if cfg.attention != "ring" and cfg.n_heads % tp != 0:
            raise ValueError(
                f"n_heads={cfg.n_heads} not divisible by tp={tp}"
            )
        if cfg.attention != "ring" and cfg.kv_heads % tp != 0:
            raise ValueError(
                f"n_kv_heads={cfg.kv_heads} not divisible by tp={tp}"
            )
        s_loc = S // tp
        b_mb = B_loc // mb
        fwd = [(i, (i + 1) % pp) for i in range(pp)]

        def embed_mb(i):
            tok = jax.lax.dynamic_slice_in_dim(tokens, i * b_mb, b_mb, 0)
            tok = jax.lax.dynamic_slice_in_dim(tok, p_tp * s_loc, s_loc, 1)
            return params["embed"][tok]  # [b_mb, S/tp, D]

        def tail_loss(y, i):
            """Last-stage head + CE on microbatch i's local slab."""
            h = _rms_norm(y, params["ln_f"])
            logits = jnp.matmul(
                h, params["head"], preferred_element_type=jnp.float32
            )
            tgt = jax.lax.dynamic_slice_in_dim(targets, i * b_mb, b_mb, 0)
            tgt = jax.lax.dynamic_slice_in_dim(tgt, p_tp * s_loc, s_loc, 1)
            return _ce_loss(logits, tgt)

        buf = jnp.zeros((b_mb, s_loc, cfg.d_model), cfg.dtype)
        loss_acc = jnp.zeros((), jnp.float32)
        aux_acc = jnp.zeros((), jnp.float32)
        for t in range(mb + pp - 1):
            if t < mb:
                x_in = jnp.where(p_pp == 0, embed_mb(t), buf)
            else:
                x_in = buf
            y, aux = stage_fn(x_in, params)
            # router aux counts only the ticks where this stage held a
            # real microbatch (bubble ticks run on garbage data)
            valid = (t >= p_pp) & (t - p_pp < mb)
            aux_acc = aux_acc + jnp.where(valid, aux, 0.0)
            fin = t - (pp - 1)
            if 0 <= fin < mb:
                # lax.cond, not jnp.where: only last-stage devices execute
                # the vocab-wide head GEMM + log-softmax; earlier stages
                # skip it at runtime instead of computing and discarding it
                # (ADVICE r1). Safe divergence: tail_loss has no collectives.
                loss_acc = loss_acc + jax.lax.cond(
                    p_pp == pp - 1,
                    lambda yy: tail_loss(yy, fin),
                    lambda yy: jnp.zeros((), jnp.float32),
                    y,
                )
            if t + 1 < mb + pp - 1:
                buf = jax.lax.ppermute(y, "pp", perm=fwd)
        # scalar reductions: surface the loss everywhere (pp), average the
        # equal-sized token blocks (dp batch shards, tp sequence shards);
        # the router aux term averages over (mb, stages, dp, tp) the same
        # way the oracle does
        loss = jax.lax.psum(loss_acc / mb, "pp")
        if cfg.router == "topk":
            loss = loss + cfg.router_aux * jax.lax.psum(
                aux_acc / mb, "pp"
            ) / pp
        loss = jax.lax.psum(loss, "dp") / dp
        loss = jax.lax.psum(loss, "tp") / tp
        return loss

    # runtime.shard_map_compat (DDLB101 migration): jax 0.4.x has no
    # jax.shard_map — the compat shim maps check_vma onto check_rep
    loss_fn = shard_map_compat(
        loss_body,
        mesh=mesh,
        in_specs=(specs, P("dp", None), P("dp", None)),
        out_specs=P(),
        check_vma=False,
    )

    shardings = {k: NamedSharding(mesh, s) for k, s in specs.items()}
    shardings["data"] = NamedSharding(mesh, P("dp", None))
    return loss_fn, shardings


def make_train_step(
    mesh,
    cfg: TransformerConfig,
    learning_rate: float = 1e-2,
    donate: bool = True,
):
    """Full manual-SPMD training step over a ``('dp', 'tp', 'pp')`` mesh.

    Returns ``(train_step, init_opt_state, shardings)`` where
    ``train_step(params, opt_state, tokens, targets) ->
    (params, opt_state, loss)`` is jitted end to end and ``shardings`` maps
    param names plus ``'data'`` to ``NamedSharding``s for ``device_put``.

    ``donate=False`` keeps the input buffers valid after the call — the
    benchmark primitive re-runs the same step on identical operands, which
    donated (invalidated) inputs would forbid.
    """
    import optax

    if cfg.mlp_kernel == "int8_weights":
        raise ValueError(
            "mlp_kernel='int8_weights' is the forward-only serving form "
            "(int8 weight leaves have no gradients); train with "
            "mlp_kernel='int8' (STE) instead"
        )
    optimizer = optax.adamw(learning_rate)
    loss_fn, shardings = make_loss_fn(mesh, cfg)

    def step(params, opt_state, tokens, targets):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens, targets)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    train_step = (
        jax.jit(step, donate_argnums=(0, 1)) if donate else jax.jit(step)
    )

    def init_opt_state(params):
        # jitted inside the mesh context so every leaf (including the
        # scalar step count) comes out committed with a mesh-wide
        # sharding — an uncommitted single-device skeleton would pin
        # checkpoint restores to one device (models/checkpoint.py places
        # onto the target's sharding)
        with set_mesh_compat(mesh):
            return jax.jit(optimizer.init)(params)

    return train_step, init_opt_state, shardings


# -- single-device oracle ----------------------------------------------------


def reference_loss(
    params, tokens, targets, cfg: TransformerConfig, tp: int, dp: int = 1
) -> jax.Array:
    """Single-device oracle reproducing the distributed math exactly.

    Attention and norms are per-batch-row, but the MoE block routing
    couples tokens within one (dp rank, microbatch, tp seq-shard) slab —
    so the oracle forwards each ``B // (dp * microbatches)``-row chunk
    independently, grouping tokens per seq shard exactly as the tp ranks
    do, and averages the chunk cross-entropies (equal-sized chunks make
    that the distributed psum-averaged loss)."""
    B, S = tokens.shape
    b_mb = B // (dp * cfg.microbatches)
    s_loc = S // tp
    D = cfg.d_model
    pp, L = params["ln1"].shape[:2]
    losses = []
    aux_sum = jnp.zeros((), jnp.float32)
    for c0 in range(0, B, b_mb):
        x = params["embed"][tokens[c0 : c0 + b_mb]]  # [b_mb, S, D]
        for st in range(pp):
            for l in range(L):
                h = _rms_norm(x, params["ln1"][st, l])
                if cfg.kv_heads == cfg.n_heads:
                    q, k, v = (
                        jnp.matmul(
                            h,
                            params["w_qkv"][st, l, i],
                            preferred_element_type=jnp.float32,
                        ).astype(x.dtype)
                        for i in range(3)
                    )
                else:
                    q = jnp.matmul(
                        h, params["w_q"][st, l],
                        preferred_element_type=jnp.float32,
                    ).astype(x.dtype)
                    k, v = (
                        jnp.matmul(
                            h, params["w_kv"][st, l, i],
                            preferred_element_type=jnp.float32,
                        ).astype(x.dtype)
                        for i in range(2)
                    )
                shape = (b_mb, S, cfg.n_heads, cfg.head_dim)
                kshape = (b_mb, S, cfg.kv_heads, cfg.head_dim)
                q4, k4, v4 = (
                    q.reshape(shape), k.reshape(kshape), v.reshape(kshape)
                )
                if cfg.rope:
                    pos = jnp.arange(S, dtype=jnp.int32)[None]
                    q4 = apply_rope(q4, pos, cfg.rope_theta)
                    k4 = apply_rope(k4, pos, cfg.rope_theta)
                attn = _causal_attention(
                    q4, k4, v4, window=cfg.attn_window
                ).reshape(b_mb, S, D)
                x = x + jnp.matmul(
                    attn, params["w_o"][st, l], preferred_element_type=jnp.float32
                ).astype(x.dtype)
                h = _rms_norm(x, params["ln2"][st, l])
                if cfg.router == "expert_choice":
                    # per seq shard, the sharded step's math verbatim:
                    # gather each expert's top-C tokens, FFN, gate-
                    # weighted scatter back
                    u = jnp.zeros_like(h)
                    T = b_mb * s_loc
                    C = min(
                        router_capacity(T, tp, 1, cfg.capacity_factor), T
                    )
                    for j in range(tp):
                        slab = h[:, j * s_loc : (j + 1) * s_loc].reshape(T, D)
                        idx, wgt = _expert_choice_assign(
                            slab, params["router"][st, l], C
                        )
                        buf_out = jnp.stack(
                            [
                                _moe_ffn(
                                    slab[idx[e]],
                                    params["moe_w1"][st, l, e],
                                    params["moe_w2"][st, l, e],
                                    cfg.mlp_kernel,
                                    x.dtype,
                                    scales=(
                                        (
                                            params["moe_w1_scale"][st, l, e],
                                            params["moe_w2_scale"][st, l, e],
                                        )
                                        if cfg.mlp_kernel == "int8_weights"
                                        else None
                                    ),
                                )
                                for e in range(tp)
                            ]
                        )
                        u_blk = _expert_choice_combine(
                            buf_out, idx, wgt, T, x.dtype
                        )
                        u = jax.lax.dynamic_update_slice(
                            u,
                            u_blk.reshape(b_mb, s_loc, D),
                            (0, j * s_loc, 0),
                        )
                    x = x + u
                    continue
                if cfg.router == "topk":
                    # per seq shard, exactly the sharded step's math: same
                    # slab, same dispatch buffer, same capacity
                    u = jnp.zeros_like(h)
                    T = b_mb * s_loc
                    C = router_capacity(
                        T, tp, cfg.router_topk, cfg.capacity_factor
                    )
                    for j in range(tp):
                        slab = h[:, j * s_loc : (j + 1) * s_loc].reshape(T, D)
                        tope, topv, slot, kept, aux_l = _router_assign(
                            slab, params["router"][st, l],
                            cfg.router_topk, C,
                        )
                        buf = _router_dispatch(slab, tope, slot, kept, tp, C)
                        buf_out = jnp.stack(
                            [
                                _moe_ffn(
                                    buf[e],
                                    params["moe_w1"][st, l, e],
                                    params["moe_w2"][st, l, e],
                                    cfg.mlp_kernel,
                                    x.dtype,
                                    scales=(
                                        (
                                            params["moe_w1_scale"][st, l, e],
                                            params["moe_w2_scale"][st, l, e],
                                        )
                                        if cfg.mlp_kernel == "int8_weights"
                                        else None
                                    ),
                                )
                                for e in range(tp)
                            ]
                        )
                        u_blk = _router_combine(
                            buf_out, tope, slot, topv, kept, C, x.dtype
                        )
                        u = jax.lax.dynamic_update_slice(
                            u,
                            u_blk.reshape(b_mb, s_loc, D),
                            (0, j * s_loc, 0),
                        )
                        aux_sum = aux_sum + aux_l
                    x = x + u
                    continue
                # per-seq-shard balanced block routing, as the tp ranks do
                u = jnp.zeros_like(h)
                T = b_mb * s_loc
                g = T // tp
                for j in range(tp):
                    blk = h[:, j * s_loc : (j + 1) * s_loc].reshape(T, D)
                    out_blk = jnp.zeros((T, D), x.dtype)
                    for e in range(tp):
                        grp = blk[e * g : (e + 1) * g]
                        z = _moe_ffn(
                            grp,
                            params["moe_w1"][st, l, e],
                            params["moe_w2"][st, l, e],
                            cfg.mlp_kernel,
                            x.dtype,
                            scales=(
                                (
                                    params["moe_w1_scale"][st, l, e],
                                    params["moe_w2_scale"][st, l, e],
                                )
                                if cfg.mlp_kernel == "int8_weights"
                                else None
                            ),
                        )
                        out_blk = jax.lax.dynamic_update_slice(
                            out_blk, z, (e * g, 0)
                        )
                    u = jax.lax.dynamic_update_slice(
                        u, out_blk.reshape(b_mb, s_loc, D), (0, j * s_loc, 0)
                    )
                x = x + u
        h = _rms_norm(x, params["ln_f"])
        logits = jnp.matmul(h, params["head"], preferred_element_type=jnp.float32)
        losses.append(_ce_loss(logits, targets[c0 : c0 + b_mb]))
    loss = jnp.mean(jnp.stack(losses))
    if cfg.router == "topk":
        n_chunks = B // b_mb
        loss = loss + cfg.router_aux * aux_sum / (n_chunks * pp * L * tp)
    return loss


def example_tokens(
    batch: int, seq: int, vocab: int, seed: int = 1
) -> Tuple[jax.Array, jax.Array]:
    """Random token stream; targets are next-token shifted."""
    rng = np.random.default_rng(seed)
    toks = rng.integers(0, vocab, (batch, seq + 1))
    return (
        jnp.asarray(toks[:, :-1], jnp.int32),
        jnp.asarray(toks[:, 1:], jnp.int32),
    )
