"""Sharded train-state checkpointing for the flagship model (orbax).

The reference has no training state to checkpoint — its only resumable
artifact is the sweep CSV (SURVEY.md section 5 "checkpoint/resume:
none"), which this framework mirrors at the runner layer (``--resume``).
This module adds the MODEL layer's counterpart: the (params, opt_state,
step) train state saved and restored as sharded global arrays via orbax
— each host writes only its addressable shards, restore places shards
directly onto the target mesh (which may differ from the save-time
mesh: orbax reshards on read), so the same checkpoint moves between
topologies and the CPU sim.

Deliberately thin over ``orbax.checkpoint``: the framework's value is
the sharding-aware round-trip contract (tests pin save -> restore ->
continue training == uninterrupted training, bitwise on the loss), not
a re-implementation of checkpoint management.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional, Tuple


def save_checkpoint(
    directory: str,
    step: int,
    params: Dict[str, Any],
    opt_state: Any = None,
    *,
    force: bool = False,
) -> str:
    """Write the train state under ``directory/<step>``; returns the path.

    Arrays may be sharded global jax.Arrays — every process must call
    this collectively (orbax coordinates the multi-host write).
    """
    import orbax.checkpoint as ocp

    directory = os.path.abspath(directory)
    state = {"params": params}
    if opt_state is not None:
        state["opt_state"] = opt_state
    with ocp.Checkpointer(ocp.StandardCheckpointHandler()) as ckptr:
        path = os.path.join(directory, str(step))
        ckptr.save(path, state, force=force)
    return path


def restore_checkpoint(
    directory: str,
    step: int,
    like: Dict[str, Any],
) -> Tuple[Dict[str, Any], Any]:
    """Restore ``(params, opt_state)`` from ``directory/<step>``.

    ``like`` is ``{"params": ..., "opt_state": ...}`` of abstract or
    concrete arrays carrying the TARGET shardings (e.g. freshly
    initialized state on the current mesh) — orbax reads each shard
    straight onto its destination devices, resharding if the save-time
    topology differed. ``opt_state`` may be omitted from ``like`` for
    params-only restores.
    """
    import jax
    import orbax.checkpoint as ocp

    directory = os.path.abspath(directory)
    abstract = jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
        if hasattr(x, "sharding")
        else x,
        like,
    )
    with ocp.Checkpointer(ocp.StandardCheckpointHandler()) as ckptr:
        state = ckptr.restore(os.path.join(directory, str(step)), abstract)
    return state["params"], state.get("opt_state")


def latest_step(directory: str) -> Optional[int]:
    """Largest integer-named subdirectory of ``directory`` holding a
    complete checkpoint, or None — the resume probe."""
    directory = os.path.abspath(directory)
    if not os.path.isdir(directory):
        return None
    steps = []
    for name in os.listdir(directory):
        if name.isdecimal():
            # orbax writes atomically: an incomplete save stays under a
            # temp name (non-decimal suffix), so a decimal-named dir is
            # a complete checkpoint
            steps.append(int(name))
    return max(steps) if steps else None
