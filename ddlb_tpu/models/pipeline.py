"""1F1B pipeline training for the flagship model: manual-vjp schedule.

The GPipe path (models/transformer.py:make_train_step) differentiates the
whole pipelined forward loop with ``jax.value_and_grad`` — autodiff then
REVERSES the loop, which is exactly GPipe's all-forwards-then-all-
backwards schedule, with every microbatch's stage input live across the
flush (O(microbatches) stash per device).

This module runs the **1F1B schedule instead**: forward and backward
ticks interleave per the host-precomputed dense tables of
``utils/pipeline_schedule.py`` (the same tables the ``pp_pipeline``
``schedules`` member executes), and the backward of each (microbatch,
stage) is taken explicitly with ``jax.vjp`` of the rematerialized
``stage_fn`` at its stashed INPUT — so the activation stash is a static
buffer of ``O(pipeline depth)`` slots, not ``O(microbatches)``: 1F1B's
memory story realized as smaller allocated buffer shapes.

Design notes (TPU/XLA):
- one traced program; per-tick behavior is ``lax.switch`` on the gathered
  table entry for this device's ``pp`` coordinate. The stage body (with
  its tp collectives) sits INSIDE the switch branches; every participant
  of those collectives shares the same ``pp`` coordinate and therefore
  the same branch, so the collective groups never diverge. Activation /
  cotangent hops ride ``ppermute`` OUTSIDE the switch, once per tick.
- the LM-head tail (ln_f + head + CE) is collective-free, so its
  forward (loss) and vjp (the backward's seed cotangent) run under a
  last-stage ``lax.cond`` — the same safe-divergence pattern the GPipe
  loop uses for its tail.
- gradients of tp/pp-sharded params come out of the stage vjp already
  correct per shard (the transposed collectives do the cross-tp
  reduction); replicated params are psum-reduced over every mesh axis
  their spec does not shard, which is the generic manual-SPMD rule.

No reference analogue: the reference has neither model nor pipeline
schedules (SURVEY.md section 2.5); the schedule-depth ambition mirrors
its overlap schedules (fuser.py:59-146) applied to PP.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ddlb_tpu.models.transformer import (
    TransformerConfig,
    _ce_loss,
    _rms_norm,
    make_stage_fn,
    param_specs,
)
from ddlb_tpu.runtime import set_mesh_compat, shard_map_compat
from ddlb_tpu.utils.pipeline_schedule import build_schedule


def _tail_loss(y, ln_f, head, tgt):
    """Last-stage tail on a local slab: ln_f + LM head + token CE."""
    h = _rms_norm(y, ln_f)
    logits = jnp.matmul(h, head, preferred_element_type=jnp.float32)
    return _ce_loss(logits, tgt)


def arrange_stage_stack(params, pp: int, virtual: int, cfg=None):
    """Permute a stage-ordered param stack (leading axis ``pp * virtual``,
    row ``s`` = global stage ``s``) into the Megatron-interleaved
    device-major layout the sharded step consumes: row ``p*virtual + c``
    holds global stage ``c*pp + p``, so the contiguous block-shard of
    device ``p`` is exactly its chunks. The oracle keeps the
    stage-ordered stack (``init_params`` output) — only the placement
    differs, never the math.

    Leaves are classified by the param SPEC (first axis named ``pp``),
    never by shape — a replicated leaf whose leading dim happens to equal
    the chain depth (e.g. ``vocab == pp*virtual``) must not be permuted.
    ``cfg`` selects the spec table; omitted, leaves present in the
    default-config spec table are classified by name (the param set is
    config-dependent only through optional leaves, which always carry a
    spec entry when present).
    """
    import numpy as np_  # local alias: params may be numpy or jax arrays

    if cfg is None:
        from ddlb_tpu.models.transformer import TransformerConfig

        # name -> spec over the union of optional leaves (the MHA/GQA
        # param sets are mutually exclusive, so merge both variants)
        specs = {}
        for c in (
            TransformerConfig(router="topk", mlp_kernel="int8_weights"),
            TransformerConfig(router="topk", n_heads=2, n_kv_heads=1),
        ):
            specs.update(param_specs(c))
    else:
        specs = param_specs(cfg)
    unknown = set(params) - set(specs)
    if unknown:
        # a leaf the spec table doesn't know would be silently treated
        # as replicated — wrong placement with no error; fail instead
        raise ValueError(
            f"param leaves missing from the spec table: {sorted(unknown)} "
            f"(pass the matching cfg, or extend param_specs)"
        )
    idx = np_.array(
        [c * pp + p for p in range(pp) for c in range(virtual)]
    )
    out = {}
    for k, v in params.items():
        spec = specs[k]
        stage_stacked = bool(len(spec)) and spec[0] == "pp"
        out[k] = v[idx] if stage_stacked else v
    return out


def make_loss_and_grads_1f1b(
    mesh, cfg: TransformerConfig, schedule: str = "1f1b", virtual: int = 1
):
    """Build ``fn(params, tokens, targets) -> (loss, grads)`` running a
    tabulated pipeline training schedule over the ``('dp', 'tp', 'pp')``
    mesh — ``1f1b`` (default), or ``interleaved`` with ``virtual`` chunks
    per device (the chain is then ``virtual * pp`` stages deep and params
    must be stage-stacked to that depth, arranged device-major via
    ``arrange_stage_stack``).

    Returns ``(fn, shardings)``; jit at the call site. ``grads`` is a
    pytree matching ``params`` (sharded identically), produced WITHOUT
    ``jax.grad`` of the loop — each backward tick applies the stage vjp
    explicitly, per the schedule tables.
    """
    dp, tp, pp = mesh.shape["dp"], mesh.shape["tp"], mesh.shape["pp"]
    mb = cfg.microbatches
    v = virtual
    specs = param_specs(cfg)
    if cfg.mlp_kernel == "int8_weights":
        raise ValueError(
            "1F1B is a training schedule and mlp_kernel='int8_weights' is "
            "the forward-only serving form; train with mlp_kernel='int8' "
            "(STE) instead"
        )
    interpret = jax.default_backend() != "tpu"
    stage_fn = make_stage_fn(cfg, tp, interpret)
    tables = build_schedule(schedule, pp, mb, v)
    S_glob = tables.n_stages
    T = {
        name: jnp.asarray(getattr(tables, name))
        for name in ("kind", "mb", "chunk", "act_slot", "in_slot",
                     "fwd_land", "bwd_land")
    }
    n_act = tables.act_slots + 1
    n_land = tables.land_slots + 1
    D = cfg.d_model

    def body(params, tokens, targets):
        p_tp = jax.lax.axis_index("tp")
        p_pp = jax.lax.axis_index("pp")
        B_loc, S = tokens.shape
        if B_loc % mb != 0:
            raise ValueError(
                f"per-dp-rank batch {B_loc} not divisible by microbatches={mb}"
            )
        if S % tp != 0:
            raise ValueError(f"sequence {S} not divisible by tp={tp}")
        s_loc = S // tp
        b_mb = B_loc // mb
        ring_r = [(i, (i + 1) % pp) for i in range(pp)]
        ring_l = [(i, (i - 1) % pp) for i in range(pp)]
        # total loss = mean over (mb, dp ranks, tp seq shards); each
        # microbatch tail therefore back-propagates with this cotangent
        cot = 1.0 / (mb * dp * tp)

        def mb_slab(arr, i):
            sl = jax.lax.dynamic_slice_in_dim(arr, i * b_mb, b_mb, 0)
            return jax.lax.dynamic_slice_in_dim(sl, p_tp * s_loc, s_loc, 1)

        zero_grads = jax.tree_util.tree_map(
            lambda x: jnp.zeros(x.shape, jnp.float32), params
        )
        act = jnp.zeros((n_act, b_mb, s_loc, D), cfg.dtype)
        fland = jnp.zeros((n_land, b_mb, s_loc, D), cfg.dtype)
        bland = jnp.zeros((n_land, b_mb, s_loc, D), cfg.dtype)
        fwd_arr = jnp.zeros((b_mb, s_loc, D), cfg.dtype)
        bwd_arr = jnp.zeros((b_mb, s_loc, D), cfg.dtype)
        loss_acc = jnp.zeros((), jnp.float32)
        aux_acc = jnp.zeros((), jnp.float32)
        grads = zero_grads
        # d(total loss)/d(per-tick stage aux): the aux term averages over
        # (mb, global stage chunks, dp, tp) with weight router_aux
        aux_cot = jnp.asarray(
            cfg.router_aux / (mb * S_glob * dp * tp), jnp.float32
        )
        # leaves with a leading stage axis (device-local size = virtual);
        # the rest (embed/ln_f/head) are replicated whole
        stage_names = {
            name for name, spec in specs.items()
            if len(spec) and spec[0] == "pp"
        }

        def sl(slot, cap):
            return jnp.where(slot < 0, cap - 1, slot)

        for t in range(tables.ticks):
            fland = jax.lax.dynamic_update_slice(
                fland, fwd_arr[None],
                (sl(T["fwd_land"][t, p_pp], n_land), 0, 0, 0),
            )
            bland = jax.lax.dynamic_update_slice(
                bland, bwd_arr[None],
                (sl(T["bwd_land"][t, p_pp], n_land), 0, 0, 0),
            )
            kind = T["kind"][t, p_pp]
            i = jnp.maximum(T["mb"][t, p_pp], 0)
            aslot = sl(T["act_slot"][t, p_pp], n_act)
            islot = sl(T["in_slot"][t, p_pp], n_land)
            c = jnp.maximum(T["chunk"][t, p_pp], 0)
            # interleaved placement: chunk c of device p is global stage
            # c*pp + p; injection/tail gate on the GLOBAL chain ends
            s_glob = c * pp + p_pp
            is_first = s_glob == 0
            is_last = s_glob == S_glob - 1

            def chunk_params():
                """This tick's stage-param slice (leading axis kept at 1
                so stage_fn's ``[0, l]`` indexing is unchanged)."""
                return {
                    name: (
                        jax.lax.dynamic_index_in_dim(
                            leaf, c, axis=0, keepdims=True
                        )
                        if name in stage_names
                        else leaf
                    )
                    for name, leaf in params.items()
                }

            def fwd_branch(act, fland, bland, loss_acc, aux_acc, grads):
                tok = mb_slab(tokens, i)
                inject = params["embed"][tok].astype(cfg.dtype)
                landed = jax.lax.dynamic_index_in_dim(
                    fland, islot, axis=0, keepdims=False
                )
                x_in = jnp.where(is_first, inject, landed)
                y, aux = stage_fn(x_in, chunk_params())
                act_n = jax.lax.dynamic_update_slice(
                    act, x_in[None], (aslot, 0, 0, 0)
                )
                # collective-free tail under the last-stage cond (the
                # GPipe loop's safe-divergence pattern)
                loss_i = jax.lax.cond(
                    is_last,
                    lambda yy: _tail_loss(
                        yy, params["ln_f"], params["head"], mb_slab(targets, i)
                    ),
                    lambda yy: jnp.zeros((), jnp.float32),
                    y,
                )
                send_f = jnp.where(is_last, jnp.zeros_like(y), y)
                return (
                    act_n, fland, bland, loss_acc + loss_i, aux_acc + aux,
                    grads, send_f, jnp.zeros_like(y),
                )

            def bwd_branch(act, fland, bland, loss_acc, aux_acc, grads):
                x_saved = jax.lax.dynamic_index_in_dim(
                    act, aslot, axis=0, keepdims=False
                )
                # rematerializing vjp: stage_fn is checkpointed, so this
                # recomputes the stage forward then backs through it —
                # the physical ~2x-forward backward tick
                sp_c = chunk_params()
                (y, _aux), pull = jax.vjp(stage_fn, x_saved, sp_c)

                def tail_seed(yy):
                    # d(total loss)/dy at the last stage, plus the tail's
                    # own param grads (ln_f, head); collective-free
                    tgt = mb_slab(targets, i)

                    def tl(yy_, lnf, hd):
                        return _tail_loss(yy_, lnf, hd, tgt)

                    _, tpull = jax.vjp(
                        tl, yy, params["ln_f"], params["head"]
                    )
                    g_y, d_lnf, d_head = tpull(jnp.asarray(cot, jnp.float32))
                    return g_y.astype(cfg.dtype), d_lnf, d_head

                def mid_seed(yy):
                    landed = jax.lax.dynamic_index_in_dim(
                        bland, islot, axis=0, keepdims=False
                    )
                    return (
                        landed,
                        jnp.zeros_like(params["ln_f"]),
                        jnp.zeros_like(params["head"]),
                    )

                g_y, d_lnf, d_head = jax.lax.cond(
                    is_last, tail_seed, mid_seed, y
                )
                dx, dparams = pull((g_y, aux_cot))
                # embed backward at the global chain head: scatter-add dx
                # at the token ids (collective-free)
                tok = mb_slab(tokens, i)
                d_embed = jax.lax.cond(
                    is_first,
                    lambda dxx: jnp.zeros(
                        params["embed"].shape, jnp.float32
                    ).at[tok].add(dxx.astype(jnp.float32)),
                    lambda dxx: jnp.zeros(params["embed"].shape, jnp.float32),
                    dx,
                )
                gr = {
                    name: (
                        grads[name].at[c].add(
                            dparams[name][0].astype(jnp.float32)
                        )
                        if name in stage_names
                        else grads[name] + dparams[name].astype(jnp.float32)
                    )
                    for name in grads
                }
                gr["embed"] = gr["embed"] + d_embed
                gr["ln_f"] = grads["ln_f"] + d_lnf.astype(jnp.float32)
                gr["head"] = grads["head"] + d_head.astype(jnp.float32)
                send_b = jnp.where(is_first, jnp.zeros_like(dx), dx)
                send_b = send_b.astype(cfg.dtype)
                return (
                    act, fland, bland, loss_acc, aux_acc, gr,
                    jnp.zeros_like(send_b), send_b,
                )

            def idle_branch(act, fland, bland, loss_acc, aux_acc, grads):
                z = jnp.zeros((b_mb, s_loc, D), cfg.dtype)
                return act, fland, bland, loss_acc, aux_acc, grads, z, z

            (act, fland, bland, loss_acc, aux_acc, grads, send_f, send_b) = (
                jax.lax.switch(
                    kind,
                    [idle_branch, fwd_branch, bwd_branch],
                    act, fland, bland, loss_acc, aux_acc, grads,
                )
            )
            if pp > 1:
                fwd_arr = jax.lax.ppermute(send_f, "pp", perm=ring_r)
                bwd_arr = jax.lax.ppermute(send_b, "pp", perm=ring_l)
            else:
                fwd_arr, bwd_arr = send_f, send_b

        # stage vjps applied a 'cot'-scaled seed per microbatch; the
        # remaining reductions are the generic manual-SPMD rule: psum a
        # grad over every mesh axis its param spec does NOT shard
        # (dp always; tp for tp-replicated leaves; pp for the shared
        # embed/ln_f/head, whose contributions live on one stage)
        loss = jax.lax.psum(loss_acc / mb, "pp")
        if cfg.router == "topk":
            # mean over all S_glob stage-chunk calls (v per device)
            loss = loss + cfg.router_aux * jax.lax.psum(
                aux_acc / mb, "pp"
            ) / S_glob
        loss = jax.lax.psum(loss, "dp") / dp
        loss = jax.lax.psum(loss, "tp") / tp
        out_grads = {}
        for name, g in grads.items():
            spec_axes = set(a for a in specs[name] if a is not None)
            for ax in ("dp", "tp", "pp"):
                if ax not in spec_axes:
                    g = jax.lax.psum(g, ax)
            out_grads[name] = g.astype(params[name].dtype)
        return loss, out_grads

    # runtime.shard_map_compat (DDLB101 migration): jax 0.4.x has no
    # jax.shard_map, and this schedule must run on the old-jax fleet
    fn = shard_map_compat(
        body,
        mesh=mesh,
        in_specs=(specs, P("dp", None), P("dp", None)),
        out_specs=(P(), specs),
        check_vma=False,
    )
    shardings = {k: NamedSharding(mesh, s) for k, s in specs.items()}
    shardings["data"] = NamedSharding(mesh, P("dp", None))
    return fn, shardings


def make_train_step_1f1b(
    mesh,
    cfg: TransformerConfig,
    learning_rate: float = 1e-2,
    donate: bool = True,
    schedule: str = "1f1b",
    virtual: int = 1,
):
    """Full 1F1B (or interleaved) training step: the drop-in counterpart
    of ``models.transformer.make_train_step`` (same returns, same
    shardings) with the schedule swapped from autodiff-GPipe to the
    table-driven manual-vjp loop. For ``schedule='interleaved'`` the
    params must be stage-stacked ``virtual * pp`` deep and arranged
    device-major (``arrange_stage_stack``)."""
    import optax

    # int8_weights (forward-only) is rejected by make_loss_and_grads_1f1b
    optimizer = optax.adamw(learning_rate)
    loss_and_grads, shardings = make_loss_and_grads_1f1b(
        mesh, cfg, schedule=schedule, virtual=virtual
    )

    def step(params, opt_state, tokens, targets):
        loss, grads = loss_and_grads(params, tokens, targets)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    train_step = (
        jax.jit(step, donate_argnums=(0, 1)) if donate else jax.jit(step)
    )

    def init_opt_state(params):
        with set_mesh_compat(mesh):
            return jax.jit(optimizer.init)(params)

    return train_step, init_opt_state, shardings
