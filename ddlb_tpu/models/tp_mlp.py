"""Flagship model: Megatron-style tensor-parallel MLP block.

The reference has no model at all — its workloads are bare GEMM primitives
(SURVEY.md section 2.5). This module shows the framework's two primitives
composed into the structure they exist to accelerate: the sequence-parallel
transformer MLP, where the up-projection is exactly ``tp_columnwise``
(all-gather the sequence-sharded activations, GEMM against a column-sharded
weight) and the down-projection is exactly ``tp_rowwise`` (GEMM against a
row-sharded weight, reduce-scatter back to sequence-sharded) — the pairing
the reference frames via TransformerEngine's ``sequence_parallel=True``
Linear layers (/root/reference/ddlb/primitives/TPColumnwise/
transformer_engine.py:58-72, TPRowwise/transformer_engine.py:66-81).

Two forms are provided:

- ``mlp_block`` — explicit ``shard_map`` body (mirrors the jax_spmd
  primitive implementations);
- ``train_step`` — GSPMD form over a (dp, tp) mesh with sequence-parallel
  activation shardings, differentiable end to end, used by the multi-chip
  dry run.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ddlb_tpu.runtime import shard_map_compat


def init_params(
    d_model: int, d_ff: int, dtype=jnp.bfloat16, seed: int = 0
) -> Dict[str, Any]:
    """Seeded host-side parameter construction (deterministic across hosts,
    like the primitive operands)."""
    rng = np.random.default_rng(seed)
    scale1 = (2.0 / d_model) ** 0.5
    scale2 = (2.0 / d_ff) ** 0.5
    return {
        "w1": jnp.asarray(
            rng.normal(0.0, scale1, (d_model, d_ff)), dtype=dtype
        ),
        "w2": jnp.asarray(
            rng.normal(0.0, scale2, (d_ff, d_model)), dtype=dtype
        ),
    }


def mlp_forward(x, w1, w2):
    """Single-device reference forward: ``gelu(x @ w1) @ w2``."""
    h = jax.nn.gelu(
        jnp.matmul(x, w1, preferred_element_type=jnp.float32).astype(x.dtype)
    )
    return jnp.matmul(h, w2, preferred_element_type=jnp.float32).astype(x.dtype)


def mlp_block(mesh, axis_name: str = "tp"):
    """Explicit sequence-parallel MLP as a ``shard_map``-able function.

    Input/output activations are sequence-sharded over ``axis_name``; w1 is
    column-sharded, w2 row-sharded. Internally: all-gather (the
    tp_columnwise pattern) -> GEMM -> gelu -> GEMM -> psum_scatter (the
    tp_rowwise pattern).
    """

    def block(x_local, w1_local, w2_local):
        x_full = jax.lax.all_gather(x_local, axis_name, axis=0, tiled=True)
        h = jax.nn.gelu(
            jnp.matmul(
                x_full, w1_local, preferred_element_type=jnp.float32
            ).astype(x_local.dtype)
        )
        y_partial = jnp.matmul(h, w2_local, preferred_element_type=jnp.float32)
        y = jax.lax.psum_scatter(
            y_partial, axis_name, scatter_dimension=0, tiled=True
        )
        return y.astype(x_local.dtype)

    # shard_map_compat: jax.shard_map where it exists, the pre-0.5
    # experimental entry point otherwise (jax 0.4.x fleet)
    return shard_map_compat(
        block,
        mesh=mesh,
        in_specs=(P(axis_name, None), P(None, axis_name), P(axis_name, None)),
        out_specs=P(axis_name, None),
        check_vma=False,
    )


def make_train_step(mesh, learning_rate: float = 1e-3):
    """Full GSPMD training step over a ``(dp, tp)`` mesh.

    Layouts: batch data-parallel over ``dp``; the sequence dimension of
    activations sharded over ``tp`` outside the matmuls (sequence
    parallelism); w1/w2 tensor-parallel. GSPMD inserts the
    all-gather/reduce-scatter pair in forward and the mirrored pair plus
    gradient all-reduces in backward.
    """
    import optax

    optimizer = optax.sgd(learning_rate)

    from ddlb_tpu.runtime import as_auto_mesh

    mesh = as_auto_mesh(mesh)

    x_sharding = NamedSharding(mesh, P("dp", "tp", None))
    w1_sharding = NamedSharding(mesh, P(None, "tp"))
    w2_sharding = NamedSharding(mesh, P("tp", None))

    def loss_fn(params, x, target):
        h = jax.nn.gelu(
            jnp.matmul(
                x, params["w1"], preferred_element_type=jnp.float32
            ).astype(x.dtype)
        )
        out = jnp.matmul(h, params["w2"], preferred_element_type=jnp.float32)
        # sequence-parallel activations: keep the output sequence-sharded
        out = jax.lax.with_sharding_constraint(
            out.astype(x.dtype), x_sharding
        )
        return jnp.mean(jnp.square(out.astype(jnp.float32) - target))

    @partial(
        jax.jit,
        in_shardings=(
            {"w1": w1_sharding, "w2": w2_sharding},
            None,
            x_sharding,
            x_sharding,
        ),
        donate_argnums=(0, 1),
    )
    def train_step(params, opt_state, x, target):
        loss, grads = jax.value_and_grad(loss_fn)(params, x, target)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    def init_opt_state(params):
        return optimizer.init(params)

    return train_step, init_opt_state, (x_sharding, w1_sharding, w2_sharding)


def example_batch(
    batch: int, seq: int, d_model: int, dtype=jnp.bfloat16, seed: int = 1
) -> Tuple[jax.Array, jax.Array]:
    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.normal(0, 1, (batch, seq, d_model)), dtype=dtype)
    t = jnp.asarray(
        rng.normal(0, 1, (batch, seq, d_model)), dtype=jnp.float32
    )
    return x, t
