"""Continuous-batching serving engine: slot-level admission over the
ragged decode step.

The serving capability the ragged machinery exists for (no reference
analogue — the reference has no model or serving path, SURVEY.md §2.5):
``make_decode_fn(ragged=True)`` decodes a batch whose sequences sit at
DIFFERENT positions in one compiled step, and the int8/bf16 cache's
out-of-bounds write semantics (drop, models/decode.py ``_cache_write``)
make an idle slot representable as "position past the cache" — its write
vanishes, its lane costs nothing but the flops it was already paying.

Design (the standard host-scheduled pattern: device steps are batched
and compiled, scheduling is host-side between steps):

- ``max_batch`` slots share one KV cache. Each request is admitted into
  a free slot by a tp-replicated prefill (batch = tp copies so the MoE
  block router's ``b % tp`` divisibility holds; copy ``e(slot)`` — the
  expert the block router assigns that slot — is the one whose cache
  rows and logits are kept, so admission numerics equal an in-batch
  prefill of that slot). One compile per distinct prompt length.
- Every engine tick runs ONE ragged decode over all ``max_batch`` lanes:
  active slots decode at their own ``pos[i]`` and advance; idle slots
  ride along at ``pos = max_len`` (write dropped, output ignored).
- A slot frees when its request hits ``max_new`` or emits ``eos_id``;
  the next queued request is admitted before the next tick. Requests
  finish and admit at different times — continuous batching, not static.

Correctness contract (pinned in tests/test_serving_engine.py): every
completed request's tokens equal the target model's own greedy chain for
that prompt in that slot — the engine changes scheduling, never tokens.

Engine mesh is ``('dp', 'tp')`` with ``dp == 1`` (slot-level scheduling
and data parallelism compose by running one engine per dp shard; the
in-engine batch axis IS the slot axis).
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

import jax
import jax.numpy as jnp

from ddlb_tpu.models.decode import (
    init_cache,
    make_decode_fn,
    make_prefill_fn,
)
from ddlb_tpu.models.transformer import TransformerConfig


@dataclass
class Request:
    """One generation request. ``max_new`` caps the generated tokens;
    ``eos_id`` (engine-level) can end it earlier."""

    prompt: np.ndarray          # [S0] int32
    max_new: int

    def __post_init__(self):
        self.prompt = np.asarray(self.prompt, np.int32)
        if self.prompt.ndim != 1 or self.prompt.size == 0:
            raise ValueError("prompt must be a non-empty 1-D token array")
        if self.max_new < 1:
            raise ValueError(f"max_new must be >= 1, got {self.max_new}")


@dataclass
class Completion:
    """A finished request: ``tokens`` is prompt + generated (including
    the eos token when one ended the request). ``slot`` is the lane it
    ran in — the block router's expert assignment is slot-stable, so the
    oracle for a completion is the greedy chain of that prompt in that
    batch row."""

    request_index: int
    slot: int
    tokens: np.ndarray
    finished_by: str            # "max_new" | "eos"
    admitted_at_step: int
    finished_at_step: int


@dataclass
class EngineStats:
    steps: int = 0              # ragged decode ticks
    generated: int = 0          # tokens emitted so far (incl. active slots)
    admissions: int = 0
    lane_ticks_active: int = 0  # per-tick count of active lanes
    lane_ticks_total: int = 0
    prefix_hits: int = 0        # admissions served from the shared prefix
    prefill_tokens_saved: int = 0

    @property
    def occupancy(self) -> float:
        """Fraction of decode-lane capacity that did useful work — the
        number continuous batching exists to raise."""
        if self.lane_ticks_total == 0:
            return 0.0
        return self.lane_ticks_active / self.lane_ticks_total


class ContinuousBatchingEngine:
    """Greedy continuous-batching engine over one ``(1, tp)`` mesh.

    ``submit()`` requests, then ``run()`` to drain; or drive manually
    with ``admit_ready()`` + ``step()`` for custom arrival processes.
    """

    def __init__(
        self,
        mesh,
        cfg: TransformerConfig,
        params: Dict[str, jax.Array],
        max_batch: int,
        max_len: int,
        eos_id: Optional[int] = None,
    ):
        if mesh.shape.get("dp", 1) != 1:
            raise ValueError(
                "engine mesh must have dp=1 (run one engine per dp shard; "
                "the in-engine batch axis is the slot axis)"
            )
        self.tp = mesh.shape["tp"]
        if max_batch % self.tp != 0:
            raise ValueError(
                f"max_batch={max_batch} not divisible by tp={self.tp} "
                f"(the MoE block router)"
            )
        self.mesh = mesh
        self.cfg = cfg
        self.params = params
        self.B = max_batch
        self.S_max = max_len
        self.eos_id = eos_id

        from ddlb_tpu.models.decode import make_chunk_decode_fn

        decode, _ = make_decode_fn(mesh, cfg, ragged=True)
        self._decode = jax.jit(decode)
        prefill, _ = make_prefill_fn(mesh, cfg)
        self._prefill = jax.jit(prefill)
        chunk, _ = make_chunk_decode_fn(mesh, cfg)
        self._chunk = jax.jit(chunk)
        # shared-prefix state (set_shared_prefix)
        self._prefix_tokens: Optional[np.ndarray] = None
        self._prefix_scratch = None

        # slot copy: scratch-cache copy `c`'s rows [0, S0) into slot `s`
        # of the big cache. slot/copy are DYNAMIC scalars so only the
        # prompt length drives compiles (same cadence as the prefill);
        # heads shard identically on both sides, so the copy is local to
        # every tp rank.
        from ddlb_tpu.models.decode import cache_specs
        from jax.sharding import PartitionSpec as P

        cs = cache_specs(cfg)

        def copy_body(big, small, slot, copy):
            out = {}
            for name in big:
                row = jax.lax.dynamic_slice_in_dim(
                    small[name], copy, 1, axis=1
                )
                out[name] = jax.lax.dynamic_update_slice(
                    big[name], row, (0, slot, 0, 0, 0)
                )
            return out

        self._copy_slot = jax.jit(
            jax.shard_map(
                copy_body,
                mesh=mesh,
                in_specs=(cs, cs, P(), P()),
                out_specs=cs,
                check_vma=False,
            )
        )

        # prefix seed: the shared-prefix scratch's rows [0, P) land at
        # the head of a fresh admission scratch (leading rows, static
        # shapes — compile per (P, S0) pair, the same cadence as the
        # prefill it replaces)
        def seed_body(dst, src):
            return {
                name: jax.lax.dynamic_update_slice(
                    dst[name], src[name], (0, 0, 0, 0, 0)
                )
                for name in dst
            }

        self._seed_prefix = jax.jit(
            jax.shard_map(
                seed_body,
                mesh=mesh,
                in_specs=(cs, cs),
                out_specs=cs,
                check_vma=False,
            )
        )

        # host-side lane state (reset() is the single definition)
        self.reset()

    def reset(self) -> None:
        """Return the engine to its just-constructed state (fresh cache,
        all lanes parked, queues/completions/stats cleared) WITHOUT
        rebuilding the jitted step functions — a benchmark loop re-runs
        the same workload against compile-cached programs."""
        self.cache = init_cache(self.cfg, self.B, self.S_max, mesh=self.mesh)
        self.pos = np.full(self.B, self.S_max, np.int32)
        self.cur_tok = np.zeros(self.B, np.int32)
        self._slot_req = [None] * self.B
        self._slot_new = [[] for _ in range(self.B)]
        self._slot_admitted = [0] * self.B
        self._queue = deque()
        self._requests = []
        self.completions = []
        self.stats = EngineStats()

    # -- scheduling --------------------------------------------------------

    def submit(self, request: Request) -> int:
        """Queue a request; returns its index (completion order may
        differ — match on ``Completion.request_index``). Fails fast on a
        request that could never fit — an admission-time failure would
        abort a drain mid-flight with the request already dequeued."""
        S0 = request.prompt.size
        if S0 + request.max_new > self.S_max:
            raise ValueError(
                f"prompt {S0} + max_new {request.max_new} exceeds "
                f"max_len {self.S_max}"
            )
        idx = len(self._requests)
        self._requests.append(request)
        self._queue.append(idx)
        return idx

    def set_shared_prefix(self, prefix) -> None:
        """Prefill a shared prompt prefix ONCE (e.g. a system prompt);
        every admission whose prompt starts with it reuses the cached
        rows and prefills only the suffix — a chunk-decode at
        ``start=P`` that attends the prefix THROUGH the cache. The K/V
        rows are identical to a full prefill's BY CONSTRUCTION (prefix
        rows depend only on prefix tokens; int8 rows are quantized once
        and read back the same way on both paths); the suffix logits
        agree to float tolerance (the chunk path accumulates attention
        in a different order than a flash prefill would), which the
        lossless tests pin at the token level across einsum AND flash
        prefill kernels. ``None`` clears the prefix and frees its device
        scratch; a set prefix survives ``reset()`` (it is derived from
        params, like the jitted step programs)."""
        if prefix is None:
            self._prefix_tokens = None
            self._prefix_scratch = None
            return
        prefix = np.asarray(prefix, np.int32)
        if prefix.ndim != 1 or prefix.size == 0:
            raise ValueError("prefix must be a non-empty 1-D token array")
        rep = jnp.asarray(
            np.broadcast_to(prefix, (self.tp, prefix.size)).copy()
        )
        scratch = init_cache(self.cfg, self.tp, prefix.size, mesh=self.mesh)
        _, scratch = self._prefill(self.params, scratch, rep)
        self._prefix_tokens = prefix
        self._prefix_scratch = jax.block_until_ready(scratch)

    def _expert_of(self, slot: int) -> int:
        # the block router's per-sequence-stable assignment on a dp=1
        # shard: slot i -> expert i // (B / tp) (models/decode._block_moe)
        return slot // (self.B // self.tp)

    def admit_ready(self) -> int:
        """Admit queued requests into free slots; returns count admitted."""
        n = 0
        for slot in range(self.B):
            if self._slot_req[slot] is not None or not self._queue:
                continue
            self._admit(slot, self._queue.popleft())
            n += 1
        return n

    def _admit(self, slot: int, req_idx: int) -> None:
        req = self._requests[req_idx]
        S0 = req.prompt.size
        assert S0 + req.max_new <= self.S_max  # screened in submit()
        # tp-replicated prefill into a scratch cache (one compile per
        # distinct S0); keep copy e(slot)'s rows + logits. With a shared
        # prefix match, seed the scratch from the prefix cache and
        # chunk-decode only the suffix (O((S0-P)*S0) attention instead of
        # O(S0^2), and no prefix MLP/projection recompute).
        e = self._expert_of(slot)
        P_len = 0
        if self._prefix_tokens is not None:
            P_len = self._prefix_tokens.size
            if not (
                S0 > P_len
                and np.array_equal(req.prompt[:P_len], self._prefix_tokens)
            ):
                P_len = 0  # no match (or no suffix): full prefill path
        scratch = init_cache(self.cfg, self.tp, S0, mesh=self.mesh)
        if P_len:
            scratch = self._seed_prefix(scratch, self._prefix_scratch)
            suffix = jnp.asarray(
                np.broadcast_to(
                    req.prompt[P_len:], (self.tp, S0 - P_len)
                ).copy()
            )
            logits, scratch = self._chunk(
                self.params, scratch, suffix, jnp.int32(P_len)
            )
            logits = logits[:, -1]
            self.stats.prefix_hits += 1
            self.stats.prefill_tokens_saved += P_len
        else:
            prompt_rep = jnp.asarray(
                np.broadcast_to(req.prompt, (self.tp, S0)).copy()
            )
            logits, scratch = self._prefill(self.params, scratch, prompt_rep)
        self.cache = self._copy_slot(
            self.cache, scratch, jnp.int32(slot), jnp.int32(e)
        )
        first = int(np.asarray(logits)[e].argmax())
        self.pos[slot] = S0
        self.cur_tok[slot] = first
        self._slot_req[slot] = req_idx
        self._slot_new[slot] = [first]
        self._slot_admitted[slot] = self.stats.steps
        self.stats.admissions += 1
        self.stats.generated += 1  # the admission's first token
        # a request can finish at admission (max_new=1 or instant eos)
        self._maybe_finish(slot)

    def _maybe_finish(self, slot: int) -> None:
        req_idx = self._slot_req[slot]
        req = self._requests[req_idx]
        new = self._slot_new[slot]
        by = None
        if self.eos_id is not None and new and new[-1] == self.eos_id:
            by = "eos"
        elif len(new) >= req.max_new:
            by = "max_new"
        if by is None:
            return
        self.completions.append(
            Completion(
                request_index=req_idx,
                slot=slot,
                tokens=np.concatenate([req.prompt, np.asarray(new, np.int32)]),
                finished_by=by,
                admitted_at_step=self._slot_admitted[slot],
                finished_at_step=self.stats.steps,
            )
        )
        self._slot_req[slot] = None
        self._slot_new[slot] = []
        self.pos[slot] = self.S_max          # park: writes drop, lane idles
        self.cur_tok[slot] = 0

    # -- the tick ----------------------------------------------------------

    def step(self) -> int:
        """One ragged decode over all lanes; returns active-lane count."""
        active = [s for s in range(self.B) if self._slot_req[s] is not None]
        if not active:
            return 0
        logits, self.cache = self._decode(
            self.params,
            self.cache,
            jnp.asarray(self.cur_tok),
            jnp.asarray(self.pos),
        )
        nxt = np.asarray(jnp.argmax(logits, axis=-1), np.int32)
        self.stats.steps += 1
        self.stats.lane_ticks_total += self.B
        self.stats.lane_ticks_active += len(active)
        self.stats.generated += len(active)
        for s in active:
            self.pos[s] += 1
            self.cur_tok[s] = nxt[s]
            self._slot_new[s].append(int(nxt[s]))
            self._maybe_finish(s)
        return len(active)

    def run(self) -> List[Completion]:
        """Admit + step until the queue and all slots drain."""
        while True:
            self.admit_ready()
            if self.step() == 0 and not self._queue:
                return self.completions


