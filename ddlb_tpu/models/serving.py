"""Continuous-batching serving engine: slot-level admission over the
ragged decode step.

The serving capability the ragged machinery exists for (no reference
analogue — the reference has no model or serving path, SURVEY.md §2.5):
``make_decode_fn(ragged=True)`` decodes a batch whose sequences sit at
DIFFERENT positions in one compiled step, and the int8/bf16 cache's
out-of-bounds write semantics (drop, models/decode.py ``_cache_write``)
make an idle slot representable as "position past the cache" — its write
vanishes, its lane costs nothing but the flops it was already paying.

Design (the standard host-scheduled pattern: device steps are batched
and compiled, scheduling is host-side between steps):

- ``max_batch`` slots share one KV cache. Each request is admitted into
  a free slot by a tp-replicated prefill (batch = tp copies so the MoE
  block router's ``b % tp`` divisibility holds; copy ``e(slot)`` — the
  expert the block router assigns that slot — is the one whose cache
  rows and logits are kept, so admission numerics equal an in-batch
  prefill of that slot). One compile per distinct prompt length.
- Every engine tick runs ONE ragged decode over all ``max_batch`` lanes:
  active slots decode at their own ``pos[i]`` and advance; idle slots
  ride along at ``pos = max_len`` (write dropped, output ignored).
- A slot frees when its request hits ``max_new`` or emits ``eos_id``;
  the next queued request is admitted before the next tick. Requests
  finish and admit at different times — continuous batching, not static.

Correctness contract (pinned in tests/test_serving_engine.py): every
completed request's tokens equal the target model's own greedy chain for
that prompt in that slot — the engine changes scheduling, never tokens.

With ``cfg.cache_layout='paged'`` the big cache is a shared page pool
indexed by a per-slot page table (models/decode.init_paged_cache — the
vLLM pattern with static pool/table shapes): admissions allocate pages
from a host-side free list, completions return them, and a mixed-length
workload runs in a pool smaller than the contiguous layout's B x S_max
(``num_pages`` engine knob; page pressure defers head-of-queue
admissions FIFO-fairly). Full pages of the shared prefix are SHARED
across same-expert slots instead of copied — table entries, not data.
Tokens are identical to the contiguous engine by construction (pinned
in tests/test_paged.py).

Engine mesh is ``('dp', 'tp')`` with ``dp == 1`` (slot-level scheduling
and data parallelism compose by running one engine per dp shard; the
in-engine batch axis IS the slot axis).
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ddlb_tpu import faults, telemetry
from ddlb_tpu.runtime import shard_map_compat
from ddlb_tpu.models.decode import (
    init_cache,
    init_paged_cache,
    make_decode_fn,
    make_prefill_fn,
)
from ddlb_tpu.models.transformer import TransformerConfig


@dataclass
class Request:
    """One generation request. ``max_new`` caps the generated tokens;
    ``eos_id`` (engine-level) can end it earlier."""

    prompt: np.ndarray          # [S0] int32
    max_new: int

    def __post_init__(self):
        self.prompt = np.asarray(self.prompt, np.int32)
        if self.prompt.ndim != 1 or self.prompt.size == 0:
            raise ValueError("prompt must be a non-empty 1-D token array")
        if self.max_new < 1:
            raise ValueError(f"max_new must be >= 1, got {self.max_new}")


@dataclass
class Completion:
    """A finished request: ``tokens`` is prompt + generated (including
    the eos token when one ended the request). ``slot`` is the lane it
    ran in — the block router's expert assignment is slot-stable, so the
    oracle for a completion is the greedy chain of that prompt in that
    batch row."""

    request_index: int
    slot: int
    tokens: np.ndarray
    finished_by: str            # "max_new" | "eos"
    admitted_at_step: int
    finished_at_step: int


@dataclass
class EngineStats:
    """Counters one ``run()``/manual drive accumulates: scheduling
    health (occupancy, deferrals), prefix-cache effectiveness, and
    page-pool pressure in the paged layout."""

    steps: int = 0              # ragged decode ticks
    generated: int = 0          # tokens emitted so far (incl. active slots)
    admissions: int = 0
    lane_ticks_active: int = 0  # per-tick count of active lanes
    lane_ticks_total: int = 0
    prefix_hits: int = 0        # admissions served from the shared prefix
    prefill_tokens_saved: int = 0
    #: load-shedding counters (ddlb_tpu/workload drives them): requests
    #: preempted mid-generation (requeued, prefix-of-work preserved) and
    #: the K/V cache rows those preemptions abandoned — the engine's
    #: eviction cost, re-paid as prefill on re-admission
    preemptions: int = 0
    kv_evicted_tokens: int = 0
    # paged layout only: page-pool pressure
    pages_capacity: int = 0
    pages_in_use: int = 0       # current gauge (incl. shared prefix pages)
    peak_pages_in_use: int = 0
    admissions_deferred: int = 0  # head-of-queue waits for free pages

    @property
    def occupancy(self) -> float:
        """Fraction of decode-lane capacity that did useful work — the
        number continuous batching exists to raise."""
        if self.lane_ticks_total == 0:
            return 0.0
        return self.lane_ticks_active / self.lane_ticks_total


class ContinuousBatchingEngine:
    """Greedy continuous-batching engine over one ``(1, tp)`` mesh.

    ``submit()`` requests, then ``run()`` to drain; or drive manually
    with ``admit_ready()`` + ``step()`` for custom arrival processes.
    """

    def __init__(
        self,
        mesh,
        cfg: TransformerConfig,
        params: Dict[str, jax.Array],
        max_batch: int,
        max_len: int,
        eos_id: Optional[int] = None,
        num_pages: Optional[int] = None,
        bucket_prefill: bool = True,
    ):
        if mesh.shape.get("dp", 1) != 1:
            raise ValueError(
                "engine mesh must have dp=1 (run one engine per dp shard; "
                "the in-engine batch axis is the slot axis)"
            )
        self.tp = mesh.shape["tp"]
        if max_batch % self.tp != 0:
            raise ValueError(
                f"max_batch={max_batch} not divisible by tp={self.tp} "
                f"(the MoE block router)"
            )
        self.mesh = mesh
        self.cfg = cfg
        self.params = params
        #: extra fault-plan match context the engine's injection sites
        #: pass (``{"shard": "1"}`` from the serving cluster) — a chaos
        #: plan can then target ONE engine of a multi-engine pool
        self.fault_context: Dict[str, str] = {}
        self.B = max_batch
        self.S_max = max_len
        self.eos_id = eos_id
        self.paged = cfg.cache_layout == "paged"
        # prefill/chunk run on small CONTIGUOUS scratch caches even in
        # paged mode (a per-admission scratch has nothing to page);
        # only the big shared cache and its ragged decode are paged
        scratch_cfg = (
            dataclasses.replace(cfg, cache_layout="contiguous")
            if self.paged
            else cfg
        )
        self._scratch_cfg = scratch_cfg
        if self.paged:
            ps = cfg.page_size
            if max_len % ps:
                raise ValueError(
                    f"max_len={max_len} not divisible by page_size={ps}"
                )
            self.page_size = ps
            self.max_pages = max_len // ps
            # default pool = contiguous parity (B full-length slots);
            # the interesting configs pass fewer — that is the feature
            self.num_pages = (
                num_pages if num_pages is not None else max_batch * self.max_pages
            )
            if self.num_pages < 1:
                raise ValueError(f"num_pages must be >= 1, got {num_pages}")
        elif num_pages is not None:
            raise ValueError(
                "num_pages only applies to cache_layout='paged'"
            )

        from ddlb_tpu.models.decode import make_chunk_decode_fn

        # bucketed admission (default): prompts pad to power-of-two
        # scratch lengths so prefill/chunk/copy compile O(log S_max)
        # programs instead of one per distinct prompt length — the
        # compile-storm hazard of realistic length distributions. The
        # pad tail is causally downstream of every real row (K/V row j
        # depends only on token j; attention is masked), so tokens are
        # identical to exact-length admission (pinned in
        # tests/test_serving_engine.py / test_paged.py).
        self._bucket_prefill = bucket_prefill
        decode, _ = make_decode_fn(mesh, cfg, ragged=True)
        self._decode = jax.jit(decode)
        prefill, _ = make_prefill_fn(
            mesh, scratch_cfg, dynamic_last=bucket_prefill
        )
        self._prefill = jax.jit(prefill)
        chunk, _ = make_chunk_decode_fn(mesh, scratch_cfg)
        self._chunk = jax.jit(chunk)
        # dynamic last-position pick for the bucketed chunk path (the
        # index is traced: logits shape, not suffix length, drives
        # compiles)
        self._pick = jax.jit(
            lambda lg, i: jax.lax.dynamic_index_in_dim(
                lg, i, axis=1, keepdims=False
            )
        )
        # shared-prefix state (set_shared_prefix)
        self._prefix_tokens: Optional[np.ndarray] = None
        self._prefix_scratch = None
        self._prefix_pages: List[int] = []

        from ddlb_tpu.models.decode import cache_specs
        from jax.sharding import PartitionSpec as P

        cs = cache_specs(scratch_cfg)
        self._table_sharding = None

        if self.paged:
            big_cs = dict(cache_specs(cfg))
            self._table_sharding = jax.sharding.NamedSharding(
                mesh, big_cs.pop("table")
            )

            # paged slot copy: scratch copy `c`'s rows [0, S0) scatter to
            # (page, row) coords computed on the host from the slot's
            # table (compile per S0, the prefill cadence; sentinel
            # coords drop)
            def copy_paged_body(big, small, pages, rows, copy):
                out = dict(big)
                for name in small:
                    data = jax.lax.dynamic_slice_in_dim(
                        small[name], copy, 1, axis=1
                    )[:, 0]  # [L, S0, H_kv, dh]
                    out[name] = (
                        big[name].at[:, pages, rows].set(data, mode="drop")
                    )
                return out

            self._copy_slot_paged = jax.jit(
                shard_map_compat(
                    copy_paged_body,
                    mesh=mesh,
                    in_specs=(big_cs, cs, P(), P(), P()),
                    out_specs=big_cs,
                    check_vma=False,
                )
            )
        else:

            # slot copy: scratch-cache copy `c`'s rows [0, S0) into slot
            # `s` of the big cache. slot/copy are DYNAMIC scalars so only
            # the prompt length drives compiles (same cadence as the
            # prefill); heads shard identically on both sides, so the
            # copy is local to every tp rank.
            def copy_body(big, small, slot, copy):
                out = {}
                for name in big:
                    row = jax.lax.dynamic_slice_in_dim(
                        small[name], copy, 1, axis=1
                    )
                    out[name] = jax.lax.dynamic_update_slice(
                        big[name], row, (0, slot, 0, 0, 0)
                    )
                return out

            self._copy_slot = jax.jit(
                shard_map_compat(
                    copy_body,
                    mesh=mesh,
                    in_specs=(cs, cs, P(), P()),
                    out_specs=cs,
                    check_vma=False,
                )
            )

        # prefix seed: the shared-prefix scratch's rows [0, P) land at
        # the head of a fresh admission scratch (leading rows, static
        # shapes — compile per (P, S0) pair, the same cadence as the
        # prefill it replaces)
        def seed_body(dst, src):
            return {
                name: jax.lax.dynamic_update_slice(
                    dst[name], src[name], (0, 0, 0, 0, 0)
                )
                for name in dst
            }

        self._seed_prefix = jax.jit(
            shard_map_compat(
                seed_body,
                mesh=mesh,
                in_specs=(cs, cs),
                out_specs=cs,
                check_vma=False,
            )
        )

        # host-side lane state (reset() is the single definition)
        self.reset()

    def reset(self) -> None:
        """Return the engine to its just-constructed state (fresh cache,
        all lanes parked, queues/completions/stats cleared) WITHOUT
        rebuilding the jitted step functions — a benchmark loop re-runs
        the same workload against compile-cached programs. A shared
        prefix survives (like the jitted programs, it derives from
        params); in paged mode its pool pages are re-seeded."""
        if self.paged:
            self.cache = init_paged_cache(
                self.cfg, self.B, self.S_max, self.num_pages, mesh=self.mesh
            )
            self._free_pages = list(range(self.num_pages))
            self._slot_pages: List[List[int]] = [[] for _ in range(self.B)]
            self._table_np = np.full(
                (self.B, self.max_pages), self.num_pages, np.int32
            )
            self._prefix_pages = []
            self._prefix_slots: set = set()
            self._retired_prefix: List[tuple] = []
        else:
            self.cache = init_cache(
                self.cfg, self.B, self.S_max, mesh=self.mesh
            )
        self.pos = np.full(self.B, self.S_max, np.int32)
        self.cur_tok = np.zeros(self.B, np.int32)
        self._slot_req = [None] * self.B
        self._slot_new = [[] for _ in range(self.B)]
        self._slot_admitted = [0] * self.B
        self._queue = deque()
        self._requests = []
        self.completions = []
        self.stats = EngineStats()
        if self.paged:
            self.stats.pages_capacity = self.num_pages
            if self._prefix_tokens is not None:
                # re-pin the surviving prefix into fresh pool pages
                self._seed_prefix_pages()

    # -- scheduling --------------------------------------------------------

    def submit(self, request: Request) -> int:
        """Queue a request; returns its index (completion order may
        differ — match on ``Completion.request_index``). Fails fast on a
        request that could never fit — an admission-time failure would
        abort a drain mid-flight with the request already dequeued."""
        S0 = request.prompt.size
        if S0 + request.max_new > self.S_max:
            raise ValueError(
                f"prompt {S0} + max_new {request.max_new} exceeds "
                f"max_len {self.S_max}"
            )
        if self.paged:
            # a request that could never fit the pool would spin run()
            # forever (admit defers, step idles, the queue never drains):
            # screen against the worst case — no prefix credit, since the
            # prefix can be cleared while the request is queued — minus
            # the pages the current prefix pins
            worst = -(-(S0 + request.max_new) // self.page_size)
            usable = self.num_pages - len(self._prefix_pages)
            if worst > usable:
                raise ValueError(
                    f"request needs up to {worst} pages but the pool has "
                    f"{usable} usable ({self.num_pages} total, "
                    f"{len(self._prefix_pages)} pinned by the prefix)"
                )
        idx = len(self._requests)
        self._requests.append(request)
        self._queue.append(idx)
        return idx

    # -- paged-pool bookkeeping (host side) --------------------------------

    def _alloc_pages(self, n: int) -> Optional[List[int]]:
        """Take ``n`` pages off the free list, or None if short."""
        if len(self._free_pages) < n:
            return None
        pages = [self._free_pages.pop() for _ in range(n)]
        self._gauge_pages()
        return pages

    def _release_pages(self, pages: List[int]) -> None:
        self._free_pages.extend(pages)
        self._gauge_pages()

    def _gauge_pages(self) -> None:
        in_use = self.num_pages - len(self._free_pages)
        self.stats.pages_in_use = in_use
        self.stats.peak_pages_in_use = max(
            self.stats.peak_pages_in_use, in_use
        )

    def _push_table(self) -> None:
        self.cache["table"] = jax.device_put(
            jnp.asarray(self._table_np), self._table_sharding
        )

    def _prefix_full_pages(self) -> int:
        """Full pages covered by the shared prefix (the shareable part;
        a partial trailing page is copied per admission, not shared)."""
        if self._prefix_tokens is None:
            return 0
        return self._prefix_tokens.size // self.page_size

    def _retire_prefix_pages(self) -> None:
        """Stop treating the current prefix page set as the prefix, and
        return its pages to the pool — DEFERRED while any active slot's
        table still maps them. An immediate release would let the next
        admission (or a replacement prefix's own scatter) reallocate and
        overwrite rows an in-flight sequence is still attending."""
        if self._prefix_pages:
            if self._prefix_slots:
                self._retired_prefix.append(
                    (self._prefix_pages, set(self._prefix_slots))
                )
            else:
                self._release_pages(self._prefix_pages)
            self._prefix_pages = []
        self._prefix_pages_by_e = [[] for _ in range(self.tp)]
        self._prefix_slots = set()

    def _drain_retired_prefix(self, slot: int) -> None:
        """Slot ``slot`` just finished: drop it from every retired prefix
        group and release any group no active slot references anymore."""
        kept = []
        for pages, slots in self._retired_prefix:
            slots.discard(slot)
            if slots:
                kept.append((pages, slots))
            else:
                self._release_pages(pages)
        self._retired_prefix = kept

    def _seed_prefix_pages(self) -> None:
        """Pin the shared prefix's FULL pages into the pool, one page set
        per expert: prefix K/V rows beyond layer 0 depend on the expert
        the block router assigns, so slots share the page set of THEIR
        expert (B/tp slots per set)."""
        self._retire_prefix_pages()
        p_full = self._prefix_full_pages()
        if p_full == 0:
            return
        ps = self.page_size
        p_len = self._prefix_tokens.size
        # capacity check BEFORE any allocation: a partial failure would
        # leave earlier experts' pages pinned with no owner and a prefix
        # that matches but cannot map
        if self.tp * p_full > len(self._free_pages):
            raise ValueError(
                f"page pool too small for the shared prefix: need "
                f"{p_full} pages x tp={self.tp}, have "
                f"{len(self._free_pages)} free of {self.num_pages}"
            )
        for e in range(self.tp):
            pages = self._alloc_pages(p_full)
            assert pages is not None  # guaranteed by the check above
            # scatter coords for every scratch row: full-page rows map to
            # the allocated pages, the partial tail (re-copied per
            # admission) to the sentinel (dropped)
            pages_vec = np.full(p_len, self.num_pages, np.int32)
            pages_vec[: p_full * ps] = np.repeat(pages, ps)
            rows_vec = np.arange(p_len, dtype=np.int32) % ps
            self._scatter_into_pool(
                self._prefix_scratch, pages_vec, rows_vec, e
            )
            self._prefix_pages_by_e[e] = pages
            self._prefix_pages.extend(pages)

    def set_shared_prefix(self, prefix) -> None:
        """Prefill a shared prompt prefix ONCE (e.g. a system prompt);
        every admission whose prompt starts with it reuses the cached
        rows and prefills only the suffix — a chunk-decode at
        ``start=P`` that attends the prefix THROUGH the cache. The K/V
        rows are identical to a full prefill's BY CONSTRUCTION (prefix
        rows depend only on prefix tokens; int8 rows are quantized once
        and read back the same way on both paths); the suffix logits
        agree to float tolerance (the chunk path accumulates attention
        in a different order than a flash prefill would), which the
        lossless tests pin at the token level across einsum AND flash
        prefill kernels. ``None`` clears the prefix and frees its device
        scratch; a set prefix survives ``reset()`` (it is derived from
        params, like the jitted step programs)."""
        if prefix is None:
            self._prefix_tokens = None
            self._prefix_scratch = None
            if self.paged:
                self._retire_prefix_pages()
            return
        prefix = np.asarray(prefix, np.int32)
        if prefix.ndim != 1 or prefix.size == 0:
            raise ValueError("prefix must be a non-empty 1-D token array")
        rep = jnp.asarray(
            np.broadcast_to(prefix, (self.tp, prefix.size)).copy()
        )
        scratch = init_cache(
            self._scratch_cfg, self.tp, prefix.size, mesh=self.mesh
        )
        if self._bucket_prefill:
            # prefix prefill stays exact-length (a one-time cost, and
            # _seed_prefix/page seeding key on the exact row count)
            _, scratch = self._prefill(
                self.params, scratch, rep, jnp.int32(prefix.size - 1)
            )
        else:
            _, scratch = self._prefill(self.params, scratch, rep)
        self._prefix_tokens = prefix
        self._prefix_scratch = jax.block_until_ready(scratch)
        if self.paged:
            try:
                self._seed_prefix_pages()
            except Exception:
                # stay consistent on failure: no half-set prefix (a match
                # with no mapped pages would crash later admissions)
                self._prefix_tokens = None
                self._prefix_scratch = None
                raise

    def _expert_of(self, slot: int) -> int:
        # the block router's per-sequence-stable assignment on a dp=1
        # shard: slot i -> expert i // (B / tp) (models/decode._block_moe)
        return slot // (self.B // self.tp)

    def _prefix_match_len(self, req: Request) -> int:
        """Length of the shared prefix if this prompt starts with it (and
        has a non-empty suffix), else 0."""
        if self._prefix_tokens is None:
            return 0
        p_len = self._prefix_tokens.size
        if req.prompt.size > p_len and np.array_equal(
            req.prompt[:p_len], self._prefix_tokens
        ):
            return p_len
        return 0

    def _pages_needed(self, req: Request) -> int:
        """Fresh pages an admission must allocate (beyond shared prefix
        pages): enough to hold prompt + every generated token. Allocated
        up front — simpler than on-demand growth and it makes admission
        the single capacity decision point."""
        ps = self.page_size
        total = -(-(req.prompt.size + req.max_new) // ps)
        shared = 0
        if self._prefix_match_len(req):
            shared = self._prefix_full_pages()
        return total - shared

    def admit_ready(self) -> int:
        """Admit queued requests into free slots; returns count admitted.

        Paged layout: admission is additionally gated on pool capacity.
        The queue stays FIFO — a head request that does not fit DEFERS
        (counted in ``admissions_deferred``) rather than being skipped,
        so completion-order fairness is preserved under page pressure.
        """
        n = 0
        for slot in range(self.B):
            if self._slot_req[slot] is not None or not self._queue:
                continue
            if self.paged:
                head = self._requests[self._queue[0]]
                need = self._pages_needed(head)
                # submit() screened against the prefix pin count AT
                # SUBMIT TIME; a prefix set/grown while the request was
                # queued can shrink the attainable pages below its worst
                # case. Deferring would spin run() forever — fail loudly
                # at the single capacity decision point instead. (Pages
                # in retired prefix groups DO return when their slots
                # finish, so only the live prefix pin is unattainable.)
                if need > self.num_pages - len(self._prefix_pages):
                    raise RuntimeError(
                        f"queued request {self._queue[0]} needs {need} "
                        f"pages but only "
                        f"{self.num_pages - len(self._prefix_pages)} can "
                        f"ever free ({self.num_pages} total, "
                        f"{len(self._prefix_pages)} pinned by a prefix "
                        f"set after it was submitted)"
                    )
                if need > len(self._free_pages):
                    self.stats.admissions_deferred += 1
                    break
            self._admit(slot, self._queue.popleft())
            n += 1
        return n

    @staticmethod
    def _bucket(n: int) -> int:
        """Next power of two >= n, floored at 16 — the prompt-length
        buckets that bound admission compiles at O(log S_max)."""
        b = 16
        while b < n:
            b *= 2
        return b

    @property
    def queue_depth(self) -> int:
        """Requests queued and not yet admitted — the gauge the load
        driver samples per tick (saturation shows here first)."""
        return len(self._queue)

    def active_slots(self) -> List[int]:
        """Slots currently running a request (a scheduling-policy view;
        the load driver's preemption policy picks among these)."""
        return [s for s in range(self.B) if self._slot_req[s] is not None]

    def slot_request(self, slot: int) -> Optional[int]:
        """The request index slot ``slot`` is running, or None (idle)."""
        return self._slot_req[slot]

    def queue_head(self) -> Optional[int]:
        """Request index waiting at the head of the admission queue, or
        None when the queue is empty."""
        return self._queue[0] if self._queue else None

    def outstanding_tokens(self) -> int:
        """Tokens still to generate across queued requests AND active
        slots — the cluster router's least-outstanding-WORK gauge (a
        queue of long generations is more load than one of short ones,
        which ``queue_depth`` alone cannot see)."""
        queued = sum(self._requests[i].max_new for i in self._queue)
        active = sum(
            self.remaining_budget(s) for s in self.active_slots()
        )
        return queued + active

    def remaining_budget(self, slot: int) -> int:
        """Tokens slot ``slot``'s request may still generate (its
        ``max_new`` minus what it has produced) — the preemption
        policy's work-remaining signal. 0 for an idle slot."""
        req_idx = self._slot_req[slot]
        if req_idx is None:
            return 0
        return self._requests[req_idx].max_new - len(self._slot_new[slot])

    def _admit(self, slot: int, req_idx: int) -> None:
        with telemetry.span(
            "serve.admit", cat="serve", slot=slot, request=req_idx
        ):
            # chaos surface: a plan can wedge/kill/delay the admission
            # path of a live serving world (faults/plan.SITES)
            faults.inject("serve.admit", **self.fault_context)
            self._admit_inner(slot, req_idx)

    def _admit_inner(self, slot: int, req_idx: int) -> None:
        req = self._requests[req_idx]
        S0 = req.prompt.size
        assert S0 + req.max_new <= self.S_max  # screened in submit()
        # tp-replicated prefill into a scratch cache (bucketed: one
        # compile per power-of-two bucket; exact-length when
        # bucket_prefill=False); keep copy e(slot)'s rows + logits. With
        # a shared prefix match, seed the scratch from the prefix cache
        # and chunk-decode only the suffix (O((S0-P)*S0) attention
        # instead of O(S0^2), and no prefix MLP/projection recompute).
        # Bucket-pad tails hold token 0: their K/V rows are garbage the
        # causal mask keeps downstream of every real row, the copy path
        # either drops them (paged sentinel coords) or parks them past
        # ``pos`` where the ragged decode write-then-masked-read
        # overwrites before any read.
        e = self._expert_of(slot)
        P_len = self._prefix_match_len(req)
        if P_len:
            t_real = S0 - P_len
            t_pad = (
                min(self._bucket(t_real), self.S_max - P_len)
                if self._bucket_prefill
                else t_real
            )
            scratch = init_cache(
                self._scratch_cfg, self.tp, P_len + t_pad, mesh=self.mesh
            )
            scratch = self._seed_prefix(scratch, self._prefix_scratch)
            suffix_np = np.zeros((self.tp, t_pad), np.int32)
            suffix_np[:, :t_real] = req.prompt[P_len:]
            logits, scratch = self._chunk(
                self.params, scratch, jnp.asarray(suffix_np), jnp.int32(P_len)
            )
            logits = self._pick(logits, jnp.int32(t_real - 1))
            self.stats.prefix_hits += 1
            self.stats.prefill_tokens_saved += P_len
        else:
            s_pad = (
                min(self._bucket(S0), self.S_max)
                if self._bucket_prefill
                else S0
            )
            scratch = init_cache(
                self._scratch_cfg, self.tp, s_pad, mesh=self.mesh
            )
            prompt_np = np.zeros((self.tp, s_pad), np.int32)
            prompt_np[:, :S0] = req.prompt
            prompt_rep = jnp.asarray(prompt_np)
            if self._bucket_prefill:
                logits, scratch = self._prefill(
                    self.params, scratch, prompt_rep, jnp.int32(S0 - 1)
                )
            else:
                logits, scratch = self._prefill(
                    self.params, scratch, prompt_rep
                )
        if self.paged:
            self._map_slot_pages(slot, req, e, P_len, scratch)
        else:
            self.cache = self._copy_slot(
                self.cache, scratch, jnp.int32(slot), jnp.int32(e)
            )
        first = int(np.asarray(logits)[e].argmax())
        self.pos[slot] = S0
        self.cur_tok[slot] = first
        self._slot_req[slot] = req_idx
        self._slot_new[slot] = [first]
        self._slot_admitted[slot] = self.stats.steps
        self.stats.admissions += 1
        self.stats.generated += 1  # the admission's first token
        # a request can finish at admission (max_new=1 or instant eos)
        self._maybe_finish(slot)

    def _map_slot_pages(self, slot, req, e, P_len, scratch) -> None:
        """Paged admission: build the slot's table row (shared prefix
        pages for the full-prefix span, fresh pages for the rest), push
        it, and scatter the scratch rows the slot OWNS — the shared span
        maps to the sentinel so shared pages are never rewritten (they
        already hold identical rows by construction)."""
        S0 = req.prompt.size
        ps = self.page_size
        p_full = self._prefix_full_pages() if P_len else 0
        # ONE capacity rule: the fresh-page count comes from the same
        # _pages_needed the admit_ready gate used, so the two cannot
        # drift into admit-then-abort
        n_fresh = self._pages_needed(req)
        total = n_fresh + p_full
        fresh = self._alloc_pages(n_fresh)
        # admit_ready gates on capacity; a direct _admit caller that
        # overcommits must fail loudly, not corrupt the pool
        if fresh is None:
            raise RuntimeError(
                f"page pool exhausted admitting slot {slot}: need "
                f"{n_fresh}, free {len(self._free_pages)}"
            )
        row = np.full(self.max_pages, self.num_pages, np.int32)
        if p_full:
            row[:p_full] = self._prefix_pages_by_e[e]
            self._prefix_slots.add(slot)
        row[p_full:total] = fresh
        self._table_np[slot] = row
        self._slot_pages[slot] = fresh
        self._push_table()
        # scatter coords for every scratch row (the scratch may be
        # bucket-padded past S0); the shared-prefix span AND the pad
        # tail map to the sentinel page and drop
        s_len = scratch["k"].shape[2]
        pages_vec = np.full(s_len, self.num_pages, np.int32)
        rows_vec = np.arange(s_len, dtype=np.int32) % ps
        owned_rows = np.arange(p_full * ps, S0, dtype=np.int32)
        pages_vec[owned_rows] = row[owned_rows // ps]
        self._scatter_into_pool(scratch, pages_vec, rows_vec, e)

    def _scatter_into_pool(self, scratch, pages_vec, rows_vec, e) -> None:
        """Run the jitted pool scatter; the table rides outside it (it is
        host-managed state, not part of the copy's pytree)."""
        pool = {k: v for k, v in self.cache.items() if k != "table"}
        pool = self._copy_slot_paged(
            pool,
            scratch,
            jnp.asarray(pages_vec),
            jnp.asarray(rows_vec),
            jnp.int32(e),
        )
        pool["table"] = self.cache["table"]
        self.cache = pool

    def _maybe_finish(self, slot: int) -> None:
        req_idx = self._slot_req[slot]
        req = self._requests[req_idx]
        new = self._slot_new[slot]
        by = None
        if self.eos_id is not None and new and new[-1] == self.eos_id:
            by = "eos"
        elif len(new) >= req.max_new:
            by = "max_new"
        if by is None:
            return
        self.completions.append(
            Completion(
                request_index=req_idx,
                slot=slot,
                tokens=np.concatenate([req.prompt, np.asarray(new, np.int32)]),
                finished_by=by,
                admitted_at_step=self._slot_admitted[slot],
                finished_at_step=self.stats.steps,
            )
        )
        self._slot_req[slot] = None
        self._slot_new[slot] = []
        self.pos[slot] = self.S_max          # park: writes drop, lane idles
        self.cur_tok[slot] = 0
        if self.paged:
            # unmap before the pages are reused: the parked lane's reads
            # must see zeros, not a later tenant's rows
            self._table_np[slot] = self.num_pages
            self._push_table()
            self._release_pages(self._slot_pages[slot])
            self._slot_pages[slot] = []
            self._prefix_slots.discard(slot)
            self._drain_retired_prefix(slot)

    def preempt(self, slot: int, requeue: str = "back") -> int:
        """Preempt slot ``slot`` mid-generation: requeue the request
        with the tokens generated so far folded into its prompt and its
        budget reduced accordingly, park the lane, and (paged) return
        its pages to the pool. Returns the requeued request's index.

        ``requeue`` places the remnant at the ``"back"`` of the queue
        (the head-of-line-relief shape: the freed slot goes to whoever
        was waiting — the default) or at the ``"front"`` (strict
        seniority: the preempted request reclaims the next slot, e.g.
        when preempting only to defragment the page pool).

        No token is ever re-GENERATED — the resumed request greedy-
        continues from exactly where it stopped — but its K/V rows are
        evicted and re-paid as prefill at re-admission: that recompute
        is preemption's honest cost, counted in
        ``stats.kv_evicted_tokens``. The scheduling layer (the
        ``serving_load`` driver's head-of-line policy, or a future
        admission controller) decides WHEN to preempt; the engine only
        provides the mechanism."""
        if requeue not in ("back", "front"):
            raise ValueError(f"requeue must be 'back' or 'front', got {requeue!r}")
        _, remnant = self.evict(slot)
        new_idx = len(self._requests)
        self._requests.append(remnant)
        if requeue == "front":
            self._queue.appendleft(new_idx)
        else:
            self._queue.append(new_idx)
        return new_idx

    def evict(self, slot: int) -> Tuple[int, Request]:
        """``preempt``'s cross-engine half: fold the tokens generated so
        far into the prompt, park the lane, (paged) release its pages —
        and hand the remnant ``Request`` to the CALLER instead of
        requeueing it. This is the serving cluster's drain/migration
        primitive (``ddlb_tpu/serve``): the remnant re-enters a
        SURVIVING engine via the KV-handoff path, while this engine's
        ledger for the request ends here. Returns ``(request_index,
        remnant)``; the same no-token-ever-re-generated contract as
        ``preempt`` (the remnant greedy-continues exactly where it
        stopped, wherever it lands)."""
        req_idx = self._slot_req[slot]
        if req_idx is None:
            raise ValueError(f"slot {slot} is idle; nothing to preempt")
        req = self._requests[req_idx]
        new = self._slot_new[slot]
        remaining = req.max_new - len(new)
        assert remaining >= 1  # else _maybe_finish would have retired it
        prompt = np.concatenate([req.prompt, np.asarray(new, np.int32)])
        self.stats.preemptions += 1
        self.stats.kv_evicted_tokens += int(self.pos[slot])
        telemetry.instant(
            "serve.preempt", cat="serve", slot=slot, request=req_idx,
            generated=len(new), remaining=remaining,
        )
        self._slot_req[slot] = None
        self._slot_new[slot] = []
        self.pos[slot] = self.S_max   # park: writes drop, lane idles
        self.cur_tok[slot] = 0
        if self.paged:
            self._table_np[slot] = self.num_pages
            self._push_table()
            self._release_pages(self._slot_pages[slot])
            self._slot_pages[slot] = []
            self._prefix_slots.discard(slot)
            self._drain_retired_prefix(slot)
        return req_idx, Request(prompt, max_new=remaining)

    def drop_queue(self) -> List[Tuple[int, Request]]:
        """Empty the admission queue, returning ``(request_index,
        Request)`` pairs in FIFO order — the cluster drain's companion
        to ``evict`` for requests an excluded engine accepted but never
        admitted (they re-route to survivors as fresh submissions: no
        KV exists yet, so no handoff to price)."""
        out = [(idx, self._requests[idx]) for idx in self._queue]
        self._queue.clear()
        return out

    # -- the tick ----------------------------------------------------------

    def step(self) -> int:
        """One ragged decode over all lanes; returns active-lane count."""
        active = [s for s in range(self.B) if self._slot_req[s] is not None]
        if not active:
            return 0
        # chaos surface: a plan can stall (kind=hang + duration_s — the
        # decode-slowdown shape the SLO gate must catch), error, or kill
        # the tick path of a live serving world (faults/plan.SITES)
        faults.inject("serve.decode_tick", **self.fault_context)
        # no per-tick span: a locked trace write per decoded token would
        # perturb the measured loop this engine runs inside — ticks are
        # counted into the metrics registry and summarized as one
        # instant at the end of run() instead
        t0 = time.perf_counter()
        logits, self.cache = self._decode(
            self.params,
            self.cache,
            jnp.asarray(self.cur_tok),
            jnp.asarray(self.pos),
        )
        nxt = np.asarray(jnp.argmax(logits, axis=-1), np.int32)
        telemetry.record("serve.decode_s", time.perf_counter() - t0)
        telemetry.record("serve.ticks", 1)
        self.stats.steps += 1
        self.stats.lane_ticks_total += self.B
        self.stats.lane_ticks_active += len(active)
        self.stats.generated += len(active)
        for s in active:
            self.pos[s] += 1
            self.cur_tok[s] = nxt[s]
            self._slot_new[s].append(int(nxt[s]))
            self._maybe_finish(s)
        return len(active)

    def run(self) -> List[Completion]:
        """Admit + step until the queue and all slots drain."""
        with telemetry.span("serve.run", cat="serve"):
            try:
                while True:
                    self.admit_ready()
                    if self.step() == 0 and not self._queue:
                        return self.completions
            finally:
                telemetry.instant(
                    "serve.ticks", cat="serve",
                    steps=self.stats.steps,
                    generated=self.stats.generated,
                    admissions=self.stats.admissions,
                )


