"""ddlb_tpu: TPU-native distributed deep-learning benchmark framework.

Brand-new framework with the capabilities of samnordmann/ddlb
(/root/reference), rebuilt TPU-first: ``jax.distributed`` + device meshes
with ``shard_map`` collectives over ICI/DCN instead of mpirun/NCCL/UCC,
GSPMD and Pallas overlap kernels instead of nvFuser/TransformerEngine.
Public API is lazily exported like the reference package root
(/root/reference/ddlb/__init__.py:5-30).
"""

from __future__ import annotations

__version__ = "0.8.0"

_LAZY = {
    "PrimitiveBenchmarkRunner": ("ddlb_tpu.benchmark", "PrimitiveBenchmarkRunner"),
    "Runtime": ("ddlb_tpu.runtime", "Runtime"),
    "enable_simulation": ("ddlb_tpu.runtime", "enable_simulation"),
    "TPColumnwise": ("ddlb_tpu.primitives.tp_columnwise.base", "TPColumnwise"),
    "TPRowwise": ("ddlb_tpu.primitives.tp_rowwise.base", "TPRowwise"),
}


def __getattr__(name: str):
    if name in _LAZY:
        import importlib

        module_name, attr = _LAZY[name]
        return getattr(importlib.import_module(module_name), attr)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
