"""Cross-rank timeline observatory: clock-aligned world traces.

The flight recorder (``ddlb_tpu/faults/flightrec.py``) answers "which
rank, at which collective" by SEQUENCE number; this module adds the
temporal join: per-rank entries aligned onto one world clock via the
collective rendezvous exchanges the run already executed
(``telemetry.clocksync`` — midpoint estimator over ``runtime.barrier``
/ ``runtime.init`` spans, drift-fitted, uncertainty bound carried on
every aligned event). From the merged timeline it derives:

- a **per-collective skew table**: for every sequence-joined two-sided
  collective, the aligned per-rank entry/exit stamps, the arrival
  spread (time the collective waited on its last arrival), the
  straggler rank, and the waited share of the collective's total time;
- a **worst-rank ranking**: per rank, the skew-wait seconds it caused
  as the last arrival and how often it was the straggler;
- a **critical-path attribution** per rank: wall time split into
  ``compute`` (between-collective work inside a timed measurement
  window), ``host`` (between-collective time outside one — setup,
  validation, bootstrap), ``skew_wait`` (inside a collective, before
  its last arrival) and ``wire`` (inside a collective, after the last
  arrival — the transfer itself).

``scripts/skew_report.py`` renders the document; ``flight_report.py
--json`` embeds the aligned event list so the sequence join and the
time join ship in one document. Stdlib-only, like the rest of the
observatory: the analysis runs post-hoc over JSONL files.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

from ddlb_tpu import telemetry
from ddlb_tpu.faults import flightrec
from ddlb_tpu.telemetry import clocksync

#: sites with all-arrive-then-all-release semantics: their spans are
#: comparable across ranks as ONE world collective per sequence number
#: (runtime.mesh_build is deliberately absent — mesh construction is
#: rank-local work that merely happens everywhere, not a rendezvous)
TWO_SIDED_SITES = (
    "runtime.init",
    "runtime.barrier",
    "runtime.collective",
)

#: worker.phase stage prefixes that bracket the timed measurement
#: window — between-collective gaps inside it attribute to compute,
#: outside it to host (setup / validation / bootstrap orchestration)
_TIMING_BEGIN_PREFIX = "warmup done"
_TIMING_END_PREFIX = "measured"


def json_safe(obj: Any) -> Any:
    """``obj`` with every non-finite float replaced by None — the
    timeline documents carry honest inf/NaN sentinels (an unalignable
    rank's uncertainty, a defaulted skew column), and ``json.dumps``
    would otherwise emit bare ``Infinity``/``NaN``, which strict JSON
    parsers (jq, JSON.parse) reject wholesale. Applied by every
    ``--json`` renderer right before dumping."""
    import math

    if isinstance(obj, dict):
        return {k: json_safe(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [json_safe(v) for v in obj]
    if isinstance(obj, float) and not math.isfinite(obj):
        return None
    return obj


def read_rank_events(run_dir: str) -> Dict[int, List[Dict[str, Any]]]:
    """Per-rank flight-recorder events under ``run_dir``, reduced to
    each rank's dominant pid stream — discovery, parsing, and pid
    selection all shared with the sequence join (``flightrec.rank_files``
    / ``read_rank_file`` / ``dominant_stream``), so the two joins
    cannot diverge on what counts as a rank's record."""
    ranks: Dict[int, List[Dict[str, Any]]] = {}
    for rank, path in flightrec.rank_files(run_dir).items():
        stream = flightrec.dominant_stream(flightrec.read_rank_file(path))
        if stream:
            ranks[rank] = stream
    return ranks


def pair_spans(events: Sequence[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Join one rank's B/E transitions by sequence number into spans;
    instants (``I``) become zero-width entries, un-ended ``B`` entries
    (a wedged or killed collective) keep ``t1: None``."""
    spans: Dict[int, Dict[str, Any]] = {}
    order: List[int] = []
    for event in events:
        ph = event.get("ph")
        if ph not in ("B", "E", "I"):
            continue
        try:
            seq = int(event.get("seq", 0))
        except (TypeError, ValueError):
            continue
        if ph == "B":
            spans[seq] = {
                "seq": seq,
                "site": str(event.get("site", "")),
                "t0": float(event.get("t", 0.0)),
                "t1": None,
                "ph": "span",
                "stage": event.get("stage"),
                "impl": event.get("impl"),
            }
            order.append(seq)
        elif ph == "E" and seq in spans:
            spans[seq]["t1"] = float(event.get("t", 0.0))
        elif ph == "I":
            t = float(event.get("t", 0.0))
            spans[seq] = {
                "seq": seq,
                "site": str(event.get("site", "")),
                "t0": t,
                "t1": t,
                "ph": "instant",
                "stage": event.get("stage"),
                "impl": event.get("impl"),
            }
            order.append(seq)
    return [spans[seq] for seq in order]


def _exchange_spans(
    spans_by_rank: Dict[int, List[Dict[str, Any]]],
    sites: Sequence[str],
) -> Dict[int, Dict[int, Dict[str, Any]]]:
    """``{seq: {rank: span}}`` for sequence numbers where EVERY rank
    completed a span at the same site in ``sites`` — the world
    collectives the sequence join certifies as one event."""
    ranks = sorted(spans_by_rank)
    per_rank = {
        rank: {
            s["seq"]: s
            for s in spans_by_rank[rank]
            if s["ph"] == "span" and s["t1"] is not None
            and s["site"] in sites
        }
        for rank in ranks
    }
    if not ranks:
        return {}
    shared = set.intersection(*(set(m) for m in per_rank.values()))
    out: Dict[int, Dict[int, Dict[str, Any]]] = {}
    for seq in sorted(shared):
        site = per_rank[ranks[0]][seq]["site"]
        if all(per_rank[r][seq]["site"] == site for r in ranks):
            out[seq] = {r: per_rank[r][seq] for r in ranks}
    return out


def _timing_windows(
    spans: Sequence[Dict[str, Any]], align
) -> List[List[float]]:
    """Aligned [begin, end] measurement windows from a rank's
    ``worker.phase`` marks (open windows close at +inf)."""
    windows: List[List[float]] = []
    for span in spans:
        if span["site"] != "worker.phase" or span.get("stage") is None:
            continue
        stage = str(span["stage"])
        t = align(span["t0"])
        if stage.startswith(_TIMING_BEGIN_PREFIX):
            windows.append([t, float("inf")])
        elif stage.startswith(_TIMING_END_PREFIX) and windows and (
            windows[-1][1] == float("inf")
        ):
            windows[-1][1] = t
    return windows


def _in_windows(t: float, windows: Sequence[Sequence[float]]) -> bool:
    return any(w[0] <= t <= w[1] for w in windows)


def build_world_timeline(
    run_dir: str, expected_ranks: Optional[int] = None
) -> Dict[str, Any]:
    """The merged, clock-aligned world timeline of one flight-recorder
    run dir — see the module docstring for the document's sections."""
    with telemetry.span("timeline.merge", cat="timeline"):
        return _build(run_dir, expected_ranks)


def _build(run_dir: str, expected_ranks: Optional[int]) -> Dict[str, Any]:
    rank_events = read_rank_events(run_dir)
    spans_by_rank = {
        rank: pair_spans(events) for rank, events in rank_events.items()
    }
    ranks = sorted(spans_by_rank)
    missing = (
        [r for r in range(expected_ranks) if r not in spans_by_rank]
        if expected_ranks
        else []
    )
    doc: Dict[str, Any] = {
        "run_dir": run_dir,
        "ranks": ranks,
        "missing_ranks": missing,
    }
    if not ranks:
        doc.update(
            alignment="none", offsets={}, events=[], collectives=[],
            attribution={}, worst_ranks=[], total_skew_s=0.0,
            headline=f"no flight files under {run_dir}",
        )
        return doc

    # -- offset fit over the certified exchange collectives ------------
    fit_exchanges = _exchange_spans(spans_by_rank, clocksync.FIT_SITES)
    fits = clocksync.fit_offsets(
        {
            rank: [
                (fit_exchanges[seq][rank]["t0"], fit_exchanges[seq][rank]["t1"])
                for seq in sorted(fit_exchanges)
            ]
            for rank in ranks
        }
    )
    # same minimum-exchange guard as the in-row fold: one or two
    # exchanges are not a clock model — a genuinely late rank at the
    # only barrier would become its "offset", halving the real skew
    # and shifting blame onto the innocent peer (raw stamps are exact
    # on one host; a multi-host dir without enough exchanges honestly
    # reports alignment "none")
    aligned = (
        len(ranks) > 1
        and len(fit_exchanges) >= clocksync.MIN_FIT_EXCHANGES
    )
    doc["alignment"] = "barrier" if aligned else "none"
    doc["offsets"] = {rank: fits[rank].as_dict() for rank in ranks}

    def align(rank: int, t: Optional[float]) -> Optional[float]:
        if t is None:
            return None
        return fits[rank].align(t) if aligned else t

    # -- the merged event list (every entry, aligned + uncertainty) ----
    origin = min(
        (
            align(rank, s["t0"])
            for rank in ranks
            for s in spans_by_rank[rank]
        ),
        default=0.0,
    )
    events: List[Dict[str, Any]] = []
    for rank in ranks:
        unc = fits[rank].uncertainty_s if aligned else 0.0
        for span in spans_by_rank[rank]:
            t0 = align(rank, span["t0"])
            t1 = align(rank, span["t1"])
            events.append(
                {
                    "rank": rank,
                    "seq": span["seq"],
                    "site": span["site"],
                    "ph": span["ph"],
                    "ts": span["t0"],
                    "aligned_ts": t0,
                    "rel_s": t0 - origin,
                    "dur_s": (t1 - t0) if t1 is not None else None,
                    "unc_s": unc,
                    **(
                        {"stage": span["stage"]}
                        if span.get("stage") is not None
                        else {}
                    ),
                }
            )
    events.sort(key=lambda e: (e["aligned_ts"], e["rank"], e["seq"]))
    doc["events"] = events

    # -- per-collective skew table --------------------------------------
    world = _exchange_spans(spans_by_rank, TWO_SIDED_SITES)
    collectives: List[Dict[str, Any]] = []
    caused = {rank: 0.0 for rank in ranks}
    strag_counts = {rank: 0 for rank in ranks}
    total_skew = 0.0
    unc_total = max(
        (fits[r].uncertainty_s for r in ranks if r != fits[r].ref_rank),
        default=0.0,
    ) if aligned else 0.0
    releases: Dict[int, float] = {}  # per-seq release, reused below
    for seq in sorted(world):
        per_rank = world[seq]
        enters = {r: align(r, per_rank[r]["t0"]) for r in ranks}
        exits = {r: align(r, per_rank[r]["t1"]) for r in ranks}
        first = min(enters.values())
        release = max(enters.values())
        releases[seq] = release
        end = max(exits.values())
        skew = release - first
        straggler = max(ranks, key=lambda r: enters[r])
        total = max(end - first, 0.0)
        collectives.append(
            {
                "seq": seq,
                "site": per_rank[ranks[0]]["site"],
                "rel_s": first - origin,
                "skew_enter_s": skew,
                "skew_exit_s": max(exits.values()) - min(exits.values()),
                "total_s": total,
                "straggler_rank": straggler if skew > 0.0 else -1,
                "straggler_frac": skew / total if total > 0.0 else 0.0,
                "unc_s": unc_total,
                "ranks": {
                    r: {
                        "enter_s": enters[r] - origin,
                        "exit_s": exits[r] - origin,
                        "late_s": enters[r] - first,
                    }
                    for r in ranks
                },
            }
        )
        total_skew += skew
        caused[straggler] += skew
        if skew > 0.0:
            strag_counts[straggler] += 1
    doc["collectives"] = collectives
    doc["total_skew_s"] = total_skew

    # -- worst-rank ranking ---------------------------------------------
    doc["worst_ranks"] = [
        {
            "rank": rank,
            "caused_skew_s": caused[rank],
            "straggler_count": strag_counts[rank],
        }
        for rank in sorted(ranks, key=lambda r: -caused[r])
    ]

    # -- critical-path attribution per rank ------------------------------
    attribution: Dict[int, Dict[str, float]] = {}
    for rank in ranks:
        windows = _timing_windows(
            spans_by_rank[rank], lambda t, _r=rank: align(_r, t)
        )
        acc = {"compute_s": 0.0, "wire_s": 0.0, "skew_wait_s": 0.0,
               "host_s": 0.0}
        prev_exit: Optional[float] = None
        for seq in sorted(world):
            per_rank = world[seq]
            enter = align(rank, per_rank[rank]["t0"])
            exit_ = align(rank, per_rank[rank]["t1"])
            release = releases[seq]
            if prev_exit is not None and enter > prev_exit:
                gap = enter - prev_exit
                mid = (prev_exit + enter) / 2.0
                key = "compute_s" if _in_windows(mid, windows) else "host_s"
                acc[key] += gap
            acc["skew_wait_s"] += max(0.0, min(release, exit_) - enter)
            acc["wire_s"] += max(0.0, exit_ - max(release, enter))
            prev_exit = exit_
        attribution[rank] = acc
    doc["attribution"] = attribution

    # -- headline --------------------------------------------------------
    if not collectives:
        doc["headline"] = (
            f"{len(ranks)} rank(s), no sequence-joined two-sided "
            f"collectives — nothing to attribute"
        )
    elif total_skew <= 0.0:
        doc["headline"] = (
            f"{len(collectives)} collective(s) across {len(ranks)} "
            f"rank(s); zero arrival skew"
        )
    else:
        worst = doc["worst_ranks"][0]
        doc["headline"] = (
            f"rank {worst['rank']} caused "
            f"{worst['caused_skew_s']:.3f}s of {total_skew:.3f}s total "
            f"arrival skew across {len(collectives)} collective(s) "
            f"(last arrival {worst['straggler_count']}x)"
        )
    return doc
