"""Run-history store: append-only JSONL bank of result rows across runs.

Every runner path — the sweep runner (in-process and pooled), the
hardware queue's ``PooledRunner``/``run_isolated``, and ``bench.py``'s
headline — banks its rows here automatically when ``DDLB_TPU_HISTORY``
points at a directory (the package's "" = disabled convention; the
un-gated fast path is one env lookup). The bank is what turns isolated
captures into a longitudinal record: the regression detector
(``observatory.regress`` / ``scripts/observatory_report.py``) compares
a run against the per-key history, and the ROADMAP's autotuning work
reads winners back per chip spec.

Format: one JSON line per banked row in ``<dir>/history.jsonl`` —

- ``key``: the stable cross-run identity (chip spec + family + base
  implementation + merged option string + shape/dtype + world size),
  computed from the row's own columns so every banking path derives it
  identically;
- ``run_id``: groups one driver process's rows (``DDLB_TPU_RUN_ID``
  override for multi-process captures that must share an id);
- ``git_rev``: the repo revision the row was measured at, so a
  regression report can say WHICH commit moved a number;
- ``banked_at``: epoch seconds; ``kind``: ``row`` (runner schema) or
  ``bench`` (headline artifact schema);
- ``row``: the full result row, untouched.

Append-only with one flushed line per row (the crash-safety contract of
the incremental CSV and the trace shards: a killed run loses at most
the row in flight), and best-effort by construction — a full disk or an
unwritable directory warns once and disables, never aborts the sweep.
"""

from __future__ import annotations

import json
import os
import subprocess
import time
from typing import (
    Any,
    Callable,
    Collection,
    Dict,
    Iterator,
    List,
    Optional,
    Union,
)

from ddlb_tpu import envs, telemetry

HISTORY_FILENAME = "history.jsonl"

#: the row columns that form the cross-run identity. Everything that
#: changes what is being measured is in; everything that is a
#: measurement outcome (times, validity, retries) is out.
KEY_COLUMNS = (
    "chip",
    "primitive",
    "base_implementation",
    "option",
    "m",
    "n",
    "k",
    "dtype",
    "world_size",
    "time_measurement_backend",
)

_run_id: Optional[str] = None
_git_rev: Optional[str] = None
_bank_failed: Optional[str] = None


def run_id() -> str:
    """This driver process's run identity: ``DDLB_TPU_RUN_ID`` when set
    (multi-process captures that must bank under one id), else a
    timestamp+pid string generated once per process."""
    global _run_id
    from ddlb_tpu import envs

    env = envs.get_run_id_override()
    if env:
        return env
    if _run_id is None:
        _run_id = time.strftime(
            "%Y%m%dT%H%M%SZ", time.gmtime()
        ) + f"-p{os.getpid()}"
    return _run_id


def git_rev() -> str:
    """The repo's short revision, cached per process; "" when the repo
    state is unreadable (a deployment from a tarball must still bank)."""
    global _git_rev
    if _git_rev is None:
        repo = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
        try:
            out = subprocess.run(
                ["git", "rev-parse", "--short", "HEAD"],
                cwd=repo,
                capture_output=True,
                text=True,
                timeout=10,
            )
            _git_rev = out.stdout.strip() if out.returncode == 0 else ""
        except (OSError, subprocess.TimeoutExpired):
            _git_rev = ""
    return _git_rev


def row_key(row: Dict[str, Any]) -> str:
    """The stable cross-run identity of one result row, as a sorted JSON
    string of ``KEY_COLUMNS`` (missing columns key as None — a row from
    an older schema still lands in a consistent bucket)."""
    return json.dumps(
        {col: row.get(col) for col in KEY_COLUMNS},
        sort_keys=True,
        default=str,
    )


def history_path(directory: Optional[str] = None) -> Optional[str]:
    """The history file path, or None when banking is disabled."""
    directory = directory or envs.get_history_dir()
    if not directory:
        return None
    return os.path.join(directory, HISTORY_FILENAME)


def bank_row(
    row: Dict[str, Any],
    kind: str = "row",
    run: Optional[str] = None,
    directory: Optional[str] = None,
) -> bool:
    """Append one result row to the history bank; returns whether it was
    banked (False when disabled or on a write failure — best effort, a
    history problem must never fail the measurement it records)."""
    global _bank_failed
    path = history_path(directory)
    if path is None or not isinstance(row, dict):
        return False
    record = {
        "key": row_key(row),
        "run_id": run or run_id(),
        "git_rev": git_rev(),
        "banked_at": time.time(),
        "kind": kind,
        "row": row,
    }
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "a", encoding="utf-8") as f:
            f.write(json.dumps(record, default=str) + "\n")
    except OSError as exc:
        if _bank_failed != path:  # one warning per path, not per row
            _bank_failed = path
            telemetry.warn(
                f"history bank {path} is not writable ({exc}); "
                f"run-history disabled for this process"
            )
        return False
    return True


def load_history(directory: Optional[str] = None) -> List[Dict[str, Any]]:
    """Every record in the bank, oldest first. Corrupt lines (a process
    killed mid-write) are skipped — the same tolerance as the trace
    reader. Empty list when banking is disabled or the file is absent."""
    path = history_path(directory)
    if path is None or not os.path.exists(path):
        return []
    records: List[Dict[str, Any]] = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                record = json.loads(line)
            except ValueError:
                continue
            if isinstance(record, dict) and isinstance(
                record.get("row"), dict
            ):
                records.append(record)
    return records


def _matches(value: Any, want: Union[None, str, Collection[str]]) -> bool:
    if want is None:
        return True
    if isinstance(want, str):
        return value == want
    return value in want


def iter_history(
    directory: Optional[str] = None,
    *,
    kind: Optional[str] = "row",
    chip: Union[None, str, Collection[str]] = None,
    family: Union[None, str, Collection[str]] = None,
    impl: Union[None, str, Collection[str]] = None,
    predicate: Optional[Callable[[Dict[str, Any]], bool]] = None,
) -> Iterator[Dict[str, Any]]:
    """Stream banked records oldest-first under key-column predicates.

    The calibration fitter reads the whole bank but fits one
    ``(chip, backend)`` group at a time; this is the streaming form of
    ``load_history`` that never materializes the full bank. Filters:
    ``kind`` (None = every kind), and ``chip`` / ``family`` / ``impl``
    each accepting one string or any collection of strings, matched
    against the row's ``chip`` / ``primitive`` / ``base_implementation``
    columns; ``predicate(record)`` for anything else. Same tolerance
    contract as ``load_history``: a torn tail (a process killed
    mid-append leaves a truncated last line) or any other corrupt line
    is skipped, and rows from older or newer schemas pass through —
    filters only read the columns they name, unknown columns ride
    along untouched.
    """
    path = history_path(directory)
    if path is None or not os.path.exists(path):
        return
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                record = json.loads(line)
            except ValueError:
                continue
            if not (
                isinstance(record, dict) and isinstance(record.get("row"), dict)
            ):
                continue
            if kind is not None and record.get("kind", "row") != kind:
                continue
            row = record["row"]
            if not _matches(row.get("chip"), chip):
                continue
            if not _matches(row.get("primitive"), family):
                continue
            if not _matches(row.get("base_implementation"), impl):
                continue
            if predicate is not None and not predicate(record):
                continue
            yield record
