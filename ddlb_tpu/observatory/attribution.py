"""Measured-overlap attribution: join a measurement to its cost model.

The perfmodel's ``roofline_frac`` says how close a row came to its
combined lower bound, but for an overlap member that one number hides
the question the ROADMAP's fusion work actually asks: *how much of the
theoretically hideable communication time did this implementation
actually hide?* T3 (arxiv 2401.16677) makes the case that the achieved
overlap fraction — not end-to-end latency — is the metric that makes
overlap regressions actionable, and "Fused Computation-Collective
Operations" (arxiv 2305.06942) shows per-phase attribution is what
turns a regression flag into a diagnosis.

Definitions, from the same closed-form terms the perfmodel computes
(``compute_s`` / ``comm_s`` / ``hbm_s``, each a per-call lower bound):

- ``t_serial  = max(compute + comm, hbm)`` — the sequential schedule's
  floor (collective and GEMM back to back);
- ``t_overlap = max(compute, comm, hbm)`` — the perfect-overlap floor;
  for a chunked-fusion member (``chunks`` passed, the engine's
  ``chunk_count``) the floor is the member's OWN schedule,
  ``max(compute, comm) + min(compute, comm)/chunks`` — perfect overlap
  minus the pipeline fill/drain (perfmodel.cost's chunk term);
- ``hideable  = t_serial - t_overlap`` — the communication (or compute)
  time the member's schedule can hide at best;
- ``measured_overlap_frac = (t_serial - measured) / hideable`` clamped
  into [0, 1] — 1.0 means the member achieved the analytical overlap
  bound, 0.0 means it ran no better than the sequential schedule.
  Defined only for ``COST_SCHEDULE == "overlap"`` members with a
  hideable window meaningfully above float noise: a 1-device
  collective has nothing to hide, and a schedule whose floor already
  hides everything it ever could (``t_serial == t_overlap`` — e.g. the
  chunked engine at ``chunk_count=1``, or a member with a zero comm or
  compute term) has a ~0 denominator that used to escape as inf/junk;
  both are clamped to NaN (schema: "no hideable window at this
  schedule's granularity"), so the column is trustworthy on every row;
- per-phase breakdown: ``phase_compute_s`` / ``phase_comm_s`` are the
  model's phase floors, and ``phase_idle_s = max(0, measured -
  t_overlap)`` is the time no roofline term explains — launch overhead,
  scheduling bubbles, idle wait. Predicted-vs-measured divergence is
  thereby a first-class field on the row itself: a regression that
  grows ``phase_idle_s`` is overhead, one that shrinks
  ``measured_overlap_frac`` is lost pipelining.

Zero-dependency and duck-typed like ``perfmodel.cost``: ``attribute``
takes anything exposing ``compute_s`` / ``comm_s`` / ``hbm_s`` (a
``CostEstimate`` or a test stub), so the JAX-free tiers and tests can
drive it with hand-computed terms.
"""

from __future__ import annotations

from typing import Any, Dict

_NAN = float("nan")

#: relative floor under which a hideable window counts as "nothing to
#: hide": dividing by a denominator this far below the serial floor
#: produces junk fractions (inf at exactly 0 pre-clamp), not signal
_HIDEABLE_RTOL = 1e-9

#: the attribution columns every result row carries (CSV header is fixed
#: by the first row written, so defaults must exist on measured, crashed
#: and quarantined rows alike — NaN marks "no measurement/model here")
ATTRIBUTION_ROW_DEFAULTS: Dict[str, Any] = {
    "measured_overlap_frac": _NAN,
    "phase_compute_s": _NAN,
    "phase_comm_s": _NAN,
    "phase_idle_s": _NAN,
}


def _term(est: Any, name: str) -> float:
    value = getattr(est, name, None)
    if value is None and isinstance(est, dict):
        value = est.get(name)
    try:
        value = float(value)
    except (TypeError, ValueError):
        return 0.0
    return value if value == value and value >= 0.0 else 0.0


def attribute(
    est: Any, schedule: str, measured_s: float, chunks: Any = None
) -> Dict[str, Any]:
    """The attribution columns for one row.

    ``est`` duck-types the perfmodel estimate (``compute_s`` /
    ``comm_s`` / ``hbm_s`` attributes or dict keys, seconds per call);
    ``schedule`` is the impl's ``COST_SCHEDULE``; ``measured_s`` the
    measured median; ``chunks`` the chunked-fusion pipeline depth when
    the member declares one (``Primitive.overlap_chunks``) — it tilts
    ``t_overlap`` to the member's own fill/drain-adjusted floor.
    Returns the ``ATTRIBUTION_ROW_DEFAULTS`` key set, with NaN wherever
    the quantity is undefined (no measurement, no hideable window at
    this schedule's granularity, non-overlap schedule for the overlap
    fraction).
    """
    compute = _term(est, "compute_s")
    comm = _term(est, "comm_s")
    hbm = _term(est, "hbm_s")
    out = dict(ATTRIBUTION_ROW_DEFAULTS)
    if compute or comm or hbm:
        out["phase_compute_s"] = compute
        out["phase_comm_s"] = comm
    measured_ok = (
        isinstance(measured_s, (int, float))
        and measured_s == measured_s  # not NaN
        and measured_s > 0.0
    )
    if not measured_ok:
        return out
    t_serial = max(compute + comm, hbm)
    t_overlap = max(compute, comm, hbm)
    if isinstance(chunks, (int, float)) and chunks >= 1:
        t_overlap = max(
            hbm, max(compute, comm) + min(compute, comm) / float(chunks)
        )
    if t_overlap > 0.0:
        out["phase_idle_s"] = max(0.0, float(measured_s) - t_overlap)
    hideable = t_serial - t_overlap
    if schedule == "overlap" and hideable > _HIDEABLE_RTOL * t_serial:
        frac = (t_serial - float(measured_s)) / hideable
        out["measured_overlap_frac"] = min(1.0, max(0.0, frac))
    return out


def rows_from_events(events) -> list:
    """Per-row span groups from a trace-event list: one record per
    ``worker.row`` span, with every complete span CONTAINED in it (same
    pid + tid, [ts, ts+dur] within the row's interval) aggregated into a
    per-category phase breakdown.

    This is the warm-pool-aware grouping ``scripts/trace_report.py``
    uses: a long-lived pool worker emits MANY rows into one process
    shard, so per-row aggregation must group by row span, not by pid
    (the pre-pool assumption of one row per process). The tid filter
    keeps a background prefetch compile (same pid, its own thread) out
    of the row it merely overlaps in time.
    """
    import bisect

    spans = [
        e
        for e in events
        if e.get("ph") == "X"
        and isinstance(e.get("ts"), (int, float))
        and isinstance(e.get("dur"), (int, float))
    ]
    # bucket once by (pid, tid), sorted by start time: each row span
    # then scans only its bisected candidate window instead of the
    # whole trace (a pooled sweep has hundreds of rows over tens of
    # thousands of spans — the naive product is minutes of Python)
    buckets: Dict[tuple, list] = {}
    for e in spans:
        buckets.setdefault((e.get("pid"), e.get("tid")), []).append(e)
    starts: Dict[tuple, list] = {}
    for key, bucket in buckets.items():
        bucket.sort(key=lambda e: e["ts"])
        starts[key] = [e["ts"] for e in bucket]
    rows = []
    for row_span in spans:
        if row_span.get("name") != "worker.row":
            continue
        r0 = row_span["ts"]
        r1 = r0 + row_span["dur"]
        args = row_span.get("args") or {}
        phases: Dict[str, float] = {}
        key = (row_span.get("pid"), row_span.get("tid"))
        bucket = buckets[key]
        # µs clock granularity slack, matching the span tests
        lo = bisect.bisect_left(starts[key], r0 - 1.0)
        for e in bucket[lo:]:
            if e["ts"] > r1 + 1.0:
                break  # sorted by start: nothing later can be contained
            if e is row_span:
                continue
            if e["ts"] + e["dur"] > r1 + 1.0:
                continue
            cat = e.get("cat") or "uncategorized"
            phases[cat] = phases.get(cat, 0.0) + e["dur"] / 1e3
        rows.append(
            {
                "impl": args.get("impl", ""),
                "primitive": args.get("primitive", ""),
                "pid": row_span.get("pid"),
                "ts_us": r0,
                "dur_ms": row_span["dur"] / 1e3,
                "phases": phases,
            }
        )
    rows.sort(key=lambda r: r["ts_us"])
    return rows
