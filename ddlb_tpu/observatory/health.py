"""Persistent-straggler indictment: transient hiccup vs bad hardware.

The skew fold (ISSUE 14) says which rank a single row's collectives
waited on; the skew GATE says a row waits more than its history. What
neither says is whether the straggler is a one-off — a scheduler
stall, a compaction pause — or a *persistently degraded component* (a
slow ICI link, a thermally-throttled chip: the dominant failure shape
of The Big Send-off's reliability-at-scale regime) that every future
run will hit again. This module is that verdict: it folds straggler
observations across rows and runs into a per-rank/per-link health
verdict the mitigating relaunch (``cli/launch.py --supervise``) can
act on.

An **observation** is one corroborating piece of evidence: a banked
result row's ``straggler_rank`` / ``skew_enter_s`` / ``clock_unc_s``
columns (``observations_from_history``), or one clock-aligned world
collective from a flight-recorder timeline
(``observations_from_timeline``). An observation *qualifies* only when

- it names a rank (``straggler_rank >= 0``),
- its skew clears the absolute noise floor ``MIN_SKEW_S`` (clean-run
  scheduler jitter must never accumulate into an indictment), and
- its skew exceeds the observation's own clock-alignment uncertainty
  bound — a skew claim inside ``clock_unc_s`` is noise by definition
  (the same guard ``regress.detect_skew`` applies). A row whose fold
  made NO alignment claim (NaN ``clock_unc_s`` on a multi-process row)
  contributes nothing.

The **verdict** (``verdict_from_observations``) refuses to indict on
thin evidence: a persistent indictment needs at least
``MIN_OBSERVATIONS`` qualifying observations AND one rank causing at
least ``DOMINANCE`` of them — a single skewed row is refused outright,
and alternating stragglers (ranks trading places run to run: host
noise, not hardware) classify *transient*. A persistent verdict names
the rank, the candidate hardware (the chip and its ring-neighbor
links, the fault model's ``link_label`` vocabulary), and the evidence
counts.

``scripts/health_report.py`` renders the verdict;
``regress.detect_all`` gates it next to the time/SLO/skew detectors;
the supervised launcher consults ``relaunch_policy`` before shrinking
a world around an indicted rank. Stdlib-only, like the rest of the
observatory.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

from ddlb_tpu.observatory.regress import finite

#: a persistent indictment needs at least this many qualifying
#: observations — a single skewed row (or one skewed collective) is
#: refused outright, whatever its magnitude
MIN_OBSERVATIONS = 3

#: ...and one rank must cause at least this share of them: stragglers
#: alternating between ranks are host noise (transient), not hardware
DOMINANCE = 0.6

#: absolute per-observation noise floor, seconds of arrival skew —
#: below it an observation never qualifies (clean-run scheduler jitter
#: lives here; the same philosophy as regress.SKEW_METRICS' floors)
MIN_SKEW_S = 0.05

HEALTHY = "healthy"
TRANSIENT = "transient"
PERSISTENT = "persistent"


def qualifying_rank(
    rank: Any, skew_s: Any, unc_s: Any, min_skew_s: float
) -> Optional[int]:
    """The qualifying rank of one observation, or None. ``unc_s``
    semantics: a finite bound gates the skew (within the bound = no
    claim); NaN/None means the source made no alignment claim at all —
    refused, matching ``detect_skew``'s NaN-uncertainty rule; 0.0 is an
    exact-clock claim (raw single-host stamps) and gates nothing."""
    try:
        r = int(rank)
    except (TypeError, ValueError):
        return None
    if r < 0:
        return None
    skew = finite(skew_s)
    if skew is None or skew <= min_skew_s:
        return None
    unc = finite(unc_s)
    if unc is None:
        return None
    if skew <= unc:
        return None
    return r


def observations_from_history(
    records: Sequence[Dict[str, Any]],
    run_id: Optional[str] = None,
) -> List[Dict[str, Any]]:
    """Observations from banked history records (``store.load_history``
    shape): one per row that carries the skew columns. ``run_id``
    restricts to one run's rows (the launcher's per-attempt check);
    None folds the whole bank (the longitudinal report)."""
    out: List[Dict[str, Any]] = []
    for record in records:
        if record.get("kind", "row") != "row":
            continue
        if run_id is not None and record.get("run_id") != run_id:
            continue
        row = record.get("row") or {}
        if "straggler_rank" not in row:
            continue
        out.append(
            {
                "rank": row.get("straggler_rank"),
                "skew_s": row.get("skew_enter_s"),
                "unc_s": row.get("clock_unc_s"),
                "source": "row",
                "run_id": record.get("run_id"),
                "label": str(row.get("implementation") or ""),
            }
        )
    return out


def observations_from_rows(
    rows: Sequence[Dict[str, Any]], run_id: Optional[str] = None
) -> List[Dict[str, Any]]:
    """Observations from bare result rows (a current, not-yet-banked
    run — the ``detect_all`` surface)."""
    return observations_from_history(
        [{"kind": "row", "row": row, "run_id": run_id} for row in rows],
    )


def observations_from_timeline(doc: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Observations from a world-timeline document
    (``observatory.timeline.build_world_timeline``): one per
    sequence-joined two-sided collective. Multi-rank timelines that
    could not align (``alignment: none`` — too few exchange points)
    contribute nothing: their raw cross-rank stamps carry no claim the
    verdict could trust (single-host dirs included, conservatively —
    the launcher's worlds fit plenty of barrier exchanges)."""
    if doc.get("alignment") != "barrier":
        return []
    out: List[Dict[str, Any]] = []
    for coll in doc.get("collectives", ()):
        out.append(
            {
                "rank": coll.get("straggler_rank"),
                "skew_s": coll.get("skew_enter_s"),
                "unc_s": coll.get("unc_s"),
                "source": "collective",
                "run_id": doc.get("run_dir"),
                "label": f"seq {coll.get('seq')} {coll.get('site')}",
            }
        )
    return out


def link_candidates(rank: int, world: Optional[int]) -> List[str]:
    """The hardware a persistently-straggling rank indicts: its chip
    and the ring-neighbor links it receives/sends on — the fault
    model's ``link_label`` vocabulary (``faults.plan``), so a chaos
    battery can assert the seeded link is among the candidates. A
    straggler observation cannot distinguish a slow chip from a slow
    inbound link; the verdict honestly names all three."""
    out = [f"chip[{rank}]"]
    if world and world > 1:
        prev = (rank - 1) % world
        out.append(f"ici[{prev}->{rank}]")
        out.append(f"ici[{rank}->{(rank + 1) % world}]")
    return out


def verdict_from_observations(
    observations: Sequence[Dict[str, Any]],
    world: Optional[int] = None,
    min_observations: int = MIN_OBSERVATIONS,
    dominance: float = DOMINANCE,
    min_skew_s: float = MIN_SKEW_S,
) -> Dict[str, Any]:
    """Fold observations into the health verdict (module docstring)."""
    counts: Dict[int, int] = {}
    caused: Dict[int, float] = {}
    runs: Dict[int, set] = {}
    qualifying = 0
    for obs in observations:
        rank = qualifying_rank(
            obs.get("rank"), obs.get("skew_s"), obs.get("unc_s"), min_skew_s
        )
        if rank is None:
            continue
        qualifying += 1
        counts[rank] = counts.get(rank, 0) + 1
        caused[rank] = caused.get(rank, 0.0) + float(obs["skew_s"])
        runs.setdefault(rank, set()).add(obs.get("run_id"))
    doc: Dict[str, Any] = {
        "observations": len(observations),
        "qualifying": qualifying,
        "per_rank": {
            r: {
                "count": counts[r],
                "caused_s": caused[r],
                "runs": len(runs[r]),
            }
            for r in sorted(counts)
        },
    }
    if qualifying == 0:
        doc.update(
            status=HEALTHY, rank=-1, share=0.0, links=[],
            reason="no qualifying straggler observations",
        )
        return doc
    top = max(counts, key=lambda r: (counts[r], caused[r]))
    share = counts[top] / qualifying
    doc.update(rank=top, share=share)
    if counts[top] < min_observations:
        doc.update(
            status=TRANSIENT, links=[],
            reason=(
                f"rank {top} straggled {counts[top]}x — below the "
                f"{min_observations}-observation corroboration floor "
                f"(a single skewed row never indicts)"
            ),
        )
        return doc
    if share < dominance:
        doc.update(
            status=TRANSIENT, rank=-1, links=[],
            reason=(
                f"stragglers alternate across ranks (top rank {top} "
                f"caused only {share:.0%} of {qualifying} qualifying "
                f"observations, dominance floor {dominance:.0%}) — host "
                f"noise, not hardware"
            ),
        )
        return doc
    doc.update(
        status=PERSISTENT,
        links=link_candidates(top, world),
        reason=(
            f"rank {top} was the straggler in {counts[top]} of "
            f"{qualifying} qualifying observations ({share:.0%}) across "
            f"{len(runs[top])} run(s), causing {caused[top]:.3f}s of "
            f"arrival skew"
        ),
    )
    return doc


def exoneration_verdict(
    healthy_windows: Sequence[bool],
    min_observations: int = MIN_OBSERVATIONS,
    dominance: float = DOMINANCE,
) -> bool:
    """The indictment machinery run in reverse (ISSUE 19): may an
    indicted-and-drained serving shard be re-admitted? Each element is
    one post-indictment probation window's verdict (the serving
    cluster's probe-tick median inside both its dominance bar and the
    TPOT SLO). Exoneration demands the SAME corroboration an
    indictment does — at least ``MIN_OBSERVATIONS`` windows with a
    ``DOMINANCE`` share of them healthy — plus a healthy LATEST window
    (a shard that just relapsed must not ride its earlier good windows
    back in). Symmetric thresholds mean a component is never excluded
    on more evidence than would re-admit it."""
    windows = [bool(w) for w in healthy_windows]
    if len(windows) < min_observations:
        return False
    if not windows[-1]:
        return False
    return sum(windows) / len(windows) >= dominance


def relaunch_policy(n_ranks: int, n_excluded: int = 0) -> str:
    """What a persistent indictment permits: ``"exclude"`` when
    shrinking the world around the indicted rank still leaves a
    genuinely distributed world (>= 2 survivors), ``"fatal"``
    otherwise — a ``link_down`` on a 2-rank world has no degraded mode
    to limp along in (excluding either endpoint leaves a single-rank
    non-world), so the failure is fatal-not-degraded and must park,
    never relaunch."""
    survivors = int(n_ranks) - int(n_excluded) - 1
    return "exclude" if survivors >= 2 else "fatal"
