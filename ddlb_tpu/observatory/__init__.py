"""Perf observatory: cross-run memory for measured-vs-predicted results.

The telemetry subsystem (ISSUE 2) records what each row *did* and the
perfmodel (ISSUE 3) predicts what it *should* do; this package joins the
two ACROSS runs — the persistent bank the ROADMAP's fusion work and
perfmodel-guided autotuning both consume (ISSUE 6). Four cooperating
pieces, all zero-dependency (stdlib only — importable from the JAX-free
process tiers, same contract as telemetry and perfmodel):

- **run-history store** (``observatory.store``): every runner path — the
  sweep runner, the warm-worker pool consumers, ``measure_queue``,
  ``bench.py`` — banks its rows into an append-only JSONL history under
  ``DDLB_TPU_HISTORY``, keyed by chip spec + family + impl + config
  signature + git rev, so "is this slower than last week" stops being a
  CSV-eyeballing question;
- **measured-overlap attribution** (``observatory.attribution``): joins
  a row's measured time against its perfmodel ``COST_SCHEDULE`` terms to
  derive ``measured_overlap_frac`` (the *achieved* compute/communication
  overlap fraction T3, arxiv 2401.16677, motivates — not just
  end-to-end time) and a per-phase compute/comm/idle breakdown, emitted
  as row columns next to ``roofline_frac`` on EVERY row;
- **regression detection** (``observatory.regress``): the current run
  against per-key history (median + MAD, perfmodel prior as the
  fallback when history is empty), ranked — the engine behind
  ``scripts/observatory_report.py`` and the history layer of bench.py's
  roofline gate;
- **live sweep stream** (``observatory.live``): an append-only event
  stream (``DDLB_TPU_LIVE``) fed by the pool's heartbeat and the
  runner's row completions, consumed by the ``scripts/sweep_dash.py``
  TUI — per-worker state, rows done/parked/quarantined, the current
  row's phase, rolling predicted-vs-measured;
- **persistent-straggler indictment** (``observatory.health``, ISSUE
  15): banked straggler/skew columns folded across rows and runs into
  a per-rank/per-link transient-vs-persistent verdict — the trigger
  for the supervised launcher's degraded relaunch, rendered by
  ``scripts/health_report.py`` and gated in ``regress.detect_all``.

Everything is env-gated with the package's "" = disabled convention and
best-effort by contract: observability must never abort or perturb the
measurement it observes.
"""

from __future__ import annotations

from ddlb_tpu.observatory.attribution import (
    ATTRIBUTION_ROW_DEFAULTS,
    attribute,
)
from ddlb_tpu.observatory.live import post_event
from ddlb_tpu.observatory.store import bank_row, load_history, row_key

__all__ = [
    "ATTRIBUTION_ROW_DEFAULTS",
    "attribute",
    "bank_row",
    "load_history",
    "post_event",
    "row_key",
]
