"""Regression detection: the current run against the per-key history.

The detector the history store exists for: given the current run's rows
and the bank's earlier records, flag and RANK the rows that got slower.
Robust statistics by construction — capture windows on the shared relay
see cold-cache outliers and congestion spikes, so the baseline is the
per-key **median** and the noise scale the per-key **MAD** (median
absolute deviation), never mean/std:

- a row regresses when its measured median exceeds the history median
  by more than ``z_tol`` robust deviations AND by more than
  ``min_excess`` relatively (the z-score alone would flag microsecond
  jitter on keys whose history is unnaturally tight — the MAD is
  floored at ``rel_floor`` of the median for the same reason);
- when the key has NO history (first capture of a new config, a wiped
  bank), the **perfmodel prior** takes over: the row's own
  ``predicted_s`` is the analytical lower bound, and a row measuring
  more than ``prior_factor`` times its prediction is flagged as a
  prior-only advisory — ranked after every history-backed finding,
  because a lower bound is a much weaker baseline than a measured
  median;
- findings are ranked by robust z (history-backed) then by
  measured/predicted ratio (prior-only), worst first.

Serving rows carry latency DISTRIBUTIONS next to their median time
(ISSUE 11), and a serving regression can hide entirely in a tail
percentile — p99 TTFT triples while the median barely moves — so the
same median+MAD machinery additionally gates every ``SLO_METRICS``
column per key (``detect_slo``), with per-metric direction (goodput
regresses DOWN), and every ``SKEW_METRICS`` column (``detect_skew``,
ISSUE 14 — a straggler rank that the timing MAX-reduce hides, gated
with absolute noise floors because the skew columns live near zero on
clean runs), and every ``CAL_METRICS`` column (``detect_calibration``,
ISSUE 17 — residual drift off the fitted calibration model, baselines
fenced per ``cal_version``). ``detect_all`` merges every gate into one
ranked report.

Consumed by ``scripts/observatory_report.py`` and
``scripts/serving_load_report.py`` (the CLIs) and by ``bench.py``'s
roofline gate (the headline's history layer). Stdlib only, like the
rest of the package.
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional

from ddlb_tpu.observatory.store import row_key

#: detector defaults (every one overridable by the callers' knobs)
Z_TOL = 3.5          # robust deviations above the history median
MIN_EXCESS = 0.10    # AND at least 10% slower than the median
REL_FLOOR = 0.05     # MAD floor, as a fraction of the median
PRIOR_FACTOR = 5.0   # prior-only: measured > 5x the analytical bound

MEASURE_COLUMN = "median time (ms)"

#: serving SLO metrics gated per key NEXT TO the default time metric
#: (ISSUE 11): direction "high" = bigger is worse (latency
#: percentiles), "low" = smaller is worse (goodput). Rows that don't
#: carry a metric (every non-serving family) contribute nothing —
#: the gate extends the detector, it never re-scopes it.
SLO_METRICS = (
    ("slo_ttft_p50_ms", "high"),
    ("slo_ttft_p95_ms", "high"),
    ("slo_ttft_p99_ms", "high"),
    ("slo_tpot_p95_ms", "high"),
    ("slo_goodput_rps", "low"),
)

#: absolute ``(noise_floor, min_excess)`` per SLO metric, same role as
#: the skew/calibration floors: on a CPU-sim drill the latency
#: percentiles live in single-digit milliseconds with near-zero MAD
#: across a two-row baseline, so the relative machinery alone z-scores
#: sub-millisecond host jitter into a finding. A latency percentile
#: must worsen by a real millisecond (goodput by a quarter rps) before
#: it counts; against production-scale baselines (tens to thousands of
#: ms) the floors are invisible. Keyed separately so ``SLO_METRICS``
#: keeps its public ``(metric, direction)`` shape.
SLO_ABS_DEFAULT = (0.5, 1.0)
SLO_ABS = {
    "slo_goodput_rps": (0.05, 0.25),
}

#: minimum baseline depth before the SLO gate may judge a row: the
#: per-topology fencing keeps SLO populations small, and a MAD
#: estimated from one or two samples is no spread estimate at all
#: (n=1 gives identically-zero MAD, so any host wobble z-scores to a
#: finding). The time gate keeps its prior fallback and the skew/cal
#: gates their absolute floors; only the SLO gate is fenced finely
#: enough to need a depth requirement.
SLO_MIN_HISTORY = 3

#: cross-rank skew metrics gated per key (ISSUE 14): ``(metric,
#: direction, abs_floor, abs_excess)``. The skew columns live near
#: zero on clean runs (scheduler jitter), so the relative machinery
#: alone would flag 3x-of-nothing noise — each metric therefore
#: carries an ABSOLUTE noise floor on the MAD scale and an absolute
#: minimum excess a finding must clear:
#: ``straggler_frac`` must grow by 0.20 of the row's collective time,
#: ``skew_enter_s`` by 100 ms of real waiting, before either counts.
SKEW_METRICS = (
    ("straggler_frac", "high", 0.02, 0.20),
    ("skew_enter_s", "high", 0.005, 0.10),
)

#: calibration-drift metric gated per key (ISSUE 17): same
#: ``(metric, direction, abs_floor, abs_excess)`` shape as the skew
#: set. ``cal_residual_frac`` sits near zero on a freshly-fitted model,
#: so the MAD scale is floored at 0.02 and a finding must clear an
#: absolute +0.10 residual excess — a run 10% slower than the fitted
#: model beyond baseline noise. Direction-aware: only drift toward
#: SLOWER gates ("high"); a run faster than the model is a refit hint,
#: not an alarm (the report shows it, the gate stays quiet).
CAL_METRICS = (
    ("cal_residual_frac", "high", 0.02, 0.10),
)


def median(values: List[float]) -> float:
    """Plain median (stdlib-only tier; statistics.median allocates the
    same sort)."""
    ordered = sorted(values)
    n = len(ordered)
    if not n:
        return float("nan")
    mid = n // 2
    if n % 2:
        return float(ordered[mid])
    return (ordered[mid - 1] + ordered[mid]) / 2.0


def mad(values: List[float], center: Optional[float] = None) -> float:
    """Median absolute deviation around ``center`` (default: the
    median) — the robust noise scale."""
    if not values:
        return float("nan")
    c = median(values) if center is None else center
    return median([abs(v - c) for v in values])


def finite(value: Any) -> Optional[float]:
    """``value`` as a finite float, else None — the one
    coerce-anything-measured helper the observatory shares (records are
    a mix of JSON numbers, CSV strings, and NaN error rows)."""
    try:
        f = float(value)
    except (TypeError, ValueError):
        return None
    return f if math.isfinite(f) else None


def baselines(
    records: List[Dict[str, Any]],
    metric: str = MEASURE_COLUMN,
    exclude_run: Optional[str] = None,
) -> Dict[str, Dict[str, Any]]:
    """Per-key robust baseline over history records: ``{key: {median,
    mad, n, runs}}`` for every key with at least one finite ``metric``
    sample. ``exclude_run`` drops the current run's own records so a
    run never baselines against itself."""
    samples: Dict[str, List[float]] = {}
    runs: Dict[str, set] = {}
    for record in records:
        if record.get("kind", "row") != "row":
            continue
        if exclude_run and record.get("run_id") == exclude_run:
            continue
        row = record.get("row") or {}
        value = finite(row.get(metric))
        if value is None:
            continue
        key = record.get("key") or row_key(row)
        samples.setdefault(key, []).append(value)
        runs.setdefault(key, set()).add(record.get("run_id"))
    out: Dict[str, Dict[str, Any]] = {}
    for key, values in samples.items():
        m = median(values)
        out[key] = {
            "median": m,
            "mad": mad(values, m),
            "n": len(values),
            "runs": len(runs[key]),
        }
    return out


def detect(
    current_rows: List[Dict[str, Any]],
    history: List[Dict[str, Any]],
    metric: str = MEASURE_COLUMN,
    exclude_run: Optional[str] = None,
    z_tol: float = Z_TOL,
    min_excess: float = MIN_EXCESS,
    rel_floor: float = REL_FLOOR,
    prior_factor: float = PRIOR_FACTOR,
) -> List[Dict[str, Any]]:
    """Regression findings for ``current_rows`` against ``history``,
    ranked worst first (history-backed findings by robust z, then
    prior-only advisories by measured/predicted ratio).

    Each finding carries the evidence a report needs: the key's
    identity columns, measured vs baseline, the robust z, the slowdown
    ratio, and ``source`` (``history`` | ``perfmodel_prior``).

    Baselines are fenced per ``tuning_version`` (ISSUE 20), exactly as
    ``detect_calibration`` fences per ``cal_version``: a row measured
    under a tuning table only baselines against history measured under
    the SAME table fingerprint — a re-tune that changes the applied
    knobs starts a fresh baseline instead of reading as a step change.
    Untuned rows (version "") compare against untuned history, which on
    a pre-tuner bank is ALL of it — behavior unchanged.
    """

    def _tuning_version(row: Dict[str, Any]) -> str:
        return str(row.get("tuning_version") or "")

    versions = {_tuning_version(row) for row in current_rows}
    base_by_version = {
        version: baselines(
            [
                rec
                for rec in history
                if _tuning_version(rec.get("row") or {}) == version
            ],
            metric=metric,
            exclude_run=exclude_run,
        )
        for version in versions
    }
    findings: List[Dict[str, Any]] = []
    for row in current_rows:
        measured = finite(row.get(metric))
        if measured is None:
            continue  # error rows have no measurement to regress
        key = row_key(row)
        stats = base_by_version[_tuning_version(row)].get(key)
        if stats is not None:
            finding = _history_finding(
                row, key, metric, measured, stats, "high",
                z_tol, min_excess, rel_floor,
            )
            if finding is not None:
                findings.append(finding)
            continue
        # perfmodel prior: no history for this key. The calibrated
        # prediction (ISSUE 17) is the preferred baseline when the row
        # was priced against a table — it tracks absolute makespans, so
        # PRIOR_FACTOR over it is a far tighter net than over the raw
        # bound; rows stamped uncalibrated (NaN) fall back to the
        # analytical lower bound, behavior unchanged.
        prior = "calibrated"
        predicted_s = finite(row.get("predicted_cal_s"))
        if predicted_s is None or predicted_s <= 0.0:
            prior = "analytical"
            predicted_s = finite(row.get("predicted_s"))
        if predicted_s is None or predicted_s <= 0.0:
            continue
        predicted_ms = predicted_s * 1e3
        ratio = measured / predicted_ms
        if ratio > prior_factor:
            findings.append(
                {
                    **_ident(row),
                    "key": key,
                    "metric": metric,
                    "source": "perfmodel_prior",
                    "prior": prior,
                    "measured_ms": measured,
                    "baseline_ms": predicted_ms,
                    "ratio": ratio,
                    "z": float("nan"),
                }
            )
    return _rank(findings)


def _ident(row: Dict[str, Any]) -> Dict[str, Any]:
    """The identity columns every finding carries — ONE definition, so
    the time gate and the SLO gate cannot drift apart on field shape."""
    return {
        "implementation": row.get("implementation"),
        "base_implementation": row.get("base_implementation"),
        "primitive": row.get("primitive"),
        "option": row.get("option"),
        "m": row.get("m"),
        "n": row.get("n"),
        "k": row.get("k"),
        "chip": row.get("chip"),
    }


def _history_finding(
    row: Dict[str, Any],
    key: str,
    metric: str,
    measured: float,
    stats: Dict[str, Any],
    direction: str,
    z_tol: float,
    min_excess: float,
    rel_floor: float,
    abs_floor: float = 0.0,
    abs_excess: float = 0.0,
) -> Optional[Dict[str, Any]]:
    """The history-backed gate core shared by ``detect``,
    ``detect_slo`` and ``detect_skew``: median+MAD z against the key's
    baseline, with ``direction`` deciding which way is worse ("high" =
    bigger is worse; "low" = smaller is worse, ``ratio`` oriented so >1
    always reads "this much worse"). ``abs_floor`` floors the noise
    scale and ``abs_excess`` demands an absolute worsening — both 0 for
    the time/SLO gates, nonzero for near-zero-baseline metrics (the
    skew columns) where relative machinery alone flags 3x-of-nothing.
    None when the row is within tolerance."""
    baseline = stats["median"]
    if baseline <= 0.0 and abs_floor <= 0.0:
        return None
    scale = max(stats["mad"], rel_floor * baseline, abs_floor)
    # ratio degrades to the robust scale as denominator when the true
    # denominator is 0 (a zero-skew clean baseline, a zeroed goodput):
    # still "this much worse", but FINITE — these findings land in
    # ``--json`` documents, and bare Infinity is not valid JSON
    if direction == "low":
        z = (baseline - measured) / scale if scale > 0 else float("inf")
        ratio = baseline / (measured if measured > 0 else scale)
        excess = baseline - measured
    else:
        z = (measured - baseline) / scale if scale > 0 else float("inf")
        ratio = measured / (baseline if baseline > 0.0 else scale)
        excess = measured - baseline
    if not (z > z_tol and ratio > 1.0 + min_excess and excess >= abs_excess):
        return None
    return {
        **_ident(row),
        "key": key,
        "metric": metric,
        "source": "history",
        "measured_ms": measured,
        "baseline_ms": baseline,
        "mad_ms": stats["mad"],
        "history_n": stats["n"],
        "history_runs": stats["runs"],
        "ratio": ratio,
        "z": z,
    }


def _rank(findings: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Health indictments first (a persistent-hardware verdict outranks
    any single-key regression — it predicts EVERY future run), then
    history-backed findings by robust z (worst first), then prior-only
    advisories by measured/predicted ratio — the one ranking rule
    shared by the time gate, the SLO gate, the health gate and their
    union."""
    health = [f for f in findings if f["source"] == "health"]
    history_backed = [f for f in findings if f["source"] == "history"]
    prior_only = [
        f for f in findings if f["source"] not in ("history", "health")
    ]
    health.sort(key=lambda f: -f.get("caused_s", 0.0))
    history_backed.sort(key=lambda f: -f["z"])
    prior_only.sort(key=lambda f: -f["ratio"])
    return health + history_backed + prior_only


def _detect_metrics(
    current_rows: List[Dict[str, Any]],
    history: List[Dict[str, Any]],
    specs,
    exclude_run: Optional[str],
    z_tol: float,
    min_excess: float,
    rel_floor: float,
    decorate=None,
    min_history: int = 1,
) -> List[Dict[str, Any]]:
    """The one per-metric history gate the SLO and skew detectors
    share: every ``(metric, direction, abs_floor, abs_excess)`` spec
    gated per key against its own baseline (rows that don't carry a
    metric contribute nothing), ``decorate(finding, row)`` adding any
    metric-family extras, ``min_history`` withholding judgment until
    the baseline is deep enough to carry a spread estimate. Factored
    so the three gates ``detect_all`` merges can never drift apart on
    the gating loop itself."""
    findings: List[Dict[str, Any]] = []
    for metric, direction, abs_floor, abs_excess in specs:
        base = baselines(history, metric=metric, exclude_run=exclude_run)
        for row in current_rows:
            measured = finite(row.get(metric))
            if measured is None:
                continue
            key = row_key(row)
            stats = base.get(key)
            if stats is None or stats["n"] < min_history:
                continue
            finding = _history_finding(
                row, key, metric, measured, stats, direction,
                z_tol, min_excess, rel_floor,
                abs_floor=abs_floor, abs_excess=abs_excess,
            )
            if finding is not None:
                if decorate is not None:
                    decorate(finding, row)
                findings.append(finding)
    return _rank(findings)


def detect_slo(
    current_rows: List[Dict[str, Any]],
    history: List[Dict[str, Any]],
    metrics=SLO_METRICS,
    exclude_run: Optional[str] = None,
    z_tol: float = Z_TOL,
    min_excess: float = MIN_EXCESS,
    rel_floor: float = REL_FLOOR,
    min_history: int = SLO_MIN_HISTORY,
) -> List[Dict[str, Any]]:
    """SLO-metric regression findings (ISSUE 11): every metric in
    ``metrics`` gated per key against its own per-key history baseline,
    with per-metric direction — a TTFT percentile regresses UP, goodput
    regresses DOWN. History-backed only (the perfmodel predicts a
    drain's time, not its percentile distribution, so there is no prior
    to fall back to); rows that don't carry a metric — every
    non-serving family — simply contribute nothing.

    Finding shape matches ``detect`` (``metric`` names the column;
    ``ratio`` is always worse/better oriented so >1 reads "this much
    worse" for both directions).

    SLO distributions are only comparable under the SAME cluster
    composition (ISSUE 18): a routed dp=2 row's TTFT tail, a
    disaggregated row's (handoff in the path), and a degraded row's
    (one shard drained mid-drill) are different populations, so
    history is fenced per ``serve_topology`` group — each current
    row gates only against records carrying ITS stamp. Unstamped
    history (rows banked before the cluster existed) folds into the
    legacy ``"single"`` bucket, so pre-cluster baselines keep gating
    single-engine rows instead of being orphaned by the new column.
    Each finding carries its ``serve_topology``. Elastic rows (ISSUE
    19) fence for free through the same mechanism: a run whose pools
    resized stamps an ``:elastic=R`` suffix (after any ``:degraded=K``),
    so transition-bearing latency distributions never pool with — or
    set the bar for — static baselines of the same nominal shape.

    Two robustness rails for the near-zero CPU-sim regime (ISSUE 19):
    per-metric ABSOLUTE floors (``SLO_ABS``) so sub-millisecond host
    jitter never z-scores into a finding off a tiny baseline, and
    ``min_history`` (default ``SLO_MIN_HISTORY``) so the gate withholds
    judgment until the fenced per-key baseline actually carries a
    spread estimate — one banked row has identically-zero MAD, and a
    z against zero spread is not evidence.
    """
    specs = [
        (metric, direction, *SLO_ABS.get(metric, SLO_ABS_DEFAULT))
        for metric, direction in metrics
    ]

    def _topology(row: Dict[str, Any]) -> str:
        return str(row.get("serve_topology") or "") or "single"

    def _stamp_topology(finding, row):
        finding["serve_topology"] = _topology(row)

    findings: List[Dict[str, Any]] = []
    for topo in sorted({_topology(row) for row in current_rows}):
        rows = [row for row in current_rows if _topology(row) == topo]
        fenced = [
            rec
            for rec in history
            if _topology(rec.get("row") or {}) == topo
        ]
        findings.extend(
            _detect_metrics(
                rows,
                fenced,
                specs,
                exclude_run,
                z_tol,
                min_excess,
                rel_floor,
                decorate=_stamp_topology,
                min_history=min_history,
            )
        )
    return _rank(findings)


def detect_skew(
    current_rows: List[Dict[str, Any]],
    history: List[Dict[str, Any]],
    metrics=SKEW_METRICS,
    exclude_run: Optional[str] = None,
    z_tol: float = Z_TOL,
    min_excess: float = MIN_EXCESS,
    rel_floor: float = REL_FLOOR,
) -> List[Dict[str, Any]]:
    """Cross-rank skew regression findings (ISSUE 14): every metric in
    ``metrics`` gated per key against its own history baseline — a row
    whose collectives suddenly wait much longer on a last arrival is a
    straggler regression even when its measured time barely moves (the
    timing MAX-reduce hides exactly this). History-backed only, with
    the per-metric absolute floors described at ``SKEW_METRICS`` so
    clean-run scheduler jitter can never alarm.

    Finding shape matches ``detect``; each finding additionally carries
    the row's ``straggler_rank`` and ``clock_unc_s`` so a report can
    name the culprit without re-reading the row. A ``skew_enter_s``
    excess inside the row's own clock-alignment uncertainty bound is
    dropped — differences below the bound are noise by definition (the
    fold carries it for exactly this) — and a row whose fold made NO
    alignment claim at all (``clock_unc_s`` NaN: too few exchanges to
    fit, raw possibly-cross-host stamps) never alarms on that metric.
    ``straggler_frac`` is unitless and keeps only its absolute floor.
    """

    def _name_straggler(finding, row):
        finding["straggler_rank"] = row.get("straggler_rank")
        if "clock_unc_s" in row:
            # None = the fold declined to align (NaN sentinel); rows
            # without the column at all (older schema) carry no key
            # and are not unc-gated
            finding["clock_unc_s"] = finite(row.get("clock_unc_s"))

    findings = _detect_metrics(
        current_rows,
        history,
        metrics,
        exclude_run,
        z_tol,
        min_excess,
        rel_floor,
        decorate=_name_straggler,
    )
    kept = []
    for finding in findings:
        if finding["metric"] == "skew_enter_s" and "clock_unc_s" in finding:
            unc = finding["clock_unc_s"]
            if unc is None:
                continue  # no alignment claim -> no skew-seconds claim
            if finding["measured_ms"] - finding["baseline_ms"] <= unc:
                continue  # inside the bound: noise by definition
        kept.append(finding)
    return kept


def detect_calibration(
    current_rows: List[Dict[str, Any]],
    history: List[Dict[str, Any]],
    metrics=CAL_METRICS,
    exclude_run: Optional[str] = None,
    z_tol: float = Z_TOL,
    min_excess: float = MIN_EXCESS,
    rel_floor: float = REL_FLOOR,
) -> List[Dict[str, Any]]:
    """Calibration-drift findings (ISSUE 17): ``cal_residual_frac``
    gated per key against its own history baseline — a run whose
    measured medians drift off the fitted latency/overhead model is a
    model-validity alarm even when no single key regresses against raw
    history (a uniform +overhead shift moves EVERY residual but may
    stay inside each key's time-metric noise).

    Residual baselines are only comparable under the SAME fitted
    constants, so history is fenced to records stamped with one of the
    current rows' ``cal_version`` values — after a refit the gate
    starts a fresh baseline instead of alarming against residuals of a
    model that no longer exists. Rows without a finite residual (every
    uncalibrated row) contribute nothing; with no calibrated rows at
    all this is a no-op, keeping ``detect_all`` unchanged for
    uncalibrated worlds. Each finding carries ``cal_version``.
    """
    versions = {
        str(row.get("cal_version") or "")
        for row in current_rows
        if finite(row.get("cal_residual_frac")) is not None
    }
    versions.discard("")
    if not versions:
        return []
    fenced = [
        rec
        for rec in history
        if str((rec.get("row") or {}).get("cal_version") or "") in versions
    ]

    def _stamp_version(finding, row):
        finding["cal_version"] = row.get("cal_version")

    return _detect_metrics(
        current_rows,
        fenced,
        metrics,
        exclude_run,
        z_tol,
        min_excess,
        rel_floor,
        decorate=_stamp_version,
    )


def detect_health(
    current_rows: List[Dict[str, Any]],
    history: List[Dict[str, Any]],
    exclude_run: Optional[str] = None,
) -> List[Dict[str, Any]]:
    """Persistent-straggler indictment finding (ISSUE 15): the health
    verdict (``observatory.health``) folded over the banked
    observations PLUS the current run's rows. At most one finding —
    ``metric="persistent_straggler"``, ``source="health"`` — and only
    when the CURRENT run contributes at least one qualifying
    observation naming the indicted rank: a bank whose old rows already
    indicted a since-replaced component must not re-flag every clean
    run after it forever."""
    from ddlb_tpu.observatory import health

    hist_obs = health.observations_from_history(
        [
            r for r in history
            if not (exclude_run and r.get("run_id") == exclude_run)
        ]
    )
    cur_obs = health.observations_from_rows(current_rows)
    # the world size names the indicted rank's neighbor-link candidates
    # (link_candidates): the rows themselves carry it
    world = max(
        (
            int(w)
            for w in (
                finite(row.get("num_processes")) for row in current_rows
            )
            if w is not None and w > 1
        ),
        default=None,
    )
    verdict = health.verdict_from_observations(
        hist_obs + cur_obs, world=world
    )
    if verdict["status"] != health.PERSISTENT:
        return []
    rank = verdict["rank"]
    corroborating = [
        row
        for row, obs in zip(current_rows, cur_obs)
        if health.qualifying_rank(
            obs.get("rank"), obs.get("skew_s"), obs.get("unc_s"),
            health.MIN_SKEW_S,
        ) == rank
    ]
    if not corroborating:
        return []
    stats = verdict["per_rank"][rank]
    return [
        {
            **_ident(corroborating[0]),
            "key": "world",
            "metric": "persistent_straggler",
            "source": "health",
            "straggler_rank": rank,
            # report-compatible numeric fields: the caused skew is the
            # measured quantity, the healthy baseline is zero, and the
            # corroboration count stands in for the ratio column
            "measured_ms": stats["caused_s"] * 1e3,
            "baseline_ms": 0.0,
            "ratio": float(stats["count"]),
            "z": float("nan"),
            "caused_s": stats["caused_s"],
            "share": verdict["share"],
            "observations": stats["count"],
            "runs": stats["runs"],
            "links": verdict["links"],
            "reason": verdict["reason"],
        }
    ]


def detect_all(
    current_rows: List[Dict[str, Any]],
    history: List[Dict[str, Any]],
    exclude_run: Optional[str] = None,
    z_tol: float = Z_TOL,
    min_excess: float = MIN_EXCESS,
    rel_floor: float = REL_FLOOR,
    prior_factor: float = PRIOR_FACTOR,
) -> List[Dict[str, Any]]:
    """The full gate: the default time metric (``detect``, perfmodel
    prior included) PLUS every SLO metric (``detect_slo``) PLUS the
    cross-rank skew metrics (``detect_skew``) PLUS the calibration
    drift gate (``detect_calibration``) PLUS the persistent-straggler
    health verdict (``detect_health``), re-ranked as one list so a
    serving SLO blow-up, a straggler regression, a model-drift alarm or
    a hardware indictment competes with — and can outrank — a
    kernel-time regression in the same report."""
    return _rank(
        detect_health(
            current_rows,
            history,
            exclude_run=exclude_run,
        )
        + detect(
            current_rows,
            history,
            exclude_run=exclude_run,
            z_tol=z_tol,
            min_excess=min_excess,
            rel_floor=rel_floor,
            prior_factor=prior_factor,
        )
        + detect_slo(
            current_rows,
            history,
            exclude_run=exclude_run,
            z_tol=z_tol,
            min_excess=min_excess,
            rel_floor=rel_floor,
        )
        + detect_skew(
            current_rows,
            history,
            exclude_run=exclude_run,
            z_tol=z_tol,
            min_excess=min_excess,
            rel_floor=rel_floor,
        )
        + detect_calibration(
            current_rows,
            history,
            exclude_run=exclude_run,
            z_tol=z_tol,
            min_excess=min_excess,
            rel_floor=rel_floor,
        )
    )
