"""Live sweep stream: the event feed behind ``scripts/sweep_dash.py``.

A sweep's progress is visible today only as interleaved log lines; this
module gives the dashboard a machine-readable stream without adding a
server, a socket, or ANY cost to the measured path: when
``DDLB_TPU_LIVE`` names a file, instrumented sites append one flushed
JSON line per event (O_APPEND — atomic for these line sizes, so the
runner, the pool parent and the queue driver can share one stream); when
unset, ``post_event`` is one dict lookup and returns. The dashboard
process tails the file — strictly read-only, a separate process, so it
cannot perturb row timings (the acceptance bar: timing deltas vs
dashboard-off within noise).

Event kinds currently posted:

- ``sweep_start`` / ``sweep_done`` — the runner's row count bookends;
- ``row_start`` / ``row_phase`` / ``row_done`` — per row: identity at
  dispatch, the worker's phase marks while it runs
  (setup/warmup/measure/validate — the heartbeat-adjacent stage marks
  ``benchmark_worker`` already logs), and the measured outcome with the
  predicted-vs-measured fields (``predicted_s``, ``roofline_frac``,
  ``measured_overlap_frac``) at completion;
- ``worker_spawn`` / ``worker_ready`` / ``worker_beat`` /
  ``worker_dead`` — the pool's lease lifecycle and the parent-observed
  heartbeat age, so the dashboard shows per-worker liveness exactly as
  the kill policy sees it;
- ``queue_parked`` — the hardware queue's park decisions;
- ``serving_tick`` — the serving_load drive loop's throttled
  queue-depth/progress gauge (the dashboard's serving panel feed,
  ISSUE 11).

Events whose ``kind`` the fold does not recognize are COUNTED, not
dropped silently (``state["unknown"]``): the stream is shared by
processes that may be newer than the dashboard tailing it, and a frame
that quietly renders less is how a forward-compat gap hides (the
pre-ISSUE-11 ``--html`` blank-table bug). Renderers surface the count.

``fold`` turns an event list into the dashboard's render state; it
lives here (not in the script) so tests pin the folding semantics and
the ``--html`` snapshot renders from the same state as the TUI.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional

from ddlb_tpu import envs, telemetry
from ddlb_tpu.observatory.regress import finite as _finite

_post_failed: Optional[str] = None


def post_event(kind: str, **fields: Any) -> bool:
    """Append one event line to the live stream; returns whether it was
    written (False when disabled — the overwhelmingly common case — or
    on a write failure, which warns once and never raises)."""
    global _post_failed
    path = envs.get_live_path()
    if not path:
        return False
    event = {"ts": time.time(), "pid": os.getpid(), "kind": kind}
    event.update(fields)
    try:
        directory = os.path.dirname(os.path.abspath(path))
        os.makedirs(directory, exist_ok=True)
        with open(path, "a", encoding="utf-8") as f:
            f.write(json.dumps(event, default=str) + "\n")
    except OSError as exc:
        if _post_failed != path:
            _post_failed = path
            telemetry.warn(
                f"live stream {path} is not writable ({exc}); "
                f"dashboard events disabled for this process"
            )
        return False
    return True


def read_events(path: str, offset: int = 0) -> tuple:
    """(events, new_offset) from ``path`` starting at byte ``offset`` —
    the dashboard's incremental tail. Corrupt/partial lines are skipped
    (a line mid-append on the final read simply lands next poll)."""
    events: List[Dict[str, Any]] = []
    try:
        # errors="replace": a torn multibyte character mid-append must
        # not crash the tail — it can only sit on the PARTIAL last line
        # (newlines are single-byte), which is deferred below anyway,
        # so consumed complete lines always decoded cleanly
        with open(path, encoding="utf-8", errors="replace") as f:
            f.seek(offset)
            data = f.read()
    except OSError:
        return events, offset
    consumed = 0
    for line in data.splitlines(keepends=True):
        if not line.endswith("\n"):
            break  # partial tail line: re-read it next poll
        consumed += len(line.encode("utf-8"))
        line = line.strip()
        if not line:
            continue
        try:
            event = json.loads(line)
        except ValueError:
            continue
        if isinstance(event, dict) and "kind" in event:
            events.append(event)
    return events, offset + consumed


def fold(
    events: List[Dict[str, Any]],
    state: Optional[Dict[str, Any]] = None,
    recent: int = 12,
) -> Dict[str, Any]:
    """Fold events into (or onto) the dashboard render state:

    - ``totals``: rows done / errors / quarantined / parked / retries,
      plus the sweep's announced row count;
    - ``workers``: per child pid — lifecycle state, setup cost, the
      last parent-observed heartbeat age;
    - ``current``: per source pid — the row in flight (identity,
      dispatch time, latest phase mark), cleared by its ``row_done``;
    - ``recent``: the last N completed rows with their
      predicted-vs-measured fields;
    - ``fracs``: every finite ``roofline_frac`` / ``overlap`` pair seen,
      for the rolling predicted-vs-measured summary;
    - ``serving``: the serving panel's state — the rolling queue-depth
      gauge ring (``serving_tick`` events), in-drain progress, and the
      latest serving row's SLO summary (TTFT percentiles, goodput,
      attainment);
    - ``lanes``: the per-rank skew panel (ISSUE 14) — per process id,
      how many completed rows named it the straggler, its accumulated
      arrival-skew seconds, and the latest row's ``straggler_frac``
      (``row_done`` events carry the skew fold's summary);
    - ``unknown``: per-kind counts of events this build did not
      recognize (surfaced by the renderers, never silently dropped).
    """
    if state is None:
        state = {}
    state.setdefault(
        "totals",
        {
            "total": 0, "done": 0, "errors": 0, "quarantined": 0,
            "parked": 0, "retries": 0,
        },
    )
    state.setdefault("workers", {})
    state.setdefault("current", {})
    state.setdefault("recent", [])
    state.setdefault("fracs", [])
    state.setdefault("sweep_done", False)
    state.setdefault("last_ts", 0.0)
    # serving panel state (ISSUE 11): rolling queue-depth gauge ring +
    # the latest completed serving row's SLO summary. setdefault (not
    # the None-branch literal) so a state folded by an OLDER dashboard
    # build gains the keys instead of KeyError-ing the renderer.
    state.setdefault("serving", {"depths": [], "progress": None, "latest": None})
    # per-rank skew lanes (ISSUE 14); setdefault for the same
    # older-dashboard-folded-state reason as the serving panel above
    state.setdefault("lanes", {})
    state.setdefault("unknown", {})
    totals = state["totals"]
    for e in events:
        kind = e.get("kind")
        ts = _finite(e.get("ts")) or 0.0
        state["last_ts"] = max(state["last_ts"], ts)
        src = e.get("pid")
        if kind == "sweep_start":
            totals["total"] += int(e.get("total") or 0)
        elif kind == "sweep_done":
            state["sweep_done"] = True
        elif kind == "row_start":
            state["current"][src] = {
                "impl": e.get("impl"),
                "primitive": e.get("primitive"),
                "m": e.get("m"), "n": e.get("n"), "k": e.get("k"),
                "stage": "dispatched",
                "since": ts,
            }
        elif kind == "row_phase":
            # phase marks come from the WORKER — in pooled/subprocess
            # mode a different pid than the runner that posted
            # row_start — so match by pid first, then by impl id
            current = state["current"].get(src)
            if current is None:
                impl = e.get("impl")
                for entry in state["current"].values():
                    if impl is not None and entry.get("impl") == impl:
                        current = entry
                        break
            if current is not None:
                current["stage"] = e.get("stage")
        elif kind == "row_done":
            state["current"].pop(src, None)
            totals["done"] += 1
            if e.get("error"):
                totals["errors"] += 1
            if e.get("quarantined"):
                totals["quarantined"] += 1
            totals["retries"] += int(e.get("retries") or 0)
            frac = _finite(e.get("roofline_frac"))
            overlap = _finite(e.get("measured_overlap_frac"))
            if frac is not None or overlap is not None:
                state["fracs"].append({"roofline": frac, "overlap": overlap})
            if _finite(e.get("slo_ttft_p95_ms")) is not None:
                # a serving_load completion: its SLO summary becomes the
                # panel's headline tiles
                state["serving"]["latest"] = {
                    "impl": e.get("impl"),
                    "ttft_p50_ms": _finite(e.get("slo_ttft_p50_ms")),
                    "ttft_p95_ms": _finite(e.get("slo_ttft_p95_ms")),
                    "ttft_p99_ms": _finite(e.get("slo_ttft_p99_ms")),
                    "goodput_rps": _finite(e.get("slo_goodput_rps")),
                    "attainment": _finite(e.get("slo_attainment")),
                }
            strag = _finite(e.get("straggler_rank"))
            if strag is not None and strag >= 0:
                # per-rank lane bookkeeping: lanes key by the straggler
                # process id (JSON round-trips dict keys as strings, so
                # pin the str form)
                lane = state["lanes"].setdefault(
                    str(int(strag)),
                    {"straggler_rows": 0, "skew_s": 0.0, "last_frac": None},
                )
                lane["straggler_rows"] += 1
                lane["skew_s"] += _finite(e.get("skew_enter_s")) or 0.0
                lane["last_frac"] = _finite(e.get("straggler_frac"))
            state["recent"].append(e)
            del state["recent"][:-recent]
        elif kind == "serving_tick":
            serving = state["serving"]
            depth = _finite(e.get("queue_depth"))
            if depth is not None:
                serving["depths"].append(int(depth))
                del serving["depths"][:-120]
            serving["progress"] = {
                "active": e.get("active"),
                "done": e.get("done"),
                "total": e.get("total"),
            }
            shard_depths = e.get("shard_depths")
            if isinstance(shard_depths, list):
                # cluster members (ISSUE 18): per-decode-shard queue
                # gauges; -1 marks a drained/excluded shard (rendered
                # as dead, not merely idle)
                serving["shard_depths"] = [
                    int(d) for d in shard_depths
                    if _finite(d) is not None
                ]
        elif kind == "worker_spawn":
            state["workers"][e.get("worker")] = {
                "state": "spawning",
                "reason": e.get("reason"),
                "setup_s": None,
                "beat_age_s": None,
                "since": ts,
            }
        elif kind == "worker_ready":
            worker = state["workers"].setdefault(
                e.get("worker"), {"since": ts}
            )
            worker["state"] = "ready"
            worker["setup_s"] = _finite(e.get("setup_s"))
            worker["platform"] = e.get("platform")
        elif kind == "worker_beat":
            worker = state["workers"].setdefault(
                e.get("worker"), {"state": "busy", "since": ts}
            )
            worker["beat_age_s"] = _finite(e.get("age_s"))
        elif kind == "worker_dead":
            worker = state["workers"].setdefault(
                e.get("worker"), {"since": ts}
            )
            worker["state"] = "dead"
            worker["error"] = str(e.get("error") or "")[:120]
        elif kind == "queue_parked":
            totals["parked"] += 1
        else:
            # forward compat: a kind this build doesn't know is counted
            # and surfaced, never silently dropped (a newer runner may
            # share the stream with an older dashboard)
            state["unknown"][str(kind)] = state["unknown"].get(
                str(kind), 0
            ) + 1
    return state
