"""Fit the calibration table from the banked observatory history.

The bank (``store.py``) holds thousands of keyed rows with both the
analytical lower bound (``predicted_s``) and the measured median —
a calibration dataset, not just a regression baseline. This driver
streams it per ``(chip, time_measurement_backend)`` group (limp-mode
``world_degraded`` rows and arrival-horizon families are filtered by
``calib.row_features``), runs the robust fitter, and persists the
versioned table the whole prediction stack prices from
(``DDLB_TPU_CALIB``).

Split from ``perfmodel.calib`` on the same line the store draws:
``calib`` is the pure model (features, fitter, table), this module is
the observatory glue (bank streaming, git_rev/banked_at stamping,
persistence).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Dict, Iterable, List, Optional, Tuple

from ddlb_tpu.observatory import store
from ddlb_tpu.perfmodel import calib


def collect_samples(
    directory: Optional[str] = None,
    records: Optional[Iterable[Dict[str, Any]]] = None,
    *,
    chip: Optional[str] = None,
    family: Optional[str] = None,
) -> Dict[Tuple[str, str], List[Dict[str, Any]]]:
    """Fit samples grouped per (chip, backend), streamed from the bank.

    ``records`` overrides the bank read (tests hand synthetic
    histories straight in); otherwise ``store.iter_history`` streams
    ``kind="row"`` records under the optional chip/family predicates.
    Rows ``calib.row_features`` rejects (errors, degraded worlds,
    serving families, unmeasured) are dropped here.
    """
    if records is None:
        records = store.iter_history(directory, kind="row", chip=chip, family=family)
    groups: Dict[Tuple[str, str], List[Dict[str, Any]]] = {}
    for record in records:
        row = record.get("row") if isinstance(record, dict) else None
        if not isinstance(row, dict):
            continue
        features = calib.row_features(row)
        if features is None:
            continue
        row_chip = str(row.get("chip") or "")
        if not row_chip:
            continue
        backend = str(row.get("time_measurement_backend") or "")
        groups.setdefault((row_chip, backend), []).append(features)
    return groups


def collect_kv_samples(
    directory: Optional[str] = None,
    records: Optional[Iterable[Dict[str, Any]]] = None,
    *,
    chip: Optional[str] = None,
    family: Optional[str] = None,
) -> Dict[Tuple[str, str], List[Dict[str, Any]]]:
    """KV-handoff fit samples grouped per (chip, backend) — the serving
    rows the residual fit excludes (``calib.kv_row_features``)."""
    if records is None:
        records = store.iter_history(
            directory, kind="row", chip=chip, family=family
        )
    groups: Dict[Tuple[str, str], List[Dict[str, Any]]] = {}
    for record in records:
        row = record.get("row") if isinstance(record, dict) else None
        if not isinstance(row, dict):
            continue
        features = calib.kv_row_features(row)
        if features is None:
            continue
        row_chip = str(row.get("chip") or "")
        if not row_chip:
            continue
        backend = str(row.get("time_measurement_backend") or "")
        groups.setdefault((row_chip, backend), []).append(features)
    return groups


def calibrate_history(
    directory: Optional[str] = None,
    records: Optional[Iterable[Dict[str, Any]]] = None,
    *,
    chip: Optional[str] = None,
    family: Optional[str] = None,
    min_rows: int = calib.MIN_ROWS,
) -> Optional[calib.CalibrationTable]:
    """Fit every (chip, backend) group the bank can support.

    Groups too thin for a trustworthy fit are skipped (the fitter
    returns None below ``min_rows``); the table carries only groups
    that fit. None when nothing fit — an empty table must not be
    mistaken for a calibrated world.

    The KV-handoff constants (ISSUE 19) ride the same table: serving
    rows with a handoff ledger fit ``kv_setup_s``/``kv_per_byte_s`` per
    group and attach to that group's residual fit — or stand alone as a
    residual-zero group when a bank holds only serving rows (the zero
    constants add nothing, the standard uncalibrated contract).
    ``records``, when given, feeds BOTH fits (one pass of synthetic
    history exercises both on CI).
    """
    if records is not None:
        records = list(records)
    groups = collect_samples(directory, records, chip=chip, family=family)
    fitted: Dict[Tuple[str, str], calib.GroupCalibration] = {}
    for (group_chip, backend), samples in sorted(groups.items()):
        fit = calib.fit_group(
            samples, chip=group_chip, backend=backend, min_rows=min_rows
        )
        if fit is not None:
            fitted[(group_chip, backend)] = fit
    kv_groups = collect_kv_samples(
        directory, records, chip=chip, family=family
    )
    for (group_chip, backend), samples in sorted(kv_groups.items()):
        kv = calib.fit_kv_group(samples, min_rows=min_rows)
        if kv is None:
            continue
        setup_s, per_byte_s, kv_rows = kv
        base = fitted.get((group_chip, backend)) or calib.GroupCalibration(
            chip=group_chip, backend=backend, dispatch_s=0.0, step_s=0.0
        )
        fitted[(group_chip, backend)] = dataclasses.replace(
            base,
            kv_setup_s=setup_s,
            kv_per_byte_s=per_byte_s,
            kv_rows=kv_rows,
        )
    if not fitted:
        return None
    return calib.make_table(
        fitted, git_rev=store.git_rev(), banked_at=time.time()
    )


def write_table(table: calib.CalibrationTable, path: str) -> str:
    """Persist a fitted table where ``DDLB_TPU_CALIB`` can point."""
    calib.save_table(table, path)
    return path
