"""Lazy g++ build of the native host-runtime shared library.

The library is compiled on first use (or via ``make native``) and cached
next to the source; a stale or missing compiler simply means the pure-
Python fallbacks in ``ddlb_tpu.native`` take over. Set
``DDLB_TPU_NO_NATIVE=1`` to force the fallbacks (used by tests to cover
both paths).
"""

from __future__ import annotations

import os
import subprocess
import threading
from typing import Optional

_DIR = os.path.dirname(os.path.abspath(__file__))
SOURCE = os.path.join(_DIR, "host_runtime.cpp")
LIBRARY = os.path.join(_DIR, "_host_runtime.so")

_lock = threading.Lock()


def build(force: bool = False) -> Optional[str]:
    """Return the path to the built library, or None if unavailable."""
    from ddlb_tpu import envs

    if envs.get_no_native():
        return None
    with _lock:
        if not os.path.exists(SOURCE):
            # source missing (e.g. prebuilt-.so-only distribution): use the
            # cached library if there is one, otherwise fall back
            return LIBRARY if os.path.exists(LIBRARY) else None
        if (
            not force
            and os.path.exists(LIBRARY)
            and os.path.getmtime(LIBRARY) >= os.path.getmtime(SOURCE)
        ):
            return LIBRARY
        cxx = os.environ.get("CXX", "g++")
        tmp = f"{LIBRARY}.{os.getpid()}.tmp"
        cmd = [
            cxx, "-O3", "-std=c++17", "-shared", "-fPIC", SOURCE, "-o", tmp,
        ]
        try:
            subprocess.run(cmd, check=True, capture_output=True, timeout=120)
            os.replace(tmp, LIBRARY)  # atomic: concurrent builders race safely
        except (OSError, subprocess.SubprocessError):
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return None
        return LIBRARY
