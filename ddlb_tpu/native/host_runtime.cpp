// Native host-runtime for ddlb_tpu: pipeline schedule planning, monotonic
// timing, and robust statistics.
//
// This is the framework's in-repo native layer. The reference keeps all of
// its native capability in dependencies (NCCL, nvFuser's C++
// MultiDeviceExecutor, TransformerEngine — SURVEY.md section 2.4,
// /root/reference/ddlb/primitives/TPColumnwise/fuser.py:247-257): the
// executor's HOST side plans which chunk each rank processes at each
// pipeline step and how staged outputs reassemble. Here that planner is
// this translation unit; the DEVICE side of the same pipelines is the
// Pallas kernel layer (ddlb_tpu/ops/). Exposed as a plain C ABI consumed
// via ctypes (ddlb_tpu/native/__init__.py).
//
// Schedule conventions (shared with the shard_map pipelines in
// ddlb_tpu/primitives/*/overlap.py and the ring kernels in
// ddlb_tpu/ops/collective_matmul.py):
//   ag_fwd: after t forward ring hops a device holds A-chunk (rank - t) mod d
//   ag_bwd: backward ring, chunk (rank + t) mod d
//   rs_fwd: accumulator schedule (rank + d - 1 - t) mod d, so after d steps
//           each device ends holding its own fully-reduced output chunk
//   rs_bwd: the backward half of the bidirectional reduce-scatter ring,
//           chunk (rank + t + 1) mod d

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <ctime>
#include <unordered_map>
#include <vector>

extern "C" {

enum DdlbRingKind : int32_t {
  DDLB_RING_AG_FWD = 0,
  DDLB_RING_AG_BWD = 1,
  DDLB_RING_RS_FWD = 2,
  DDLB_RING_RS_BWD = 3,
};

// Monotonic nanosecond clock (CLOCK_MONOTONIC_RAW is immune to NTP slew).
int64_t ddlb_now_ns() {
  timespec ts;
#ifdef CLOCK_MONOTONIC_RAW
  clock_gettime(CLOCK_MONOTONIC_RAW, &ts);
#else
  clock_gettime(CLOCK_MONOTONIC, &ts);
#endif
  return static_cast<int64_t>(ts.tv_sec) * 1000000000LL + ts.tv_nsec;
}

// Fill out[d*d] with out[rank*d + t] = chunk id processed by `rank` at ring
// step `t`. Returns 0 on success.
int32_t ddlb_ring_schedule(int32_t d, int32_t kind, int32_t* out) {
  if (d <= 0 || out == nullptr) return -1;
  for (int32_t r = 0; r < d; ++r) {
    for (int32_t t = 0; t < d; ++t) {
      int64_t c;
      switch (kind) {
        case DDLB_RING_AG_FWD: c = r - t; break;
        case DDLB_RING_AG_BWD: c = r + t; break;
        case DDLB_RING_RS_FWD: c = r + d - 1 - t; break;
        case DDLB_RING_RS_BWD: c = r + t + 1; break;
        default: return -2;
      }
      c %= d;
      if (c < 0) c += d;
      out[r * d + t] = static_cast<int32_t>(c);
    }
  }
  return 0;
}

// coll_pipeline reassembly map: stage outputs concatenate stage-major
// ([s, d, rows_per_block, n]) but the global result is rank-major
// ([d, s, rows_per_block, n]). out[j] = global row index of concat-order
// row j; m must be divisible by d*s. Returns 0 on success.
int32_t ddlb_coll_pipeline_row_map(int32_t m, int32_t d, int32_t s,
                                   int32_t* out) {
  if (m <= 0 || d <= 0 || s <= 0 || out == nullptr) return -1;
  if (m % (d * s) != 0) return -3;
  const int32_t b = m / (d * s);
  int32_t j = 0;
  for (int32_t stage = 0; stage < s; ++stage)
    for (int32_t rank = 0; rank < d; ++rank)
      for (int32_t row = 0; row < b; ++row, ++j)
        out[j] = rank * (s * b) + stage * b + row;
  return 0;
}

// Robust statistics over xs[n] into out[8]:
//   {mean, std(pop), min, max, median, p05, p95, mad}
// Percentiles use numpy's default linear interpolation on the sorted
// sample; mad is the median absolute deviation from the median.
int32_t ddlb_robust_stats(const double* xs, int32_t n, double* out) {
  if (xs == nullptr || n <= 0 || out == nullptr) return -1;
  std::vector<double> v(xs, xs + n);
  std::sort(v.begin(), v.end());

  double sum = 0.0;
  for (double x : v) sum += x;
  const double mean = sum / n;
  double var = 0.0;
  for (double x : v) var += (x - mean) * (x - mean);
  var /= n;

  auto percentile = [&](const std::vector<double>& sorted, double q) {
    const double pos = q * (sorted.size() - 1);
    const size_t lo = static_cast<size_t>(pos);
    const size_t hi = std::min(lo + 1, sorted.size() - 1);
    const double frac = pos - static_cast<double>(lo);
    return sorted[lo] * (1.0 - frac) + sorted[hi] * frac;
  };

  const double median = percentile(v, 0.5);
  std::vector<double> dev(v.size());
  for (size_t i = 0; i < v.size(); ++i) dev[i] = std::fabs(v[i] - median);
  std::sort(dev.begin(), dev.end());

  out[0] = mean;
  out[1] = std::sqrt(var);
  out[2] = v.front();
  out[3] = v.back();
  out[4] = median;
  out[5] = percentile(v, 0.05);
  out[6] = percentile(v, 0.95);
  out[7] = percentile(dev, 0.5);
  return 0;
}

// -- pipeline training schedule simulator ------------------------------------
//
// The native form of utils/pipeline_schedule.py: simulate the fwd/bwd
// dependency graph of a GPipe / 1F1B / interleaved-virtual-chunk pipeline
// under FIXED per-device issue orders (the Megatron sequences) and emit
// the dense per-tick tables the SPMD executors run from. Semantics are a
// line-for-line port of the Python simulator; the test suite pins the two
// implementations exactly equal over a (schedule, d, mb, v) matrix, so
// either path may serve any caller.
//
// Outputs (all int32, caller-allocated as [max_ticks * d]):
//   kind, mb, chunk, act_slot, in_slot, fwd_land, bwd_land
// meta[0..3] = {ticks, act_slots, land_slots, 0}; busy[d].
// Returns actual ticks, or <0 on error (-4: did not converge within
// max_ticks — the same safety net the Python version raises on).

enum DdlbSchedKind : int32_t {
  DDLB_SCHED_GPIPE = 0,
  DDLB_SCHED_1F1B = 1,
  DDLB_SCHED_INTERLEAVED = 2,
};

namespace {

struct FreeList {
  std::vector<int32_t> free;
  int32_t next = 0;
  int32_t high = 0;
  int32_t take() {
    if (!free.empty()) {
      int32_t s = free.back();
      free.pop_back();
      return s;
    }
    int32_t s = next++;
    if (next > high) high = next;
    return s;
  }
  void give(int32_t s) { free.push_back(s); }
};

}  // namespace

int32_t ddlb_pipeline_schedule(
    int32_t sched, int32_t d, int32_t mb, int32_t v, int32_t max_ticks,
    int32_t* kind, int32_t* mb_out, int32_t* chunk_out, int32_t* act_slot,
    int32_t* in_slot, int32_t* fwd_land, int32_t* bwd_land, int32_t* busy,
    int32_t* meta) {
  if (d <= 0 || mb <= 0 || v <= 0 || max_ticks <= 0) return -1;
  if (sched < 0 || sched > 2) return -2;
  if (sched == DDLB_SCHED_1F1B && v != 1) return -3;
  if (sched == DDLB_SCHED_INTERLEAVED && v < 2) return -3;
  const int32_t S = d * v;
  auto dev = [d](int32_t s) { return s % d; };
  auto chunk_of = [d](int32_t s) { return s / d; };
  auto key = [S](int32_t i, int32_t s) { return i * S + s; };

  // completion tick per op (absent = not done)
  std::unordered_map<int32_t, int32_t> fwd_done, bwd_done;
  std::vector<FreeList> acts(d), lands_f(d), lands_b(d);
  std::unordered_map<int32_t, int32_t> act_of, land_of_f, land_of_b;
  std::vector<int32_t> outstanding(d, 0);

  auto warmup_cap = [&](int32_t p) -> int32_t {
    if (sched == DDLB_SCHED_GPIPE) return mb * v;
    if (v == 1) return d - p;
    return (d - p - 1) * 2 + (v - 1) * d + 1;
  };

  // fixed Megatron issue orders: forwards round-robin chunk groups of d
  // microbatches, backwards the same groups chunks-deepest-first
  std::vector<std::vector<std::pair<int32_t, int32_t>>> fwd_order(d),
      bwd_order(d);
  for (int32_t p = 0; p < d; ++p) {
    auto& f = fwd_order[p];
    auto& b = bwd_order[p];
    for (int32_t c = 0; c < v; ++c)
      for (int32_t i = 0; i < mb; ++i) {
        f.push_back({i, c * d + p});
        b.push_back({i, c * d + p});
      }
    auto fkey = [&](const std::pair<int32_t, int32_t>& x) {
      return std::make_tuple(x.first / d, chunk_of(x.second), x.first % d);
    };
    auto bkey = [&](const std::pair<int32_t, int32_t>& x) {
      return std::make_tuple(x.first / d, v - 1 - chunk_of(x.second),
                             x.first % d);
    };
    std::stable_sort(f.begin(), f.end(),
                     [&](const auto& a, const auto& b_) {
                       return fkey(a) < fkey(b_);
                     });
    std::stable_sort(b.begin(), b.end(),
                     [&](const auto& a, const auto& b_) {
                       return bkey(a) < bkey(b_);
                     });
  }
  std::vector<int32_t> fptr(d, 0), bptr(d, 0);

  const int64_t n_ops_total = 2LL * mb * S;
  const int64_t total_fwd = 1LL * mb * S;
  int64_t done_ops = 0, fwd_issued = 0;
  int32_t t = 0;
  for (int32_t p = 0; p < d; ++p) busy[p] = 0;

  while (done_ops < n_ops_total) {
    if (t >= max_ticks) return -4;
    int32_t* row_kind = kind + static_cast<int64_t>(t) * d;
    int32_t* row_mb = mb_out + static_cast<int64_t>(t) * d;
    int32_t* row_chunk = chunk_out + static_cast<int64_t>(t) * d;
    int32_t* row_act = act_slot + static_cast<int64_t>(t) * d;
    int32_t* row_in = in_slot + static_cast<int64_t>(t) * d;
    int32_t* row_fl = fwd_land + static_cast<int64_t>(t) * d;
    int32_t* row_bl = bwd_land + static_cast<int64_t>(t) * d;
    for (int32_t p = 0; p < d; ++p) {
      row_kind[p] = 0;
      row_mb[p] = row_chunk[p] = row_act[p] = row_in[p] = -1;
      row_fl[p] = row_bl[p] = -1;
    }
    // 1) land last tick's arrivals (op finished at t-1 -> input
    // available from t on); iterate ops in deterministic (i, s) order
    // to match the Python dict-insertion iteration
    for (int32_t i = 0; i < mb; ++i)
      for (int32_t s = 0; s < S; ++s) {
        auto it = fwd_done.find(key(i, s));
        if (it != fwd_done.end() && it->second == t - 1 && s + 1 < S) {
          int32_t p = dev(s + 1);
          int32_t slot = lands_f[p].take();
          land_of_f[key(i, s + 1)] = slot;
          row_fl[p] = slot;
        }
        auto ib = bwd_done.find(key(i, s));
        if (ib != bwd_done.end() && ib->second == t - 1 && s - 1 >= 0) {
          int32_t p = dev(s - 1);
          int32_t slot = lands_b[p].take();
          land_of_b[key(i, s - 1)] = slot;
          row_bl[p] = slot;
        }
      }
    // 2) each device runs the next ready op of its fixed order
    for (int32_t p = 0; p < d; ++p) {
      bool picked = false;
      const bool bwd_ok =
          sched != DDLB_SCHED_GPIPE || fwd_issued == total_fwd;
      if (bwd_ok && bptr[p] < static_cast<int32_t>(bwd_order[p].size())) {
        auto [i, s] = bwd_order[p][bptr[p]];
        auto tf = fwd_done.find(key(i, s));
        bool ready = tf != fwd_done.end() && tf->second < t;
        if (ready && s + 1 < S) {
          auto td = bwd_done.find(key(i, s + 1));
          ready = td != bwd_done.end() && td->second < t;
        }
        if (ready) {
          bwd_done[key(i, s)] = t;
          outstanding[p] -= 1;
          int32_t slot = act_of[key(i, s)];
          act_of.erase(key(i, s));
          acts[p].give(slot);
          row_kind[p] = 2;
          row_mb[p] = i;
          row_chunk[p] = chunk_of(s);
          row_act[p] = slot;
          if (s + 1 < S) {
            int32_t l = land_of_b[key(i, s)];
            land_of_b.erase(key(i, s));
            row_in[p] = l;
            lands_b[p].give(l);
          }
          ++done_ops;
          ++busy[p];
          picked = true;
        }
      }
      if (!picked && outstanding[p] < warmup_cap(p) &&
          fptr[p] < static_cast<int32_t>(fwd_order[p].size())) {
        auto [i, s] = fwd_order[p][fptr[p]];
        bool ready = true;
        if (s > 0) {
          auto td = fwd_done.find(key(i, s - 1));
          ready = td != fwd_done.end() && td->second < t;
        }
        if (ready) {
          fwd_done[key(i, s)] = t;
          ++fwd_issued;
          outstanding[p] += 1;
          int32_t slot = acts[p].take();
          act_of[key(i, s)] = slot;
          row_kind[p] = 1;
          row_mb[p] = i;
          row_chunk[p] = chunk_of(s);
          row_act[p] = slot;
          if (s > 0) {
            int32_t l = land_of_f[key(i, s)];
            land_of_f.erase(key(i, s));
            row_in[p] = l;
            lands_f[p].give(l);
          }
          ++fptr[p];
          ++done_ops;
          ++busy[p];
          picked = true;
        }
      }
      if (picked && row_kind[p] == 2) ++bptr[p];
    }
    ++t;
  }

  int32_t act_high = 1, land_high = 1;
  for (int32_t p = 0; p < d; ++p) {
    act_high = std::max(act_high, acts[p].high);
    land_high = std::max(land_high, lands_f[p].high);
    land_high = std::max(land_high, lands_b[p].high);
  }
  meta[0] = t;
  meta[1] = act_high;
  meta[2] = land_high;
  meta[3] = 0;
  return t;
}

}  // extern "C"
