// Native host-runtime for ddlb_tpu: pipeline schedule planning, monotonic
// timing, and robust statistics.
//
// This is the framework's in-repo native layer. The reference keeps all of
// its native capability in dependencies (NCCL, nvFuser's C++
// MultiDeviceExecutor, TransformerEngine — SURVEY.md section 2.4,
// /root/reference/ddlb/primitives/TPColumnwise/fuser.py:247-257): the
// executor's HOST side plans which chunk each rank processes at each
// pipeline step and how staged outputs reassemble. Here that planner is
// this translation unit; the DEVICE side of the same pipelines is the
// Pallas kernel layer (ddlb_tpu/ops/). Exposed as a plain C ABI consumed
// via ctypes (ddlb_tpu/native/__init__.py).
//
// Schedule conventions (shared with the shard_map pipelines in
// ddlb_tpu/primitives/*/overlap.py and the ring kernels in
// ddlb_tpu/ops/collective_matmul.py):
//   ag_fwd: after t forward ring hops a device holds A-chunk (rank - t) mod d
//   ag_bwd: backward ring, chunk (rank + t) mod d
//   rs_fwd: accumulator schedule (rank + d - 1 - t) mod d, so after d steps
//           each device ends holding its own fully-reduced output chunk
//   rs_bwd: the backward half of the bidirectional reduce-scatter ring,
//           chunk (rank + t + 1) mod d

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <ctime>
#include <vector>

extern "C" {

enum DdlbRingKind : int32_t {
  DDLB_RING_AG_FWD = 0,
  DDLB_RING_AG_BWD = 1,
  DDLB_RING_RS_FWD = 2,
  DDLB_RING_RS_BWD = 3,
};

// Monotonic nanosecond clock (CLOCK_MONOTONIC_RAW is immune to NTP slew).
int64_t ddlb_now_ns() {
  timespec ts;
#ifdef CLOCK_MONOTONIC_RAW
  clock_gettime(CLOCK_MONOTONIC_RAW, &ts);
#else
  clock_gettime(CLOCK_MONOTONIC, &ts);
#endif
  return static_cast<int64_t>(ts.tv_sec) * 1000000000LL + ts.tv_nsec;
}

// Fill out[d*d] with out[rank*d + t] = chunk id processed by `rank` at ring
// step `t`. Returns 0 on success.
int32_t ddlb_ring_schedule(int32_t d, int32_t kind, int32_t* out) {
  if (d <= 0 || out == nullptr) return -1;
  for (int32_t r = 0; r < d; ++r) {
    for (int32_t t = 0; t < d; ++t) {
      int64_t c;
      switch (kind) {
        case DDLB_RING_AG_FWD: c = r - t; break;
        case DDLB_RING_AG_BWD: c = r + t; break;
        case DDLB_RING_RS_FWD: c = r + d - 1 - t; break;
        case DDLB_RING_RS_BWD: c = r + t + 1; break;
        default: return -2;
      }
      c %= d;
      if (c < 0) c += d;
      out[r * d + t] = static_cast<int32_t>(c);
    }
  }
  return 0;
}

// coll_pipeline reassembly map: stage outputs concatenate stage-major
// ([s, d, rows_per_block, n]) but the global result is rank-major
// ([d, s, rows_per_block, n]). out[j] = global row index of concat-order
// row j; m must be divisible by d*s. Returns 0 on success.
int32_t ddlb_coll_pipeline_row_map(int32_t m, int32_t d, int32_t s,
                                   int32_t* out) {
  if (m <= 0 || d <= 0 || s <= 0 || out == nullptr) return -1;
  if (m % (d * s) != 0) return -3;
  const int32_t b = m / (d * s);
  int32_t j = 0;
  for (int32_t stage = 0; stage < s; ++stage)
    for (int32_t rank = 0; rank < d; ++rank)
      for (int32_t row = 0; row < b; ++row, ++j)
        out[j] = rank * (s * b) + stage * b + row;
  return 0;
}

// Robust statistics over xs[n] into out[8]:
//   {mean, std(pop), min, max, median, p05, p95, mad}
// Percentiles use numpy's default linear interpolation on the sorted
// sample; mad is the median absolute deviation from the median.
int32_t ddlb_robust_stats(const double* xs, int32_t n, double* out) {
  if (xs == nullptr || n <= 0 || out == nullptr) return -1;
  std::vector<double> v(xs, xs + n);
  std::sort(v.begin(), v.end());

  double sum = 0.0;
  for (double x : v) sum += x;
  const double mean = sum / n;
  double var = 0.0;
  for (double x : v) var += (x - mean) * (x - mean);
  var /= n;

  auto percentile = [&](const std::vector<double>& sorted, double q) {
    const double pos = q * (sorted.size() - 1);
    const size_t lo = static_cast<size_t>(pos);
    const size_t hi = std::min(lo + 1, sorted.size() - 1);
    const double frac = pos - static_cast<double>(lo);
    return sorted[lo] * (1.0 - frac) + sorted[hi] * frac;
  };

  const double median = percentile(v, 0.5);
  std::vector<double> dev(v.size());
  for (size_t i = 0; i < v.size(); ++i) dev[i] = std::fabs(v[i] - median);
  std::sort(dev.begin(), dev.end());

  out[0] = mean;
  out[1] = std::sqrt(var);
  out[2] = v.front();
  out[3] = v.back();
  out[4] = median;
  out[5] = percentile(v, 0.05);
  out[6] = percentile(v, 0.95);
  out[7] = percentile(dev, 0.5);
  return 0;
}

}  // extern "C"
