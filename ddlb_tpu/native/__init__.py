"""ctypes bindings for the native host-runtime, with Python fallbacks.

The in-repo native layer (see ``host_runtime.cpp`` for the design note):
pipeline schedule planning shared by the shard_map overlap pipelines and
the Pallas ring kernels, a monotonic nanosecond clock for the timing
subsystem, and robust statistics for the benchmark rows. Every entry point
has a numpy fallback with identical semantics, so the framework works
without a C++ toolchain — ``available()`` reports which path is live.
"""

from __future__ import annotations

import ctypes
from typing import Dict, Optional

import numpy as np

RING_KINDS = {"ag_fwd": 0, "ag_bwd": 1, "rs_fwd": 2, "rs_bwd": 3}
SCHED_KINDS = {"gpipe": 0, "1f1b": 1, "interleaved": 2}
STAT_NAMES = ("mean", "std", "min", "max", "median", "p05", "p95", "mad")
SCHEDULE_TABLE_NAMES = (
    "kind", "mb", "chunk", "act_slot", "in_slot", "fwd_land", "bwd_land",
)

_lib: Optional[ctypes.CDLL] = None
_tried = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _tried:
        return _lib
    _tried = True
    from ddlb_tpu import envs

    if envs.get_no_native():
        return None
    from ddlb_tpu.native.build import build

    path = build()
    if path is None:
        return None
    try:
        lib = ctypes.CDLL(path)
        lib.ddlb_now_ns.restype = ctypes.c_int64
        lib.ddlb_now_ns.argtypes = []
        lib.ddlb_ring_schedule.restype = ctypes.c_int32
        lib.ddlb_ring_schedule.argtypes = [
            ctypes.c_int32, ctypes.c_int32, ctypes.POINTER(ctypes.c_int32),
        ]
        lib.ddlb_coll_pipeline_row_map.restype = ctypes.c_int32
        lib.ddlb_coll_pipeline_row_map.argtypes = [
            ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int32),
        ]
        lib.ddlb_robust_stats.restype = ctypes.c_int32
        lib.ddlb_robust_stats.argtypes = [
            ctypes.POINTER(ctypes.c_double), ctypes.c_int32,
            ctypes.POINTER(ctypes.c_double),
        ]
        i32p = ctypes.POINTER(ctypes.c_int32)
        lib.ddlb_pipeline_schedule.restype = ctypes.c_int32
        lib.ddlb_pipeline_schedule.argtypes = (
            [ctypes.c_int32] * 5 + [i32p] * 9
        )
    except (OSError, AttributeError):
        # AttributeError: a stale cached .so built from an older source
        # revision missing a symbol — fall back to the numpy path
        return None
    _lib = lib
    return _lib


def available() -> bool:
    """True when the compiled library is loaded (vs Python fallbacks)."""
    return _load() is not None


def now_ns() -> int:
    """Monotonic nanosecond timestamp."""
    lib = _load()
    if lib is not None:
        return int(lib.ddlb_now_ns())
    import time

    return time.perf_counter_ns()


def ring_schedule(d: int, kind: str = "ag_fwd") -> np.ndarray:
    """``[d, d]`` int32 table: entry ``[rank, t]`` is the chunk id that
    ``rank`` processes at ring step ``t`` (conventions in host_runtime.cpp).
    """
    if kind not in RING_KINDS:
        raise ValueError(f"unknown ring kind '{kind}'; valid: {sorted(RING_KINDS)}")
    if d <= 0:
        raise ValueError(f"d must be positive, got {d}")
    lib = _load()
    if lib is not None:
        out = np.empty((d, d), np.int32)
        rc = lib.ddlb_ring_schedule(
            d, RING_KINDS[kind],
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        )
        if rc != 0:  # pragma: no cover - args validated above
            raise RuntimeError(f"ddlb_ring_schedule failed: {rc}")
        return out
    r = np.arange(d, dtype=np.int64)[:, None]
    t = np.arange(d, dtype=np.int64)[None, :]
    table = {
        "ag_fwd": r - t,
        "ag_bwd": r + t,
        "rs_fwd": r + d - 1 - t,
        "rs_bwd": r + t + 1,
    }[kind]
    return np.asarray(np.mod(table, d), np.int32)


def coll_pipeline_row_map(m: int, d: int, s: int) -> np.ndarray:
    """``[m]`` int32 map from stage-major concatenated output rows to global
    row indices (the reference's host-side ``[s,d,b,n] -> [d,s,b,n]``
    reassembly, /root/reference/ddlb/primitives/TPColumnwise/fuser.py:271-279,
    as an explicit permutation).

    This is the planner's specification of the reassembly; the on-device
    coll_pipeline keeps the equivalent reshape/transpose because a
    constant-index row gather measured ~19% slower than the transpose copy
    on v5e (8192x8192) — the permutation form is for host-side consumers
    and kernel authors, and the test suite pins the two forms equal.
    """
    if m <= 0 or d <= 0 or s <= 0 or m % (d * s) != 0:
        raise ValueError(f"m={m} must be a positive multiple of d*s={d * s}")
    lib = _load()
    if lib is not None:
        out = np.empty(m, np.int32)
        rc = lib.ddlb_coll_pipeline_row_map(
            m, d, s, out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))
        )
        if rc != 0:  # pragma: no cover - args validated above
            raise RuntimeError(f"ddlb_coll_pipeline_row_map failed: {rc}")
        return out
    b = m // (d * s)
    idx = np.arange(m, dtype=np.int32).reshape(d, s, b)  # global rank-major
    return idx.transpose(1, 0, 2).reshape(m).astype(np.int32)


def pipeline_schedule(
    schedule: str, n_devices: int, microbatches: int, virtual: int = 1
) -> Optional[Dict[str, object]]:
    """Native pipeline-schedule simulator (``ddlb_pipeline_schedule``).

    Simulates the GPipe / 1F1B / interleaved dependency graph under the
    fixed Megatron issue orders and returns the dense per-tick tables the
    SPMD executors run from — the same outputs as the Python simulator in
    ``utils/pipeline_schedule.py``, to which it is pinned exactly equal by
    ``tests/test_native.py`` over a (schedule, d, mb, v) matrix.

    Returns ``None`` when the compiled library is unavailable (callers
    fall back to the Python simulator). Raises on invalid arguments or a
    non-converging schedule, mirroring the Python path.
    """
    if schedule not in SCHED_KINDS:
        raise ValueError(
            f"unknown schedule '{schedule}'; one of {sorted(SCHED_KINDS)}"
        )
    d, mb, v = int(n_devices), int(microbatches), int(virtual)
    if d <= 0 or mb <= 0 or v <= 0:
        raise ValueError(f"d/mb/v must be positive, got {(d, mb, v)}")
    lib = _load()
    if lib is None:
        return None
    # same safety-net bound as the Python simulator
    max_ticks = 16 * (mb * v + d) + 64
    bufs = {
        name: np.empty((max_ticks, d), np.int32)
        for name in SCHEDULE_TABLE_NAMES
    }
    busy = np.zeros(d, np.int32)
    meta = np.zeros(4, np.int32)

    def _p(a: np.ndarray):
        return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))

    rc = lib.ddlb_pipeline_schedule(
        SCHED_KINDS[schedule], d, mb, v, max_ticks,
        *(_p(bufs[name]) for name in SCHEDULE_TABLE_NAMES),
        _p(busy), _p(meta),
    )
    if rc < 0:
        raise RuntimeError(
            f"ddlb_pipeline_schedule('{schedule}', d={d}, mb={mb}, v={v}) "
            f"failed: rc={rc}"
        )
    ticks = int(meta[0])
    out: Dict[str, object] = {
        name: bufs[name][:ticks].copy() for name in SCHEDULE_TABLE_NAMES
    }
    out["ticks"] = ticks
    out["act_slots"] = max(int(meta[1]), 1)
    out["land_slots"] = max(int(meta[2]), 1)
    out["busy"] = busy.astype(np.int64)
    return out


def robust_stats(xs) -> Dict[str, float]:
    """Mean/std(pop)/min/max/median/p05/p95/MAD of a 1-D sample.

    A sample containing any non-finite value yields all-NaN stats on both
    the native and fallback paths (sorting NaNs is undefined in C++, so the
    contract is pinned here rather than left to diverge).
    """
    arr = np.ascontiguousarray(np.asarray(xs, np.float64).ravel())
    if arr.size == 0:
        raise ValueError("robust_stats needs a non-empty sample")
    if not np.all(np.isfinite(arr)):
        return {name: float("nan") for name in STAT_NAMES}
    lib = _load()
    if lib is not None:
        out = np.empty(8, np.float64)
        rc = lib.ddlb_robust_stats(
            arr.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            arr.size,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        )
        if rc != 0:  # pragma: no cover - args validated above
            raise RuntimeError(f"ddlb_robust_stats failed: {rc}")
        return dict(zip(STAT_NAMES, out.tolist()))
    med = float(np.median(arr))
    return {
        "mean": float(np.mean(arr)),
        "std": float(np.std(arr)),
        "min": float(np.min(arr)),
        "max": float(np.max(arr)),
        "median": med,
        "p05": float(np.percentile(arr, 5)),
        "p95": float(np.percentile(arr, 95)),
        "mad": float(np.median(np.abs(arr - med))),
    }
