"""Benchmark runner: per-implementation measurement, sweep orchestration, CSV.

TPU-native rebuild of the reference runner (/root/reference/ddlb/
benchmark.py:19-425). Same measurement methodology — warmup, optional
profiler window, timing loop with selectable backend and per-iteration
barrier, cross-process MAX-reduce of times, TFLOPS = 2mnk/1e9/ms, soft
validation, incremental CSV, bar-chart plotting — with TPU-shaped
mechanics:

- timing backends are ``host_clock`` (perf_counter + completion fence,
  the analogue of the reference's cpu_clock + cuda.synchronize,
  benchmark.py:161-186) and ``device_loop`` (the cuda_event analogue done
  the XLA way: the N-iteration loop compiled into one device program with
  differential two-window overhead cancellation — see utils/timing.py);
- the profiler window wraps ``jax.profiler`` instead of cudaProfilerApi
  (benchmark.py:87-104; SURVEY.md section 5 "tracing");
- per-implementation isolation: the reference spawns a child process per
  implementation (benchmark.py:336-370) because CUDA backends poison each
  other; the TPU runtime owns its chips for the process lifetime, so the
  default is in-process with ``jax.clear_caches()`` at executable-signature
  boundaries (configs sharing an executable run adjacently and keep the
  warm cache — utils/compile_ahead.py), and ``isolation='subprocess'``
  restores full process isolation where the platform allows it — verified
  working on CPU simulation AND on the real single-chip TPU (children run
  sequentially, each owning the chip for its row; they pay a fresh compile
  unless the persistent cache answers, so the in-process default stays
  faster). Subprocess rows run on the persistent warm-worker pool
  (``ddlb_tpu/pool.py``): one long-lived child per environment
  signature, leased and reused across rows, so process spawn, JAX
  import, PJRT init and mesh build are paid once per sweep instead of
  once per row — ``worker_pool=False`` (or ``pool_max_rows=1``) keeps
  spawn-per-row as the degenerate case, and every row records
  ``worker_reused`` / ``worker_setup_s`` so the amortization is visible
  in the CSV;
- compile-ahead: with ``DDLB_TPU_COMPILE_CACHE`` set, the in-process
  runner AOT-compiles config N+1 on a background thread while config N's
  timing loop runs on device, and every row records ``compile_time_s`` /
  ``compile_cache_hit`` so the engine's win is visible in the CSV.
"""

from __future__ import annotations

import os
import socket
import time
from typing import Any, Dict, List, Optional

import numpy as np

from ddlb_tpu import envs, faults, telemetry
from ddlb_tpu.faults import flightrec, heartbeat
from ddlb_tpu.telemetry import clocksync
from ddlb_tpu.observatory import attribution as overlap_attribution
from ddlb_tpu.perfmodel import cost as perfmodel_cost
from ddlb_tpu.observatory import live, store
from ddlb_tpu.faults.classify import TRANSIENT, classify_error
from ddlb_tpu.native import now_ns, robust_stats
from ddlb_tpu.primitives.registry import (
    ALLOWED_PRIMITIVES,
    load_impl_class,
    throughput_unit,
)
from ddlb_tpu.utils.compile_ahead import (
    CompileAheadScheduler,
    compile_metrics,
    executable_signature,
    order_by_signature,
)
from ddlb_tpu.utils.timing import fence, measure_device_loop

TIMING_BACKENDS = ("host_clock", "device_loop")

#: the analytical-perfmodel columns every row carries (measured, crashed
#: and timed-out alike — the CSV header is fixed by the first row
#: written): the predicted lower bound, the achieved fraction of it, the
#: dominating roofline term, and the spec the prediction was made
#: against — plus the observatory's measured-overlap attribution set
#: (``measured_overlap_frac`` and the per-phase compute/comm/idle
#: breakdown, ISSUE 6). Defaults fill rows whose worker died before an
#: impl existed.
PERF_ROW_DEFAULTS: Dict[str, Any] = {
    "predicted_s": float("nan"),
    "roofline_frac": float("nan"),
    "bound": "",
    "chip": "",
    # the calibrated-prediction trio (ISSUE 17): stamped only when a
    # calibration table covers the chip; these defaults otherwise, so
    # an uncalibrated sweep's rows are byte-identical to pre-calib ones
    "predicted_cal_s": float("nan"),
    "cal_residual_frac": float("nan"),
    "cal_version": "",
    # the tuning trio (ISSUE 20): stamped only when an active
    # DDLB_TPU_TUNING table hit applied banked knobs to this impl;
    # these defaults otherwise, so an untuned sweep's rows are
    # byte-identical to pre-tuner ones
    "tuned": False,
    "tuning_version": "",
    "prior_rank": float("nan"),
    **overlap_attribution.ATTRIBUTION_ROW_DEFAULTS,
}


def _perfmodel_fields(
    impl, times_ms: np.ndarray, backend: str = "host_clock"
) -> Dict[str, Any]:
    """The perfmodel columns for one row: the impl's ``cost_model()``
    verdict plus ``roofline_frac`` against the measured MEDIAN (the
    jitter-robust statistic the headline bench also pins), and the
    observatory's measured-overlap attribution — the achieved overlap
    fraction and per-phase compute/comm/idle breakdown derived by
    joining the measurement against the model's ``COST_SCHEDULE`` terms
    (``ddlb_tpu/observatory/attribution.py``). A model failure must
    never discard a completed measurement — it degrades to the default
    columns with a warning."""
    if impl is None:
        return {}
    # the tuning trio (ISSUE 20): which banked winner this construction
    # applied, if any (Primitive._consult_tuning_table) — stamped even
    # when the cost model below fails, so tuned rows stay fenceable
    stamp = getattr(impl, "tuning_stamp", None)
    tuning_fields: Dict[str, Any] = {}
    if isinstance(stamp, dict):
        tuning_fields = {
            "tuned": bool(stamp.get("tuned", False)),
            "tuning_version": str(stamp.get("tuning_version", "")),
            "prior_rank": float(stamp.get("prior_rank", float("nan"))),
        }
    try:
        est = impl.cost_model()
    except Exception as exc:
        telemetry.warn(
            f"perfmodel cost estimate failed: {type(exc).__name__}: {exc}"
        )
        return tuning_fields
    finite = times_ms[np.isfinite(times_ms)]
    measured_s = float(np.median(finite)) * 1e-3 if finite.size else float("nan")
    fields = {
        "predicted_s": est.predicted_s,
        "roofline_frac": est.roofline_frac(measured_s),
        "bound": est.bound,
        "chip": est.chip,
        **overlap_attribution.attribute(
            est,
            getattr(impl, "COST_SCHEDULE", "sequential"),
            measured_s,
            chunks=perfmodel_cost.overlap_chunks(impl),
        ),
    }
    # the calibrated trio (ISSUE 17): priced per (chip, timing backend)
    # from the DDLB_TPU_CALIB table; absent table/group leaves the
    # PERF_ROW_DEFAULTS in place — the uncalibrated row is untouched
    try:
        cal = perfmodel_cost.calibrated_estimate(impl, backend=backend)
    except Exception as exc:
        telemetry.warn(
            f"calibrated estimate failed: {type(exc).__name__}: {exc}"
        )
        cal = None
    if cal is not None:
        fields["predicted_cal_s"] = cal.predicted_cal_s
        fields["cal_residual_frac"] = cal.residual_frac(measured_s)
        fields["cal_version"] = cal.version
    fields.update(tuning_fields)
    return fields


# ---------------------------------------------------------------------------
# Worker: one implementation, one shape (reference _benchmark_worker_entry,
# benchmark.py:19-256)
# ---------------------------------------------------------------------------


def benchmark_worker(config: Dict[str, Any]) -> Dict[str, Any]:
    """Measure one implementation; returns one result row."""
    import jax

    primitive = config["primitive"]
    impl_id = config["impl_id"]
    base_impl = config["base_implementation"]
    options = dict(config.get("options", {}))
    m, n, k = config["m"], config["n"], config["k"]
    dtype = config.get("dtype", "bfloat16")
    num_iterations = config.get("num_iterations", 50)
    num_warmups = config.get("num_warmups", 5)
    timing_backend = config.get("time_measurement_backend", "host_clock")
    barrier_each = config.get("barrier_at_each_iteration", True)
    do_validate = config.get("validate", True)
    profile_dir = config.get("profile_dir")

    # which retry attempt this run is (the self-healing runner threads it
    # through the config): fault-plan rules gate on it (fail_attempts),
    # and it lands in the row's ``retries`` column
    fault_attempt = int(config.get("fault_attempt", 0) or 0)

    if timing_backend not in TIMING_BACKENDS:
        raise ValueError(
            f"Unknown timing backend '{timing_backend}'. "
            f"Allowed: {TIMING_BACKENDS}"
        )

    from ddlb_tpu.runtime import Runtime, configure_compile_cache

    # apply DDLB_TPU_COMPILE_CACHE even when a Runtime singleton predates
    # the env var (idempotent; a no-op when unset)
    configure_compile_cache()
    runtime = Runtime()
    # allocator high-water mark BEFORE this config touches the device:
    # hbm_peak_gib is attached only if this config raises it (see below)
    peak_at_entry = _device_hbm_peak()
    error: Optional[str] = None
    result = None
    impl = None
    option_repr = _format_options(options)
    # Phase heartbeats: flushed BEFORE each long stage so a worker that
    # dies on a timeout leaves a log saying WHICH stage ate the clock
    # (the r2 live session burned 1800 s on a ctx=8192 row and the
    # TimeoutError could not distinguish a slow compile from a hung
    # relay — r4 verdict weak #8). hw_common forwards these lines from
    # crashed/hung children on every exit path.
    def _mark(stage: str, t0=[now_ns()]) -> None:
        t1 = now_ns()
        telemetry.log(
            f"worker: {stage}", elapsed_s=round((t1 - t0[0]) * 1e-9, 1)
        )
        # liveness beat at every phase boundary: a subprocess parent with
        # worker_timeout extends a beating child's deadline instead of
        # killing a slow-but-alive row (ddlb_tpu/faults/heartbeat.py)
        heartbeat.beat()
        # the same phase boundary feeds the live dashboard's "current
        # row" line (a no-op env check unless DDLB_TPU_LIVE is set)
        live.post_event("row_phase", stage=stage, impl=impl_id)
        # ... and the flight recorder's sequenced record: in a launched
        # world, per-rank phase marks bracket the collective entries so
        # a post-mortem shows the last phase every rank reached
        flightrec.mark("worker.phase", stage=stage, impl=impl_id)
        t0[0] = t1

    # compile accounting for the whole measured region (setup, warmup,
    # timing loops, validation); a real with-block so the thread-local
    # collector can never leak, even on BaseException (SystemExit,
    # KeyboardInterrupt) escaping the crash-isolation except below.
    # The metrics scope rides along: barrier wait, loop overhead, HBM
    # high-water and collective wire bytes recorded anywhere under this
    # row land in its result columns (telemetry.ROW_METRIC_DEFAULTS).
    # the fault scope rides along: injection sites below see this row's
    # retry attempt + impl identity, and the sites that actually fired
    # are collected into the row's ``fault_injected`` column
    with compile_metrics() as _cm, telemetry.metrics_scope() as _ms, \
            faults.scope(
                attempt=fault_attempt, impl=impl_id, primitive=primitive
            ) as _fs, \
            telemetry.span(
                "worker.row", cat="row", impl=impl_id, primitive=primitive
            ):
        # the cross-rank skew fold reads exactly this row's collective
        # spans: drop whatever a previous row (or bootstrap) recorded
        clocksync.reset_row()
        skew_fields: Optional[Dict[str, Any]] = None
        try:
            faults.inject("worker.setup")
            impl_class = load_impl_class(primitive, base_impl)
            # option merge: DEFAULT_OPTIONS ∪ overrides (reference
            # benchmark.py:76-77); crash isolation covers construction too —
            # a bad option or OOM becomes a row, not an aborted sweep
            # (reference per-impl child process, benchmark.py:336-370).
            _mark("setup begin (backend init + operand placement + prefill)")
            with telemetry.span("worker.setup", cat="setup", impl=impl_id):
                impl = impl_class(m, n, k, dtype=dtype, **options)
            option_repr = _format_options(impl.options)
            wire = getattr(impl, "wire_bytes", None)
            if callable(wire):
                # bytes one device moves per collective op — primitive
                # metadata, snapshotted into the row's collective_bytes
                try:
                    telemetry.record_max("collective_bytes", float(wire()))
                except Exception as exc:
                    # metadata-only: never fail the measurement, but a
                    # family whose wire_bytes() breaks must be visible
                    telemetry.warn(
                        f"wire_bytes() failed for {impl_id}: "
                        f"{type(exc).__name__}: {exc}"
                    )
            _mark("setup done; warmup begin (first compile happens here)")

            # warmup (reference benchmark.py:84-85)
            faults.inject("worker.warmup")
            with telemetry.span("worker.warmup", cat="warmup", impl=impl_id):
                for _ in range(num_warmups):
                    result = impl.run()
                fence(result)
            _mark("warmup done; measuring")

            # profiler window (reference cudaProfilerStart/Stop window,
            # benchmark.py:87-104 -> jax.profiler trace for xprof/tensorboard)
            if profile_dir:
                with telemetry.span(
                    "worker.profile", cat="profile", impl=impl_id
                ):
                    with jax.profiler.trace(profile_dir):
                        for _ in range(5):
                            result = impl.run()
                        fence(result)
                    # re-warm after tracing overhead (reference
                    # benchmark.py:121-122)
                    for _ in range(num_warmups):
                        result = impl.run()
                    fence(result)

            faults.inject("worker.timing")
            with telemetry.span(
                "worker.timing", cat="timing", impl=impl_id,
                backend=timing_backend,
            ):
                times_ms = _timing_loop(
                    impl,
                    runtime,
                    num_iterations,
                    timing_backend,
                    barrier_each,
                    num_windows=config.get("device_loop_windows", 5),
                    min_window_s=config.get("device_loop_min_window_ms", 100.0)
                    * 1e-3,
                )
                times_ms = _max_reduce_across_processes(times_ms, runtime)
            # cross-rank skew fold (ISSUE 14): while the world is still
            # in lock-step, allgather every rank's collective entry/exit
            # stamps, align clocks on the row's own barrier exchanges,
            # and fold the arrival skew into the row's skew columns. A
            # no-op (defaults) on single-process worlds.
            skew_fields = clocksync.fold_row_skew(runtime)
            _mark("measured; validation begin" if do_validate else "measured")

            valid = True
            if do_validate:
                # a validation crash (e.g. the oracle OOMs at a context the
                # measured step handles fine) must not discard the completed
                # measurement: times stand, valid=False + error records why
                faults.inject("worker.validate")
                with telemetry.span(
                    "worker.validate", cat="validate", impl=impl_id
                ):
                    try:
                        result = impl.run()
                        fence(result)
                        # corrupted-numerics site: the array comes back
                        # wrong and validate() must catch it — the
                        # deterministic stand-in for silent data
                        # corruption
                        result = faults.corrupt("worker.result", result)
                        valid = bool(impl.validate(result))
                    except Exception as exc:
                        error = (
                            f"validation crashed: {type(exc).__name__}: {exc}"
                        )
                        valid = False
                if not valid:
                    # soft failure: recorded, not fatal (reference
                    # benchmark.py:242-245)
                    telemetry.warn(f"validation failed for {impl_id}")
        except Exception as exc:  # crash isolation: report as a row
            error = f"{type(exc).__name__}: {exc}"
            times_ms = np.array([float("nan")])
            valid = False
        # allocator high-water: recorded while the row's scope is still
        # active so it lands in the hbm_high_water_bytes column (same
        # raised-by-THIS-config rule as hbm_peak_gib below)
        peak = _device_hbm_peak()
        peak_raised = peak is not None and (
            peak_at_entry is None or peak > peak_at_entry
        )
        if peak_raised:
            telemetry.record_max("hbm_high_water_bytes", peak)

    # TFLOPS = flops / 1e9 / time_ms; GEMM primitives use the reference's
    # 2*m*n*k (benchmark.py:209-214), attention primitives override
    # flops(). No impl -> no flop convention: NaN, matching the row's
    # NaN times (a number here would imply a semantics the family may
    # not have — transformer/collectives flops are not 2mnk)
    flop_count = impl.flops() if impl is not None else float("nan")
    row = make_result_row(
        config,
        times_ms=times_ms,
        flop_count=flop_count,
        option_repr=option_repr,
        valid=valid,
        error=error or "",
        world_size=runtime.num_devices,
        num_processes=runtime.num_processes,
        platform=runtime.platform,
        compile_time_s=round(_cm.compile_time_s, 4),
        compile_cache_hit=_cm.cache_hit,
        metrics=_ms.row_fields(),
        # the robustness columns (ISSUE 4): which retry attempt this row
        # came from, which fault-plan sites fired under it, and the
        # transient-vs-deterministic class of its error (the retry/park
        # decision, recorded so every failure is attributable)
        retries=fault_attempt,
        fault_injected=",".join(dict.fromkeys(_fs.fired)),
        error_class=classify_error(error or "", valid),
        # the analytical lower bound rides EVERY row that constructed an
        # impl — including error rows (the prediction is shape-only, so a
        # timing/validation crash still gets predicted_s and bound; only
        # roofline_frac needs the measurement and degrades to NaN)
        perf=_perfmodel_fields(impl, times_ms, backend=timing_backend),
        # the cross-rank skew columns (ISSUE 14): arrival-skew seconds,
        # exit spread, the straggler rank and its waited-on share, with
        # the clock-alignment uncertainty bound alongside; defaults on
        # single-process rows and rows whose worker died pre-fold
        skew=skew_fields,
    )
    if impl is not None and np.isfinite(times_ms).any():
        # family-specific measured quantities (speculate acceptance
        # rate, serve engine stats); a failure here must not discard
        # the completed measurement
        try:
            row.update(impl.extra_row_fields())
        except Exception as exc:
            telemetry.warn(
                f"extra_row_fields failed: {type(exc).__name__}: {exc}"
            )
    if peak_raised:
        # measured HBM peak next to the row: each hardware capture
        # doubles as a calibration point for the static budget model
        # (utils/hbm_budget.py) that right-sizes the long-context rows.
        # The allocator's high-water mark is PROCESS-lifetime and never
        # resets, so the field only lands when THIS config raised it —
        # always true in the subprocess-per-config paths (hw batches,
        # isolation='subprocess'), and only for the high-water config
        # in an in-process sweep (other rows would inherit its value).
        row["hbm_peak_gib"] = round(peak / 2**30, 3)
    del impl, result
    return row


def _device_hbm_peak() -> Optional[int]:
    """Device 0's peak allocated bytes, or None where the backend does
    not report allocator stats (the CPU sim)."""
    try:
        import jax

        stats = jax.local_devices()[0].memory_stats()
        peak = (stats or {}).get("peak_bytes_in_use")
        return int(peak) if peak is not None else None
    except Exception:
        return None


def make_result_row(
    config: Dict[str, Any],
    times_ms: np.ndarray,
    flop_count: float,
    option_repr: str,
    valid: bool,
    error: str,
    world_size: int,
    num_processes: int,
    platform: str,
    compile_time_s: float = float("nan"),
    compile_cache_hit: bool = False,
    metrics: Optional[Dict[str, Any]] = None,
    perf: Optional[Dict[str, Any]] = None,
    skew: Optional[Dict[str, Any]] = None,
    retries: int = 0,
    fault_injected: str = "",
    error_class: str = "",
    quarantined: bool = False,
) -> Dict[str, Any]:
    """The one result-row schema, shared by measured, crashed and
    timed-out workers so the CSV columns cannot drift apart.

    Statistics come from the native host-runtime
    (ddlb_tpu/native/host_runtime.cpp); median and p95 are
    jitter-resistant additions over the reference's four. Error rows
    carry NaN times -> all-NaN stats by the native contract.

    ``metrics`` is the worker's telemetry snapshot; only the fixed
    ``telemetry.ROW_METRIC_DEFAULTS`` keys land as columns (defaults on
    rows that never recorded them — error rows included — so the CSV
    header is identical on every path).
    """
    metric_fields = dict(telemetry.ROW_METRIC_DEFAULTS)
    if metrics:
        metric_fields.update(
            {k: metrics[k] for k in metric_fields if k in metrics}
        )
    perf_fields = dict(PERF_ROW_DEFAULTS)
    if perf:
        perf_fields.update({k: perf[k] for k in perf_fields if k in perf})
    skew_fields = dict(clocksync.SKEW_ROW_DEFAULTS)
    if skew:
        skew_fields.update(
            {k: skew[k] for k in skew_fields if k in skew}
        )
    tflops = flop_count / 1e9 / times_ms
    stats = robust_stats(times_ms)
    return {
        "implementation": config["impl_id"],
        "primitive": config["primitive"],
        "base_implementation": config.get(
            "base_implementation", config["impl_id"]
        ),
        "mean time (ms)": stats["mean"],
        "std time (ms)": stats["std"],
        "min time (ms)": stats["min"],
        "max time (ms)": stats["max"],
        "median time (ms)": stats["median"],
        "p95 time (ms)": stats["p95"],
        "m": config["m"],
        "n": config["n"],
        "k": config["k"],
        # defaults mirror benchmark_worker's config.get defaults — rows
        # must build even for minimal configs (crash isolation narrows
        # otherwise)
        "dtype": config.get("dtype", "bfloat16"),
        "Throughput (TFLOPS)": float(np.mean(tflops)),
        "Throughput std (TFLOPS)": float(np.std(tflops)),
        # what the Throughput column actually measures for this family
        # ("GB/s" for collectives — registry.throughput_unit)
        "unit": throughput_unit(config["primitive"]),
        "world_size": world_size,
        "num_processes": num_processes,
        "hostname": socket.gethostname(),
        "platform": platform,
        "time_measurement_backend": config.get(
            "time_measurement_backend", "host_clock"
        ),
        "barrier_at_each_iteration": config.get(
            "barrier_at_each_iteration", True
        ),
        # what compilation cost this row and whether the persistent
        # cache (DDLB_TPU_COMPILE_CACHE) served it — the compile-ahead
        # engine's win, visible in every CSV; NaN/False on rows whose
        # worker died before compiling anything
        "compile_time_s": compile_time_s,
        "compile_cache_hit": compile_cache_hit,
        # the telemetry attribution columns: where the row's overhead
        # went (barrier wait, device_loop dispatch slack, HBM high-water,
        # collective wire bytes) — ISSUE 2's measurement layer
        **metric_fields,
        # the analytical-perfmodel columns (ISSUE 3): the predicted
        # lower bound for this config, the fraction of it achieved, and
        # the roofline term that dominates (compute/comm/hbm)
        **perf_fields,
        # the cross-rank skew columns (ISSUE 14): how long this row's
        # collectives waited on their last arrival, which rank it was,
        # and the clock-alignment uncertainty the attribution carries
        **skew_fields,
        # the robustness columns (ISSUE 4), identical on every path so
        # the CSV header cannot drift: how many retries this row took,
        # which fault-plan sites fired, the error's transient-vs-
        # deterministic class, and whether the impl was quarantined
        "retries": int(retries),
        "fault_injected": fault_injected,
        "error_class": error_class,
        "quarantined": bool(quarantined),
        # the degraded-world stamp (ISSUE 15): True on rows measured by
        # a world the supervised launcher relaunched shrunk/remapped
        # around an indicted rank — identical on every path so the CSV
        # header cannot drift
        "world_degraded": envs.get_world_degraded(),
        # the warm-worker-pool columns (ISSUE 5), defaults here so the
        # schema is identical on every path (in-process rows, pooled
        # rows, error rows); the subprocess dispatcher overwrites them
        # with the lease's actual reuse state and setup cost
        "worker_reused": False,
        "worker_setup_s": float("nan"),
        "option": option_repr,
        "valid": valid,
        # always present so the CSV header (fixed by the first row written)
        # has the column when a later implementation crashes
        "error": error,
    }


def _timing_loop(
    impl, runtime, num_iterations, backend, barrier_each, num_windows=5,
    min_window_s=0.1,
):
    """The measured region (reference hot loop, benchmark.py:124-188)."""
    if backend == "host_clock" and barrier_each:
        # per-iteration: barrier, then time one run to completion
        # (reference cpu_clock+barrier, benchmark.py:161-172)
        times = np.empty(num_iterations, dtype=np.float64)
        for i in range(num_iterations):
            runtime.barrier()
            t0 = now_ns()
            fence(impl.run())
            times[i] = (now_ns() - t0) * 1e-6
            # per-iteration liveness beat: a long timing loop must not
            # look hung to a heartbeat-aware parent (one is-None check
            # when no channel is installed)
            heartbeat.beat()
        return times
    if backend == "host_clock":
        # sync once, run N iterations back to back, sync, divide
        # (reference cpu_clock no-barrier, benchmark.py:173-186). One
        # aggregate window = ONE sample: report a length-1 vector rather
        # than broadcasting the average into N slots, so std/median are
        # never fabricated (VERDICT r1 weak #2 applied consistently).
        runtime.barrier()
        t0 = now_ns()
        out = None
        for _ in range(num_iterations):
            out = impl.run()
        fence(out)
        return np.array([(now_ns() - t0) * 1e-6 / num_iterations])
    # device_loop: the CUDA-event analogue done the XLA way — the whole
    # N-iteration loop compiles into one device program and a differential
    # measurement cancels dispatch/fence overhead (see utils/timing.py).
    # The barrier flag is irrelevant: iterations are device-side chained.
    # The returned vector is one entry PER WINDOW (a real distribution
    # across independent runs), not num_iterations broadcast copies.
    fn, args = impl.timed_call()
    runtime.barrier()
    return measure_device_loop(
        fn,
        args,
        num_iterations,
        num_windows,
        compiler_options=getattr(impl, "xla_compiler_options", None),
        min_window_s=min_window_s,
        num_processes=runtime.num_processes,
    )


def _max_reduce_across_processes(times_ms: np.ndarray, runtime) -> np.ndarray:
    """Reported time is the slowest process's (reference all_reduce(MAX),
    benchmark.py:190-204)."""
    if runtime.num_processes <= 1:
        return times_ms
    from jax.experimental import multihost_utils

    # the one cross-process collective OUTSIDE the jitted impl programs:
    # injectable (a plan can wedge/kill a specific rank mid-allgather,
    # or charge a degraded link's payload-proportional delay) and
    # flight-recorded (a rank that never arrives leaves its peers
    # in-flight here — named by scripts/flight_report.py)
    faults.inject(
        "runtime.collective",
        payload_bytes=int(times_ms.size * 8 * runtime.num_processes),
    )
    # clock-sync stamps AFTER the injection site (a fault-delayed rank
    # must arrive late on its own stamp) — this collective is the
    # preferred slowdown-injection point, so it feeds the skew fold but
    # never the offset fit (clocksync.FIT_SITES excludes it)
    t_enter = time.monotonic()
    with flightrec.record(
        "runtime.collective",
        payload_bytes=int(times_ms.size * 8 * runtime.num_processes),
    ):
        gathered = multihost_utils.process_allgather(times_ms)
    clocksync.record_span("runtime.collective", t_enter, time.monotonic())
    return np.max(gathered, axis=0)


def _format_options(options: Dict[str, Any]) -> str:
    return ";".join(f"{k}={v}" for k, v in sorted(options.items())) or "-"


def _row_has_measurement(row: Dict[str, Any]) -> bool:
    """True when the row carries finite measured times — e.g. a
    validation-phase crash AFTER a completed timing loop (the worker's
    'times stand' contract). Such a row must never be retried: a retry
    would discard a real measurement to re-pay the full row cost for
    the same validation answer."""
    try:
        return bool(np.isfinite(float(row.get("median time (ms)"))))
    except (TypeError, ValueError):
        return False


# The subprocess-isolation child entry lives in ``ddlb_tpu/pool.py``
# (``_pool_child_main``): one long-lived dispatch loop per leased
# worker, hosting the same per-row ``subprocess.entry`` /
# ``subprocess.result`` fault surface the old spawn-per-row child did.


# ---------------------------------------------------------------------------
# Runner (reference PrimitiveBenchmarkRunner, benchmark.py:264-425)
# ---------------------------------------------------------------------------


class PrimitiveBenchmarkRunner:
    """Run one (primitive, shape) across many implementations."""

    ALLOWED_PRIMITIVES = set(ALLOWED_PRIMITIVES)

    def __init__(
        self,
        primitive: str,
        m: int,
        n: int,
        k: int,
        implementations: Dict[str, Dict[str, Any]],
        dtype: str = "bfloat16",
        num_iterations: int = 50,
        num_warmups: int = 5,
        validate: bool = True,
        time_measurement_backend: str = "host_clock",
        barrier_at_each_iteration: bool = True,
        output_csv: Optional[str] = None,
        profile_dir: Optional[str] = None,
        isolation: str = "none",
        progress: bool = True,
        worker_timeout: Optional[float] = None,
        resume: bool = False,
        device_loop_windows: int = 5,
        device_loop_min_window_ms: float = 100.0,
        compile_ahead: bool = True,
        group_by_signature: bool = True,
        max_retries: Optional[int] = None,
        retry_backoff_s: float = 0.5,
        quarantine_after: Optional[int] = None,
        worker_pool: Optional[bool] = None,
        pool_max_rows: Optional[int] = None,
    ) -> None:
        if primitive not in self.ALLOWED_PRIMITIVES:
            raise ValueError(
                f"Unknown primitive '{primitive}'. "
                f"Allowed: {sorted(self.ALLOWED_PRIMITIVES)}"
            )
        if isolation not in ("none", "subprocess"):
            raise ValueError("isolation must be 'none' or 'subprocess'")
        if worker_timeout is not None and isolation != "subprocess":
            # only a separate process can be killed mid-collective; the
            # in-process path has no safe preemption point
            raise ValueError("worker_timeout requires isolation='subprocess'")
        self.primitive = primitive
        self.m, self.n, self.k = m, n, k
        self.implementations = implementations
        self.dtype = dtype
        self.num_iterations = num_iterations
        self.num_warmups = num_warmups
        self.validate = validate
        self.time_measurement_backend = time_measurement_backend
        self.barrier_at_each_iteration = barrier_at_each_iteration
        self.output_csv = output_csv
        self.profile_dir = profile_dir
        self.isolation = isolation
        self.progress = progress
        self.worker_timeout = worker_timeout
        self.resume = resume
        self.device_loop_windows = device_loop_windows
        self.device_loop_min_window_ms = device_loop_min_window_ms
        # compile-ahead engine knobs: overlap config N+1's XLA compile
        # with config N's timing loop (in-process mode + persistent cache
        # only — see _make_scheduler), and group same-signature configs
        # adjacently so caches clear once per executable, not per row
        self.compile_ahead = compile_ahead
        self.group_by_signature = group_by_signature
        # self-healing knobs (ISSUE 4): transient failures retry with
        # exponential backoff + jitter up to max_retries; an impl whose
        # configs fail quarantine_after times IN A ROW stops being run
        # and its remaining configs emit cheap quarantined rows. Both
        # default from the environment (DDLB_TPU_MAX_RETRIES /
        # DDLB_TPU_QUARANTINE_AFTER; 0 disables either).
        from ddlb_tpu.envs import get_max_retries, get_quarantine_after

        self.max_retries = (
            get_max_retries() if max_retries is None else int(max_retries)
        )
        self.retry_backoff_s = float(retry_backoff_s)
        self.quarantine_after = (
            get_quarantine_after()
            if quarantine_after is None
            else int(quarantine_after)
        )
        #: per-base-implementation consecutive-failure strikes; reaching
        #: quarantine_after moves the impl into _quarantined
        self._strikes: Dict[str, int] = {}
        self._quarantined: set = set()
        self._probed_world_size: Optional[int] = None  # subprocess probe cache
        # warm-worker-pool knobs (ISSUE 5): default from the environment
        # (DDLB_TPU_WORKER_POOL / DDLB_TPU_POOL_MAX_ROWS); worker_pool
        # off degenerates to spawn-per-row (pool_max_rows=1)
        from ddlb_tpu.envs import get_pool_max_rows, get_worker_pool

        self.worker_pool = (
            get_worker_pool() if worker_pool is None else bool(worker_pool)
        )
        self.pool_max_rows = (
            get_pool_max_rows()
            if pool_max_rows is None
            else int(pool_max_rows)
        )
        #: the lease manager, created lazily on the first subprocess row
        #: and shut down at the end of run()
        self._pool = None
        #: config N+1, handed to the leased worker with config N so its
        #: compile-ahead thread can prefetch (pool-mode analogue of the
        #: in-process scheduler)
        self._pool_prefetch: Optional[Dict[str, Any]] = None

    def _worker_config(self, impl_id: str, spec: Dict[str, Any]) -> Dict[str, Any]:
        spec = dict(spec)
        base_impl = spec.pop("implementation", impl_id.rsplit("_", 1)[0])
        return {
            "primitive": self.primitive,
            "impl_id": impl_id,
            "base_implementation": base_impl,
            "options": spec,
            "m": self.m,
            "n": self.n,
            "k": self.k,
            "dtype": self.dtype,
            "num_iterations": self.num_iterations,
            "num_warmups": self.num_warmups,
            "validate": self.validate,
            "time_measurement_backend": self.time_measurement_backend,
            "barrier_at_each_iteration": self.barrier_at_each_iteration,
            "profile_dir": self.profile_dir,
            "device_loop_windows": self.device_loop_windows,
            "device_loop_min_window_ms": self.device_loop_min_window_ms,
        }

    def run(self):
        """Benchmark every implementation; returns a pandas DataFrame."""
        import pandas as pd

        from ddlb_tpu.envs import get_num_processes, get_process_id

        is_primary = get_process_id() == 0
        if self.resume and get_num_processes() > 1:
            # the skip decision reads the primary's CSV; without a shared
            # view every process could skip differently and deadlock the
            # collective world
            raise ValueError("resume is single-process only")
        items = list(self.implementations.items())
        # one signature computation per entry (load_impl_class + option
        # merge each time): ordering, boundary detection and prefetch
        # all read this dict
        sigs = {
            impl_id: self._signature_key(impl_id, spec)
            for impl_id, spec in items
        }
        if self.group_by_signature:
            # configs sharing an executable signature run adjacently so
            # the isolation clear below fires once per signature group
            items = order_by_signature(items, lambda i, _s: sigs[i])

        done = self._completed_rows() if self.resume else set()
        pending: List[tuple] = []
        for impl_id, spec in items:
            # key computation probes the device count — only pay that (and
            # only touch the backend) when there is a resume set to match
            if done and self._resume_key(impl_id, spec) in done:
                # checkpoint/resume: the incremental CSV is the resumable
                # artifact (SURVEY.md section 5) — rows already recorded
                # for this (impl, shape, dtype) are skipped, so an
                # interrupted sweep restarts where it stopped
                if is_primary:
                    telemetry.log(f"resume: skipping {impl_id} (in CSV)")
                continue
            pending.append((impl_id, spec))

        scheduler = self._make_scheduler()
        iterator = pending
        if self.progress and is_primary:
            try:
                from tqdm import tqdm

                iterator = tqdm(pending, desc=f"{self.primitive} impls")
            except ImportError:  # pragma: no cover
                pass

        rows: List[Dict[str, Any]] = []
        prev_sig = None
        if is_primary and pending:
            live.post_event(
                "sweep_start", total=len(pending), primitive=self.primitive
            )
        try:
            rows = self._run_pending(
                pending, iterator, sigs, scheduler, prev_sig, is_primary, pd
            )
        finally:
            if self._pool is not None:
                # retire the leased worker(s): bounded (sentinel, join,
                # kill on teardown hang); a crashed sweep must not leak
                # a chip-holding child past its runner
                self._pool.shutdown()
                self._pool = None
        if scheduler is not None:
            scheduler.shutdown()
            # sweep-level compile-ahead effectiveness into the global
            # registry: hit/miss counts for the prefetch ratio the trace
            # report surfaces next to overlap efficiency
            telemetry.record(
                "compile_ahead.prefetched", scheduler.prefetched
            )
            telemetry.record("compile_ahead.failed", scheduler.failed)
            telemetry.record("compile_ahead.skipped", scheduler.skipped)
            if is_primary and (
                scheduler.prefetched or scheduler.failed or scheduler.skipped
            ):
                telemetry.log(
                    f"compile-ahead: {scheduler.prefetched} prefetched, "
                    f"{scheduler.failed} failed, "
                    f"{scheduler.skipped} skipped"
                )
        if (
            self.isolation == "none"
            and pending
            and (scheduler is None or not scheduler.busy)
        ):
            # leave the process's caches clean for whatever runs next —
            # the same end state the old per-row clearing guaranteed
            # (skipped only if a wedged prefetch survived shutdown's
            # bounded wait: clearing under it would race the caches)
            import jax

            jax.clear_caches()
        if is_primary:
            if pending:
                live.post_event("sweep_done", rows=len(rows))
            # join per-process trace shards (this process's, and the
            # subprocess-isolation children's) into the Perfetto-loadable
            # trace.json; a no-op when DDLB_TPU_TRACE is unset
            merged = telemetry.merge_trace()
            if merged:
                telemetry.log(f"trace merged: {merged}")
        return pd.DataFrame(rows)

    def _run_pending(
        self, pending, iterator, sigs, scheduler, prev_sig, is_primary, pd
    ) -> List[Dict[str, Any]]:
        """The sweep's row loop, factored so run() can bound the pool's
        lifetime with one try/finally around it."""
        rows: List[Dict[str, Any]] = []
        for idx, (impl_id, spec) in enumerate(iterator):
            scheduler_busy = False
            if scheduler is not None:
                # reap this config's prefetch (launched during the
                # previous row's timing loop) before touching caches —
                # never clear under an active compile thread. Bounded:
                # a prefetch wedged against a dying backend must not
                # deadlock the sweep (no worker_timeout exists in-process)
                scheduler.wait(timeout=scheduler.WAIT_TIMEOUT_S)
                scheduler_busy = scheduler.busy
                if scheduler_busy:
                    telemetry.warn(
                        "compile-ahead prefetch still running after the "
                        "bounded wait; skipping the cache clear this "
                        "boundary (clearing under an active compile "
                        "thread races the global caches)"
                    )
            sig = sigs[impl_id]
            if (
                self.isolation == "none"
                and not scheduler_busy
                and prev_sig is not None
                and sig != prev_sig
            ):
                # cache-aware clearing: the cross-impl isolation contract
                # now holds at executable-signature boundaries instead of
                # per row — same-signature neighbors share the warm cache
                # (the persistent disk cache is untouched by design)
                import jax

                jax.clear_caches()
            prev_sig = sig
            config = self._worker_config(impl_id, spec)
            if is_primary:
                live.post_event(
                    "row_start", impl=impl_id, primitive=self.primitive,
                    m=self.m, n=self.n, k=self.k,
                )
            if scheduler is not None and idx + 1 < len(pending):
                # overlap: config N+1 compiles on a background thread
                # while config N's timing loop owns the device
                nxt_id, nxt_spec = pending[idx + 1]
                scheduler.prefetch(self._worker_config(nxt_id, nxt_spec))
            self._pool_prefetch = None
            if self.isolation == "subprocess" and idx + 1 < len(pending):
                # pool-mode compile-ahead: the NEXT config rides along
                # with this row's request, and the leased worker's own
                # background thread prefetch-compiles it into the
                # persistent cache (ignored without a cache configured
                # — utils/compile_ahead.make_worker_scheduler)
                nxt_id, nxt_spec = pending[idx + 1]
                self._pool_prefetch = self._worker_config(nxt_id, nxt_spec)
            row = self._run_one_healed(config)
            rows.append(row)
            if is_primary:
                # cross-run memory + live feed (both env-gated no-ops by
                # default): bank the row into the history store, and
                # post the completion with its predicted-vs-measured
                # fields for the dashboard's rolling view
                store.bank_row(row)
                live.post_event(
                    "row_done", impl=impl_id, primitive=self.primitive,
                    median_ms=row.get("median time (ms)"),
                    predicted_s=row.get("predicted_s"),
                    roofline_frac=row.get("roofline_frac"),
                    measured_overlap_frac=row.get("measured_overlap_frac"),
                    error=str(row.get("error") or "")[:200],
                    quarantined=bool(row.get("quarantined")),
                    retries=row.get("retries"),
                    worker_reused=row.get("worker_reused"),
                    # cross-rank skew summary (the dashboard's per-rank
                    # lane panel keys on these; defaults off multi-
                    # process worlds fold to nothing)
                    skew_enter_s=row.get("skew_enter_s"),
                    straggler_rank=row.get("straggler_rank"),
                    straggler_frac=row.get("straggler_frac"),
                    # serving SLO summary (absent on non-serving rows;
                    # the dashboard's serving panel keys on these)
                    slo_ttft_p50_ms=row.get("slo_ttft_p50_ms"),
                    slo_ttft_p95_ms=row.get("slo_ttft_p95_ms"),
                    slo_ttft_p99_ms=row.get("slo_ttft_p99_ms"),
                    slo_goodput_rps=row.get("slo_goodput_rps"),
                    slo_attainment=row.get("slo_attainment"),
                )
                # mirror=False: the row is already in the CSV and the
                # worker.row span — echoing the table into the trace
                # would duplicate the whole results file as event payload
                telemetry.log(
                    pd.DataFrame([row]).to_string(index=False), mirror=False
                )
                if self.output_csv:
                    # incremental append so a crash loses one row at most
                    # (reference benchmark.py:375-384)
                    with telemetry.span("runner.csv_append", cat="csv"):
                        self._append_csv(row)
        return rows

    def _make_scheduler(self) -> Optional[CompileAheadScheduler]:
        """The compile-ahead scheduler, or None where it cannot help:
        subprocess isolation (the parent must never touch the
        accelerator — reference 'no CUDA init in parent',
        cli/benchmark.py:126 — so children compile synchronously, still
        sharing the persistent disk cache), or no persistent cache
        configured (a prefetched executable has no channel to the
        worker's fresh jit closures without the disk cache)."""
        if not self.compile_ahead or self.isolation != "none":
            return None
        from ddlb_tpu.runtime import configure_compile_cache

        if configure_compile_cache() is None:
            return None
        return CompileAheadScheduler()

    def _merged_options(self, impl_id: str, spec: Dict[str, Any]):
        """(base_implementation, DEFAULT-merged options) for one sweep
        entry — the exact merge path the worker records (OptionsManager
        over the class schema), shared by the resume key and the
        executable signature so neither can drift from the CSV."""
        spec = dict(spec)
        base = spec.pop("implementation", impl_id.rsplit("_", 1)[0])
        # seed/mesh bind to named Primitive.__init__ params in the worker
        # (impl_class(m, n, k, dtype=..., **options)) and never reach the
        # recorded option string — drop them here identically
        spec.pop("seed", None)
        spec.pop("mesh", None)
        try:
            from ddlb_tpu.options import OptionsManager

            cls = load_impl_class(self.primitive, base)
            merged = OptionsManager(*cls.option_schema()).parse(spec)
        except Exception:
            merged = spec
        return base, merged

    def _signature_key(self, impl_id: str, spec: Dict[str, Any]):
        """Executable-signature identity of one sweep entry: configs with
        equal keys compile the same programs (measurement knobs live on
        the runner, not in the spec), so they may share a warm cache."""
        base, merged = self._merged_options(impl_id, spec)
        return executable_signature(
            self.primitive, base, merged, self.m, self.n, self.k, self.dtype
        )

    def _resume_key(self, impl_id: str, spec: Dict[str, Any]):
        """Identity of one benchmark config, independent of the positional
        ``impl_id`` numbering (which renumbers when the sweep is edited):
        base implementation name + fully-merged option repr + shape/dtype.
        Matches the ``option`` column the worker records (defaults merged
        by OptionsManager via ``_merged_options``)."""
        base, merged = self._merged_options(impl_id, spec)
        return (
            self.primitive,
            base,
            _format_options(merged),
            self.m,
            self.n,
            self.k,
            self.dtype,
            self._known_world_size(),
        )

    def _known_world_size(self):
        """Device count for the resume key, obtained without touching the
        accelerator from the parent when isolation is 'subprocess': the
        sim env var when set, a subprocess probe otherwise (the parent
        itself must never create the backend — reference 'no CUDA init in
        parent', cli/benchmark.py:126). In-process mode already owns the
        backend and asks it directly. Returns None only when the probe
        fails — with a warning, since resume keys then omit world size and
        rows recorded under a different topology would be trusted."""
        from ddlb_tpu.envs import get_sim_device_count

        sim = get_sim_device_count()
        if sim > 0:
            return sim
        # explicit override: on flaky hardware the 120 s probe below is
        # pure cost when the operator already knows the topology
        override = envs.get_world_size_override()
        if override:
            try:
                n = int(override)
            except ValueError:
                n = 0
                telemetry.warn(
                    f"ignoring non-integer DDLB_TPU_WORLD_SIZE={override!r}"
                )
            if n > 0:  # 0 = disabled, the DDLB_TPU_* env convention
                return n
        if self.isolation == "subprocess":
            # disk cache next to the CSV: a resumed sweep re-pays the
            # probe (120 s against a hung relay) at most once per artifact
            cache_path = (
                f"{self.output_csv}.world_size" if self.output_csv else None
            )
            if self._probed_world_size is None and cache_path:
                try:
                    with open(cache_path) as f:
                        cached = int(f.read().strip())
                except (OSError, ValueError):
                    cached = 0
                if cached > 0:  # a corrupt/zero file never becomes a key
                    self._probed_world_size = cached
                    telemetry.log(
                        f"resume world_size={cached} from {cache_path} — "
                        f"delete it if the topology changed"
                    )
            if self._probed_world_size is None:
                import subprocess
                import sys

                try:
                    out = subprocess.run(
                        [
                            sys.executable,
                            "-c",
                            "import jax; print(len(jax.devices()))",
                        ],
                        timeout=120,
                        capture_output=True,
                        text=True,
                    )
                    if out.returncode != 0:
                        raise RuntimeError(f"probe rc={out.returncode}")
                    # last line: runtime/plugin banners may precede it
                    self._probed_world_size = int(
                        out.stdout.strip().splitlines()[-1]
                    )
                    if cache_path:
                        try:
                            with open(cache_path, "w") as f:
                                f.write(f"{self._probed_world_size}\n")
                        except OSError:
                            pass
                except Exception:
                    telemetry.warn(
                        "could not probe the device count for the resume "
                        "key; completed-row matching will ignore "
                        "world_size — do not resume a sweep recorded on "
                        "a different topology"
                    )
                    self._probed_world_size = -1  # probe failed, don't retry
            return (
                None
                if self._probed_world_size == -1
                else self._probed_world_size
            )
        # In-process: go through Runtime, NOT a bare jax.devices() — in a
        # multi-process world the backend must first be initialized via
        # jax.distributed (Runtime._initialize ordering); a premature
        # devices() call here would pin a local-only backend and the
        # worker's Runtime() would then fail to form the joint world.
        from ddlb_tpu.runtime import Runtime

        return Runtime().num_devices

    def _completed_rows(self) -> set:
        """Keys already recorded in the output CSV (resume support).

        Crashed/timed-out rows (non-empty ``error``) do NOT count as
        completed — a transient failure is retried on resume; recorded
        measurements (including soft validation failures) are not.
        """
        import pandas as pd

        path = self.output_csv
        if not path or not os.path.exists(path) or os.path.getsize(path) == 0:
            return set()
        df = pd.read_csv(path)
        needed = {
            "implementation",
            "primitive",
            "base_implementation",
            "option",
            "world_size",
            "m",
            "n",
            "k",
            "dtype",
        }
        if not needed.issubset(df.columns):
            raise ValueError(
                f"cannot resume from {path}: it predates resume support "
                f"(missing columns {sorted(needed - set(df.columns))}); "
                f"start a fresh CSV or add the columns"
            )
        if "error" in df.columns:
            df = df[df["error"].isna() | (df["error"].astype(str) == "")]
        world = self._known_world_size()
        keys = set()
        for _, r in df.iterrows():
            row_world = int(r["world_size"]) if world is not None else world
            keys.add(
                (
                    r["primitive"],
                    r["base_implementation"],
                    r["option"],
                    int(r["m"]),
                    int(r["n"]),
                    int(r["k"]),
                    r["dtype"],
                    row_world,
                )
            )
        return keys

    def _run_one_healed(self, config: Dict[str, Any]) -> Dict[str, Any]:
        """One config under the self-healing policy: quarantine check,
        then run with per-row retries — only failures the classifier
        calls transient (``ddlb_tpu/faults/classify.py``) are retried,
        with exponential backoff + deterministic jitter; deterministic
        failures are recorded immediately (a retry re-pays the full cost
        for the same answer). Returns exactly one row — the first clean
        attempt or the last failed one, with ``retries`` set to the
        attempts consumed and ``fault_injected`` accumulated across
        them."""
        base = config.get("base_implementation", config.get("impl_id", ""))
        if base in self._quarantined:
            # graceful degradation: a cheap classified row instead of
            # another guaranteed timeout/crash burning worker_timeout
            telemetry.record("runner.quarantine_skips")
            row = self._error_row(
                config,
                f"skipped: quarantined after {self.quarantine_after} "
                f"consecutive failures of '{base}'",
            )
            row["quarantined"] = True
            row["error_class"] = "quarantined"
            return row
        delays = faults.backoff_delays(
            self.retry_backoff_s, self.max_retries,
            seed=str(config.get("impl_id", "")),
        )
        fired: List[str] = []
        attempt = 0
        while True:
            config["fault_attempt"] = attempt
            row = self._run_one(config)
            error = str(row.get("error") or "")
            valid = bool(row.get("valid", True))
            cls = str(row.get("error_class") or "") or classify_error(
                error, valid
            )
            row["error_class"] = cls
            if row.get("fault_injected"):
                fired.extend(str(row["fault_injected"]).split(","))
            if (
                error
                and cls == TRANSIENT
                and attempt < self.max_retries
                and not _row_has_measurement(row)
            ):
                delay = delays[attempt]
                telemetry.record("runner.retries")
                with telemetry.span(
                    "runner.retry", cat="retry",
                    impl=config.get("impl_id", ""), attempt=attempt + 1,
                    error=error[:200],
                ):
                    telemetry.warn(
                        f"transient failure on {config.get('impl_id')} "
                        f"(attempt {attempt + 1}/{self.max_retries + 1}): "
                        f"{error[:200]} — retrying in {delay:.2f}s"
                    )
                    time.sleep(delay)
                attempt += 1
                continue
            break
        row["retries"] = attempt
        # fault attribution survives recovery: sites that fired on
        # discarded attempts stay visible on the final (possibly clean)
        # row, so a chaos CSV shows WHERE the recovered fault hit
        row["fault_injected"] = ",".join(dict.fromkeys(s for s in fired if s))
        self._note_outcome(base, failed=bool(error))
        return row

    def _note_outcome(self, base: str, failed: bool) -> None:
        """Quarantine bookkeeping: consecutive failed rows per base
        implementation; a clean row resets the strike count."""
        if self.quarantine_after <= 0:
            return
        if not failed:
            self._strikes[base] = 0
            return
        strikes = self._strikes.get(base, 0) + 1
        self._strikes[base] = strikes
        if strikes >= self.quarantine_after and base not in self._quarantined:
            self._quarantined.add(base)
            telemetry.record("runner.quarantined_impls")
            telemetry.instant(
                "runner.quarantine", cat="retry", impl=base, strikes=strikes
            )
            telemetry.warn(
                f"quarantining implementation '{base}' after {strikes} "
                f"consecutive failures — its remaining configs will be "
                f"skipped with 'quarantined' rows"
            )

    def _run_one(self, config: Dict[str, Any]) -> Dict[str, Any]:
        if self.isolation == "subprocess":
            with telemetry.span(
                "runner.subprocess_row", cat="row",
                impl=config.get("impl_id", ""),
            ):
                return self._run_one_subprocess(config)
        # cross-impl cache isolation is the run() loop's job now: it
        # clears at executable-signature boundaries instead of per row
        return benchmark_worker(config)

    def _run_one_subprocess(self, config: Dict[str, Any]) -> Dict[str, Any]:
        """One row on the warm-worker pool (full per-row process
        isolation is the ``pool_max_rows=1`` degenerate case; the
        reference's spawn-per-impl, benchmark.py:336-370, is what the
        pool amortizes). The lease reuses a live child whose environment
        signature matches; a hung/dead child (the hung/dead policy lives
        in ``pool.await_row``: heartbeat-aware per-row deadline, kill on
        silence) becomes an error row here, its fault markers merged,
        and the dead lease respawns for the retry the self-healing
        policy will issue."""
        from ddlb_tpu.pool import WorkerPool, run_one_row

        if self._pool is None:
            # worker_pool=False is exactly spawn-per-row: a pool whose
            # workers retire after every single row
            self._pool = WorkerPool(
                max_rows=self.pool_max_rows if self.worker_pool else 1,
                worker_timeout=self.worker_timeout,
            )
        return run_one_row(
            self._pool, config, self._error_row,
            prefetch=self._pool_prefetch,
        )

    def _error_row(self, config: Dict[str, Any], error: str) -> Dict[str, Any]:
        """Error row for a worker that hung or died — the same schema as
        measured rows via ``make_result_row``. Deliberately JAX-free: in
        subprocess mode the parent must never touch the accelerator
        (reference 'no CUDA init in parent', cli/benchmark.py:126)."""
        from ddlb_tpu.envs import get_num_processes

        return make_result_row(
            config,
            times_ms=np.array([float("nan")]),
            # the worker died before an impl existed to define a flop
            # convention; NaN (not 2mnk) so the dead row implies nothing
            flop_count=float("nan"),
            option_repr=_format_options(config.get("options", {})),
            valid=False,
            error=error,
            world_size=-1,  # unknown: the worker died before reporting
            num_processes=get_num_processes(),
            platform="unknown",
            retries=int(config.get("fault_attempt", 0) or 0),
            error_class=classify_error(error, valid=False),
        )

    def _append_csv(self, row: Dict[str, Any]) -> None:
        import pandas as pd

        path = self.output_csv
        assert path is not None
        directory = os.path.dirname(path)
        if directory:
            os.makedirs(directory, exist_ok=True)
        frame = pd.DataFrame([row])
        if os.path.exists(path) and os.path.getsize(path) > 0:
            # align to the existing header so appends to CSVs written by an
            # older schema stay parseable (extra keys dropped, missing NaN)
            existing = pd.read_csv(path, nrows=0).columns.tolist()
            frame.reindex(columns=existing).to_csv(
                path, mode="a", header=False, index=False
            )
        else:
            frame.to_csv(path, mode="a", header=True, index=False)

    # -- plotting (reference plot_results, benchmark.py:391-425) -------------

    @staticmethod
    def plot_results(df, output_path: str, metric: str = "mean time (ms)"):
        """Bar chart with error bars per implementation/option."""
        import matplotlib

        matplotlib.use("Agg")
        import matplotlib.pyplot as plt

        labels = [
            f"{r['implementation']}\n{r['option']}" for _, r in df.iterrows()
        ]
        values = df[metric]
        err = (
            df["std time (ms)"]
            if metric == "mean time (ms)" and "std time (ms)" in df
            else None
        )
        fig, ax = plt.subplots(figsize=(max(6, 1.2 * len(labels)), 5))
        ax.bar(range(len(labels)), values, yerr=err, capsize=3)
        ax.set_xticks(range(len(labels)))
        ax.set_xticklabels(labels, rotation=30, ha="right", fontsize=8)
        ylabel = metric
        if metric.startswith("Throughput") and "unit" in df:
            units = sorted(set(df["unit"].dropna()))
            if units == ["GB/s"]:  # the collectives family's convention
                ylabel = "Throughput (GB/s, per-device wire)"
        ax.set_ylabel(ylabel)
        row0 = df.iloc[0]
        ax.set_title(
            f"{row0.get('m')}x{row0.get('k')}x{row0.get('n')} "
            f"{row0.get('dtype')} world={row0.get('world_size')}"
        )
        fig.tight_layout()
        directory = os.path.dirname(output_path)
        if directory:
            os.makedirs(directory, exist_ok=True)
        fig.savefig(output_path, dpi=120)
        plt.close(fig)
        return output_path
