"""Program builders: traced members, closed-form impls, synthetic algos.

Three ways a ``ScheduleProgram`` comes to exist, each with a different
fidelity/availability trade:

1. **Traced** (``program_from_schedule``): the semantic SPMD
   interpreter's ordered collective trace for a real registered member
   (``analysis.spmd.families.member_schedule``) replays step-by-step —
   a chunked double-buffered ring arrives as its literal ``c*(d-1)``
   ppermutes, a pipeline schedule table as its per-tick hop sequence,
   and a fused Pallas RDMA kernel (the ``analysis.pallas`` kernel
   model's de-opaqued members) as its literal in-kernel
   ``make_async_remote_copy`` hops — ``remote_copy`` entries lower to
   single hops like ppermutes, the export's ``chunks`` carries the
   kernel's hop count as the pipeline depth, and the engine's
   arbitration (not a closed form) decides what overlaps.
2. **Closed-form** (``program_from_impl``): a duck-typed impl's
   ``perfmodel.cost`` terms lowered into ring-granularity steps — the
   validation front-end: on a degenerate flat topology the replayed
   makespan must equal ``cost.estimate().predicted_s`` to float
   precision, because the engine's arbitration of the sequential /
   ideal-overlap / chunked shapes IS the cost model's combination rule.
3. **Synthetic** (``flat_ring_program`` / ``hierarchical_program`` /
   ``striped_program``): algorithms written directly against the IR —
   flat world-spanning ring, HiCCL-style RS-intra → AR-inter →
   AG-intra phases, and multi-path striping across the ICI mesh
   dimensions — so compositions are ranked per topology *before*
   anyone builds them as impl members.

Placement conventions the lowering uses (stated once here): the
per-chunk GEMM leads the wire for the reduce-side families
(``compute_first``), trails it for the gather-side ones
(``comm_first``), and splits around the dispatch/combine pair for
ep_alltoall (``sandwich`` — traced path only; the closed-form path
groups the pair so the replay lands exactly on the cost model's
two-phase fill/drain law).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

from ddlb_tpu.perfmodel.cost import (
    FAMILY_COST_MODELS,
    canonical_op,
    hierarchical_phases,
    overlap_chunks,
    ring_step_count,
    ring_wire_bytes,
)
from ddlb_tpu.perfmodel.topology import Topology
from ddlb_tpu.simulator.program import (
    ComputeStep,
    HbmStep,
    ScheduleProgram,
    Stage,
    WireStep,
    pipelined,
    sequential,
)

#: the collective shape each family's wire rides, for closed-form
#: lowering and for the per-family synthetic ranking
FAMILY_COLLECTIVES: Dict[str, str] = {
    "tp_columnwise": "all_gather",
    "tp_rowwise": "reduce_scatter",
    "dp_allreduce": "all_reduce",
    "ep_alltoall": "all_to_all",
    "cp_ring_attention": "ppermute",
    "pp_pipeline": "ppermute",
    "collectives": "all_reduce",
}

#: where the per-chunk GEMM sits relative to its wire (module docstring)
FAMILY_PLACEMENT: Dict[str, str] = {
    "tp_columnwise": "comm_first",
    "tp_rowwise": "compute_first",
    "dp_allreduce": "compute_first",
    "ep_alltoall": "sandwich",
}


class ProgramBuildError(ValueError):
    """A front-end could not lower its input into the schedule IR
    (unsizeable traced payload, unknown family, empty schedule)."""


# ---------------------------------------------------------------------------
# synthetic compositions (written directly against the IR)
# ---------------------------------------------------------------------------


def _ring_steps(
    op: str, nbytes: float, d: int, scope: str, tag: str
) -> List[WireStep]:
    """One collective lowered to its synchronous ring steps: the
    bandwidth-optimal step count with the closed-form total spread
    evenly (totals are exact; the granularity is what replay needs)."""
    total = ring_wire_bytes(op, nbytes, d)
    count = ring_step_count(op, d)
    if count <= 0 or total <= 0.0:
        return []
    return [
        WireStep(total / count, scope=scope, op=canonical_op(op), tag=tag)
        for _ in range(count)
    ]


def flat_ring_program(
    op: str, nbytes: float, topology: Topology, name: str = ""
) -> ScheduleProgram:
    """The baseline: one ring over all chips. On a multi-pod world every
    synchronous step is gated by the slowest link in the ring (the
    ``flat`` channel), which is precisely why this loses to the
    hierarchical composition on DCN-bound topologies."""
    n = topology.num_chips
    scope = "flat" if topology.pods > 1 else "ici0"
    steps = _ring_steps(op, nbytes, n, scope, "flat-ring")
    return sequential(
        name or f"flat/{canonical_op(op)}",
        steps,
        algo="flat",
        op=canonical_op(op),
        payload_bytes=nbytes,
    )


def hierarchical_program(
    op: str, nbytes: float, topology: Topology, name: str = ""
) -> ScheduleProgram:
    """HiCCL-style two-level composition (``perfmodel.cost
    .hierarchical_phases``): intra phases ride one ICI ring family, the
    inter phase rides each chip's DCN share. Phases chain — they are
    data-dependent by construction. Under a ``Degradation`` with a
    downed ICI axis the intra phases reroute onto the first SURVIVING
    axis (the composition needs one healthy ring family, not a
    particular one); a downed DCN has no alternative and the program
    honestly replays unroutable."""
    alive = topology.alive_ici_axes()
    intra_scope = f"ici{alive[0]}" if alive else "ici0"
    steps: List[WireStep] = []
    for ph in hierarchical_phases(
        op, nbytes, topology.chips_per_pod, topology.pods
    ):
        scope = intra_scope if ph["scope"] == "intra" else "dcn"
        steps.extend(
            _ring_steps(ph["op"], ph["nbytes"], ph["axis"], scope, ph["tag"])
        )
    return sequential(
        name or f"hier/{canonical_op(op)}",
        steps,
        algo="hierarchical",
        op=canonical_op(op),
        payload_bytes=nbytes,
        intra_scope=intra_scope,
    )


def striped_program(
    op: str, nbytes: float, topology: Topology, name: str = ""
) -> ScheduleProgram:
    """FlexLink-style multi-path striping: the payload splits across one
    stripe per ICI mesh dimension (each torus axis is an independent
    ring family), every stripe running the hierarchical composition on
    its own ICI channel; the stripes contend for the shared DCN share,
    which the engine arbitrates. One ICI dimension degenerates to
    ``hierarchical_program`` exactly.

    This is the composition whose redundancy pays off under link
    failure (the FlexLink case the degraded ranking quantifies): under
    a ``Degradation`` the stripes are laid over the SURVIVING axes only
    — a downed torus axis's share reroutes onto its peers at build
    time, visible in the per-link utilization table as the dead class
    carrying zero bytes while the survivors carry its payload."""
    alive = list(topology.alive_ici_axes()) or [0]
    stripes = len(alive)
    stages: List[Stage] = []
    for s, axis in enumerate(alive):
        steps: List[WireStep] = []
        for ph in hierarchical_phases(
            op, nbytes / stripes, topology.chips_per_pod, topology.pods
        ):
            scope = f"ici{axis}" if ph["scope"] == "intra" else "dcn"
            steps.extend(
                _ring_steps(
                    ph["op"], ph["nbytes"], ph["axis"], scope,
                    f"{ph['tag']}#s{s}",
                )
            )
        stages.append(Stage(steps, label=f"stripe{s}"))
    prog = pipelined(
        name or f"striped/{canonical_op(op)}",
        stages,
        algo="striped",
        op=canonical_op(op),
        payload_bytes=nbytes,
        stripes=stripes,
        stripe_axes=alive,
    )
    return prog


SYNTHETIC_ALGOS = ("flat", "hierarchical", "striped")


def synthetic_program(
    algo: str, op: str, nbytes: float, topology: Topology
) -> ScheduleProgram:
    """Dispatch one of the ranked compositions by name."""
    if algo == "flat":
        return flat_ring_program(op, nbytes, topology)
    if algo == "hierarchical":
        return hierarchical_program(op, nbytes, topology)
    if algo == "striped":
        return striped_program(op, nbytes, topology)
    raise ProgramBuildError(
        f"Unknown synthetic algorithm {algo!r}; known: {SYNTHETIC_ALGOS}"
    )


# ---------------------------------------------------------------------------
# closed-form front-end (the validation path)
# ---------------------------------------------------------------------------


def _impl_cost_dtype(impl) -> str:
    hook = getattr(impl, "cost_dtype", None)
    if callable(hook):
        try:
            return hook()
        except Exception:
            return impl.dtype
    return impl.dtype


def program_from_impl(
    impl, topology: Topology, transport: Optional[str] = None
) -> ScheduleProgram:
    """Lower one duck-typed implementation's cost terms into a program.

    The censuses (``flops()`` / ``wire_bytes()`` / ``hbm_bytes()``) come
    through the family's registered cost model — one source of truth
    with the perfmodel — and the SCHEDULE comes from the IR: sequential
    members chain, ideal-overlap members race, chunked members pipeline
    ``overlap_chunks()`` two-phase stages. On a degenerate flat
    topology the replayed makespan therefore equals
    ``cost.estimate(impl).predicted_s`` to float precision — the
    validation contract ``simulator.validate.closed_form_check``
    asserts per family.
    """
    family = getattr(impl, "primitive_name", None)
    if family not in FAMILY_COST_MODELS:
        raise ProgramBuildError(
            f"No cost model for primitive family {family!r}"
        )
    spec = topology.chip
    compute_s, comm_s, hbm_s = FAMILY_COST_MODELS[family](impl, spec)
    schedule = getattr(impl, "COST_SCHEDULE", "sequential")
    if schedule == "compute_only":
        comm_s = 0.0
    if transport is None:
        transport = impl.options.get("transport", "ici")
    scope = "dcn" if transport == "dcn" else "ici0"
    dtype = _impl_cost_dtype(impl)
    # invert the terms back into engine quantities priced by the SAME
    # spec, so rates cancel exactly
    flops = compute_s * spec.peak_flops(dtype)
    wire = comm_s * spec.link_bw(transport)
    hbm = hbm_s * spec.hbm_bw
    d = max(1, int(impl.num_partitions))
    op = FAMILY_COLLECTIVES.get(family, "ppermute")
    if family == "collectives":
        op = impl.options.get("op", "all_reduce")
    count = max(1, ring_step_count(op, d)) if wire > 0.0 else 0

    label = f"{family}/{getattr(impl, 'implementation_name', type(impl).__name__)}"
    meta = {
        "family": family,
        "schedule": schedule,
        "transport": transport,
        "frontend": "closed-form",
    }

    def wire_steps(total: float, tag: str) -> List[WireStep]:
        if total <= 0.0 or count == 0:
            return []
        return [
            WireStep(total / count, scope=scope, op=canonical_op(op), tag=tag)
            for _ in range(count)
        ]

    compute = (
        [ComputeStep(flops, dtype=dtype, tag="gemm")] if flops > 0.0 else []
    )
    hbm_steps = [HbmStep(hbm, tag="hbm")] if hbm > 0.0 else []

    chunks = overlap_chunks(impl) if schedule == "overlap" else None
    if schedule == "overlap" and chunks is None:
        # ideal overlap: independent tracks, the engine takes the max
        stages = [Stage(wire_steps(wire, "ring"), label="comm")]
        if compute:
            stages.insert(0, Stage(compute, label="compute"))
        if hbm_steps:
            stages.append(Stage(hbm_steps, label="hbm"))
        return pipelined(label, [s for s in stages if s.steps], **meta)
    if schedule == "overlap" and chunks is not None:
        # the chunked-fusion engine's two-phase pipeline: per chunk,
        # 1/chunks of each census, GEMM placed per the family table
        # (the sandwich family is grouped here so the fill/drain lands
        # exactly on the cost model's law — module docstring)
        placement = FAMILY_PLACEMENT.get(family, "comm_first")
        stages = []
        for j in range(chunks):
            csteps = (
                [ComputeStep(flops / chunks, dtype=dtype, tag=f"gemm#{j}")]
                if flops > 0.0
                else []
            )
            wsteps = wire_steps(wire / chunks, f"ring#{j}")
            if placement == "compute_first":
                stages.append(Stage(csteps + wsteps, label=f"chunk{j}"))
            else:
                stages.append(Stage(wsteps + csteps, label=f"chunk{j}"))
        if hbm_steps:
            stages.append(Stage(hbm_steps, label="hbm"))
        return pipelined(label, stages, chunks=chunks, **meta)
    # sequential (and compute_only, whose comm is zeroed): one chain,
    # HBM racing it on its own track (Stage.hbm_parallel)
    placement = FAMILY_PLACEMENT.get(family, "comm_first")
    wsteps = wire_steps(wire, "ring")
    if placement == "compute_first":
        chain: List[Any] = compute + wsteps
    else:
        chain = wsteps + compute
    return sequential(label, chain + hbm_steps, **meta)


# ---------------------------------------------------------------------------
# traced front-end (the semantic SPMD interpreter's schedule export)
# ---------------------------------------------------------------------------


def _entry_steps(
    entry: Dict[str, Any],
    scope_default: str,
    tag: str,
    topology: Optional[Topology] = None,
) -> List[WireStep]:
    """One exported trace entry -> its ring steps. ppermute entries ARE
    single hops already (the chunked rings' literal schedule); closed-
    form collectives (a jax_spmd member's one psum) decompose into
    their ring step count."""
    op = entry["op"]
    d = entry["axis"]
    nbytes = entry["nbytes"]
    if d is None or nbytes is None:
        raise ProgramBuildError(
            f"trace entry {op} at line {entry.get('line')} did not "
            f"resolve (axis={d}, nbytes={nbytes})"
        )
    axes = entry["axes"]
    if "dcn" in axes:
        scope = "dcn"
    elif "sy" in axes:
        # the striped members' torus mesh (runtime.torus_mesh): each
        # intra-slice torus axis is its own ring family / link class
        scope = "ici1"
    elif "sx" in axes:
        scope = "ici0"
    else:
        scope = scope_default
    if (
        topology is not None
        and topology.pods > 1
        and scope.startswith("ici")
        and int(d) >= topology.num_chips
    ):
        # a ring spanning the whole multi-pod world (a flat member's one
        # collective over the full device axis) crosses the pod boundary:
        # bill it to the slowest-link-gated flat channel, exactly like
        # the synthetic flat_ring_program — otherwise the traced flat
        # baseline would replay at ICI speed and the comparison lies
        scope = "flat"
    if op in ("ppermute", "remote_copy"):
        return [
            WireStep(float(nbytes), scope=scope, op="ppermute", tag=tag)
        ]
    return _ring_steps(op, float(nbytes), int(d), scope, tag)


def program_from_schedule(
    export: Dict[str, Any],
    topology: Topology,
    transport: Optional[str] = None,
) -> ScheduleProgram:
    """Replay input from ``analysis.spmd.families.member_schedule``.

    The exported entries replay in traced order. Chunked members
    (``export['chunks']``) partition their entries into ``chunks``
    equal groups — the trace of the double-buffered engine is exactly
    ``chunks`` repetitions of one chunk's ring — and each group becomes
    one pipeline stage with its share of the GEMM placed per the family
    table (including the true ``sandwich`` split for ep_alltoall, which
    is the fidelity the closed-form front-end deliberately gives up).
    """
    entries: Sequence[Dict[str, Any]] = export["entries"]
    family = export["family"]
    if transport is None:
        transport = export.get("options", {}).get("transport", "ici")
    scope_default = "dcn" if transport == "dcn" else "ici0"
    d = max(1, int(export["partitions"]))
    flops_total = export.get("flops") or 0.0
    flops = flops_total / d
    dtype = export.get("options", {}).get("dtype", "bfloat16")
    label = f"{family}/{export['member']}"
    meta = {
        "family": family,
        "member": export["member"],
        "schedule": export.get("schedule", "sequential"),
        "frontend": "traced",
    }

    chunks = export.get("chunks")
    if chunks and chunks > 1 and entries:
        # the double-buffered engine's trace is `chunks` repetitions of
        # one chunk's ring, so the split is normally exact; a member
        # with ride-along collectives (an odd trailing psum) still
        # pipelines — near-even contiguous groups — but says so, since
        # the grouping is then a guess rather than the traced structure
        if len(entries) % chunks:
            from ddlb_tpu import telemetry

            telemetry.warn(
                f"{label}: {len(entries)} traced collectives do not "
                f"split evenly into chunk_count={chunks} pipeline "
                f"stages; grouping near-evenly (meta.chunk_fallback)"
            )
            meta["chunk_fallback"] = True
        base, extra = divmod(len(entries), chunks)
        placement = FAMILY_PLACEMENT.get(family, "comm_first")
        stages: List[Stage] = []
        cursor = 0
        for j in range(chunks):
            size = base + (1 if j < extra else 0)
            group = entries[cursor:cursor + size]
            cursor += size
            wsteps: List[WireStep] = []
            for e in group:
                wsteps.extend(
                    _entry_steps(e, scope_default, f"chunk{j}", topology)
                )
            csteps = (
                [ComputeStep(flops / chunks, dtype=dtype, tag=f"gemm#{j}")]
                if flops > 0.0
                else []
            )
            if placement == "compute_first":
                steps = csteps + wsteps
            elif placement == "sandwich" and len(wsteps) >= 2:
                half = len(wsteps) // 2
                steps = wsteps[:half] + csteps + wsteps[half:]
            else:
                steps = wsteps + csteps
            stages.append(Stage(steps, label=f"chunk{j}"))
        return pipelined(label, stages, chunks=chunks, **meta)

    stripes = int(export.get("stripes") or 1)
    rides_torus = any(
        "sx" in e.get("axes", ()) or "sy" in e.get("axes", ())
        for e in entries
    )
    if stripes > 1 and rides_torus and len(entries) % stripes == 0:
        # the striped members' trace is stripe-major (stripe w's whole
        # sandwich/exchange, then stripe w+1's): one contiguous group
        # per stripe, replayed as concurrent stages — distinct ring
        # families contend only where they genuinely share a link
        # class (the DCN psum), which is the engine's arbitration to
        # decide, not a closed form's
        per = len(entries) // stripes
        placement = FAMILY_PLACEMENT.get(family, "comm_first")
        stages = []
        for s in range(stripes):
            group = entries[s * per:(s + 1) * per]
            wsteps = []
            for e in group:
                wsteps.extend(
                    _entry_steps(e, scope_default, f"stripe{s}", topology)
                )
            csteps = (
                [ComputeStep(
                    flops / stripes, dtype=dtype, tag=f"gemm#s{s}"
                )]
                if flops > 0.0
                else []
            )
            if placement == "compute_first":
                steps: List[Any] = csteps + wsteps
            elif placement == "sandwich" and len(wsteps) >= 2:
                half = len(wsteps) // 2
                steps = wsteps[:half] + csteps + wsteps[half:]
            else:
                steps = wsteps + csteps
            stages.append(Stage(steps, label=f"stripe{s}"))
        return pipelined(label, stages, stripes=stripes, **meta)

    wsteps = []
    for e in entries:
        wsteps.extend(_entry_steps(e, scope_default, "trace", topology))
    csteps = (
        [ComputeStep(flops, dtype=dtype, tag="gemm")] if flops > 0.0 else []
    )
    if export.get("schedule") == "overlap":
        stages = [Stage(csteps, label="compute"), Stage(wsteps, label="comm")]
        return pipelined(label, [s for s in stages if s.steps], **meta)
    placement = FAMILY_PLACEMENT.get(family, "comm_first")
    if placement == "compute_first":
        chain: List[Any] = csteps + wsteps
    else:
        chain = wsteps + csteps
    if not chain:
        raise ProgramBuildError(
            f"{label}: traced schedule is empty "
            f"(status={export.get('status')!r}: {export.get('reason')})"
        )
    return sequential(label, chain, **meta)


def program_from_member(
    family: str,
    member: str,
    topology: Topology,
    overrides: Optional[Dict[str, Any]] = None,
    shapes: Optional[Dict[str, int]] = None,
) -> ScheduleProgram:
    """Convenience: trace a registered member (``member_schedule``) and
    lower it — the one-call form the report script uses."""
    from ddlb_tpu.analysis.spmd.families import member_schedule

    export = member_schedule(family, member, overrides, shapes=shapes)
    return program_from_schedule(export, topology)
