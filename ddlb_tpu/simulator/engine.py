"""Discrete-event engine: replay one schedule program on one topology.

A classic event-heap simulation over the representative chip's resource
set (``program.py`` explains why one chip suffices): tasks are released
when their chain dependency finishes, each resource executes one task
at a time, and a free resource always takes the *lowest program index*
among its released tasks — the deterministic FIFO arbitration that
models XLA's in-order per-channel issue. No randomness, no wall clock:
identical inputs replay to bit-identical timelines (the determinism
test's contract).

The replay emits a ``sim.replay`` telemetry span and counts processed
events into the ``sim.events`` metric, so traced driver runs show
simulator cost next to everything else.

Outputs (``ReplayResult``): the end-to-end makespan, the per-task
timeline, per-resource busy seconds and payload totals, the achieved
overlap fraction (hidden / hideable — NaN when the schedule has no
hideable window, the same convention as the observatory's
``measured_overlap_frac`` column), and the per-link utilization
breakdown with ``flat``-scoped bytes attributed to the physical link
classes they cross.
"""

from __future__ import annotations

import heapq
import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ddlb_tpu import telemetry
from ddlb_tpu.perfmodel.calib import scope_link_class
from ddlb_tpu.perfmodel.topology import Topology
from ddlb_tpu.simulator.program import (
    ComputeStep,
    HbmStep,
    ScheduleProgram,
    WireStep,
)


@dataclass(frozen=True)
class TimelineEntry:
    """One executed task: where it ran and when."""

    index: int
    stage: int
    label: str
    resource: str
    start_s: float
    finish_s: float


@dataclass
class ReplayResult:
    """Everything one replay predicts."""

    program: str
    topology: str
    makespan_s: float
    timeline: List[TimelineEntry]
    busy_s: Dict[str, float]
    payload: Dict[str, float]  # resource -> FLOPs (mxu) or bytes
    events: int
    meta: dict = field(default_factory=dict)

    @property
    def compute_busy_s(self) -> float:
        return self.busy_s.get("mxu", 0.0)

    @property
    def comm_busy_s(self) -> float:
        return sum(
            s for r, s in self.busy_s.items() if r not in ("mxu", "hbm")
        )

    @property
    def overlap_frac(self) -> float:
        """Hidden / hideable over the compute and wire tracks; NaN when
        the schedule has no hideable window (either track empty) —
        mirrors the ``measured_overlap_frac`` schema convention."""
        compute, comm = self.compute_busy_s, self.comm_busy_s
        hideable = min(compute, comm)
        if hideable <= 0.0:
            return float("nan")
        hidden = compute + comm - self.makespan_s
        return max(0.0, min(1.0, hidden / hideable))

    def link_utilization(self, topology: Topology) -> Dict[str, Dict[str, float]]:
        """Per link class: busy fraction of the makespan and bytes one
        chip moved over it, with ``flat`` ring steps' bytes additionally
        attributed to the physical classes their hops cross."""
        out: Dict[str, Dict[str, float]] = {}
        span = self.makespan_s or float("nan")
        flat_split = topology.flat_hop_fractions()
        for res in topology.comm_resources():
            bytes_res = self.payload.get(res, 0.0)
            if res != "flat":
                bytes_res += self.payload.get("flat", 0.0) * flat_split.get(
                    res, 0.0
                )
            out[res] = {
                "busy_frac": self.busy_s.get(res, 0.0) / span,
                "bytes": bytes_res,
            }
        return out


def _duration(step, topology: Topology, calibration=None) -> float:
    """One task's duration; ``calibration`` (a fitted
    ``calib.GroupCalibration``, duck-typed) prices the additive
    latency/overhead terms on top of the bandwidth/FLOP floor: every
    ComputeStep pays one step of software overhead, every WireStep one
    step plus one hop of its link class (``flat``/``ici*`` scopes are
    ici hops, ``dcn`` is a dcn hop). HbmStep is untouched — the HBM
    term is a byte census, not a dispatched schedule step. None adds
    exactly zero, preserving gate 1's float-precision agreement with
    the uncalibrated closed form by construction."""
    if isinstance(step, ComputeStep):
        base = step.flops / topology.resource_rate("mxu", step.dtype)
        if calibration is not None:
            base += calibration.compute_overhead_s()
        return base
    if isinstance(step, HbmStep):
        return step.nbytes / topology.resource_rate("hbm")
    rate = topology.resource_rate(step.resource)
    if rate <= 0.0:
        # a downed link (Degradation overlay): the step never completes —
        # an unroutable program honestly replays to an infinite makespan
        # instead of crashing, so degraded rankings can SHOW the outage
        return math.inf if step.nbytes > 0.0 else 0.0
    base = step.nbytes / rate
    if calibration is not None:
        base += calibration.wire_overhead_s(scope_link_class(step.resource))
    return base


def replay(
    program: ScheduleProgram, topology: Topology, calibration=None
) -> ReplayResult:
    """Replay ``program`` on ``topology``; see module docstring.

    ``calibration`` (optional fitted constants for the world's chip +
    timing backend) turns the lower-bound replay into an absolute
    prediction: per-step terms via ``_duration`` plus the fixed
    ``dispatch_s`` once on the makespan — the quantities validation
    gate 3 holds against banked measured medians.
    """
    with telemetry.span(
        "sim.replay", cat="sim", program=program.name, topo=topology.name
    ):
        return _replay(program, topology, calibration)


def _replay(
    program: ScheduleProgram, topology: Topology, calibration=None
) -> ReplayResult:
    flat: List[Tuple[int, object, Optional[int]]] = [
        (si, step, dep) for si, _ji, step, dep in program.tasks()
    ]
    n = len(flat)
    durations = [
        _duration(step, topology, calibration) for _si, step, _dep in flat
    ]
    children: Dict[int, List[int]] = {}
    indegree = [0] * n
    for idx, (_si, _step, dep) in enumerate(flat):
        if dep is not None:
            children.setdefault(dep, []).append(idx)
            indegree[idx] = 1

    #: released-but-not-started tasks per resource, lowest index first
    queues: Dict[str, List[int]] = {}
    idle: Dict[str, bool] = {}
    busy_s: Dict[str, float] = {}
    payload: Dict[str, float] = {}
    finish = [0.0] * n
    start = [0.0] * n
    done = [False] * n
    timeline: List[TimelineEntry] = []

    events: List[Tuple[float, int, int]] = []  # (time, seq, task)
    seq = 0

    def release(idx: int) -> None:
        res = flat[idx][1].resource
        heapq.heappush(queues.setdefault(res, []), idx)
        idle.setdefault(res, True)

    def start_task(res: str, now: float) -> None:
        nonlocal seq
        if not idle.get(res, True) or not queues.get(res):
            return
        idx = heapq.heappop(queues[res])
        idle[res] = False
        start[idx] = now
        finish[idx] = now + durations[idx]
        seq += 1
        heapq.heappush(events, (finish[idx], seq, idx))

    for idx in range(n):
        if indegree[idx] == 0:
            release(idx)
    for res in list(queues):
        start_task(res, 0.0)

    processed = 0
    while events:
        now, _s, idx = heapq.heappop(events)
        processed += 1
        done[idx] = True
        si, step, _dep = flat[idx]
        res = step.resource
        idle[res] = True
        busy_s[res] = busy_s.get(res, 0.0) + durations[idx]
        qty = step.flops if isinstance(step, ComputeStep) else step.nbytes
        payload[res] = payload.get(res, 0.0) + qty
        timeline.append(
            TimelineEntry(
                index=idx,
                stage=si,
                label=getattr(step, "tag", "") or type(step).__name__,
                resource=res,
                start_s=start[idx],
                finish_s=finish[idx],
            )
        )
        for child in children.get(idx, ()):
            release(child)
        # the freed resource first, then any resource a release touched
        start_task(res, now)
        for other in list(queues):
            start_task(other, now)

    telemetry.record("sim.events", processed)
    makespan = max((e.finish_s for e in timeline), default=0.0)
    meta = dict(program.meta)
    if calibration is not None:
        makespan += calibration.dispatch_s
        meta["calibration"] = {
            "chip": calibration.chip,
            "backend": calibration.backend,
        }
    if not all(done):  # pragma: no cover - would mean a malformed IR
        stuck = [i for i, d in enumerate(done) if not d]
        raise RuntimeError(
            f"replay of {program.name} deadlocked with tasks {stuck[:8]} "
            f"unexecuted — the schedule IR produced an unsatisfiable "
            f"dependency"
        )
    return ReplayResult(
        program=program.name,
        topology=topology.name,
        makespan_s=makespan,
        timeline=timeline,
        busy_s=busy_s,
        payload=payload,
        events=processed,
        meta=meta,
    )


def summarize(result: ReplayResult, topology: Topology) -> Dict[str, object]:
    """Plain-data summary (the ``--json`` report row): makespan, busy
    fractions, overlap, per-link breakdown."""
    ovl = result.overlap_frac
    return {
        "program": result.program,
        "topology": result.topology,
        "chips": topology.num_chips,
        "makespan_s": result.makespan_s,
        "compute_busy_s": result.compute_busy_s,
        "comm_busy_s": result.comm_busy_s,
        "hbm_busy_s": result.busy_s.get("hbm", 0.0),
        "overlap_frac": None if math.isnan(ovl) else ovl,
        "events": result.events,
        "links": result.link_utilization(topology),
        "meta": result.meta,
    }
