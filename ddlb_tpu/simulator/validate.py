"""Simulator validation: closed-form agreement + observatory history.

Three gates keep the simulator honest before anyone trusts a 4096-chip
ranking from it:

1. **Closed-form agreement** (``closed_form_check``): on a degenerate
   flat single-slice topology, the replayed makespan of every
   registered family's representative member — and the chunked-engine
   variants at several pipeline depths — must equal
   ``perfmodel.cost.estimate().predicted_s`` to float precision
   (``CLOSED_FORM_RTOL``). The engine's event arbitration of the
   sequential / ideal-overlap / chunked shapes is thereby proven
   equivalent to the cost model's combination rules, with the censuses
   shared rather than restated: the DDLB123 wire census becomes a
   latency census.

2. **History join** (``history_check``): banked observatory rows
   (``observatory.store`` — e.g. a seeded cpu-sim capture) are
   replayed through the closed-form front-end on a flat topology
   matching each row's chip and world size. Per history key the sim
   prediction must (a) agree with the row's own banked ``predicted_s``
   within ``HISTORY_RTOL`` and (b) stay a lower bound on the measured
   median up to ``LOWER_BOUND_SLACK`` — the tolerance-gated small-scale
   validation the ROADMAP's simulator item calls for. Families whose
   banked predictions depend on measurement-time state (the serving
   families' arrival-horizon floor, the compute_only HBM race) join
   only through gate (b).

3. **Calibration gate** (``calibration_check``): with a fitted
   calibration table (``perfmodel.calib`` — per-hop latency, per-step
   software overhead, per-row dispatch, fitted from the same bank),
   the calibrated replay of every reproducible banked key must land
   *within* ``CALIBRATION_RTOL`` of the measured median — two-sided,
   not merely below it. Gate 2 proves the lower bound; gate 3 proves
   the absolute number, which is what the ROADMAP's capacity-planner
   item needs before a 16pod4096 world is planned from replays.

This module is the one simulator tier that imports implementation
classes (and therefore JAX, at module-import level only): rebuilding a
row's duck-typed stub needs the real ``wire_bytes``/``flops`` methods.
The ranking tier (``frontends`` synthetics + engine) stays JAX-free.
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ddlb_tpu import telemetry
from ddlb_tpu.perfmodel.cost import estimate
from ddlb_tpu.perfmodel.topology import Topology, flat_topology
from ddlb_tpu.simulator.engine import replay
from ddlb_tpu.simulator.frontends import ProgramBuildError, program_from_impl

#: float-precision bar for gate (1): the engine and the cost model run
#: the same float arithmetic in a different order, nothing more
CLOSED_FORM_RTOL = 1e-9

#: gate (2a): sim vs the row's banked perfmodel prediction. Not zero:
#: banked rows may predate a formula fix (the bank keeps history)
HISTORY_RTOL = 0.05

#: gate (2b): sim must stay a lower bound on the measured median, with
#: slack for measurement noise at CPU-sim microsecond scales
LOWER_BOUND_SLACK = 0.02

#: families whose banked ``predicted_s`` is reproducible from shape
#: alone — the gate-(2a) join set; everything else joins via (2b) only
REPRODUCIBLE_FAMILIES = (
    "tp_columnwise",
    "tp_rowwise",
    "dp_allreduce",
    "ep_alltoall",
    "cp_ring_attention",
    "pp_pipeline",
    "collectives",
)

#: representative member + canonical overrides per registered family,
#: for gate (1); shapes come from the analysis tier's canonical table
REPRESENTATIVES: Dict[str, Tuple[str, Dict[str, Any]]] = {
    "tp_columnwise": ("jax_spmd", {}),
    "tp_rowwise": ("jax_spmd", {}),
    "dp_allreduce": ("jax_spmd", {}),
    "ep_alltoall": ("jax_spmd", {}),
    "cp_ring_attention": ("ring", {}),
    "pp_pipeline": ("jax_spmd", {}),
    "transformer_step": ("compute_only", {}),
    "transformer_decode": ("spmd", {}),
    "serving_load": ("static", {}),
    "collectives": ("jax_spmd", {}),
}

#: chunked-engine variants additionally checked per overlap family —
#: the pipeline fill/drain law must replay, not just the serial floor
CHUNKED_VARIANTS = (1, 2, 4)

#: gate (3): a real member's traced schedule replayed next to its
#: synthetic twin. Flat and hierarchical traces lower to step-for-step
#: identical programs (rel ~0); the headroom covers future granularity
#: changes, not modeling slack
TWIN_RTOL = 0.2

#: the striped compositions' own bar: the synthetic twin idealizes each
#: stripe onto one isolated ICI axis, but the real torus sandwich runs
#: every stripe's big ring on its own axis AND small rings on its
#: peers' (the fully-scattered legs), so the stripes contend at the
#: gather tail — per-link byte totals still coincide exactly; the
#: makespan gap is that measured interference, the fidelity the traced
#: replay exists to expose
TWIN_RTOL_STRIPED = 0.5

#: the multi-pod world gate (3) ranks on — ISSUE 16's acceptance
#: topology (``perfmodel.topology.PRESETS``)
TWIN_TOPOLOGY = "4pod1024"

#: per family: the composed member, its per-composition overrides, and
#: shapes sized so every scatter/stripe split is exact at the twin
#: topology's 1024 devices (m constraints: collectives shard divides
#: stripes x intra; dp m divides stripes x intra; ep tokens-per-group
#: m/d^2 divides stripes). Traces are static — no arrays materialize —
#: so the token counts are free.
TWIN_FAMILIES: Dict[str, Dict[str, Any]] = {
    "collectives": {
        "member": "jax_spmd_hier",
        "shapes": {"m": 524288, "n": 1, "k": 64},
        "op": "all_reduce",
        "payload": lambda shp, d, isz: (shp["m"] // d) * shp["k"] * isz,
    },
    "dp_allreduce": {
        "member": "jax_spmd_hier",
        "shapes": {"m": 512, "n": 256, "k": 64},
        "op": "all_reduce",
        "payload": lambda shp, d, isz: shp["m"] * shp["n"] * isz,
    },
    "ep_alltoall": {
        "member": "jax_spmd_hier",
        "shapes": {"m": 2097152, "n": 64, "k": 64},
        "op": "all_to_all",
        "payload": lambda shp, d, isz: (
            (shp["m"] // d) * (shp["k"] + shp["n"]) * isz
        ),
    },
}



class _RuntimeProbe:
    """The few runtime attributes shape-only censuses read (the
    transformer families factor their mesh from ``num_devices``)."""

    def __init__(self, num_devices: int) -> None:
        self.num_devices = int(num_devices)
        self.num_slices = 1
        self.platform = "cpu"
        self.num_processes = 1


def build_stub(
    family: str,
    member: str,
    m: int,
    n: int,
    k: int,
    d: int,
    dtype: str = "bfloat16",
    **options: Any,
):
    """An uninitialized impl instance carrying only the state the cost
    model and the closed-form front-end read — the same probe idiom the
    perfmodel tests use, so the closed forms are checkable without
    operand construction or a compile."""
    from ddlb_tpu.primitives.registry import load_impl_class

    cls = load_impl_class(family, member)
    impl = object.__new__(cls)
    impl.m, impl.n, impl.k = int(m), int(n), int(k)
    impl.dtype = dtype
    impl.num_partitions = int(d)
    impl.runtime = _RuntimeProbe(d)
    defaults, _allowed = cls.option_schema()
    impl.options = {**defaults, **options}
    if family == "serving_load":
        # the one family whose censuses read the (seeded, host-built)
        # workload trace rather than shape alone — build it the way
        # ``_input_setup`` would, still without touching a device
        from ddlb_tpu.workload import generate_trace

        impl.seed = 42
        impl._trace = generate_trace(impl.workload_spec())
    return impl


def _agreement(
    impl, topology: Topology, transport: str = "ici"
) -> Dict[str, Any]:
    est = estimate(impl, topology.chip)
    result = replay(program_from_impl(impl, topology, transport), topology)
    want = est.predicted_s
    got = result.makespan_s
    rel = abs(got - want) / want if want > 0.0 else abs(got - want)
    return {
        "family": impl.primitive_name,
        "member": type(impl).__name__,
        "options": dict(impl.options),
        "predicted_cost_s": want,
        "predicted_sim_s": got,
        "rel_err": rel,
        "ok": rel <= CLOSED_FORM_RTOL,
    }


def closed_form_check(
    chip: str = "v5e",
    families: Optional[Sequence[str]] = None,
    shapes: Optional[Dict[str, Dict[str, int]]] = None,
) -> List[Dict[str, Any]]:
    """Gate (1): per-family float-precision agreement on the degenerate
    flat world (plus the chunked variants for every family that has an
    ``overlap`` member with the chunked engine). Returns one record per
    checked config; a config's ``ok=False`` is a simulator bug, full
    stop."""
    from ddlb_tpu.analysis.spmd.families import FAMILY_SHAPES
    from ddlb_tpu.primitives.registry import implementation_names

    shapes = shapes or FAMILY_SHAPES
    out: List[Dict[str, Any]] = []
    with telemetry.span("sim.validate", cat="sim", mode="closed-form"):
        for family, (member, overrides) in REPRESENTATIVES.items():
            if families is not None and family not in families:
                continue
            shp = shapes[family]
            topo = flat_topology(shp["d"], chip)
            impl = build_stub(
                family, member, shp["m"], shp["n"], shp["k"], shp["d"],
                **overrides,
            )
            out.append(_agreement(impl, topo))
            # registry-driven, like the DDLB007/DDLB123 coverage
            # invariants: any family that ships an ``overlap`` member
            # runs the chunked engine and must replay its fill/drain law
            if "overlap" in implementation_names(family):
                for chunks in CHUNKED_VARIANTS:
                    impl = build_stub(
                        family, "overlap", shp["m"], shp["n"], shp["k"],
                        shp["d"], algorithm="chunked", chunk_count=chunks,
                    )
                    out.append(_agreement(impl, topo))
    return out


# ---------------------------------------------------------------------------
# member twins: real traced schedules vs synthetic compositions
# ---------------------------------------------------------------------------


def member_twin_check(
    topology: str = TWIN_TOPOLOGY,
    families: Optional[Sequence[str]] = None,
    rtol: float = TWIN_RTOL,
    striped_rtol: float = TWIN_RTOL_STRIPED,
) -> Dict[str, Any]:
    """Gate (3): the topology-adaptive members (ISSUE 16) replayed from
    their TRACED schedules next to the synthetic compositions that
    predicted them.

    Per family, the composed member traces once per composition (flat /
    hierarchical / striped) at the twin topology's own axis sizes
    (``pods``/``ici_mesh`` pinned through the shapes dict), the traced
    program replays comm-only (``flops`` zeroed — the synthetics carry
    no GEMM), and:

    - **agreement**: each traced makespan lands within tolerance of its
      synthetic twin — ``rtol`` for flat/hierarchical (step-for-step
      identical programs, landing at ~0), ``striped_rtol`` for the
      striped members (see ``TWIN_RTOL_STRIPED``: the twin idealizes
      away cross-stripe interference the traced torus sandwich really
      has);
    - **ranking**: hierarchical and striped both beat flat on the
      multi-pod world, in the traced replays AND the synthetics — the
      simulator's ranking is realized by the real members, the
      acceptance the issue names.

    Returns a summary dict; ``ok`` is the gate verdict.
    """
    from ddlb_tpu.analysis.spmd.families import member_schedule
    from ddlb_tpu.perfmodel.cost import wire_itemsize
    from ddlb_tpu.perfmodel.topology import resolve_topology
    from ddlb_tpu.simulator.frontends import (
        program_from_schedule,
        synthetic_program,
    )

    topo = resolve_topology(topology)
    d = topo.num_chips
    mesh = topo.ici_mesh
    axis_pins = {
        "dcn": topo.pods,
        "ici": topo.chips_per_pod,
        "sx": mesh[0],
        "sy": mesh[1] if len(mesh) > 1 else 1,
    }
    isz = wire_itemsize("bfloat16")
    records: List[Dict[str, Any]] = []
    failures: List[str] = []
    with telemetry.span("sim.validate", cat="sim", mode="member-twin"):
        for family, cfg in TWIN_FAMILIES.items():
            if families is not None and family not in families:
                continue
            shapes = {**cfg["shapes"], "d": d, **axis_pins}
            op = cfg["op"]
            payload = cfg["payload"](cfg["shapes"], d, isz)
            if family == "collectives":
                base_overrides: Dict[str, Any] = {"op": op}
            else:
                base_overrides = {}
            traced_s: Dict[str, float] = {}
            synth_s: Dict[str, float] = {}
            for comp in ("flat", "hierarchical", "striped"):
                export = member_schedule(
                    family,
                    cfg["member"],
                    {**base_overrides, "composition": comp},
                    shapes=shapes,
                )
                if export["status"] != "verified":
                    failures.append(
                        f"{family}/{cfg['member']}[{comp}]: trace status "
                        f"{export['status']!r} ({export['reason']})"
                    )
                    continue
                comm_only = dict(export, flops=0.0)
                traced = replay(
                    program_from_schedule(comm_only, topo), topo
                ).makespan_s
                synth = replay(
                    synthetic_program(comp, op, payload, topo), topo
                ).makespan_s
                traced_s[comp] = traced
                synth_s[comp] = synth
                rel = abs(traced - synth) / synth if synth > 0.0 else 0.0
                bar = striped_rtol if comp == "striped" else rtol
                ok = rel <= bar
                if not ok:
                    failures.append(
                        f"{family}/{comp}: traced {traced:.6e}s vs "
                        f"synthetic {synth:.6e}s (rel {rel:.3f} > {bar})"
                    )
                records.append(
                    {
                        "family": family,
                        "member": cfg["member"],
                        "composition": comp,
                        "traced_s": traced,
                        "synthetic_s": synth,
                        "rel_err": rel,
                        "rtol": bar,
                        "ok": ok,
                    }
                )
            # ranking agreement: the adaptive compositions beat flat on
            # the multi-pod world, for the real members and the
            # synthetics alike
            for name, span in (("traced", traced_s), ("synthetic", synth_s)):
                if set(span) != {"flat", "hierarchical", "striped"}:
                    continue
                for comp in ("hierarchical", "striped"):
                    if span[comp] >= span["flat"]:
                        failures.append(
                            f"{family} {name} ranking: {comp} "
                            f"({span[comp]:.6e}s) does not beat flat "
                            f"({span['flat']:.6e}s) on {topo.name}"
                        )
    return {
        "topology": topo.name,
        "rtol": rtol,
        "records": records,
        "failures": failures,
        "ok": bool(records) and not failures,
    }


# ---------------------------------------------------------------------------
# history join
# ---------------------------------------------------------------------------


def _infer_scalar(text: str) -> Any:
    """'true'/'false' -> bool, then int, then float, else str (the CLI
    option-string convention, restated for the row join so the
    simulator tier does not import the CLI)."""
    low = str(text).strip().lower()
    if low == "true":
        return True
    if low == "false":
        return False
    try:
        return int(text)
    except (TypeError, ValueError):
        pass
    try:
        return float(text)
    except (TypeError, ValueError):
        pass
    return str(text).strip()


def parse_option_string(option: str) -> Dict[str, Any]:
    """``'algorithm=chunked;chunk_count=2'`` -> dict, scalar-inferred."""
    out: Dict[str, Any] = {}
    for part in str(option or "").split(";"):
        part = part.strip()
        if not part or "=" not in part:
            continue
        key, _, value = part.partition("=")
        out[key.strip()] = _infer_scalar(value)
    return out


def _fnum(value: Any) -> Optional[float]:
    try:
        v = float(value)
    except (TypeError, ValueError):
        return None
    return v if math.isfinite(v) else None


def _median(values: List[float]) -> Optional[float]:
    vals = sorted(values)
    if not vals:
        return None
    mid = len(vals) // 2
    if len(vals) % 2:
        return vals[mid]
    return 0.5 * (vals[mid - 1] + vals[mid])


def history_check(
    directory: Optional[str] = None,
    records: Optional[List[Dict[str, Any]]] = None,
    rtol: float = HISTORY_RTOL,
    lower_bound_slack: float = LOWER_BOUND_SLACK,
) -> Dict[str, Any]:
    """Gate (2): replay every reproducible banked history key and hold
    the sim prediction to the banked prediction (rtol) and to the
    measured median (lower bound + slack). Returns a summary with the
    violation list; ``ok`` is the gate verdict. Rows that cannot be
    rebuilt (unknown member, missing columns) are counted ``skipped``,
    never silently dropped."""
    from ddlb_tpu.observatory.store import load_history, row_key

    if records is None:
        records = load_history(directory)
    groups: Dict[str, List[Dict[str, Any]]] = {}
    for rec in records:
        if rec.get("kind") != "row":
            continue
        row = rec["row"]
        if str(row.get("error", "") or "").strip():
            continue
        groups.setdefault(row_key(row), []).append(row)

    checked = 0
    skipped: List[str] = []
    violations: List[Dict[str, Any]] = []
    with telemetry.span("sim.validate", cat="sim", mode="history"):
        for key, rows in sorted(groups.items()):
            row = rows[0]
            family = row.get("primitive")
            member = row.get("base_implementation")
            medians = [
                v / 1e3
                for v in (_fnum(r.get("median time (ms)")) for r in rows)
                if v is not None and v > 0.0
            ]
            measured_s = _median(medians)
            world = _fnum(row.get("world_size"))
            m, n, k = (
                _fnum(row.get("m")), _fnum(row.get("n")), _fnum(row.get("k"))
            )
            if measured_s is None or not world or world < 1 or not all(
                (m, n, k)
            ):
                skipped.append(f"{family}/{member}: row lacks shape/median")
                continue
            chip = str(row.get("chip") or "cpu-sim")
            try:
                topo = flat_topology(int(world), chip)
                impl = build_stub(
                    family, member, int(m), int(n), int(k), int(world),
                    dtype=str(row.get("dtype") or "bfloat16"),
                    **parse_option_string(row.get("option", "")),
                )
                sim_s = replay(
                    program_from_impl(impl, topo), topo
                ).makespan_s
            except (ProgramBuildError, ValueError, KeyError, TypeError) as exc:
                skipped.append(f"{family}/{member}: {exc}")
                continue
            checked += 1
            # gate (2a) only for families whose banked prediction is
            # reproducible from shape alone; every rebuilt row — the
            # serving/decode families included — still faces (2b)
            banked = _fnum(row.get("predicted_s"))
            if family not in REPRODUCIBLE_FAMILIES:
                banked = None
            if banked and banked > 0.0:
                rel = abs(sim_s - banked) / banked
                if rel > rtol:
                    violations.append(
                        {
                            "key": key,
                            "kind": "banked-prediction",
                            "sim_s": sim_s,
                            "banked_predicted_s": banked,
                            "rel_err": rel,
                        }
                    )
            if sim_s > measured_s * (1.0 + lower_bound_slack):
                violations.append(
                    {
                        "key": key,
                        "kind": "lower-bound",
                        "sim_s": sim_s,
                        "measured_median_s": measured_s,
                    }
                )
    return {
        "checked": checked,
        "skipped": len(skipped),
        "skipped_reasons": skipped,
        "violations": violations,
        "rtol": rtol,
        "lower_bound_slack": lower_bound_slack,
        "ok": checked > 0 and not violations,
    }


#: gate (3): calibrated replay vs the measured median, two-sided. The
#: residual MAD of a healthy cpu-sim fit sits well under this; real
#: hardware groups are tighter still (host noise shrinks per-row)
CALIBRATION_RTOL = 0.05


def calibration_check(
    directory: Optional[str] = None,
    records: Optional[List[Dict[str, Any]]] = None,
    table=None,
    rtol: float = CALIBRATION_RTOL,
) -> Dict[str, Any]:
    """Gate (3): calibrated replays must land WITHIN ``rtol`` of banked
    measured medians — the absolute-makespan promise, two-sided where
    gate (2b) is one-sided. Joins the same reproducible keys as gate
    (2a); rows whose (chip, backend) has no fitted group are skipped
    (a table can legitimately cover one chip of a mixed bank), as are
    degraded-world rows (the fit excludes them, so must the gate).
    ``table`` defaults to the env-selected one (``DDLB_TPU_CALIB``);
    with no table at all the gate reports ``ok: False`` with a reason —
    an uncalibrated world must not read as a passing absolute check.
    """
    from ddlb_tpu.observatory.store import load_history, row_key
    from ddlb_tpu.perfmodel import calib

    if table is None:
        table = calib.get_table()
    summary: Dict[str, Any] = {
        "checked": 0,
        "skipped": 0,
        "skipped_reasons": [],
        "violations": [],
        "rtol": rtol,
        "table_version": getattr(table, "version", ""),
        "ok": False,
    }
    if table is None:
        summary["skipped_reasons"].append("no calibration table")
        summary["skipped"] = 1
        return summary
    if records is None:
        records = load_history(directory)
    groups: Dict[str, List[Dict[str, Any]]] = {}
    for rec in records:
        if rec.get("kind") != "row":
            continue
        row = rec["row"]
        if str(row.get("error", "") or "").strip():
            continue
        if str(row.get("world_degraded", "")).strip().lower() in (
            "1", "true", "yes", "on",
        ):
            continue
        groups.setdefault(row_key(row), []).append(row)

    checked = 0
    skipped: List[str] = []
    violations: List[Dict[str, Any]] = []
    with telemetry.span("sim.validate", cat="sim", mode="calibration"):
        for key, rows in sorted(groups.items()):
            row = rows[0]
            family = row.get("primitive")
            member = row.get("base_implementation")
            if family not in REPRODUCIBLE_FAMILIES:
                skipped.append(f"{family}/{member}: family not reproducible")
                continue
            medians = [
                v / 1e3
                for v in (_fnum(r.get("median time (ms)")) for r in rows)
                if v is not None and v > 0.0
            ]
            measured_s = _median(medians)
            world = _fnum(row.get("world_size"))
            m, n, k = (
                _fnum(row.get("m")), _fnum(row.get("n")), _fnum(row.get("k"))
            )
            if measured_s is None or not world or world < 1 or not all(
                (m, n, k)
            ):
                skipped.append(f"{family}/{member}: row lacks shape/median")
                continue
            chip = str(row.get("chip") or "cpu-sim")
            group = table.group(
                chip, str(row.get("time_measurement_backend") or "") or None
            )
            if group is None:
                skipped.append(f"{family}/{member}: no fit for chip {chip}")
                continue
            try:
                topo = flat_topology(int(world), chip)
                impl = build_stub(
                    family, member, int(m), int(n), int(k), int(world),
                    dtype=str(row.get("dtype") or "bfloat16"),
                    **parse_option_string(row.get("option", "")),
                )
                sim_cal_s = replay(
                    program_from_impl(impl, topo), topo, calibration=group
                ).makespan_s
            except (ProgramBuildError, ValueError, KeyError, TypeError) as exc:
                skipped.append(f"{family}/{member}: {exc}")
                continue
            checked += 1
            rel = abs(sim_cal_s - measured_s) / measured_s
            if rel > rtol:
                violations.append(
                    {
                        "key": key,
                        "kind": "calibrated-absolute",
                        "sim_cal_s": sim_cal_s,
                        "measured_median_s": measured_s,
                        "rel_err": rel,
                    }
                )
    summary.update(
        checked=checked,
        skipped=len(skipped),
        skipped_reasons=skipped,
        violations=violations,
        ok=checked > 0 and not violations,
    )
    return summary
