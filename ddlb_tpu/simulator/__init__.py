"""Static multi-pod performance simulator (discrete-event replay).

Replays a *schedule program* — an ordered, dependency-structured list of
compute / wire / HBM steps — on a synthetic ``perfmodel.topology
.Topology``, producing a predicted timeline, overlap fraction, and
per-link utilization breakdown without booting a single chip. Three
front-ends build programs (``frontends``):

- the semantic SPMD interpreter's per-member ordered collective trace
  (``analysis/spmd``), so chunked double-buffered rings and pipeline
  schedule tables replay step-by-step;
- the perfmodel closed forms over a duck-typed impl (the validation
  front-end — on a degenerate flat topology the replay must agree with
  ``perfmodel.cost`` to float precision);
- synthetic compositions written directly against the schedule IR
  (flat ring, HiCCL-style hierarchical phases, multi-path striped), so
  hierarchical collectives are ranked *before* they exist as impl
  members.

``scripts/sim_report.py`` is the ranking/validation CLI;
``scripts/sim_demo.py`` (= ``make sim-report``) is the banked
acceptance transcript.
"""

from ddlb_tpu.simulator.engine import ReplayResult, replay
from ddlb_tpu.simulator.program import (
    ComputeStep,
    HbmStep,
    ScheduleProgram,
    Stage,
    WireStep,
)

__all__ = [
    "ComputeStep",
    "HbmStep",
    "ReplayResult",
    "ScheduleProgram",
    "Stage",
    "WireStep",
    "replay",
]
