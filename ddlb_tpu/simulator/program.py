"""Schedule IR: the program the discrete-event engine replays.

A ``ScheduleProgram`` is an ordered list of ``Stage``s; a stage is an
ordered list of steps. The dependency rules encode exactly the two
schedules the framework's members run:

- *within* a stage, steps form a chain (step ``i+1`` starts after step
  ``i`` finishes) — a stage is one chunk's/one tick's serial recipe;
- *across* stages, there is **no** data dependency when the program is
  ``overlap=True`` (the chunked double-buffered pipeline: chunk
  ``j+1``'s ring hops carry no dependency on chunk ``j``'s GEMM, so
  only resource contention orders them — the T3 schedule), and a full
  barrier dependency when ``overlap=False`` (the sequential members:
  every stage waits for the previous one).

Steps are SPMD-symmetric: every chip executes the same step at the same
time, so the engine simulates one representative chip's resource set
(``mxu``, ``hbm``, one ring channel per ICI mesh dim, ``dcn``, and the
``flat`` world-spanning ring channel) and the result holds for all of
them — which is what lets a 4096-chip replay cost microseconds.

Quantities are *per-chip*: a ``WireStep``'s ``nbytes`` is what one chip
sends in that synchronous ring/exchange step (the same per-device
convention as ``wire_bytes()``/``trace.wire_contribution``); a
``ComputeStep``'s ``flops`` is one chip's share. Durations are priced
by ``Topology.resource_rate`` at replay time, so one program ranks
identically-shaped worlds of different chips.

Stdlib-only by design: programs must be buildable and replayable on the
JAX-free tier (the whole point of judging algorithms before booking
chips).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Iterator, List, Optional, Sequence, Tuple, Union


@dataclass(frozen=True)
class ComputeStep:
    """One chip's MXU work for one chunk/tick, in FLOPs."""

    flops: float
    dtype: str = "bfloat16"
    tag: str = "compute"

    @property
    def resource(self) -> str:
        return "mxu"


@dataclass(frozen=True)
class WireStep:
    """One synchronous collective step: each chip sends ``nbytes`` over
    the ``scope`` link class (``ici<dim>``, ``dcn``, or ``flat`` — the
    world-spanning ring gated by its slowest link). ``op`` names the
    originating collective for the report; ``tag`` labels the phase
    (``rs-intra``, ``ar-inter``, ...)."""

    nbytes: float
    scope: str = "ici0"
    op: str = "ppermute"
    tag: str = "comm"

    @property
    def resource(self) -> str:
        return self.scope


@dataclass(frozen=True)
class HbmStep:
    """One chip's HBM traffic (bytes moved once) — the memory-bound
    families' census. Independent of the compute/wire chain by default
    (the ``max(·, hbm)`` roofline race), see ``Stage.hbm_parallel``."""

    nbytes: float
    tag: str = "hbm"

    @property
    def resource(self) -> str:
        return "hbm"


Step = Union[ComputeStep, WireStep, HbmStep]


@dataclass
class Stage:
    """One chunk's / one tick's serial recipe: steps chain in order.

    ``hbm_parallel`` lifts the stage's ``HbmStep``s out of the chain
    onto their own dependency-free track — the roofline-race form the
    cost model prices as ``max(compute + comm, hbm)``; leave it False
    to model an HBM phase that genuinely serializes (none of today's
    families do)."""

    steps: List[Step] = field(default_factory=list)
    label: str = ""
    hbm_parallel: bool = True


@dataclass
class ScheduleProgram:
    """A named, ordered list of stages plus the overlap contract."""

    name: str
    stages: List[Stage] = field(default_factory=list)
    #: True: stages are independent (double-buffered pipeline — resource
    #: contention alone orders them); False: stage j+1 waits on stage j
    overlap: bool = False
    #: metadata for reports (family, member, option string, ...)
    meta: dict = field(default_factory=dict)

    def num_steps(self) -> int:
        return sum(len(s.steps) for s in self.stages)

    def total(self, kind: type) -> float:
        """Summed per-chip quantity of one step kind (FLOPs for
        ``ComputeStep``, bytes otherwise) — the census the validation
        mode compares against ``wire_bytes()``/``flops()``."""
        out = 0.0
        for stage in self.stages:
            for step in stage.steps:
                if isinstance(step, kind):
                    out += step.flops if kind is ComputeStep else step.nbytes
        return out

    def tasks(self) -> Iterator[Tuple[int, int, Step, Optional[int]]]:
        """Flatten into ``(stage_idx, step_idx, step, dep)`` where
        ``dep`` is the flat index of the task this one chains after
        (None = no data dependency). This is the engine's input; the
        flat index is ``sum(len(stages[:i])) + j`` in program order."""
        flat = 0
        prev_stage_last: Optional[int] = None
        for si, stage in enumerate(self.stages):
            prev_in_chain: Optional[int] = (
                None if self.overlap else prev_stage_last
            )
            last_flat: Optional[int] = prev_stage_last
            for ji, step in enumerate(stage.steps):
                if isinstance(step, HbmStep) and stage.hbm_parallel:
                    # its own track: races the chain, never in it
                    dep = None if self.overlap else prev_stage_last
                    yield si, ji, step, dep
                else:
                    yield si, ji, step, prev_in_chain
                    prev_in_chain = flat
                    last_flat = flat
                flat += 1
            prev_stage_last = last_flat


def sequential(name: str, steps: Sequence[Step], **meta) -> ScheduleProgram:
    """One-stage serial program (the ``COST_SCHEDULE='sequential'``
    shape: everything chains)."""
    return ScheduleProgram(
        name, [Stage(list(steps), label="serial")], overlap=False, meta=meta
    )


def pipelined(
    name: str, stages: Sequence[Stage], **meta
) -> ScheduleProgram:
    """Double-buffered pipeline (the chunked-fusion engine's shape:
    stages independent, resources arbitrate)."""
    return ScheduleProgram(name, list(stages), overlap=True, meta=meta)
