"""The search driver: propose -> prune -> measure -> bank -> consult.

One ``search()`` call closes the loop for a single ``(family, impl,
shape, dtype, world)`` target: the knob registry proposes the feasible
space (``tuner.space``), the priors price and prune it
(``tuner.priors``), the survivors are measured in prior-rank order —
on a leased warm-pool worker with the NEXT candidate prefetch-compiling
in the worker's background thread when a pool is provided
(``pool.run_one_row(prefetch=...)`` -> ``compile_ahead
.make_worker_scheduler``, the workload in-worker compile-ahead was
built for), in-process otherwise — with ``patience`` early-stop, and
every trial is banked to the observatory store under ``kind="tune"`` so
tuning history is queryable exactly like sweep history.

Determinism: trials already banked for the same ``tune_key`` +
``tune_candidate`` are REUSED instead of re-measured (``reuse_banked``),
so a re-run against the same history bank reproduces identical medians,
identical winners, and a byte-identical table fingerprint — the
``scripts/tune_demo.py`` contract. The registered default knobs are
always measured (prior-exempt), so the banked winner is never worse
than what an untuned run would have used.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from ddlb_tpu import telemetry
from ddlb_tpu.tuner import priors
from ddlb_tpu.tuner import space as spaces
from ddlb_tpu.tuner import table as tables
from ddlb_tpu.tuner.space import SearchSpec
from ddlb_tpu.tuner.table import TuneEntry, canonical_knobs

#: the sweep schema's measurement column (observatory.regress reads the
#: same literal) — the driver ranks trials by it
MEASURE_COLUMN = "median time (ms)"


@dataclass(frozen=True)
class Trial:
    """One measured candidate."""

    knobs: Dict[str, Any]
    prior_s: float
    prior_rank: int
    median_ms: float
    from_bank: bool = False  # reused a banked trial, no re-measure
    error: str = ""


@dataclass
class SearchResult:
    """Everything one search produced (the demo transcript's facts)."""

    spec: SearchSpec
    entry: Optional[TuneEntry] = None
    trials: List[Trial] = field(default_factory=list)
    #: candidates the priors cut before any compile
    pruned: List[priors.ScoredCandidate] = field(default_factory=list)
    #: statically infeasible points (never scored, never built)
    rejected: List[Tuple[Dict[str, Any], str]] = field(default_factory=list)
    #: feasible candidates proposed (scored)
    candidates: int = 0
    #: the search short-circuited on an existing table hit
    table_hit: bool = False
    #: early-stop fired after `patience` non-improvements
    early_stopped: bool = False
    default_ms: float = float("nan")

    def spearman(self) -> float:
        """Prior-vs-measured rank agreement over the finite trials."""
        xs = [t.prior_s for t in self.trials if t.median_ms == t.median_ms]
        ys = [t.median_ms for t in self.trials if t.median_ms == t.median_ms]
        return priors.spearman(xs, ys)


def trial_config(
    spec: SearchSpec,
    knobs: Dict[str, Any],
    *,
    num_iterations: int = 5,
    num_warmups: int = 2,
) -> Dict[str, Any]:
    """The benchmark-worker config for one candidate — the same contract
    the sweep runner dispatches, so pool leasing, compile-ahead and
    fault classification all behave identically under the tuner."""
    options = spec.options_base()
    options.update(knobs)
    return {
        "primitive": spec.family,
        "impl_id": f"tune:{spec.family}/{spec.impl}",
        "base_implementation": spec.impl,
        "options": options,
        "m": spec.m,
        "n": spec.n,
        "k": spec.k,
        "dtype": spec.dtype,
        "num_iterations": num_iterations,
        "num_warmups": num_warmups,
        "time_measurement_backend": spec.backend,
        "barrier_at_each_iteration": False,
        "validate": False,
    }


def _median_ms(row: Optional[Dict[str, Any]]) -> float:
    if not isinstance(row, dict):
        return float("nan")
    try:
        value = float(row.get(MEASURE_COLUMN))  # type: ignore[arg-type]
    except (TypeError, ValueError):
        return float("nan")
    return value


def _error_row(config: Dict[str, Any], error: str) -> Dict[str, Any]:
    """Dead/hung-worker row for the pool path: enough columns for the
    trial record and the bank, nothing the sweep schema would miss."""
    return {
        "primitive": config.get("primitive", ""),
        "implementation": config.get("impl_id", ""),
        MEASURE_COLUMN: float("nan"),
        "error": str(error or "worker died"),
    }


def _banked_median(
    history_dir: str, tune_key: str, cand_key: str
) -> Optional[float]:
    """The most recent banked ``kind="tune"`` trial for this exact
    (search target, candidate), when one exists with a clean finite
    median — the reuse that makes re-runs byte-identical."""
    from ddlb_tpu.observatory import store

    found: Optional[float] = None
    try:
        records = store.iter_history(history_dir, kind="tune")
    except Exception:
        return None
    for record in records:
        row = record.get("row") or {}
        if row.get("tune_key") != tune_key:
            continue
        if row.get("tune_candidate") != cand_key:
            continue
        if str(row.get("error") or ""):
            continue
        median = _median_ms(row)
        if median == median:
            found = median
    return found


def search(
    spec: SearchSpec,
    *,
    prior_margin: float = 1.5,
    patience: int = 3,
    pool: Optional[Any] = None,
    measure: Optional[Callable[[Dict[str, Any]], Dict[str, Any]]] = None,
    history_dir: Optional[str] = None,
    num_iterations: int = 5,
    num_warmups: int = 2,
    reuse_banked: bool = True,
    force: bool = False,
) -> SearchResult:
    """Run one prior-guided search. ``pool``: a ``WorkerPool`` to lease
    measurement workers from (with next-candidate prefetch-compile);
    ``measure``: explicit row function (tests inject synthetic
    landscapes here); neither -> in-process ``benchmark_worker``.
    ``force=False`` short-circuits on an existing table hit — the
    zero-search-trials path a primed sweep pays."""
    result = SearchResult(spec=spec)
    if not force:
        tbl = tables.get_table()
        if tbl is not None:
            hit = tbl.lookup(
                spec.family, spec.impl, spec.m, spec.n, spec.k,
                spec.dtype, spec.num_partitions, chip=spec.chip,
            )
            if hit is not None:
                result.entry = hit
                result.table_hit = True
                return result

    with telemetry.span(
        "tune.search", cat="tune",
        family=spec.family, impl=spec.impl,
        shape=f"{spec.m}x{spec.n}x{spec.k}", dtype=spec.dtype,
    ):
        proposal = spaces.propose(spec)
        result.rejected = list(proposal.rejected)
        candidates = list(proposal.candidates)
        default = spaces.default_knobs(spec)
        default_key = canonical_knobs(default)
        if default_key not in {canonical_knobs(c) for c in candidates}:
            candidates.append(dict(default))
        chip = priors.chip_spec_for(spec)
        scored = priors.score_all(spec, candidates, chip)
        result.candidates = len(scored)
        survivors, pruned = priors.prune(
            scored, margin=prior_margin, keep=default
        )
        result.pruned = pruned
        for cand in pruned:
            telemetry.instant(
                "tune.prune", cat="tune",
                family=spec.family, impl=spec.impl,
                knobs=cand.key(), prior_s=round(cand.prior_s, 9),
            )

        # measurement order: the registered default FIRST (the untuned
        # baseline every winner must beat), then prior-rank order
        ordered = sorted(
            survivors, key=lambda s: (s.key() != default_key, s.prior_rank)
        )
        tune_key = tables.entry_key(
            spec.family, spec.impl, spec.m, spec.n, spec.k,
            spec.dtype, spec.num_partitions,
        )
        run_row = measure
        if run_row is None and pool is None:
            from ddlb_tpu.benchmark import benchmark_worker

            run_row = benchmark_worker

        best_ms = float("inf")
        stale = 0
        for index, cand in enumerate(ordered):
            cand_key = cand.key()
            config = trial_config(
                spec, cand.knobs,
                num_iterations=num_iterations, num_warmups=num_warmups,
            )
            banked = (
                _banked_median(history_dir, tune_key, cand_key)
                if (reuse_banked and history_dir)
                else None
            )
            if banked is not None:
                trial = Trial(
                    dict(cand.knobs), cand.prior_s, cand.prior_rank,
                    banked, from_bank=True,
                )
            else:
                if pool is not None:
                    from ddlb_tpu.pool import run_one_row

                    nxt = (
                        trial_config(
                            spec, ordered[index + 1].knobs,
                            num_iterations=num_iterations,
                            num_warmups=num_warmups,
                        )
                        if index + 1 < len(ordered)
                        else None
                    )
                    row = run_one_row(pool, config, _error_row, prefetch=nxt)
                else:
                    try:
                        row = run_row(config)  # type: ignore[misc]
                    except Exception as exc:  # a trial must never
                        row = _error_row(config, repr(exc))  # kill the search
                median = _median_ms(row)
                trial = Trial(
                    dict(cand.knobs), cand.prior_s, cand.prior_rank,
                    median, error=str(row.get("error") or ""),
                )
                if history_dir:
                    from ddlb_tpu.observatory import store

                    banked_row = dict(row)
                    banked_row["tune_key"] = tune_key
                    banked_row["tune_candidate"] = cand_key
                    banked_row["prior_rank"] = cand.prior_rank
                    store.bank_row(
                        banked_row, kind="tune", directory=history_dir
                    )
                    telemetry.instant(
                        "tune.bank", cat="tune", knobs=cand_key,
                    )
            telemetry.instant(
                "tune.trial", cat="tune",
                family=spec.family, impl=spec.impl, knobs=cand_key,
                prior_rank=cand.prior_rank,
                median_ms=trial.median_ms if trial.median_ms == trial.median_ms
                else None,
                from_bank=trial.from_bank,
            )
            result.trials.append(trial)
            if cand_key == default_key and trial.median_ms == trial.median_ms:
                result.default_ms = trial.median_ms
            # early-stop bookkeeping over the prior-ranked tail (the
            # default seeds `best` but never counts as a stale probe)
            if trial.median_ms == trial.median_ms and (
                trial.median_ms < best_ms
            ):
                best_ms = trial.median_ms
                if cand_key != default_key:
                    stale = 0
            elif cand_key != default_key:
                stale += 1
                if stale >= max(1, patience):
                    result.early_stopped = True
                    break

        finite = [t for t in result.trials if t.median_ms == t.median_ms]
        if not finite:
            return result  # nothing measured cleanly: no entry banked
        winner = min(
            finite,
            key=lambda t: (t.median_ms, t.prior_rank, canonical_knobs(t.knobs)),
        )
        result.entry = TuneEntry(
            family=spec.family,
            impl=spec.impl,
            m=spec.m,
            n=spec.n,
            k=spec.k,
            dtype=spec.dtype,
            world_size=spec.num_partitions,
            knobs=dict(winner.knobs),
            measured_ms=winner.median_ms,
            prior_s=winner.prior_s,
            prior_rank=winner.prior_rank,
            trials=len(result.trials),
            pruned=len(result.pruned),
            candidates=result.candidates,
        )
    return result


def bank_winners(
    results: List[SearchResult],
    path: str,
    *,
    chip: str = "",
    backend: str = "",
) -> Optional[tables.TuningTable]:
    """Merge the searches' winners into the table at ``path`` (atomic;
    existing entries for other keys survive) and return the new table.
    None when no search produced an entry — an all-failed search must
    not version-churn a good table."""
    entries = {
        r.entry.key(): r.entry
        for r in results
        if r.entry is not None and not r.table_hit
    }
    if not entries:
        return None
    from ddlb_tpu.observatory import store

    existing = tables.load_table(path) if os.path.exists(path) else None
    merged = tables.merge_entries(existing, entries)
    table = tables.make_table(
        merged, chip=chip, backend=backend, git_rev=store.git_rev()
    )
    tables.save_table(table, path)
    return table
