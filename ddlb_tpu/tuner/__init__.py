"""Prior-guided autotuner: propose -> prune -> measure -> bank -> consult.

The search driver the ROADMAP's "perfmodel+simulator-guided autotuning
at sweep scale" item names (ISSUE 20). Submodules, in loop order:

- ``space``   — knob registry + static feasibility (the propose half)
- ``priors``  — cost/calibrated scoring and margin pruning
- ``driver``  — the measurement loop (pool leases, compile-ahead,
  ``kind="tune"`` banking, early stop)
- ``table``   — versioned per-(chip, backend) winner tables the
  runners consult by default (``DDLB_TPU_TUNING``)

Only ``table`` is imported eagerly: it is stdlib-only, and it is the
one module the hot consult path (``Primitive.__init__``) and
``utils.autotune``'s cache need — searching imports the heavier
submodules on demand.
"""

from ddlb_tpu.tuner import table  # noqa: F401  (the consult-path module)
from ddlb_tpu.tuner.table import (  # noqa: F401
    TuneEntry,
    TuningTable,
    get_table,
    load_table,
    save_table,
)
