"""Prior scoring and pruning: the model-guided half of the autotuner.

Every feasible candidate (``tuner.space``) is priced BEFORE any compile
by the same cost model the rows are audited against — ``cost.estimate``
over a duck-typed stub that restates the family's published closed
forms (``flops() = 2mnk``, the family bases' ring ``wire_bytes()``,
the chunked engine's ``overlap_chunks()``), plus the calibrated replay
(``cost.calibrated_estimate``) whenever a ``DDLB_TPU_CALIB`` table is
active, so fitted overheads sharpen the ranking on the machine being
tuned. Candidates worse than ``prior_margin`` x the best prior are
pruned; survivors carry a deterministic 1-based ``prior_rank`` the
driver measures in, so early-stop cuts the tail and the demo can report
Spearman prior-vs-measured rank agreement.

The analytic schedule laws are tile-blind (a GEMM's roofline does not
see ``block_m``), so tile candidates add the census's HBM-traffic term
— operand re-streaming per tile pass, the DDLB130/131 arithmetic — as
the differentiator. Deliberately JAX-free (imports only ``perfmodel``),
like the cost layer itself.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, replace
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

from ddlb_tpu.perfmodel import cost
from ddlb_tpu.perfmodel.specs import ChipSpec, detect_spec, get_spec
from ddlb_tpu.tuner.space import SearchSpec
from ddlb_tpu.tuner.table import canonical_knobs

#: impls whose members declare COST_SCHEDULE = "overlap" (every
#: overlap.py / pallas_impl.py member of the searchable families);
#: the jax_spmd*/xla_gspmd members keep the "sequential" default
_OVERLAP_IMPLS = ("overlap", "pallas")


def chip_spec_for(spec: SearchSpec) -> ChipSpec:
    """The chip the priors price against: the named spec when the
    search pins one, else the runtime-detected chip (respects
    ``DDLB_TPU_CHIP``, so CPU-sim searches price the cpu profile)."""
    if spec.chip:
        try:
            return get_spec(spec.chip)
        except KeyError:
            from ddlb_tpu.telemetry.logger import warn

            warn(
                f"tuner: unknown chip {spec.chip!r} in search spec; "
                f"pricing against the detected chip instead"
            )
    return detect_spec()


def _two_level(d: int, num_slices: int) -> Tuple[int, int]:
    """(intra, inter) factorization — ``topo_compose.two_level_factors``
    restated here so the prior tier stays importable without the
    primitives tier (which pulls JAX)."""
    d = max(1, int(d))
    inter = max(1, int(num_slices or 1))
    if inter > d or d % inter:
        inter = 1
    return d // inter, inter


def _family_payload(
    spec: SearchSpec, options: Mapping[str, Any]
) -> Optional[Tuple[str, float]]:
    """(collective op, LOCAL payload bytes) in the ``ring_wire_bytes``
    convention — the same closed forms the family bases state, so the
    stub's flat wire EQUALS the real member's ``wire_bytes()``."""
    d = max(1, spec.num_partitions)
    isz = cost.wire_itemsize(spec.dtype)
    if spec.family == "tp_columnwise":
        return "all_gather", float((spec.m // d) * spec.k * isz)
    if spec.family == "tp_rowwise":
        return "psum_scatter", float(spec.m * spec.n * isz)
    if spec.family == "dp_allreduce":
        return "psum", float(spec.m * spec.n * isz)
    if spec.family == "ep_alltoall":
        return "all_to_all", float((spec.m // d) * (spec.k + spec.n) * isz)
    if spec.family == "collectives":
        op = str(options.get("op", "all_gather"))
        return op, float((spec.m // d) * spec.k * isz)
    return None


class _Stub:
    """Duck-typed impl for ``cost.estimate``/``calibrated_estimate``:
    one candidate's knobs wearing the member's published cost facts,
    without constructing (or compiling) the member."""

    def __init__(self, spec: SearchSpec, knobs: Mapping[str, Any]):
        self.primitive_name = spec.family
        self.COST_SCHEDULE = (
            "overlap" if spec.impl in _OVERLAP_IMPLS else "sequential"
        )
        self.m, self.n, self.k = spec.m, spec.n, spec.k
        self.dtype = spec.dtype
        self.num_partitions = max(1, spec.num_partitions)
        self._spec = spec
        self.options: Dict[str, Any] = {"transport": "ici"}
        self.options.update(spec.options_base())
        self.options.update(knobs)

    def flops(self) -> float:
        if self._spec.family == "collectives":
            return 0.0  # pure wire; the family reports bandwidth
        return 2.0 * self.m * self.n * self.k

    def overlap_chunks(self) -> Optional[int]:
        # the prior differentiates chunk_count whenever the candidate
        # carries one (the knob IS the pipeline depth), not only under
        # the engine's algorithm="chunked" spelling
        chunks = self.options.get("chunk_count")
        if isinstance(chunks, (int, float)) and chunks >= 1:
            return int(chunks)
        return None

    def wire_bytes(self) -> float:
        payload = _family_payload(self._spec, self.options)
        if payload is None:
            return 0.0
        op, nbytes = payload
        d = self.num_partitions
        comp = str(self.options.get("composition", "flat"))
        if comp in ("hierarchical", "striped") and d > 1:
            intra, inter = _two_level(d, self._spec.num_slices)
            if comp == "striped":
                cls = cost.striped_wire_bytes(
                    op, nbytes, inter, cost.torus_factors(intra)
                )
            else:
                cls = cost.hierarchical_wire_bytes(op, nbytes, intra, inter)
            return float(cls["ici"] + cls["dcn"])
        return cost.ring_wire_bytes(op, nbytes, d)


def tile_traffic_s(
    spec: SearchSpec, knobs: Mapping[str, Any], chip: ChipSpec
) -> float:
    """HBM re-streaming seconds of one tiled GEMM pass — the census's
    traffic arithmetic (each A tile re-reads per ``n/bn`` column pass,
    each B tile per ``m/bm`` row pass, the product written once). Zero
    for candidates without tile knobs: the analytic laws already rank
    those."""
    if not any(key in knobs for key in ("block_m", "block_n", "block_k")):
        return 0.0
    d = max(1, spec.num_partitions)
    m_eff = spec.m
    if spec.options_base().get("order") == "AG_after":
        m_eff = max(1, spec.m // d)
    k_eff = spec.k
    if spec.family == "tp_rowwise":
        k_eff = max(1, spec.k // d)  # the kernel GEMMs the k shard
    bm = int(knobs.get("block_m", m_eff) or m_eff)
    bn = int(knobs.get("block_n", spec.n) or spec.n)
    isz = float(spec.itemsize())
    passes_a = max(1.0, spec.n / max(1, bn))
    passes_b = max(1.0, m_eff / max(1, bm))
    traffic = isz * (
        m_eff * k_eff * passes_a + k_eff * spec.n * passes_b
        + m_eff * spec.n
    )
    return traffic / max(1.0, float(chip.hbm_bw))


@dataclass(frozen=True)
class ScoredCandidate:
    """One candidate with its prior verdict attached."""

    knobs: Dict[str, Any]
    prior_s: float
    prior_source: str  # "calibrated" | "analytic"
    prior_rank: int = 0  # 1-based, assigned by prune()

    def key(self) -> str:
        return canonical_knobs(self.knobs)


def score(
    spec: SearchSpec,
    knobs: Mapping[str, Any],
    chip: Optional[ChipSpec] = None,
) -> ScoredCandidate:
    """Price one candidate: analytic roofline (``cost.estimate``),
    upgraded to the calibrated replay when a ``DDLB_TPU_CALIB`` table is
    active, plus the tile-traffic differentiator."""
    chip = chip or chip_spec_for(spec)
    stub = _Stub(spec, knobs)
    est = cost.estimate(stub, spec=chip)
    prior_s = float(est.predicted_s)
    source = "analytic"
    try:
        cal = cost.calibrated_estimate(stub, spec=chip, backend=spec.backend)
    except Exception:
        cal = None
    if cal is not None and math.isfinite(cal.predicted_cal_s):
        prior_s = float(cal.predicted_cal_s)
        source = "calibrated"
    prior_s += tile_traffic_s(spec, knobs, chip)
    return ScoredCandidate(dict(knobs), prior_s, source)


def score_all(
    spec: SearchSpec,
    candidates: Sequence[Mapping[str, Any]],
    chip: Optional[ChipSpec] = None,
) -> List[ScoredCandidate]:
    chip = chip or chip_spec_for(spec)
    return [score(spec, knobs, chip) for knobs in candidates]


def prune(
    scored: Sequence[ScoredCandidate],
    *,
    margin: float = 1.5,
    keep: Optional[Mapping[str, Any]] = None,
) -> Tuple[List[ScoredCandidate], List[ScoredCandidate]]:
    """(survivors, pruned): candidates beyond ``margin`` x the best
    prior are cut before any compile. Survivors come back in prior-rank
    order — ``(prior_s, canonical knobs)``, a total order with no
    float-tie churn — wearing their 1-based rank. ``keep``: knobs that
    bypass the margin (the registered default, so the measured winner
    is never worse than the default by construction)."""
    keep_key = canonical_knobs(keep) if keep is not None else None
    ordered = sorted(scored, key=lambda s: (s.prior_s, s.key()))
    if not ordered:
        return [], []
    best = ordered[0].prior_s
    cut = margin * best if best > 0.0 else float("inf")
    survivors: List[ScoredCandidate] = []
    pruned: List[ScoredCandidate] = []
    for cand in ordered:
        if cand.prior_s <= cut or cand.key() == keep_key:
            survivors.append(replace(cand, prior_rank=len(survivors) + 1))
        else:
            pruned.append(cand)
    return survivors, pruned


def spearman(xs: Sequence[float], ys: Sequence[float]) -> float:
    """Spearman rank correlation (average-rank ties), stdlib-only —
    the demo's prior-vs-measured agreement number. NaN for degenerate
    inputs (n < 2 or a constant side)."""
    n = len(xs)
    if n != len(ys) or n < 2:
        return float("nan")

    def _ranks(vals: Sequence[float]) -> List[float]:
        order = sorted(range(n), key=lambda i: (vals[i], i))
        ranks = [0.0] * n
        i = 0
        while i < n:
            j = i
            while j + 1 < n and vals[order[j + 1]] == vals[order[i]]:
                j += 1
            avg = (i + j) / 2.0 + 1.0
            for t in range(i, j + 1):
                ranks[order[t]] = avg
            i = j + 1
        return ranks

    rx, ry = _ranks(xs), _ranks(ys)
    mx = sum(rx) / n
    my = sum(ry) / n
    cov = sum((a - mx) * (b - my) for a, b in zip(rx, ry))
    vx = sum((a - mx) ** 2 for a in rx)
    vy = sum((b - my) ** 2 for b in ry)
    if vx <= 0.0 or vy <= 0.0:
        return float("nan")
    return cov / math.sqrt(vx * vy)
