"""Knob registry: the tunable axes each family declares, with static
feasibility.

The propose half of the prior-guided autotuner (ISSUE 20). A candidate
is one point in a family member's knob product space — a Pallas tile
triple, a chunked-engine ``chunk_count``, a collective ``composition``,
or a named XLA option set — expressed as the option dict the member
would be constructed with. Candidates are validated HERE, statically,
by the same divisibility / tile-granule / VMEM-budget rules the Pallas
census (DDLB130/131, ``analysis/pallas/model.py``) encodes, so an
unbuildable point is rejected before it costs a compile — the search
driver only ever measures points that can build.

Coverage is an analyzer invariant (DDLB140, the same shape as
DDLB007's cost-model coverage): every family in
``registry.ALLOWED_PRIMITIVES`` either appears in ``SPACES`` or is
listed in ``KNOB_FREE`` with a reason — a new family cannot silently
ship with no tuning story.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Mapping, Tuple

# the census's tile-granule constants (DDLB131 tile alignment): the
# second-to-last dim packs SUBLANE[dtype] rows per register tile, the
# last dim LANE columns — a block off the granule (unless it spans the
# whole axis, one un-tiled block) repacks on every load
from ddlb_tpu.analysis.pallas.model import LANE, SUBLANE

_ITEMSIZE = {
    "float64": 8, "int64": 8,
    "float32": 4, "int32": 4,
    "bfloat16": 2, "float16": 2,
    "int8": 1, "bool": 1,
}


@dataclass(frozen=True)
class SearchSpec:
    """One search target: which member, at which shape, on which world.

    ``base_options`` are the FIXED options every candidate shares (the
    member's algorithm selector, e.g. ``algorithm="chunked"``); knob
    values layer on top. ``vmem_bytes`` defaults to the conservative
    16 MiB census budget (``perfmodel.specs`` raises it per chip)."""

    family: str
    impl: str
    m: int
    n: int
    k: int
    dtype: str = "float32"
    num_partitions: int = 1
    num_slices: int = 1
    chip: str = ""
    backend: str = "host_clock"
    vmem_bytes: float = 16 * (1 << 20)
    seed: int = 42
    base_options: Tuple[Tuple[str, Any], ...] = ()

    def options_base(self) -> Dict[str, Any]:
        return dict(self.base_options)

    def itemsize(self) -> int:
        return _ITEMSIZE.get(self.dtype, 4)


@dataclass(frozen=True)
class FeasibleSpace:
    """The proposed space after static feasibility: what survives, and
    what was rejected with the rule that rejected it (the census-style
    evidence the demo transcript prints)."""

    candidates: List[Dict[str, Any]] = field(default_factory=list)
    rejected: List[Tuple[Dict[str, Any], str]] = field(default_factory=list)


# ---------------------------------------------------------------------------
# feasibility checks (the census rules, statically)
# ---------------------------------------------------------------------------


def tile_feasible(
    spec: SearchSpec, bm: int, bn: int, bk: int, m_eff: int = 0
) -> Tuple[bool, str]:
    """Why (or that) one GEMM tile triple can build at this shape:
    divisibility (DDLB133 grid/block mismatch), tile-granule alignment
    (DDLB131), and the double-buffered VMEM working set against the
    chip budget (DDLB130). ``m_eff``: the per-device m the kernel
    actually sees (0 = the global m)."""
    m_eff = m_eff or spec.m
    if bm <= 0 or bn <= 0 or bk <= 0:
        return False, "non-positive block"
    if m_eff % bm or spec.n % bn or spec.k % bk:
        return False, (
            f"divisibility: ({bm},{bn},{bk}) does not divide "
            f"{m_eff}x{spec.n}x{spec.k}"
        )
    sublane = SUBLANE.get(spec.dtype, 8)
    if bm % sublane and bm != m_eff:
        return False, f"granule: block_m={bm} off the {sublane}-row sublane"
    if bn % LANE and bn != spec.n:
        return False, f"granule: block_n={bn} off the {LANE}-lane register"
    # resident working set, double-buffered: one A tile, one B tile,
    # one accumulator tile, times two for the pipeline's in-flight copy
    itemsize = spec.itemsize()
    working = 2.0 * itemsize * (bm * bk + bk * bn + bm * bn)
    if working > spec.vmem_bytes:
        return False, (
            f"vmem: working set {working / (1 << 20):.1f} MiB over the "
            f"{spec.vmem_bytes / (1 << 20):.0f} MiB budget"
        )
    return True, ""


def chunk_feasible(spec: SearchSpec, chunk_count: int) -> Tuple[bool, str]:
    """The chunked-fusion engine's constraint: every chunk is a whole
    per-device row slab, so ``m % (partitions * chunk_count) == 0``."""
    d = max(1, spec.num_partitions)
    if chunk_count < 1:
        return False, "chunk_count < 1"
    if spec.m % (d * chunk_count):
        return False, (
            f"divisibility: m={spec.m} not divisible by partitions*"
            f"chunk_count={d * chunk_count}"
        )
    return True, ""


def composition_feasible(spec: SearchSpec, composition: str) -> Tuple[bool, str]:
    """The composed members scatter the payload across the world: the
    row dim must split across every device regardless of composition
    (the members' own ``_check_shapes`` contract)."""
    d = max(1, spec.num_partitions)
    if spec.m % d:
        return False, f"divisibility: m={spec.m} not divisible by d={d}"
    return True, ""


# ---------------------------------------------------------------------------
# axis generators
# ---------------------------------------------------------------------------

#: power-of-two tile dims the generalized grid draws from — the curated
#: 8-entry ``_GEMM_TILE_GRID`` is a hand-picked subset of this product;
#: priors are what make the larger space affordable (ISSUE 20)
TILE_DIMS = (128, 256, 512, 1024, 2048)

#: chunked-engine pipeline depths worth proposing: 1 (no pipelining,
#: the degenerate baseline) through deep; infeasible depths filter out
CHUNK_COUNTS = (1, 2, 4, 8, 16)

#: composition vocabulary (mirrors topo_compose.COMPOSITIONS — kept
#: literal so this module stays importable without the primitives tier)
COMPOSITION_CHOICES = ("flat", "hierarchical", "striped")

#: named XLA option sets for the GSPMD members — each candidate is one
#: coherent scheduler posture (primitives/xla_options.py knobs), not a
#: free product of bools that would mostly measure identical binaries
XLA_OPTION_SETS: Dict[str, Dict[str, Any]] = {
    "default": {
        "latency_hiding_scheduler": True,
        "async_collective_fusion": True,
        "collective_matmul": "auto",
    },
    "no_latency_hiding": {
        "latency_hiding_scheduler": False,
        "async_collective_fusion": True,
        "collective_matmul": "auto",
    },
    "windowed_einsum": {
        "latency_hiding_scheduler": True,
        "async_collective_fusion": True,
        "collective_matmul": "force",
    },
    "plain": {
        "latency_hiding_scheduler": False,
        "async_collective_fusion": False,
        "collective_matmul": "off",
    },
}


def _tile_axis(spec: SearchSpec, size: int, granule: int = 1) -> List[int]:
    """Candidate block sizes for one axis of extent ``size``: the
    power-of-two dims clamped to the axis (the ``min(bm, m)`` clamp
    ``gemm_block_candidates`` applies), deduplicated, divisors only."""
    dims = sorted({min(d, size) for d in TILE_DIMS} | {size})
    return [d for d in dims if d > 0 and size % d == 0]


def _gemm_tile_space(spec: SearchSpec, m_eff: int = 0) -> FeasibleSpace:
    m_eff = m_eff or spec.m
    out = FeasibleSpace()
    for bm in _tile_axis(spec, m_eff):
        for bn in _tile_axis(spec, spec.n):
            for bk in _tile_axis(spec, spec.k):
                knobs = {"block_m": bm, "block_n": bn, "block_k": bk}
                ok, why = tile_feasible(spec, bm, bn, bk, m_eff=m_eff)
                if ok:
                    out.candidates.append(knobs)
                else:
                    out.rejected.append((knobs, why))
    return out


def _chunked_space(spec: SearchSpec) -> FeasibleSpace:
    out = FeasibleSpace()
    for c in CHUNK_COUNTS:
        knobs = {"chunk_count": c}
        ok, why = chunk_feasible(spec, c)
        if ok:
            out.candidates.append(knobs)
        else:
            out.rejected.append((knobs, why))
    return out


def _composition_space(spec: SearchSpec) -> FeasibleSpace:
    out = FeasibleSpace()
    for comp in COMPOSITION_CHOICES:
        knobs = {"composition": comp}
        ok, why = composition_feasible(spec, comp)
        if ok:
            out.candidates.append(knobs)
        else:
            out.rejected.append((knobs, why))
    return out


def _xla_space(spec: SearchSpec) -> FeasibleSpace:
    # every named set is buildable by construction (CPU degrades the
    # options to a no-op — xla_options.build_compiler_options)
    return FeasibleSpace(
        candidates=[dict(XLA_OPTION_SETS[name]) for name in XLA_OPTION_SETS]
    )


def _tp_pallas_space(spec: SearchSpec) -> FeasibleSpace:
    """The tp pallas members' tile space. The GEMM sees the gathered m
    (AG_before, the registered default order) so candidates divide the
    global m; AG_after searches would pass the shard via base_options
    ``order`` and the sharded clamp applies."""
    m_eff = spec.m
    if spec.options_base().get("order") == "AG_after":
        m_eff = spec.m // max(1, spec.num_partitions)
    return _gemm_tile_space(spec, m_eff=m_eff)


def _tp_rowwise_pallas_space(spec: SearchSpec) -> FeasibleSpace:
    # the rowwise kernel GEMMs the k-sharded slab: [m, k/d] x [k/d, n]
    out = FeasibleSpace()
    k_local = spec.k // max(1, spec.num_partitions)
    for bn in _tile_axis(spec, spec.n):
        for bk in _tile_axis(spec, k_local):
            knobs = {"block_n": bn, "block_k": bk}
            ok, why = tile_feasible(
                spec, spec.m, bn, bk, m_eff=spec.m
            )
            if bk > 0 and k_local % bk:
                ok, why = False, (
                    f"divisibility: block_k={bk} does not divide the "
                    f"k shard {k_local}"
                )
            if ok:
                out.candidates.append(knobs)
            else:
                out.rejected.append((knobs, why))
    return out


#: (family, impl) -> candidate generator. The registry the coverage
#: rule (DDLB140) and the search driver both read.
SPACES: Dict[Tuple[str, str], Callable[[SearchSpec], FeasibleSpace]] = {
    ("tp_columnwise", "pallas"): _tp_pallas_space,
    ("tp_columnwise", "overlap"): _chunked_space,
    ("tp_columnwise", "xla_gspmd"): _xla_space,
    ("tp_rowwise", "pallas"): _tp_rowwise_pallas_space,
    ("tp_rowwise", "overlap"): _chunked_space,
    ("tp_rowwise", "xla_gspmd"): _xla_space,
    ("dp_allreduce", "overlap"): _chunked_space,
    ("dp_allreduce", "jax_spmd_hier"): _composition_space,
    ("dp_allreduce", "jax_spmd_striped"): _composition_space,
    ("dp_allreduce", "xla_gspmd"): _xla_space,
    ("ep_alltoall", "overlap"): _chunked_space,
    ("ep_alltoall", "jax_spmd_hier"): _composition_space,
    ("ep_alltoall", "jax_spmd_striped"): _composition_space,
    ("collectives", "jax_spmd_hier"): _composition_space,
    ("collectives", "jax_spmd_striped"): _composition_space,
}

#: families with no declared knob space, each with the reason — the
#: DDLB140 coverage rule requires every registered family to appear in
#: SPACES or here, so "we never thought about tuning it" is impossible
KNOB_FREE: Dict[str, str] = {
    "cp_ring_attention": (
        "ring schedule granularity is pinned to the context shard; the "
        "window/causal options are workload axes, not perf knobs"
    ),
    "pp_pipeline": (
        "microbatch count is a swept workload axis (the bubble law is "
        "what the sweep measures, not a knob to hide)"
    ),
    "transformer_step": (
        "the (dp, tp, pp) factorization is the sweep's subject — "
        "tuning it away would erase the measurement"
    ),
    "transformer_decode": (
        "decode batch/page geometry is the serving workload's contract, "
        "owned by the serving engine, not a member knob"
    ),
    "serving_load": (
        "admission/routing knobs are controlled by the serving cluster "
        "policies (serve/), tuned by the elastic controller at runtime"
    ),
}


def default_knobs(spec: SearchSpec) -> Dict[str, Any]:
    """The member's registered default point, clamped to the shape the
    way the members themselves clamp (``min(block, axis)``) — the
    untuned baseline the driver always measures so a banked winner is
    never worse than what an untuned run would have used."""
    d = max(1, spec.num_partitions)
    generator = SPACES.get((spec.family, spec.impl))
    if generator is _tp_pallas_space:
        m_eff = spec.m
        if spec.options_base().get("order") == "AG_after":
            m_eff = spec.m // d
        return {
            "block_m": min(1024, m_eff),
            "block_n": min(1024, spec.n),
            "block_k": min(512, spec.k),
        }
    if generator is _tp_rowwise_pallas_space:
        return {
            "block_n": min(1024, spec.n),
            "block_k": min(512, spec.k // d),
        }
    if generator is _chunked_space:
        return {"chunk_count": 2}
    if generator is _composition_space:
        return {
            "composition": "striped" if "striped" in spec.impl
            else "hierarchical"
        }
    if generator is _xla_space:
        return dict(XLA_OPTION_SETS["default"])
    raise ValueError(
        f"no knob space declared for ({spec.family!r}, {spec.impl!r})"
    )


def tunable_families() -> Dict[str, List[str]]:
    """family -> its searchable impl names (registry view)."""
    out: Dict[str, List[str]] = {}
    for family, impl in sorted(SPACES):
        out.setdefault(family, []).append(impl)
    return out


def propose(spec: SearchSpec) -> FeasibleSpace:
    """The feasible candidate space for one search target. Raises for
    a (family, impl) with no declared space — the caller asked to
    search something the registry says is not searchable."""
    generator = SPACES.get((spec.family, spec.impl))
    if generator is None:
        raise ValueError(
            f"no knob space declared for ({spec.family!r}, {spec.impl!r});"
            f" searchable: {sorted(SPACES)}"
        )
    return generator(spec)
