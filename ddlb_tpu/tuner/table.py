"""Versioned per-chip tuning tables: the bank the runners consult.

The persistence half of the prior-guided autotuner (ISSUE 20). The
search driver (``tuner.driver``) measures pruned candidate spaces and
banks each winner here as a ``TuneEntry`` keyed by
``(family, impl, m, n, k, dtype, world_size)``; the whole table is
scoped to one ``(chip, backend)`` pair — a table primed on a v5e is
never silently applied to a v5p (or to the CPU sim), the same guard
``utils.autotune.make_key`` bakes into its cache keys.

The format follows ``perfmodel.calib.CalibrationTable`` deliberately:

- frozen dataclasses with ``to_json`` / ``from_json``;
- a content fingerprint ``version`` (``t1-`` + sha256 of the canonical
  sorted entries) so two searches that landed the same winners produce
  byte-identical tables, and regression gates can fence baselines per
  table version exactly as ``detect_calibration`` fences per
  ``cal_version``;
- atomic writes (tmp + rename), warn-once tolerant loads, and an
  env-selected ``get_table()`` cached by (path, mtime) so the consult
  path in ``Primitive.__init__`` costs one env read when untuned and
  one stat() when tuned.

No wall-clock field enters the table or its fingerprint — re-running
the search under the same seed and banked trials reproduces the file
byte-identically (the determinism contract ``scripts/tune_demo.py``
asserts). Provenance is ``git_rev`` only.

The generic JSON helpers at the bottom (``load_json_file`` /
``atomic_write_json``) are the ONE persistence path shared with
``utils.autotune``'s block cache — the ISSUE 20 satellite that stops
the cache and the table growing divergent atomicity/tolerance rules.
"""

from __future__ import annotations

import hashlib
import json
import os
from dataclasses import dataclass, field
from typing import Any, Dict, Mapping, Optional

TABLE_FORMAT = "ddlb-tpu-tuning-v1"


def canonical_knobs(knobs: Mapping[str, Any]) -> str:
    """A knob dict as its canonical sorted-JSON string — the identity
    the banked trial rows and the table fingerprint both use, so a
    re-run matches its predecessor's trials key-for-key."""
    return json.dumps(dict(knobs), sort_keys=True, default=str)


def entry_key(
    family: str,
    impl: str,
    m: int,
    n: int,
    k: int,
    dtype: str,
    world_size: int,
) -> str:
    """The stable identity of one tuning decision — everything that
    changes which knobs are optimal is in (shape, dtype, world size);
    chip and backend scope the whole table, not the entry."""
    return json.dumps(
        {
            "family": str(family),
            "impl": str(impl),
            "m": int(m),
            "n": int(n),
            "k": int(k),
            "dtype": str(dtype),
            "world_size": int(world_size),
        },
        sort_keys=True,
    )


@dataclass(frozen=True)
class TuneEntry:
    """One banked winner plus the search metadata behind it."""

    family: str
    impl: str
    m: int
    n: int
    k: int
    dtype: str
    world_size: int
    #: the winning knob assignment the consult path applies
    knobs: Dict[str, Any] = field(default_factory=dict)
    #: the winner's measured median (ms) from the search trials
    measured_ms: float = float("nan")
    #: the winner's prior score (seconds; calibrated when a table was
    #: active during the search, analytical otherwise)
    prior_s: float = float("nan")
    #: the winner's 1-based rank in prior order among the survivors —
    #: rank 1 means the priors called it; stamped on consuming rows
    prior_rank: int = 0
    #: candidates actually measured (after pruning + early stop)
    trials: int = 0
    #: candidates the priors pruned before any compile
    pruned: int = 0
    #: feasible candidates proposed (after static feasibility rejects)
    candidates: int = 0

    def key(self) -> str:
        return entry_key(
            self.family, self.impl, self.m, self.n, self.k,
            self.dtype, self.world_size,
        )

    def to_json(self) -> Dict[str, Any]:
        return {
            "family": self.family,
            "impl": self.impl,
            "m": self.m,
            "n": self.n,
            "k": self.k,
            "dtype": self.dtype,
            "world_size": self.world_size,
            "knobs": dict(self.knobs),
            "measured_ms": self.measured_ms,
            "prior_s": self.prior_s,
            "prior_rank": self.prior_rank,
            "trials": self.trials,
            "pruned": self.pruned,
            "candidates": self.candidates,
        }

    @classmethod
    def from_json(cls, data: Mapping[str, Any]) -> "TuneEntry":
        return cls(
            family=str(data.get("family", "")),
            impl=str(data.get("impl", "")),
            m=int(data.get("m", 0)),  # type: ignore[arg-type]
            n=int(data.get("n", 0)),  # type: ignore[arg-type]
            k=int(data.get("k", 0)),  # type: ignore[arg-type]
            dtype=str(data.get("dtype", "")),
            world_size=int(data.get("world_size", 0)),  # type: ignore[arg-type]
            knobs=dict(data.get("knobs") or {}),
            measured_ms=float(data.get("measured_ms", float("nan"))),  # type: ignore[arg-type]
            prior_s=float(data.get("prior_s", float("nan"))),  # type: ignore[arg-type]
            prior_rank=int(data.get("prior_rank", 0)),  # type: ignore[arg-type]
            trials=int(data.get("trials", 0)),  # type: ignore[arg-type]
            pruned=int(data.get("pruned", 0)),  # type: ignore[arg-type]
            candidates=int(data.get("candidates", 0)),  # type: ignore[arg-type]
        )


@dataclass(frozen=True)
class TuningTable:
    """Versioned set of banked winners for one (chip, backend)."""

    version: str
    chip: str = ""
    backend: str = ""
    git_rev: str = ""
    entries: Dict[str, TuneEntry] = field(default_factory=dict)

    def lookup(
        self,
        family: str,
        impl: str,
        m: int,
        n: int,
        k: int,
        dtype: str,
        world_size: int,
        chip: str = "",
        degraded: Optional[bool] = None,
    ) -> Optional[TuneEntry]:
        """The banked winner for this exact config, or None (a miss
        falls back to the registered defaults).

        ``chip`` (when both sides name one) must match the table's
        scope — a mismatch is a miss, never a cross-chip apply.

        The online re-tune hook (ISSUE 20 stretch): an entry that
        pins a ``composition`` knob is INVALIDATED while the world is
        degraded — ``degraded`` None consults
        ``topo_compose.degraded_world_signal`` (the degraded-relaunch
        stamp, a seeded link fault, or a persistent health indictment)
        lazily, only when the hit actually carries the knob. The miss
        sends the member back to its default (``composition=auto``
        re-resolves via ``select_composition`` against the degraded
        topology) and the next search re-banks under that world.
        """
        if chip and self.chip and chip != self.chip:
            return None
        entry = self.entries.get(
            entry_key(family, impl, m, n, k, dtype, world_size)
        )
        if entry is None:
            return None
        if "composition" in entry.knobs:
            if degraded is None:
                from ddlb_tpu.primitives.topo_compose import (
                    degraded_world_signal,
                )

                degraded = degraded_world_signal(world_size)
            if degraded:
                return None
        return entry

    def to_json(self) -> Dict[str, Any]:
        return {
            "format": TABLE_FORMAT,
            "version": self.version,
            "chip": self.chip,
            "backend": self.backend,
            "git_rev": self.git_rev,
            "entries": {
                key: entry.to_json()
                for key, entry in sorted(self.entries.items())
            },
        }

    @classmethod
    def from_json(cls, data: Mapping[str, Any]) -> "TuningTable":
        entries: Dict[str, TuneEntry] = {}
        for raw in dict(data.get("entries") or {}).values():
            entry = TuneEntry.from_json(raw)
            entries[entry.key()] = entry
        return cls(
            version=str(data.get("version", "")),
            chip=str(data.get("chip", "")),
            backend=str(data.get("backend", "")),
            git_rev=str(data.get("git_rev", "")),
            entries=entries,
        )


def table_version(entries: Mapping[str, TuneEntry]) -> str:
    """Content fingerprint of the banked winners. Floats are rounded
    before hashing (the same tolerance trick as
    ``calib.table_version``) so re-serialization noise can never move
    the version; any winner or knob that actually changes does."""
    canonical = json.dumps(
        {
            key: {
                "knobs": {
                    k: v for k, v in sorted(entry.knobs.items())
                },
                "measured_ms": round(float(entry.measured_ms), 9)
                if entry.measured_ms == entry.measured_ms
                else None,
                "prior_s": round(float(entry.prior_s), 12)
                if entry.prior_s == entry.prior_s
                else None,
                "prior_rank": entry.prior_rank,
                "trials": entry.trials,
                "pruned": entry.pruned,
                "candidates": entry.candidates,
            }
            for key, entry in sorted(entries.items())
        },
        sort_keys=True,
        default=str,
    )
    return "t1-" + hashlib.sha256(canonical.encode()).hexdigest()[:10]


def make_table(
    entries: Mapping[str, TuneEntry],
    *,
    chip: str = "",
    backend: str = "",
    git_rev: str = "",
) -> TuningTable:
    return TuningTable(
        version=table_version(entries),
        chip=chip,
        backend=backend,
        git_rev=git_rev,
        entries=dict(entries),
    )


def merge_entries(
    table: Optional[TuningTable], entries: Mapping[str, TuneEntry]
) -> Dict[str, TuneEntry]:
    """Existing entries with ``entries`` layered on top (new winners
    replace old ones for the same key) — the re-tune update path."""
    merged: Dict[str, TuneEntry] = dict(table.entries) if table else {}
    merged.update(entries)
    return merged


def save_table(table: TuningTable, path: str) -> None:
    """Atomic write (tmp + rename) so readers never see a torn table."""
    atomic_write_json(path, table.to_json(), label="tuning table")


def load_table(path: str) -> Optional[TuningTable]:
    """Load a table from ``path``; None when missing/corrupt (warned
    once — a broken table must never take a sweep down, the sweep just
    runs untuned)."""
    try:
        with open(path, encoding="utf-8") as handle:
            data = json.load(handle)
        if not isinstance(data, dict) or not isinstance(
            data.get("entries"), dict
        ):
            raise ValueError("not a tuning table")
        return TuningTable.from_json(data)
    except (OSError, ValueError) as exc:
        _warn_once(path, f"tuning table unreadable at {path}: {exc}")
        return None


_WARNED_PATHS: set = set()


def _warn_once(path: str, message: str) -> None:
    if path in _WARNED_PATHS:
        return
    _WARNED_PATHS.add(path)
    from ddlb_tpu.telemetry.logger import warn

    warn(message)


_TABLE_CACHE: Dict[str, object] = {}


def get_table() -> Optional[TuningTable]:
    """The env-selected table (``DDLB_TPU_TUNING``), cached by (path,
    mtime) so the per-construction consult stays one stat() when tuned
    and one env read when not. A path pointing at a file that does not
    exist YET (the search is about to create it) is a quiet miss, not
    a warning — ``tune_demo`` sets the env before the first search."""
    from ddlb_tpu import envs

    path = envs.get_tuning_table_path()
    if not path:
        return None
    try:
        mtime = os.stat(path).st_mtime_ns
    except OSError:
        return None
    if _TABLE_CACHE.get("path") == path and _TABLE_CACHE.get("mtime") == mtime:
        return _TABLE_CACHE.get("table")  # type: ignore[return-value]
    table = load_table(path)
    _TABLE_CACHE.update(path=path, mtime=mtime, table=table)
    return table


# ---------------------------------------------------------------------------
# the shared JSON persistence path (utils.autotune routes through these)
# ---------------------------------------------------------------------------


def load_json_file(path: str) -> Dict[str, Any]:
    """A JSON object from ``path``, or {} on any failure — the tolerant
    read contract every cache consumer here shares (a corrupt cache
    must degrade to 'cold', never to a crash)."""
    try:
        with open(path, encoding="utf-8") as handle:
            data = json.load(handle)
        return data if isinstance(data, dict) else {}
    except Exception:
        return {}


def atomic_write_json(
    path: str, data: Mapping[str, Any], label: str = "json"
) -> bool:
    """Best-effort atomic JSON write (tmp.PID + os.replace): a
    persistence failure warns and returns False, never raises — a full
    disk must not fail the measurement whose winner it was recording."""
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        directory = os.path.dirname(path)
        if directory:
            os.makedirs(directory, exist_ok=True)
        with open(tmp, "w", encoding="utf-8") as handle:
            json.dump(data, handle, indent=1, sort_keys=True)
            handle.write("\n")
        os.replace(tmp, path)
    except OSError as exc:
        from ddlb_tpu import telemetry

        telemetry.warn(
            f"{label} write to {path} failed: {type(exc).__name__}: {exc}"
        )
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False
    return True
