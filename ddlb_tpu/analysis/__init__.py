"""Static-analysis rule engine for the framework's hard-won invariants.

Six PRs of hardening produced a set of correctness rules that used to
live only in reviewers' heads and in an ad-hoc ``scripts/lint.py``:
monotonic clocks in deadline code, the lock-free heartbeat construction,
every ``DDLB_TPU_*`` env read routed through ``envs.py``, fault-injection
sites that actually exist (so seeded chaos plans never silently no-op),
telemetry span/metric names the report joins can rely on, and the
in-flight ``jax.shard_map`` -> ``runtime.shard_map_compat`` migration.
This package machine-checks all of them:

- ``core``: the engine — each file is parsed ONCE into a shared
  AST/token context (``FileContext``), then every registered rule runs
  over it; findings carry ``file:line:col``, severity, and a stable
  snippet key. Inline suppression via ``# ddlb: ignore[rule-id]``
  (unused suppressions are themselves findings, DDLB100).
- ``rules_style``: the checks ported from the old ``scripts/lint.py``
  (undefined names, dangerous calls, bare print, docstrings,
  ``Process()`` construction) under stable DDLB0xx ids.
- ``rules_domain``: the DDLB1xx invariant rules (legacy shard_map,
  wall-clock deadlines, raw env reads, fault-site registry, locked sync
  primitives, telemetry-name registry, silent swallows).
- ``rules_project``: repo-level rules needing cross-file state
  (cost-model coverage, row-schema coverage).
- ``baseline``: the committed grandfather file
  (``analysis_baseline.json``) — known findings are masked, STALE
  entries are errors, so the baseline can only ever shrink.
- ``output``: text / JSON / SARIF 2.1.0 rendering plus the DDLB101
  per-family migration inventory.

``scripts/analyze.py`` is the CLI (``make analyze`` / ``make lint``);
``docs/source/static_analysis.rst`` is the rule catalog.

Zero third-party dependencies (stdlib + the package's own JAX-free
modules), so the lint tier never needs an accelerator backend.
"""

from __future__ import annotations

from ddlb_tpu.analysis.core import (
    FileContext,
    Finding,
    Rule,
    all_rules,
    analyze,
    build_context,
)

__all__ = [
    "FileContext",
    "Finding",
    "Rule",
    "all_rules",
    "analyze",
    "build_context",
]
