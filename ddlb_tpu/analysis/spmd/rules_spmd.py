"""The DDLB12x semantic SPMD rules — the collective-trace battery.

Where the DDLB10x rules are syntactic (they can see a ``jax.shard_map``
*call*), these read the collective traces the abstract interpreter
(``spmd.interp``) extracts from every ``shard_map`` /
``shard_map_compat`` body and Pallas-adjacent function in
``ddlb_tpu/primitives``, ``ddlb_tpu/ops`` and ``ddlb_tpu/models``:

- **DDLB120 undeclared-collective-axis**: a collective (or
  ``axis_index``) naming an axis the enclosing mesh axes / partition
  specs never declare — at runtime this is a ``NameError`` deep inside
  jax, at sweep time a family that cannot launch.
- **DDLB121 rank-divergent-collective**: a collective reachable on one
  arm of a rank-dependent branch but unmatched on the other — the rank
  that takes the other arm never arrives, and the world wedges exactly
  like the PR 8 flight recorder's post-mortems show (findings cite the
  divergence site the way ``flight_report.py`` names it).
- **DDLB122 non-bijective-ppermute**: a concrete ``ppermute`` perm with
  duplicate sources, duplicate destinations, or a source set differing
  from its destination set — ranks outside the perm silently receive
  zeros, the wrong-answer-without-an-error class. The symbolic ring
  comprehension ``[(i, (i ± 1) % d) for i in range(d)]`` is recognized
  as bijective for every ``d``.
- **DDLB123 wire-bytes-drift** (project rule): every registered
  family's members driven under canonical shapes
  (``spmd.families``); when the traced per-device wire bytes and the
  family's ``perfmodel``-facing ``wire_bytes()`` formula both resolve
  and DISAGREE, the formula is wrong — and with it every
  ``roofline_frac`` column and the bench regression gate. Findings
  anchor at the defining ``def wire_bytes`` line.
"""

from __future__ import annotations

from typing import Iterable, List, Sequence, Set, Tuple

from ddlb_tpu.analysis.core import FileContext, Finding, ProjectRule, Rule
from ddlb_tpu.analysis.spmd.interp import trace_file
from ddlb_tpu.analysis.spmd.trace import COLLECTIVE_OPS

#: the package subtrees the semantic pass walks (the ISSUE 9 surface:
#: every shard_map body the benchmark can measure)
_SPMD_DIRS = ("primitives", "ops", "models")


def _in_spmd_scope(ctx: FileContext) -> bool:
    return ctx.in_package() and any(d in ctx.parts for d in _SPMD_DIRS)


class UndeclaredAxisRule(Rule):
    """Collective axis names must be declared by the enclosing site."""

    id = "DDLB120"
    name = "undeclared-collective-axis"
    rationale = (
        "a psum/ppermute/all_gather naming an axis the mesh never "
        "declares fails only at trace time on a real world — the "
        "trace-level check catches it before any launch"
    )

    def scope(self, ctx: FileContext) -> bool:
        return _in_spmd_scope(ctx)

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        out: List[Finding] = []
        seen: Set[Tuple] = set()
        for trace in trace_file(ctx):
            declared = trace.declared_axes()
            if declared is None:
                continue
            for e in trace.entries:
                if e.op not in COLLECTIVE_OPS + ("axis_index",):
                    continue
                for ax in e.axes:
                    if ax in declared:
                        continue
                    key = (e.line, e.col, e.op, ax)
                    if key in seen:
                        continue
                    seen.add(key)
                    out.append(
                        self.finding(
                            ctx, e.line, e.col,
                            f"{e.op} over axis '{ax}' which the "
                            f"enclosing shard_map (line {trace.line}) "
                            f"never declares — declared axes: "
                            f"{', '.join(declared) or 'none'}",
                        )
                    )
        return out


class StaticDivergenceRule(Rule):
    """A collective on one arm of a rank-dependent branch only."""

    id = "DDLB121"
    name = "rank-divergent-collective"
    rationale = (
        "a collective reachable on one side of a data-dependent branch "
        "wedges every peer that takes the other side — the static twin "
        "of the PR 8 flight recorder's divergence post-mortem"
    )

    def scope(self, ctx: FileContext) -> bool:
        return _in_spmd_scope(ctx)

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        out: List[Finding] = []
        seen: Set[Tuple] = set()
        for trace in trace_file(ctx):
            for div in trace.divergences:
                e = div.entry
                key = (e.line, e.op, e.axes, div.branch_line)
                if key in seen:
                    continue
                seen.add(key)
                axes = ",".join(e.axes) or "?"
                out.append(
                    self.finding(
                        ctx, e.line, e.col,
                        f"divergence site {e.op}[{axes}]: reachable on "
                        f"one arm of the rank-dependent {div.branch_kind} "
                        f"at line {div.branch_line} but unmatched on the "
                        f"other — the rank taking the other arm never "
                        f"arrives (runtime twin: flight_report.py "
                        f"'lagging rank / divergence site')",
                    )
                )
        return out


class PpermuteBijectionRule(Rule):
    """Concrete ppermute perms must be closed permutations."""

    id = "DDLB122"
    name = "non-bijective-ppermute"
    rationale = (
        "jax fills ranks missing from a ppermute perm with ZEROS "
        "instead of raising — a dropped or duplicated pair is a silent "
        "wrong answer circulating the ring"
    )

    def scope(self, ctx: FileContext) -> bool:
        return _in_spmd_scope(ctx)

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        out: List[Finding] = []
        seen: Set[Tuple] = set()
        for trace in trace_file(ctx):
            for e in trace.entries:
                if e.op != "ppermute" or e.perm_pattern == "ring":
                    continue
                if e.perm is None:
                    continue  # statically unresolvable: nothing to prove
                problem = self._perm_problem(e.perm)
                if problem is None:
                    continue
                key = (e.line, e.col, problem)
                if key in seen:
                    continue
                seen.add(key)
                out.append(
                    self.finding(
                        ctx, e.line, e.col,
                        f"ppermute perm {e.perm} is not a bijection: "
                        f"{problem} — ranks outside the perm receive "
                        f"zeros silently",
                    )
                )
        return out

    @staticmethod
    def _perm_problem(perm):
        srcs = [s for s, _ in perm]
        dsts = [d for _, d in perm]
        if len(set(srcs)) != len(srcs):
            return "duplicate source rank(s)"
        if len(set(dsts)) != len(dsts):
            return "duplicate destination rank(s)"
        if set(srcs) != set(dsts):
            missing = sorted(set(srcs) ^ set(dsts))
            return (
                f"source and destination sets differ (unbalanced ranks "
                f"{missing})"
            )
        return None


class WireDriftRule(ProjectRule):
    """Traced wire bytes vs the family ``wire_bytes()`` formula — and
    the registered-opaque discipline: a member whose wire the tracer
    cannot see must carry an ``OPAQUE_JUSTIFIED`` entry
    (``spmd.families``), and a stale entry (member no longer opaque)
    must be removed, so the opaque set can only shrink deliberately."""

    id = "DDLB123"
    name = "wire-bytes-drift"
    rationale = (
        "perfmodel wire_bytes() feeds every roofline_frac column and "
        "the bench regression gate; a formula that drifts from the "
        "member's actual collective traffic silently corrupts them all "
        "— and a member that silently lands opaque escapes the check "
        "entirely, so opacity itself must be registered"
    )

    def check_project(
        self, contexts: Sequence[FileContext]
    ) -> Iterable[Finding]:
        if not any(_in_spmd_scope(ctx) for ctx in contexts):
            return []
        from ddlb_tpu.analysis.spmd import families

        try:
            reports = families.verify_families()
        except Exception as exc:
            return [
                Finding(
                    self.id, "ddlb_tpu/analysis/spmd/families.py", 1, 1,
                    f"family verification failed to run: "
                    f"{type(exc).__name__}: {exc}",
                )
            ]
        return self.findings_from(reports)

    def findings_from(self, reports, justified=None) -> List[Finding]:
        """Drift + unregistered/stale-opaque reports -> findings
        (shared with the fixture tests, which drive
        ``families.verify_families`` over a synthetic tree and inject
        their own ``justified`` registry)."""
        from ddlb_tpu.analysis.spmd import families

        if justified is None:
            justified = families.OPAQUE_JUSTIFIED
        out: List[Finding] = []
        opaque_seen = set()
        for r in reports:
            if r.status == "opaque":
                opaque_seen.add((r.family, r.member))
            if r.status == "opaque" and (
                (r.family, r.member) not in justified
            ):
                rel = r.formula_rel or r.rel
                line = r.formula_line or 1
                out.append(
                    Finding(
                        self.id, rel, line, 1,
                        f"{r.label()} is opaque to the tracer with no "
                        f"registered justification — model its wire "
                        f"(analysis/pallas traces kernel DMA rings) or "
                        f"register ({r.family!r}, {r.member!r}) in "
                        f"families.OPAQUE_JUSTIFIED with why it cannot "
                        f"be checked",
                        snippet=_line_of(rel, line),
                    )
                )
                continue
            if r.status != "drift":
                continue
            rel = r.formula_rel or r.rel
            line = r.formula_line or 1
            out.append(
                Finding(
                    self.id, rel, line, 1,
                    f"wire-bytes drift for {r.label()}: {r.reason} "
                    f"(canonical shapes "
                    f"{families_shapes_label(r.family)}) — the formula "
                    f"feeds predicted_s/roofline_frac and the bench "
                    f"gate",
                    snippet=_line_of(rel, line),
                )
            )
        covered = {(r.family, r.member) for r in reports}
        families_seen = {r.family for r in reports}
        for key in sorted(justified):
            if key[0] not in families_seen:
                # the whole family is outside this sweep (fixture runs,
                # --spmd-trace subsets): its entries are not judgeable
                continue
            if key not in opaque_seen:
                why = (
                    "the member now traces"
                    if key in covered
                    else "the member is no longer registered"
                )
                rel, line = _justified_anchor()
                out.append(
                    Finding(
                        self.id, rel, line, 1,
                        f"stale OPAQUE_JUSTIFIED entry {key}: {why} — "
                        f"remove the entry so the opaque set only "
                        f"shrinks deliberately",
                        snippet=_line_of(rel, line),
                    )
                )
        return out


def _justified_anchor() -> Tuple[str, int]:
    """The ``OPAQUE_JUSTIFIED = {`` definition line in families.py —
    where a stale-entry finding sends the reader."""
    rel = "ddlb_tpu/analysis/spmd/families.py"
    from ddlb_tpu.analysis.core import repo_root

    try:
        lines = (repo_root() / rel).read_text(
            encoding="utf-8"
        ).splitlines()
    except OSError:
        return rel, 1
    for i, line in enumerate(lines, 1):
        if line.startswith("OPAQUE_JUSTIFIED"):
            return rel, i
    return rel, 1


def families_shapes_label(family: str) -> str:
    from ddlb_tpu.analysis.spmd.families import FAMILY_SHAPES

    s = FAMILY_SHAPES.get(family, {})
    return (
        f"m={s.get('m')}, n={s.get('n')}, k={s.get('k')}, d={s.get('d')}"
    )


def _line_of(rel: str, line: int) -> str:
    """The stripped source line for baseline-stable finding keys."""
    from ddlb_tpu.analysis.core import repo_root

    try:
        lines = (repo_root() / rel).read_text(
            encoding="utf-8"
        ).splitlines()
        return lines[line - 1].strip() if 1 <= line <= len(lines) else ""
    except OSError:
        return ""


RULES = [
    UndeclaredAxisRule(),
    StaticDivergenceRule(),
    PpermuteBijectionRule(),
    WireDriftRule(),
]
