"""Join flight-recorder site names to their static collective traces.

The PR 8 flight recorder names runtime sites (``runtime.barrier``,
``runtime.collective``, ``pool.row``, …) in its per-rank dumps, and
``scripts/flight_report.py`` attributes a wedged world to one of them.
The semantic SPMD pass traces the *code* behind several of those sites
— the barrier's ``psum``, the cross-process result allgather — so a
runtime divergence can be linked straight to the static location (and
collective sequence) the interpreter certified.

``static_site_index()`` builds the join table: every
``flightrec.record("<site>", …)`` / ``flightrec.mark("<site>", …)``
call site in the package, keyed by the site literal, with the
collective trace entries that fall inside the same enclosing function
(empty for sites that guard host-only regions — worker phases, pool
row dispatch). ``flight_report.py --json`` attaches the matching rows
as the report's ``static_trace`` field.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple

from ddlb_tpu.analysis.core import build_context, repo_root
from ddlb_tpu.analysis.spmd.interp import trace_file
from ddlb_tpu.analysis.spmd.trace import COLLECTIVE_OPS


def _site_calls(tree: ast.Module) -> List[Tuple[str, ast.Call]]:
    """Every ``flightrec.record/mark`` call with a constant site name."""
    out: List[Tuple[str, ast.Call]] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if not (
            isinstance(fn, ast.Attribute)
            and fn.attr in ("record", "mark")
            and isinstance(fn.value, ast.Name)
            and fn.value.id == "flightrec"
        ):
            continue
        if node.args and isinstance(node.args[0], ast.Constant) and (
            isinstance(node.args[0].value, str)
        ):
            out.append((node.args[0].value, node))
    return out


def _enclosing_span(
    tree: ast.Module, lineno: int
) -> Tuple[str, int, int]:
    """(qualname-ish, first line, last line) of the innermost function
    containing ``lineno``."""
    best: Tuple[str, int, int] = ("<module>", 1, 10 ** 9)
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            end = getattr(node, "end_lineno", node.lineno)
            if node.lineno <= lineno <= end and node.lineno >= best[1]:
                best = (node.name, node.lineno, end)
    return best


def static_site_index(
    root: Optional[Path] = None,
) -> Dict[str, Dict[str, Any]]:
    """Site name -> static location + traced collectives (see module
    docstring). Files are only parsed when their text mentions the
    flight recorder; traces are only built for files whose sites sit
    in functions with SPMD markers."""
    root = Path(root or repo_root())
    index: Dict[str, Dict[str, Any]] = {}
    for path in sorted((root / "ddlb_tpu").rglob("*.py")):
        if "__pycache__" in path.parts:
            continue
        try:
            text = path.read_text(encoding="utf-8")
        except OSError:
            continue
        if "flightrec." not in text:
            continue
        ctx = build_context(path, root=root)
        if ctx.tree is None:
            continue
        calls = _site_calls(ctx.tree)
        if not calls:
            continue
        traces = trace_file(ctx)
        for site, node in calls:
            fn_name, lo, hi = _enclosing_span(ctx.tree, node.lineno)
            collectives: List[Dict[str, Any]] = []
            for trace in traces:
                for e in trace.entries:
                    if e.op not in COLLECTIVE_OPS:
                        continue
                    if not (lo <= e.line <= hi):
                        continue
                    row = {
                        "op": e.op,
                        "axes": list(e.axes),
                        "line": e.line,
                    }
                    if row not in collectives:
                        collectives.append(row)
            entry = {
                "rel": ctx.rel,
                "line": node.lineno,
                "fn": fn_name,
                "collectives": collectives,
            }
            # first definition wins; re-records of the same site from
            # helper paths keep the primary anchor
            index.setdefault(site, entry)
    return index
