"""Abstract AST interpreter that walks ``shard_map`` bodies.

The interpreter executes Python function bodies over the value domain of
``spmd.trace``: concrete scalars stay concrete (so canonical-shape
evaluation runs real loop trip counts and real reshapes), device arrays
become symbolic ``Arr`` shapes, and everything unmodeled collapses to
``Unk``. JAX's program-construction surface is modeled just far enough
to (a) find every collective inside a mapped body, (b) size its payload
when shapes are known, and (c) preserve branch/loop structure — the
collective trace the DDLB120-123 rules read.

Design points:

- **No real JAX execution.** ``jax.lax.psum`` et al are name-pattern
  handlers on dotted paths resolved from each file's own imports; the
  interpreter never imports jax.
- **Branch forking.** A Python ``if`` on an unknown/rank-tainted
  condition interprets both arms against forked environments and merges
  (differing bindings become bounded ``UnionVal``s); ``lax.cond`` /
  ``lax.switch`` interpret every branch. Arm entry lists feed the
  DDLB121 divergence comparison.
- **Loops.** Concrete ``range``/sequence loops iterate for real (with a
  global step budget); unknown iterables run the body once under a
  ``loop`` frame. ``fori_loop``/``while_loop``/``scan`` run their body
  once symbolically.
- **Budgets.** A step budget and call-depth cap bound every analysis;
  exhaustion marks the trace ``truncated`` rather than failing the
  sweep.
"""

from __future__ import annotations

import ast
from typing import Any, Callable, Dict, List, Optional, Tuple

from ddlb_tpu.analysis.spmd.trace import (
    UNKNOWN,
    Arr,
    Frame,
    FuncVal,
    MeshVal,
    ModVal,
    OpaqueReal,
    ShardMapTrace,
    ShardMapVal,
    SpecVal,
    Tracer,
    UnionVal,
    Unk,
    is_unknown,
    taint_of,
)

#: dtype attribute names resolvable off jnp/np module paths
_DTYPE_NAMES = (
    "float32", "float64", "float16", "bfloat16", "int32", "int64",
    "int8", "bool_",
)

_MAX_STEPS = 400_000
_MAX_DEPTH = 20
_MAX_CONCRETE_ITERS = 256


class Budget:
    """Shared step budget; exhaustion aborts interpretation cleanly."""

    def __init__(self, steps: int = _MAX_STEPS) -> None:
        self.steps = steps
        self.exhausted = False

    def tick(self) -> bool:
        self.steps -= 1
        if self.steps <= 0:
            self.exhausted = True
        return not self.exhausted


class _Return(Exception):
    def __init__(self, value) -> None:
        self.value = value


class _Break(Exception):
    pass


class _Continue(Exception):
    pass


class _Abort(Exception):
    """Budget exhausted / depth exceeded: unwind to the trace driver."""


class Env:
    """Lexical environment: one dict frame chained to a parent."""

    __slots__ = ("vars", "parent")

    def __init__(self, parent: Optional["Env"] = None) -> None:
        self.vars: Dict[str, Any] = {}
        self.parent = parent

    def get(self, name: str):
        env: Optional[Env] = self
        while env is not None:
            if name in env.vars:
                return env.vars[name]
            env = env.parent
        return None if name == "__missing__" else _MISSING

    def set(self, name: str, value) -> None:
        self.vars[name] = value

    def fork(self) -> "Env":
        child = Env(self.parent)
        child.vars = dict(self.vars)
        return child


_MISSING = object()

_SAFE_BUILTINS: Dict[str, Any] = {
    "range": range, "len": len, "min": min, "max": max, "abs": abs,
    "int": int, "float": float, "bool": bool, "str": str, "sum": sum,
    "sorted": sorted, "list": list, "tuple": tuple, "dict": dict,
    "set": set, "enumerate": enumerate, "zip": zip, "reversed": reversed,
    "True": True, "False": False, "None": None, "isinstance": None,
    "getattr": None, "print": None,
}

#: the real callables among _SAFE_BUILTINS — ``call_value`` applies
#: these for real (everything else routes through handler protocols)
_REAL_BUILTINS = tuple(
    v for v in _SAFE_BUILTINS.values() if callable(v)
)


class SelfVal:
    """The interpreter's ``self``: a dict of written attributes with an
    optional real stub instance behind it for data/property reads, and
    an optional ``StaticClass`` (``spmd.families``) resolving methods,
    properties and class attributes purely from source — the family
    driver's import-free instance model."""

    def __init__(self, stub=None, attrs=None, klass=None) -> None:
        self.stub = stub
        self.attrs: Dict[str, Any] = dict(attrs or {})
        self.klass = klass


class PartialVal:
    """``functools.partial`` over an interpretable callee: the bound
    positional args lead, bound keywords merge under call-site keywords
    — what lets the Pallas kernel model see the concrete ``d``/``bn``/
    ``bk`` every ops kernel binds via ``functools.partial(kernel, ...)``
    before handing it to ``pallas_call``."""

    __slots__ = ("fn", "args", "kwargs")

    def __init__(self, fn, args=(), kwargs=None) -> None:
        self.fn = fn
        self.args = tuple(args)
        self.kwargs = dict(kwargs or {})


class HostNS:
    """A host-side namespace (e.g. the family driver's ``self.runtime``
    stand-in): attribute reads return the named member — plain abstract
    values, or host closures ``(args, kwargs, node, interp) -> value``
    that ``call_value`` already dispatches."""

    __slots__ = ("members",)

    def __init__(self, members: Dict[str, Any]) -> None:
        self.members = dict(members)


def module_alias_env(tree: ast.Module) -> Env:
    """Top frame for a file: its imports as ``ModVal`` paths / markers,
    plus module-level constants and function defs (bound lazily by the
    interpreter as it encounters them)."""
    env = Env()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                name = alias.asname or alias.name.split(".")[0]
                env.set(name, ModVal(alias.name if alias.asname else name))
        elif isinstance(node, ast.ImportFrom) and node.module:
            for alias in node.names:
                if alias.name == "*":
                    continue
                bound = alias.asname or alias.name
                env.set(bound, ModVal(f"{node.module}.{alias.name}"))
    return env


def _const_axis(v) -> Tuple[str, ...]:
    """Axis-name operand of a collective: str or tuple of strs."""
    if isinstance(v, str):
        return (v,)
    if isinstance(v, (tuple, list)) and all(isinstance(x, str) for x in v):
        return tuple(v)
    return ()


def _broadcast(s1, s2):
    """NumPy-style shape broadcast; None dims propagate."""
    if s1 is None or s2 is None:
        return None
    out = []
    for a, b in zip(
        (1,) * (len(s2) - len(s1)) + tuple(s1),
        (1,) * (len(s1) - len(s2)) + tuple(s2),
    ):
        if a == 1:
            out.append(b)
        elif b == 1 or a == b:
            out.append(a)
        elif a is None or b is None:
            out.append(None)
        else:
            return None
    return tuple(out)


def _shape_of(v) -> Optional[Tuple]:
    if isinstance(v, Arr):
        return v.shape
    if isinstance(v, (int, float, bool)):
        return ()
    return None


def _dtype_of(v) -> Optional[str]:
    if isinstance(v, Arr):
        return v.dtype
    if isinstance(v, bool):
        return "bool"
    if isinstance(v, int):
        return "int32"
    if isinstance(v, float):
        return "float32"
    return None


def _as_dtype(v) -> Optional[str]:
    """Resolve a dtype-ish value (ModVal path tail / str) to a name."""
    if isinstance(v, str):
        return v if v in Arr.__init__.__defaults__ or True else v
    if isinstance(v, ModVal):
        tail = v.path.rsplit(".", 1)[-1]
        if tail in _DTYPE_NAMES:
            return "bool" if tail == "bool_" else tail
    return None


def _ring_perm_pattern(node: ast.AST) -> Optional[str]:
    """Recognize ``[(i, (i ± c) % d) for i in range(d)]`` as a ring
    bijection without needing a concrete ``d``."""
    if not isinstance(node, ast.ListComp) or len(node.generators) != 1:
        return None
    gen = node.generators[0]
    if not (
        isinstance(gen.target, ast.Name)
        and isinstance(gen.iter, ast.Call)
        and isinstance(gen.iter.func, ast.Name)
        and gen.iter.func.id == "range"
        and len(gen.iter.args) == 1
        and not gen.ifs
    ):
        return None
    rng = ast.dump(gen.iter.args[0])
    elt = node.elt
    if not (isinstance(elt, ast.Tuple) and len(elt.elts) == 2):
        return None
    var = gen.target.id

    def is_var(e):
        return isinstance(e, ast.Name) and e.id == var

    def is_shifted_mod(e):
        return (
            isinstance(e, ast.BinOp)
            and isinstance(e.op, ast.Mod)
            and ast.dump(e.right) == rng
            and isinstance(e.left, ast.BinOp)
            and isinstance(e.left.op, (ast.Add, ast.Sub))
            and (is_var(e.left.left) or is_var(e.left.right))
        )

    a, b = elt.elts
    if (is_var(a) and is_shifted_mod(b)) or (is_shifted_mod(a) and is_var(b)):
        return "ring"
    return None


class Interpreter:
    """Evaluates one function body, recording collectives into a Tracer."""

    def __init__(
        self,
        tracer: Tracer,
        budget: Optional[Budget] = None,
        summaries: Optional[Dict[str, Callable]] = None,
        self_summaries: Optional[Dict[str, Callable]] = None,
        module_resolver: Optional[Callable] = None,
        axis_sizes: Optional[Dict[str, int]] = None,
        pallas_model: Optional[Any] = None,
    ) -> None:
        self.tracer = tracer
        self.budget = budget or Budget()
        #: dotted-path -> handler(args, kwargs, node, interp) overrides
        self.summaries = dict(summaries or {})
        #: self-method name -> handler for methods too heavy to interpret
        self.self_summaries = dict(self_summaries or {})
        #: optional cross-module FuncVal resolver(path) for ddlb_tpu.*
        self.module_resolver = module_resolver
        self.axis_sizes = dict(axis_sizes or {})
        #: optional ``analysis.pallas.model.PallasModel``: when set, the
        #: pl/pltpu surface (pallas_call, BlockSpec, DMA semaphores,
        #: emit_pipeline, ...) dispatches to it and kernel BODIES are
        #: interpreted instead of stopping at ``out_shape``
        self.pallas = pallas_model
        self.depth = 0
        #: family-driver phase control: when set, shard_map bodies traced
        #: from direct calls record under this phase instead of the
        #: mode-derived default ("init" during _input_setup, "measured"
        #: while driving the member's _fn)
        self.phase_override: Optional[str] = None
        #: active FuncVal stack — super() dispatch needs the defining
        #: class of the method currently executing
        self._fn_stack: List[FuncVal] = []

    # ------------------------------------------------------------------
    # function-call machinery
    # ------------------------------------------------------------------

    def call_function(self, fn: FuncVal, args, kwargs) -> Any:
        if self.depth >= _MAX_DEPTH or not self.budget.tick():
            raise _Abort()
        env = Env(fn.env)
        node = fn.node
        params = node.args
        pos = list(args)
        if fn.self_val is not None:
            pos = [fn.self_val] + pos
        names = [a.arg for a in params.posonlyargs + params.args]
        defaults = params.defaults
        # bind positional
        for i, name in enumerate(names):
            if i < len(pos):
                env.set(name, pos[i])
            elif name in kwargs:
                env.set(name, kwargs.pop(name))
            else:
                j = i - (len(names) - len(defaults))
                if 0 <= j < len(defaults):
                    env.set(name, self.eval(defaults[j], fn.env))
                else:
                    env.set(name, UNKNOWN)
        if params.vararg is not None:
            env.set(params.vararg.arg, tuple(pos[len(names):]))
        for a, dflt in zip(params.kwonlyargs, params.kw_defaults):
            if a.arg in kwargs:
                env.set(a.arg, kwargs.pop(a.arg))
            elif dflt is not None:
                env.set(a.arg, self.eval(dflt, fn.env))
            else:
                env.set(a.arg, UNKNOWN)
        if params.kwarg is not None:
            env.set(params.kwarg.arg, dict(kwargs))
        self.depth += 1
        self._fn_stack.append(fn)
        try:
            if isinstance(node, ast.Lambda):
                return self.eval(node.body, env)
            returns: List[Any] = []
            try:
                self.exec_block(node.body, env)
            except _Return as r:
                returns.append(r.value)
            if not returns:
                return None
            return returns[0]
        finally:
            self._fn_stack.pop()
            self.depth -= 1

    def call_value(self, fn, args, kwargs, node) -> Any:
        """Dispatch a call on any callee value."""
        if isinstance(fn, FuncVal):
            return self.call_function(fn, args, kwargs)
        if isinstance(fn, PartialVal):
            merged_kw = dict(fn.kwargs)
            merged_kw.update(kwargs)
            return self.call_value(
                fn.fn, list(fn.args) + list(args), merged_kw, node
            )
        if isinstance(fn, ShardMapVal):
            return self.apply_shard_map(fn, args)
        if isinstance(fn, UnionVal):
            results = [
                self.call_value(o, list(args), dict(kwargs), node)
                for o in fn.options
            ]
            return UnionVal(results)
        if isinstance(fn, ModVal):
            return self.call_path(fn.path, args, kwargs, node)
        if any(fn is b for b in _REAL_BUILTINS):
            # a real builtin bound by _e_Name: apply it for real — with
            # concrete-scalar guards on the casts, whose truthiness over
            # abstract values would silently "succeed" wrong
            if fn in (int, float, bool, str) and not all(
                isinstance(a, (int, float, bool, str)) for a in args
            ):
                return Unk(tainted=taint_of(args))
            try:
                result = fn(*args, **kwargs)
                if fn in (zip, enumerate, reversed):
                    result = list(result)  # materialize for _s_For
                return result
            except _Abort:
                raise
            except Exception:
                return Unk(tainted=taint_of(args))
        if callable(fn) and not isinstance(fn, (Arr, Unk)):
            # a host-level summary closure produced by another handler
            try:
                return fn(args, kwargs, node, self)
            except _Abort:
                raise
            except Exception:
                return UNKNOWN
        return Unk(tainted=taint_of(fn))

    # ------------------------------------------------------------------
    # shard_map modeling
    # ------------------------------------------------------------------

    def make_shard_map(self, args, kwargs, node) -> Any:
        fn = args[0] if args else kwargs.get("f", UNKNOWN)
        if isinstance(fn, ModVal) and self.module_resolver is not None:
            # an imported helper mapped directly (e.g. the quantized
            # members' shard_map(quantize_rowwise, ...) init step)
            resolved = self.module_resolver(fn.path)
            if resolved is not None:
                fn = resolved
        mesh = kwargs.get("mesh", args[1] if len(args) > 1 else None)
        in_specs = kwargs.get("in_specs", args[2] if len(args) > 2 else None)
        out_specs = kwargs.get("out_specs", args[3] if len(args) > 3 else None)
        mesh_axes = None
        if isinstance(mesh, MeshVal):
            mesh_axes = mesh.axes
        specs = in_specs if isinstance(in_specs, tuple) else (in_specs,)
        smv = ShardMapVal(fn, mesh_axes, specs, out_specs, node)
        if self.tracer.mode == "file":
            self.trace_shard_map_body(smv, call_args=None)
        return smv

    def _spec_axis_names(self, smv: ShardMapVal) -> Tuple[str, ...]:
        names: List[str] = []
        for spec in list(smv.in_specs) + [smv.out_specs]:
            for s in spec if isinstance(spec, tuple) else (spec,):
                if isinstance(s, SpecVal):
                    names.extend(s.axis_names())
        seen: Dict[str, bool] = {}
        for n in names:
            seen.setdefault(n, True)
        return tuple(seen)

    def _shard_value(self, value, spec) -> Any:
        """The local view of a global operand under a PartitionSpec."""
        if not isinstance(value, Arr) or value.shape is None:
            return value if isinstance(value, Arr) else UNKNOWN
        if not isinstance(spec, SpecVal):
            return value.with_shape(None)
        dims = list(value.shape)
        for i, entry in enumerate(spec.entries[: len(dims)]):
            axes = (
                (entry,) if isinstance(entry, str)
                else tuple(entry) if isinstance(entry, (tuple, list))
                else ()
            )
            d = 1
            for ax in axes:
                d *= self.axis_sizes.get(ax, 0) or 0
            if axes:
                if d and isinstance(dims[i], int) and dims[i] % d == 0:
                    dims[i] //= d
                else:
                    dims[i] = None
        return value.with_shape(tuple(dims))

    def _unshard_value(self, value, spec) -> Any:
        if not isinstance(value, Arr) or value.shape is None:
            return value
        if not isinstance(spec, SpecVal):
            return value.with_shape(None)
        dims = list(value.shape)
        for i, entry in enumerate(spec.entries[: len(dims)]):
            axes = (
                (entry,) if isinstance(entry, str)
                else tuple(entry) if isinstance(entry, (tuple, list))
                else ()
            )
            d = 1
            for ax in axes:
                d *= self.axis_sizes.get(ax, 0) or 0
            if axes and d and isinstance(dims[i], int):
                dims[i] *= d
            elif axes:
                dims[i] = None
        return value.with_shape(tuple(dims))

    def trace_shard_map_body(
        self, smv: ShardMapVal, call_args, phase: str = "measured"
    ) -> Any:
        """Open a trace for a shard_map site and interpret its body."""
        node = smv.node
        fn = smv.fn
        trace = ShardMapTrace(
            self.tracer.rel,
            getattr(node, "lineno", 0),
            getattr(node, "col_offset", 0) + 1,
            getattr(fn, "name", "") if isinstance(fn, FuncVal) else "",
            smv.mesh_axes,
            self._spec_axis_names(smv),
            phase=phase,
        )
        self.tracer.open_trace(trace)
        result: Any = UNKNOWN
        try:
            if not isinstance(fn, (FuncVal, UnionVal)):
                trace.unresolved = True
                return UNKNOWN
            fns = fn.options if isinstance(fn, UnionVal) else [fn]
            for f in fns:
                if not isinstance(f, FuncVal):
                    trace.unresolved = True
                    continue
                n_params = len(f.node.args.posonlyargs) + len(f.node.args.args)
                if isinstance(f.node, ast.Lambda):
                    n_params = len(f.node.args.args)
                if call_args is None:
                    args = [
                        self._shard_value(
                            Arr(None),
                            smv.in_specs[i]
                            if i < len(smv.in_specs)
                            else UNKNOWN,
                        )
                        for i in range(n_params)
                    ]
                else:
                    args = [
                        self._shard_value(
                            v,
                            smv.in_specs[i]
                            if i < len(smv.in_specs)
                            else UNKNOWN,
                        )
                        for i, v in enumerate(call_args)
                    ]
                try:
                    result = self.call_function(f, args, {})
                except _Abort:
                    trace.truncated = True
        finally:
            self.tracer.close_trace()
        return result

    def apply_shard_map(self, smv: ShardMapVal, args) -> Any:
        """A shard_map value called directly (init-time helpers)."""
        phase = self.phase_override or (
            "init" if self.tracer.mode == "family" else "measured"
        )
        result = self.trace_shard_map_body(smv, list(args), phase=phase)
        out = smv.out_specs
        if isinstance(result, tuple) and isinstance(out, tuple):
            return tuple(
                self._unshard_value(v, s) for v, s in zip(result, out)
            )
        return self._unshard_value(result, out)

    # ------------------------------------------------------------------
    # dotted-path call handlers (the modeled JAX surface)
    # ------------------------------------------------------------------

    def call_path(self, path: str, args, kwargs, node) -> Any:
        if path in self.summaries:
            return self.summaries[path](args, kwargs, node, self)
        tail = path.rsplit(".", 1)[-1]
        rec = self.tracer.record
        arr0 = args[0] if args else UNKNOWN

        def axis_arg(pos: int, kw: str = "axis_name"):
            if kw in kwargs:
                return _const_axis(kwargs[kw])
            if len(args) > pos:
                return _const_axis(args[pos])
            return ()

        def axis_size(axes) -> int:
            d = 1
            for ax in axes:
                d *= self.axis_sizes.get(ax, 0) or 0
            return d

        if self.pallas is not None:
            handled = self.pallas.dispatch(path, tail, args, kwargs,
                                           node, self)
            if handled is not _MISSING:
                return handled
        if tail == "partial":
            # functools.partial over any interpretable callee
            if args:
                return PartialVal(args[0], args[1:], kwargs)
            return UNKNOWN
        if tail in ("rem", "cdiv") and len(args) >= 2 and all(
            isinstance(a, int) for a in args[:2]
        ) and args[1] != 0:
            a, b = args[0], args[1]
            return a % b if tail == "rem" else -(-a // b)
        if tail in ("shard_map", "shard_map_compat"):
            return self.make_shard_map(args, kwargs, node)
        if tail == "PartitionSpec":
            entries = []
            for a in args:
                if isinstance(a, list):
                    a = tuple(a)
                entries.append(
                    a if isinstance(a, (str, tuple)) or a is None else None
                )
            return SpecVal(entries)
        if tail == "Mesh":
            axes = kwargs.get(
                "axis_names", args[1] if len(args) > 1 else None
            )
            if isinstance(axes, str):
                axes = (axes,)
            if isinstance(axes, (tuple, list)) and all(
                isinstance(a, str) for a in axes
            ):
                return MeshVal(tuple(axes))
            return UNKNOWN
        # cross-module ddlb_tpu functions interpret from their own file
        # (ops/ helpers, family bases) — resolved lazily, cached
        if self.module_resolver is not None and path.startswith("ddlb_tpu"):
            resolved = self.module_resolver(path)
            if resolved is not None:
                return self.call_value(resolved, args, kwargs, node)
        if tail == "jit":
            return args[0] if args else UNKNOWN
        if tail in ("block_until_ready", "device_put", "stop_gradient",
                    "with_sharding_constraint", "checkpoint", "remat"):
            return arr0
        if tail == "axis_index":
            axes = axis_arg(0)
            rec("axis_index", axes, node)
            return Arr((), "int32", tainted=True)
        if tail in ("psum", "pmean"):
            axes = axis_arg(1)
            payload = arr0 if isinstance(arr0, Arr) else None
            rec(tail, axes, node, payload=payload)
            return arr0 if isinstance(arr0, Arr) else UNKNOWN
        if tail == "psum_scatter":
            axes = axis_arg(1)
            payload = arr0 if isinstance(arr0, Arr) else None
            rec("psum_scatter", axes, node, payload=payload)
            dim = kwargs.get("scatter_dimension", 0)
            d = axis_size(axes)
            if isinstance(arr0, Arr) and arr0.shape is not None and d:
                dims = list(arr0.shape)
                if (
                    isinstance(dim, int)
                    and dim < len(dims)
                    and isinstance(dims[dim], int)
                    and dims[dim] % d == 0
                ):
                    dims[dim] //= d
                    return arr0.with_shape(tuple(dims))
            return Arr(None, _dtype_of(arr0))
        if tail == "all_gather":
            axes = axis_arg(1)
            payload = arr0 if isinstance(arr0, Arr) else None
            rec("all_gather", axes, node, payload=payload)
            dim = kwargs.get("axis", 0)
            tiled = kwargs.get("tiled", False)
            d = axis_size(axes)
            if isinstance(arr0, Arr) and arr0.shape is not None and d:
                dims = list(arr0.shape)
                if isinstance(dim, int) and dim <= len(dims):
                    if tiled:
                        if dim < len(dims) and isinstance(dims[dim], int):
                            dims[dim] *= d
                            return arr0.with_shape(tuple(dims))
                    else:
                        dims.insert(dim, d)
                        return arr0.with_shape(tuple(dims))
            return Arr(None, _dtype_of(arr0))
        if tail == "all_to_all":
            axes = axis_arg(1)
            payload = arr0 if isinstance(arr0, Arr) else None
            rec("all_to_all", axes, node, payload=payload)
            split = kwargs.get("split_axis", args[2] if len(args) > 2 else 0)
            concat = kwargs.get(
                "concat_axis", args[3] if len(args) > 3 else 0
            )
            d = axis_size(axes)
            if (
                isinstance(arr0, Arr)
                and arr0.shape is not None
                and d
                and isinstance(split, int)
                and isinstance(concat, int)
            ):
                dims = list(arr0.shape)
                if (
                    split < len(dims)
                    and concat < len(dims)
                    and isinstance(dims[split], int)
                    and isinstance(dims[concat], int)
                    and dims[split] % d == 0
                ):
                    dims[split] //= d
                    dims[concat] *= d
                    return arr0.with_shape(tuple(dims))
            return Arr(None, _dtype_of(arr0))
        if tail == "ppermute":
            axes = axis_arg(1)
            perm = kwargs.get("perm", args[2] if len(args) > 2 else None)
            concrete = None
            if isinstance(perm, (list, tuple)) and all(
                isinstance(p, (tuple, list))
                and len(p) == 2
                and all(isinstance(x, int) for x in p)
                for p in perm
            ):
                concrete = [tuple(p) for p in perm]
            pattern = getattr(node, "_ddlb_perm_pattern", None)
            payload = arr0 if isinstance(arr0, Arr) else None
            rec(
                "ppermute", axes, node, payload=payload, perm=concrete,
                perm_pattern=pattern,
            )
            return arr0 if isinstance(arr0, Arr) else UNKNOWN
        if tail == "make_async_remote_copy":
            src = args[0] if args else kwargs.get("src_ref", UNKNOWN)
            rec(
                "remote_copy", (), node,
                payload=src if isinstance(src, Arr) else None,
            )
            return OpaqueReal(None)
        if tail == "ShapeDtypeStruct":
            shape = args[0] if args else kwargs.get("shape")
            dt = _as_dtype(args[1] if len(args) > 1 else kwargs.get("dtype"))
            if isinstance(shape, (tuple, list)) and all(
                isinstance(d, int) for d in shape
            ):
                return Arr(tuple(shape), dt)
            return Arr(None, dt)
        if tail == "pallas_call":
            # kernel-internal DMAs are opaque by design (DDLB123 lists
            # such members as 'opaque'); what matters downstream is the
            # result's SHAPE, declared right here by out_shape
            out_shape = kwargs.get("out_shape")

            def _pallas_result(cargs, ckwargs, cnode, cinterp, _o=out_shape):
                if isinstance(_o, (tuple, list)):
                    return tuple(
                        o if isinstance(o, Arr) else UNKNOWN for o in _o
                    )
                return _o if isinstance(_o, Arr) else UNKNOWN

            return _pallas_result
        if tail == "cond":
            return self._lax_cond(args, kwargs, node)
        if tail == "switch":
            return self._lax_switch(args, kwargs, node)
        if tail == "fori_loop":
            return self._lax_fori(args, kwargs, node)
        if tail == "while_loop":
            return self._lax_while(args, kwargs, node)
        if tail == "scan":
            return self._lax_scan(args, kwargs, node)
        return self._shape_op(path, tail, args, kwargs, node)

    # -- structured control flow -------------------------------------------

    def _interp_branch(self, fn, operands, frame: Frame) -> Tuple[Any, list]:
        trace = self.tracer.current()
        start = len(trace.entries) if trace else 0
        self.tracer.push_frame(frame)
        try:
            result = self.call_value(fn, list(operands), {}, None)
        except _Abort:
            result = UNKNOWN
        finally:
            self.tracer.pop_frame()
        entries = trace.entries[start:] if trace else []
        return result, list(entries)

    def _lax_cond(self, args, kwargs, node) -> Any:
        if len(args) < 3:
            return UNKNOWN
        pred, true_fn, false_fn, *operands = args
        tainted = taint_of(pred)
        arms = []
        result = UNKNOWN
        for i, fn in enumerate((true_fn, false_fn)):
            frame = Frame(
                "cond", "lax.cond", tainted=tainted, arm=i,
                line=getattr(node, "lineno", 0),
            )
            res, entries = self._interp_branch(fn, operands, frame)
            arms.append(entries)
            if i == 0:
                result = res
        self.tracer.record_divergences(
            arms,
            Frame("cond", "lax.cond", tainted=tainted,
                  line=getattr(node, "lineno", 0)),
        )
        return result

    def _lax_switch(self, args, kwargs, node) -> Any:
        if len(args) < 2:
            return UNKNOWN
        idx, branches, *operands = args
        if not isinstance(branches, (list, tuple)):
            return UNKNOWN
        tainted = taint_of(idx)
        arms = []
        result = UNKNOWN
        for i, fn in enumerate(branches):
            frame = Frame(
                "switch", "lax.switch", tainted=tainted, arm=i,
                line=getattr(node, "lineno", 0),
            )
            res, entries = self._interp_branch(fn, operands, frame)
            arms.append(entries)
            if i == 0:
                result = res
        self.tracer.record_divergences(
            arms,
            Frame("switch", "lax.switch", tainted=tainted,
                  line=getattr(node, "lineno", 0)),
        )
        return result

    def _lax_fori(self, args, kwargs, node) -> Any:
        if len(args) < 4:
            return args[3] if len(args) > 3 else UNKNOWN
        lo, hi, body, init = args[:4]
        if (
            isinstance(lo, int)
            and isinstance(hi, int)
            and 0 <= hi - lo <= _MAX_CONCRETE_ITERS
        ):
            carry = init
            frame = Frame("loop", f"fori[{lo},{hi})",
                          line=getattr(node, "lineno", 0))
            self.tracer.push_frame(frame)
            try:
                for i in range(lo, hi):
                    if not self.budget.tick():
                        raise _Abort()
                    carry = self.call_value(body, [i, carry], {}, node)
            finally:
                self.tracer.pop_frame()
            return carry
        frame = Frame("loop", "fori[?]", line=getattr(node, "lineno", 0))
        self.tracer.push_frame(frame)
        try:
            return self.call_value(
                body, [Arr((), "int32"), init], {}, node
            )
        except _Abort:
            return UNKNOWN
        finally:
            self.tracer.pop_frame()

    def _lax_while(self, args, kwargs, node) -> Any:
        if len(args) < 3:
            return UNKNOWN
        _cond, body, init = args[:3]
        frame = Frame("while", "while_loop", line=getattr(node, "lineno", 0))
        self.tracer.push_frame(frame)
        try:
            return self.call_value(body, [init], {}, node)
        except _Abort:
            return UNKNOWN
        finally:
            self.tracer.pop_frame()

    def _lax_scan(self, args, kwargs, node) -> Any:
        if len(args) < 2:
            return UNKNOWN
        f, init = args[:2]
        xs = args[2] if len(args) > 2 else kwargs.get("xs", UNKNOWN)
        x = UNKNOWN
        if isinstance(xs, Arr) and xs.shape:
            x = xs.with_shape(xs.shape[1:])
        frame = Frame("loop", "scan", line=getattr(node, "lineno", 0))
        self.tracer.push_frame(frame)
        try:
            res = self.call_value(f, [init, x], {}, node)
        except _Abort:
            res = UNKNOWN
        finally:
            self.tracer.pop_frame()
        if isinstance(res, tuple) and len(res) == 2:
            return res
        return (UNKNOWN, UNKNOWN)

    # -- shape-level jnp/np/misc ops ---------------------------------------

    def _shape_op(self, path, tail, args, kwargs, node) -> Any:
        arr0 = args[0] if args else UNKNOWN
        tainted = taint_of(args) or taint_of(tuple(kwargs.values()))
        if tail in ("zeros", "ones", "full", "empty"):
            shape = args[0] if args else kwargs.get("shape")
            if isinstance(shape, int):
                shape = (shape,)
            dt = None
            cand = (
                args[1] if tail != "full" and len(args) > 1
                else args[2] if tail == "full" and len(args) > 2
                else kwargs.get("dtype")
            )
            dt = _as_dtype(cand) or "float32"
            if isinstance(shape, tuple) and all(
                isinstance(d, int) for d in shape
            ):
                return Arr(shape, dt)
            return Arr(None, dt)
        if tail in ("zeros_like", "ones_like", "full_like"):
            return (
                Arr(arr0.shape, arr0.dtype) if isinstance(arr0, Arr)
                else UNKNOWN
            )
        if tail == "asarray" or tail == "array":
            if isinstance(arr0, Arr):
                return arr0
            shape = _shape_of(arr0)
            dt = _as_dtype(
                args[1] if len(args) > 1 else kwargs.get("dtype")
            )
            if isinstance(arr0, (list, tuple)):
                return Arr(None, dt, tainted=tainted)
            return Arr(shape, dt or _dtype_of(arr0), tainted=tainted)
        if tail in ("matmul", "dot"):
            return self.matmul_shape(
                arr0, args[1] if len(args) > 1 else UNKNOWN
            )
        if tail == "dot_general":
            b = args[1] if len(args) > 1 else UNKNOWN
            if self.pallas is not None:
                self.pallas.note_dot(arr0, b)
            dn = args[2] if len(args) > 2 else kwargs.get(
                "dimension_numbers"
            )
            sa, sb = _shape_of(arr0), _shape_of(b)
            dt = (
                _as_dtype(kwargs.get("preferred_element_type"))
                or _dtype_of(arr0)
                or _dtype_of(b)
            )
            if (
                sa is None or sb is None
                or not (isinstance(dn, tuple) and len(dn) == 2)
            ):
                return Arr(None, dt, tainted)
            try:
                (ca, cb), (ba, bb) = dn
                ca, cb, ba, bb = (tuple(x) for x in (ca, cb, ba, bb))
                batch = tuple(sa[i] for i in ba)
                rest_a = tuple(
                    s for i, s in enumerate(sa) if i not in ca + ba
                )
                rest_b = tuple(
                    s for i, s in enumerate(sb) if i not in cb + bb
                )
                return Arr(batch + rest_a + rest_b, dt, tainted)
            except (TypeError, IndexError):
                return Arr(None, dt, tainted)
        if tail == "einsum":
            return self._einsum(args)
        if tail == "stack":
            seq = arr0
            axis = kwargs.get("axis", args[1] if len(args) > 1 else 0)
            if (
                isinstance(seq, (list, tuple))
                and seq
                and all(isinstance(x, Arr) for x in seq)
                and seq[0].shape is not None
                and isinstance(axis, int)
            ):
                dims = list(seq[0].shape)
                dims.insert(axis if axis >= 0 else len(dims) + 1 + axis,
                            len(seq))
                return Arr(tuple(dims), seq[0].dtype, tainted)
            return Arr(None, None, tainted)
        if tail == "concatenate":
            seq = arr0
            axis = kwargs.get("axis", args[1] if len(args) > 1 else 0)
            if (
                isinstance(seq, (list, tuple))
                and seq
                and all(
                    isinstance(x, Arr) and x.shape is not None for x in seq
                )
                and isinstance(axis, int)
            ):
                dims = list(seq[0].shape)
                if axis < len(dims):
                    total = 0
                    for x in seq:
                        d = x.shape[axis] if axis < len(x.shape) else None
                        if not isinstance(d, int):
                            total = None
                            break
                        total += d
                    dims[axis] = total
                    return Arr(tuple(dims), seq[0].dtype, tainted)
            return Arr(None, None, tainted)
        if tail == "repeat":
            reps = args[1] if len(args) > 1 else kwargs.get("repeats")
            axis = kwargs.get("axis", args[2] if len(args) > 2 else None)
            if (
                isinstance(arr0, Arr)
                and arr0.shape is not None
                and isinstance(reps, int)
                and isinstance(axis, int)
                and axis < len(arr0.shape)
                and isinstance(arr0.shape[axis], int)
            ):
                dims = list(arr0.shape)
                dims[axis] *= reps
                return arr0.with_shape(tuple(dims))
            return Arr(None, _dtype_of(arr0), tainted)
        if tail == "reshape":
            return self.reshape(arr0, args[1:], kwargs)
        if tail == "transpose":
            if isinstance(arr0, Arr):
                axes = args[1] if len(args) > 1 else kwargs.get("axes")
                return self.transpose(arr0, axes)
            return UNKNOWN
        if tail == "where":
            a = args[1] if len(args) > 1 else UNKNOWN
            b = args[2] if len(args) > 2 else UNKNOWN
            sa, sb = _shape_of(a), _shape_of(b)
            shape = _broadcast(
                _broadcast(sa, sb), _shape_of(arr0)
            )
            dt = _dtype_of(a) or _dtype_of(b)
            return Arr(shape, dt, tainted)
        if tail == "broadcasted_iota":
            dt = _as_dtype(arr0) or "int32"
            shape = args[1] if len(args) > 1 else None
            if isinstance(shape, tuple) and all(
                isinstance(d, int) for d in shape
            ):
                return Arr(shape, dt)
            return Arr(None, dt)
        if tail == "arange":
            if isinstance(arr0, int):
                return Arr((arr0,), "int32")
            return Arr(None, "int32")
        if tail in (
            "ceil", "floor", "sqrt", "log", "log2", "exp", "isqrt",
            "fabs", "prod",
        ) and args and all(
            isinstance(a, (int, float, bool))
            or (tail == "prod" and isinstance(a, (tuple, list)))
            for a in args
        ):
            import math

            try:
                return getattr(math, tail)(*args)
            except (AttributeError, ValueError, TypeError, OverflowError):
                return UNKNOWN
        if tail in (
            "exp", "log", "sqrt", "square", "tanh", "gelu", "relu",
            "abs", "negative", "sign", "rsqrt", "sigmoid", "softmax",
            "round", "rint", "trunc", "clip",
        ):
            return arr0 if isinstance(arr0, Arr) else UNKNOWN
        if tail in ("maximum", "minimum", "add", "subtract", "multiply",
                    "divide", "power", "equal", "not_equal"):
            a, b = arr0, args[1] if len(args) > 1 else UNKNOWN
            shape = _broadcast(_shape_of(a), _shape_of(b))
            return Arr(shape, _dtype_of(a) or _dtype_of(b), tainted)
        if tail in ("sum", "max", "min", "mean", "prod"):
            return self.reduce(arr0, args[1:], kwargs)
        if tail == "astype":
            return arr0
        if tail.startswith("dynamic_update_slice"):
            return arr0 if isinstance(arr0, Arr) else UNKNOWN
        if tail == "dynamic_slice_in_dim":
            size = args[2] if len(args) > 2 else kwargs.get("slice_size")
            axis = kwargs.get("axis", args[3] if len(args) > 3 else 0)
            if (
                isinstance(arr0, Arr)
                and arr0.shape is not None
                and isinstance(size, int)
                and isinstance(axis, int)
                and axis < len(arr0.shape)
            ):
                dims = list(arr0.shape)
                dims[axis] = size
                return arr0.with_shape(tuple(dims))
            return Arr(None, _dtype_of(arr0), tainted)
        if tail == "dynamic_slice":
            sizes = args[2] if len(args) > 2 else kwargs.get("slice_sizes")
            if isinstance(sizes, tuple) and all(
                isinstance(d, int) for d in sizes
            ):
                return Arr(sizes, _dtype_of(arr0), tainted)
            return Arr(None, _dtype_of(arr0), tainted)
        if tail == "dynamic_index_in_dim":
            axis = kwargs.get("axis", args[2] if len(args) > 2 else 0)
            keep = kwargs.get("keepdims", True)
            if (
                isinstance(arr0, Arr)
                and arr0.shape is not None
                and isinstance(axis, int)
                and axis < len(arr0.shape)
            ):
                dims = list(arr0.shape)
                if keep:
                    dims[axis] = 1
                else:
                    dims.pop(axis)
                return arr0.with_shape(tuple(dims))
            return Arr(None, _dtype_of(arr0), tainted)
        if tail in _DTYPE_NAMES:
            # jnp.float32(x)-style cast call
            return arr0 if isinstance(arr0, Arr) else arr0
        # unmodeled: keep array-ness when the sole array arg dominates
        return Unk(tainted=tainted)

    # -- shape helpers ------------------------------------------------------

    def matmul_shape(self, a, b) -> Any:
        if self.pallas is not None:
            self.pallas.note_dot(a, b)
        sa, sb = _shape_of(a), _shape_of(b)
        dt = _dtype_of(a) or _dtype_of(b)
        tainted = taint_of(a) or taint_of(b)
        if sa is None or sb is None or len(sa) < 2 or len(sb) < 2:
            return Arr(None, dt, tainted)
        batch = _broadcast(sa[:-2], sb[:-2])
        if batch is None:
            return Arr(None, dt, tainted)
        return Arr(tuple(batch) + (sa[-2], sb[-1]), dt, tainted)

    def _einsum(self, args) -> Any:
        spec = args[0] if args else None
        ops = args[1:]
        if not isinstance(spec, str) or "->" not in spec:
            return Arr(None, None, taint_of(ops))
        ins, out = spec.replace(" ", "").split("->")
        sizes: Dict[str, Any] = {}
        for term, op in zip(ins.split(","), ops):
            shape = _shape_of(op)
            if shape is None or len(term) != len(shape):
                continue
            for ch, dim in zip(term, shape):
                sizes.setdefault(ch, dim)
        shape = tuple(sizes.get(ch) for ch in out)
        dt = next((_dtype_of(o) for o in ops if _dtype_of(o)), None)
        return Arr(shape, dt, taint_of(ops))

    def reshape(self, arr, args, kwargs) -> Any:
        if not isinstance(arr, Arr):
            return UNKNOWN
        dims: Tuple = ()
        if len(args) == 1 and isinstance(args[0], (tuple, list)):
            dims = tuple(args[0])
        else:
            dims = tuple(args)
        if not dims:
            shape = kwargs.get("shape")
            dims = tuple(shape) if isinstance(shape, (tuple, list)) else ()
        if dims and all(isinstance(d, int) for d in dims):
            if -1 in dims:
                total = arr.elems()
                known = 1
                for d in dims:
                    if d != -1:
                        known *= d
                if total is not None and known and total % known == 0:
                    dims = tuple(
                        total // known if d == -1 else d for d in dims
                    )
                else:
                    return arr.with_shape(None)
            return arr.with_shape(dims)
        return arr.with_shape(None)

    def transpose(self, arr: Arr, axes) -> Any:
        if arr.shape is None:
            return arr
        if axes is None:
            return arr.with_shape(tuple(reversed(arr.shape)))
        if isinstance(axes, (tuple, list)) and all(
            isinstance(a, int) and a < len(arr.shape) for a in axes
        ) and len(axes) == len(arr.shape):
            return arr.with_shape(tuple(arr.shape[a] for a in axes))
        return arr.with_shape(None)

    def reduce(self, arr, args, kwargs) -> Any:
        if not isinstance(arr, Arr):
            return UNKNOWN
        axis = kwargs.get("axis", args[0] if args else None)
        keep = kwargs.get("keepdims", False)
        if arr.shape is None:
            return arr
        if axis is None:
            return Arr((), arr.dtype, arr.tainted)
        axes = axis if isinstance(axis, (tuple, list)) else (axis,)
        try:
            norm = {a % len(arr.shape) for a in axes}
        except (TypeError, ZeroDivisionError):
            return arr.with_shape(None)
        dims = [
            1 if i in norm and keep else d
            for i, d in enumerate(arr.shape)
            if keep or i not in norm
        ]
        return arr.with_shape(tuple(dims))

    # ------------------------------------------------------------------
    # expression evaluation
    # ------------------------------------------------------------------

    def eval(self, node: ast.AST, env: Env) -> Any:
        if not self.budget.tick():
            raise _Abort()
        method = getattr(self, f"_e_{type(node).__name__}", None)
        if method is None:
            return UNKNOWN
        return method(node, env)

    def _e_Constant(self, node, env):
        return node.value

    def _e_Name(self, node, env):
        v = env.get(node.id)
        if v is _MISSING:
            if node.id in _SAFE_BUILTINS:
                b = _SAFE_BUILTINS[node.id]
                return ModVal(f"__builtin__.{node.id}") if b is None else b
            return UNKNOWN
        return v

    def _e_Tuple(self, node, env):
        out = []
        for e in node.elts:
            if isinstance(e, ast.Starred):
                v = self.eval(e.value, env)
                if isinstance(v, (tuple, list)):
                    out.extend(v)
                else:
                    return UNKNOWN
            else:
                out.append(self.eval(e, env))
        return tuple(out)

    def _e_List(self, node, env):
        t = self._e_Tuple(node, env)
        return list(t) if isinstance(t, tuple) else t

    def _e_Set(self, node, env):
        t = self._e_Tuple(node, env)
        return UNKNOWN if is_unknown(t) else set(
            x if not isinstance(x, (Arr, Unk, list, dict)) else id(x)
            for x in t
        )

    def _e_Dict(self, node, env):
        out = {}
        for k, v in zip(node.keys, node.values):
            if k is None:
                spread = self.eval(v, env)
                if isinstance(spread, dict):
                    out.update(spread)
                continue
            key = self.eval(k, env)
            val = self.eval(v, env)
            if isinstance(key, (Arr, Unk, list, dict)):
                continue
            out[key] = val
        return out

    def _e_JoinedStr(self, node, env):
        return Unk()

    def _e_Lambda(self, node, env):
        return FuncVal("<lambda>", node, env)

    def _e_IfExp(self, node, env):
        cond = self.eval(node.test, env)
        if isinstance(cond, (bool, int, float, str)) or cond is None:
            return (
                self.eval(node.body, env)
                if cond
                else self.eval(node.orelse, env)
            )
        a = self.eval(node.body, env)
        b = self.eval(node.orelse, env)
        return UnionVal([a, b])

    def _e_Attribute(self, node, env):
        base = self.eval(node.value, env)
        return self.get_attr(base, node.attr, node)

    def _e_Subscript(self, node, env):
        base = self.eval(node.value, env)
        idx = self.eval(node.slice, env)
        return self.subscript(base, idx, node)

    def _e_Slice(self, node, env):
        lo = self.eval(node.lower, env) if node.lower else None
        hi = self.eval(node.upper, env) if node.upper else None
        step = self.eval(node.step, env) if node.step else None
        return slice(
            lo if isinstance(lo, int) or lo is None else None,
            hi if isinstance(hi, int) or hi is None else None,
            step if isinstance(step, int) or step is None else None,
        )

    def _e_Starred(self, node, env):
        return self.eval(node.value, env)

    def _e_UnaryOp(self, node, env):
        v = self.eval(node.operand, env)
        if isinstance(v, (int, float, bool)):
            try:
                if isinstance(node.op, ast.USub):
                    return -v
                if isinstance(node.op, ast.UAdd):
                    return +v
                if isinstance(node.op, ast.Not):
                    return not v
                if isinstance(node.op, ast.Invert):
                    return ~int(v)
            except Exception:
                return UNKNOWN
        if isinstance(v, Arr):
            return v
        return Unk(tainted=taint_of(v))

    _BINOPS = {
        ast.Add: lambda a, b: a + b,
        ast.Sub: lambda a, b: a - b,
        ast.Mult: lambda a, b: a * b,
        ast.Div: lambda a, b: a / b,
        ast.FloorDiv: lambda a, b: a // b,
        ast.Mod: lambda a, b: a % b,
        ast.Pow: lambda a, b: a ** b,
        ast.BitAnd: lambda a, b: a & b,
        ast.BitOr: lambda a, b: a | b,
        ast.BitXor: lambda a, b: a ^ b,
        ast.LShift: lambda a, b: a << b,
        ast.RShift: lambda a, b: a >> b,
    }

    def _e_BinOp(self, node, env):
        a = self.eval(node.left, env)
        b = self.eval(node.right, env)
        if isinstance(node.op, ast.MatMult):
            return self.matmul_shape(a, b)
        concrete = (int, float, bool, str, tuple, list)
        if isinstance(a, concrete) and isinstance(b, concrete):
            fn = self._BINOPS.get(type(node.op))
            if fn is not None:
                try:
                    return fn(a, b)
                except Exception:
                    return UNKNOWN
        if isinstance(a, Arr) or isinstance(b, Arr):
            shape = _broadcast(_shape_of(a), _shape_of(b))
            return Arr(
                shape,
                _dtype_of(a) if isinstance(a, Arr) else _dtype_of(b),
                taint_of(a) or taint_of(b),
            )
        return Unk(tainted=taint_of(a) or taint_of(b))

    def _e_BoolOp(self, node, env):
        vals = [self.eval(v, env) for v in node.values]
        if all(isinstance(v, (int, float, bool, str)) or v is None
               for v in vals):
            if isinstance(node.op, ast.And):
                out: Any = True
                for v in vals:
                    out = v
                    if not v:
                        return v
                return out
            for v in vals:
                if v:
                    return v
            return vals[-1]
        return Unk(tainted=any(taint_of(v) for v in vals))

    def _e_Compare(self, node, env):
        left = self.eval(node.left, env)
        vals = [self.eval(c, env) for c in node.comparators]

        def norm(v):
            # dtype-name ModVals compare like their names, so guards of
            # the form ``cache.dtype == jnp.int8`` stay concrete (the
            # decode kernels' precision dispatch)
            if isinstance(v, ModVal):
                dt = _as_dtype(v)
                if dt is not None:
                    return dt
            return v

        left = norm(left)
        vals = [norm(v) for v in vals]
        concrete = (int, float, bool, str)
        if isinstance(left, concrete) and all(
            isinstance(v, concrete) or v is None for v in vals
        ):
            cur = left
            try:
                for op, right in zip(node.ops, vals):
                    ok = {
                        ast.Eq: lambda a, b: a == b,
                        ast.NotEq: lambda a, b: a != b,
                        ast.Lt: lambda a, b: a < b,
                        ast.LtE: lambda a, b: a <= b,
                        ast.Gt: lambda a, b: a > b,
                        ast.GtE: lambda a, b: a >= b,
                        ast.Is: lambda a, b: a is b,
                        ast.IsNot: lambda a, b: a is not b,
                        ast.In: lambda a, b: a in b,
                        ast.NotIn: lambda a, b: a not in b,
                    }.get(type(op))
                    if ok is None or not ok(cur, right):
                        return False
                    cur = right
                return True
            except Exception:
                return UNKNOWN
        tainted = taint_of(left) or any(taint_of(v) for v in vals)
        if isinstance(left, Arr) or any(isinstance(v, Arr) for v in vals):
            shape = _shape_of(left)
            for v in vals:
                shape = _broadcast(shape, _shape_of(v))
            return Arr(shape, "bool", tainted)
        return Unk(tainted=tainted)

    def _e_ListComp(self, node, env):
        return self._comprehension(node, env, list)

    def _e_GeneratorExp(self, node, env):
        return self._comprehension(node, env, tuple)

    def _e_SetComp(self, node, env):
        return self._comprehension(node, env, list)

    def _e_DictComp(self, node, env):
        if len(node.generators) != 1:
            return UNKNOWN
        gen = node.generators[0]
        it = self.eval(gen.iter, env)
        if not isinstance(it, (list, tuple, range, dict)):
            return UNKNOWN
        items = list(it)[:_MAX_CONCRETE_ITERS]
        out = {}
        for item in items:
            child = Env(env)
            self.bind_target(gen.target, item, child)
            if all(
                bool(c) is True
                for c in (self.eval(i, child) for i in gen.ifs)
                if isinstance(c, (bool, int))
            ):
                k = self.eval(node.key, child)
                v = self.eval(node.value, child)
                if not isinstance(k, (Arr, Unk, list, dict)):
                    out[k] = v
        return out

    def _comprehension(self, node, env, factory):
        if len(node.generators) != 1:
            return UNKNOWN
        gen = node.generators[0]
        it = self.eval(gen.iter, env)
        pattern = _ring_perm_pattern(node) if factory is list else None
        if not isinstance(it, (list, tuple, range)):
            result = Unk()
            if pattern:
                result = Unk()
                result_pattern_holder = result
                setattr(result_pattern_holder, "tainted", False)
            if pattern:
                marker = PermPattern(pattern)
                return marker
            return result
        items = list(it)[:_MAX_CONCRETE_ITERS]
        out = []
        for item in items:
            child = Env(env)
            self.bind_target(gen.target, item, child)
            keep = True
            for cond in gen.ifs:
                c = self.eval(cond, child)
                if isinstance(c, (bool, int)):
                    keep = bool(c)
                if not keep:
                    break
            if keep:
                out.append(self.eval(node.elt, child))
        result = factory(out)
        if pattern and isinstance(result, list):
            return result  # concrete wins over the pattern
        return result

    def _resolve_super(self, name: str) -> Any:
        """``super().<name>`` from the innermost method whose receiver
        has a static class: resolve ``name`` starting AFTER the defining
        class in the receiver's linearization."""
        for fv in reversed(self._fn_stack):
            sv = fv.self_val
            if (
                isinstance(sv, SelfVal)
                and sv.klass is not None
                and fv.owner is not None
            ):
                bound = sv.klass.super_method(name, fv.owner, sv)
                return bound if bound is not None else UNKNOWN
        return UNKNOWN

    def _e_Call(self, node, env):
        super_name = is_super_call(node)
        if super_name is not None:
            fn = self._resolve_super(super_name)
        else:
            fn = self.eval(node.func, env)
        args: List[Any] = []
        for a in node.args:
            if isinstance(a, ast.Starred):
                v = self.eval(a.value, env)
                if isinstance(v, (tuple, list)):
                    args.extend(v)
                else:
                    args.append(UNKNOWN)
            else:
                args.append(self.eval(a, env))
        kwargs: Dict[str, Any] = {}
        for kw in node.keywords:
            if kw.arg is None:
                v = self.eval(kw.value, env)
                if isinstance(v, dict):
                    kwargs.update(
                        {k: x for k, x in v.items() if isinstance(k, str)}
                    )
            else:
                kwargs[kw.arg] = self.eval(kw.value, env)
        # annotate ppermute calls whose perm arg is the ring comprehension
        for kw in node.keywords:
            if kw.arg == "perm":
                pat = _ring_perm_pattern(kw.value)
                if pat is None and isinstance(kw.value, ast.Name):
                    bound = env.get(kw.value.id)
                    if isinstance(bound, PermPattern):
                        pat = bound.pattern
                if pat:
                    node._ddlb_perm_pattern = pat
        if len(node.args) > 2 and isinstance(fn, ModVal) and (
            fn.path.endswith("ppermute")
        ):
            pat = _ring_perm_pattern(node.args[2])
            if pat is None and isinstance(node.args[2], ast.Name):
                bound = env.get(node.args[2].id)
                if isinstance(bound, PermPattern):
                    pat = bound.pattern
            if pat:
                node._ddlb_perm_pattern = pat
        # builtin dispatch
        if isinstance(fn, ModVal) and fn.path.startswith("__builtin__."):
            return self._call_builtin(fn.path, args, kwargs, node, env)
        return self.call_value(fn, args, kwargs, node)

    def _call_builtin(self, path, args, kwargs, node, env):
        name = path.rsplit(".", 1)[-1]
        if name == "isinstance":
            return UNKNOWN
        if name == "getattr":
            if len(args) >= 2 and isinstance(args[1], str):
                return self.get_attr(args[0], args[1], node)
            return UNKNOWN
        if name == "print":
            return None
        return UNKNOWN

    # -- attribute / subscript semantics ------------------------------------

    def get_attr(self, base, attr: str, node) -> Any:
        if isinstance(base, ModVal):
            return ModVal(f"{base.path}.{attr}")
        if isinstance(base, SelfVal):
            return self.self_attr(base, attr, node)
        if isinstance(base, HostNS):
            return base.members.get(attr, UNKNOWN)
        if isinstance(base, MeshVal):
            if attr == "axis_names":
                return base.axes if base.axes is not None else UNKNOWN
            if attr == "shape":
                return dict(base.sizes) if base.sizes else UNKNOWN
            return UNKNOWN
        if isinstance(base, Arr):
            if attr == "shape":
                return base.shape if base.shape is not None else UNKNOWN
            if attr == "dtype":
                return base.dtype or UNKNOWN
            if attr == "ndim":
                return (
                    len(base.shape) if base.shape is not None else UNKNOWN
                )
            if attr == "T":
                return self.transpose(base, None)
            if attr == "at":
                return _AtVal(base)
            if attr in (
                "reshape", "transpose", "astype", "sum", "max", "min",
                "mean", "prod", "copy", "flatten", "ravel", "squeeze",
            ):
                return _ArrMethod(base, attr, self)
            return Unk(tainted=base.tainted)
        if isinstance(base, OpaqueReal):
            try:
                real = getattr(base.obj, attr)
            except Exception:
                return UNKNOWN
            return wrap_real(real)
        if isinstance(base, dict):
            if attr in ("get", "items", "keys", "values", "setdefault"):
                return _DictMethod(base, attr)
            return UNKNOWN
        if isinstance(base, list):
            if attr in ("append", "extend", "insert"):
                return _ListMethod(base, attr)
            return UNKNOWN
        if isinstance(base, UnionVal):
            return UnionVal(
                [self.get_attr(o, attr, node) for o in base.options]
            )
        if isinstance(base, FuncVal):
            return UNKNOWN
        hook = getattr(base, "ddlb_attr", None)
        if hook is not None:
            # the kernel-model value protocol (analysis.pallas.model):
            # Refs, semaphores and DMA handles resolve their own attrs
            return hook(attr, self, node)
        return Unk(tainted=taint_of(base))

    def self_attr(self, selfval: SelfVal, attr: str, node) -> Any:
        if attr in selfval.attrs:
            return selfval.attrs[attr]
        if attr in self.self_summaries:
            return _SelfSummary(self.self_summaries[attr], selfval)
        if selfval.klass is not None:
            got = selfval.klass.resolve_attr(attr, selfval, self)
            if got is not _MISSING:
                return got
        stub = selfval.stub
        if stub is not None:
            # plain data / property reads off the real stub instance
            try:
                real = getattr(stub, attr)
            except Exception:
                return UNKNOWN
            if callable(real) and not isinstance(real, (int, float)):
                fv = self.resolve_method(type(stub), attr, selfval)
                return fv if fv is not None else UNKNOWN
            return wrap_real(real)
        return UNKNOWN

    def resolve_method(self, cls, name: str, selfval) -> Optional[FuncVal]:
        """Find a method's AST through the MRO and bind it to selfval;
        set up its module's import environment."""
        import inspect
        import textwrap

        for klass in cls.__mro__:
            if name in vars(klass):
                fn = vars(klass)[name]
                if isinstance(fn, property):
                    fn = fn.fget
                fn = getattr(fn, "__func__", fn)
                try:
                    src = textwrap.dedent(inspect.getsource(fn))
                    path = inspect.getsourcefile(fn) or ""
                    tree = ast.parse(src)
                except (OSError, TypeError, SyntaxError):
                    return None
                fdef = tree.body[0]
                if not isinstance(fdef, ast.FunctionDef):
                    return None
                env = self.env_for_path(path)
                return FuncVal(name, fdef, env, self_val=selfval, path=path)
        return None

    def env_for_path(self, path: str) -> Env:
        """Module import env for a source file (cached)."""
        cache = getattr(self, "_env_cache", None)
        if cache is None:
            cache = self._env_cache = {}
        if path in cache:
            return cache[path]
        try:
            with open(path, "r", encoding="utf-8") as fh:
                tree = ast.parse(fh.read())
            env = module_alias_env(tree)
        except (OSError, SyntaxError):
            env = Env()
        cache[path] = env
        return env

    def subscript(self, base, idx, node) -> Any:
        if isinstance(base, (list, tuple, str)):
            if isinstance(idx, int):
                try:
                    return base[idx]
                except IndexError:
                    return UNKNOWN
            if isinstance(idx, slice):
                try:
                    return base[idx]
                except Exception:
                    return UNKNOWN
            return UNKNOWN
        if isinstance(base, dict):
            if isinstance(idx, (str, int, bool, float, tuple)):
                if idx in base:
                    return base[idx]
                return UNKNOWN
            # unknown selector over a small function table: union
            vals = list(base.values())
            if vals and all(isinstance(v, FuncVal) for v in vals):
                return UnionVal(vals)
            return UNKNOWN
        if isinstance(base, Arr):
            return self.index_arr(base, idx)
        if isinstance(base, UnionVal):
            return UnionVal(
                [self.subscript(o, idx, node) for o in base.options]
            )
        hook = getattr(base, "ddlb_subscript", None)
        if hook is not None:
            return hook(idx, self, node)
        return Unk(tainted=taint_of(base) or taint_of(idx))

    def index_arr(self, arr: Arr, idx) -> Any:
        if arr.shape is None:
            return Arr(None, arr.dtype, arr.tainted or taint_of(idx))
        items = idx if isinstance(idx, tuple) else (idx,)
        tainted = arr.tainted or taint_of(idx)
        dims: List[Any] = []
        pos = 0
        shape = list(arr.shape)
        for it in items:
            if it is None:  # newaxis
                dims.append(1)
                continue
            if it is Ellipsis:
                remaining = len(shape) - pos - sum(
                    1 for x in items[items.index(it) + 1:]
                    if x is not None and x is not Ellipsis
                )
                while pos < remaining:
                    dims.append(shape[pos])
                    pos += 1
                continue
            if pos >= len(shape):
                return Arr(None, arr.dtype, tainted)
            if isinstance(it, bool):
                return Arr(None, arr.dtype, tainted)
            if isinstance(it, int):
                pos += 1  # dim dropped
                continue
            if isinstance(it, slice):
                d = shape[pos]
                if isinstance(d, int):
                    lo, hi, step = it.indices(d) if all(
                        isinstance(x, int) or x is None
                        for x in (it.start, it.stop, it.step)
                    ) else (None, None, None)
                    if lo is None:
                        dims.append(None)
                    else:
                        dims.append(max(0, (hi - lo + (step - 1)) // step)
                                    if step and step > 0 else None)
                else:
                    dims.append(None)
                pos += 1
                continue
            if isinstance(it, Arr):
                # integer-array indexing: result gets the index shape
                dims.extend(
                    it.shape if it.shape is not None else (None,)
                )
                tainted = tainted or it.tainted
                pos += 1
                continue
            # unknown scalar index (e.g. a tainted table lookup)
            pos += 1
        dims.extend(shape[pos:])
        return Arr(tuple(dims), arr.dtype, tainted)

    # ------------------------------------------------------------------
    # statement execution
    # ------------------------------------------------------------------

    def exec_block(self, stmts, env: Env) -> None:
        for stmt in stmts:
            self.exec_stmt(stmt, env)

    def exec_stmt(self, node, env: Env) -> None:
        if not self.budget.tick():
            raise _Abort()
        kind = type(node).__name__
        method = getattr(self, f"_s_{kind}", None)
        if method is not None:
            method(node, env)

    def _s_Expr(self, node, env):
        self.eval(node.value, env)

    def _s_Return(self, node, env):
        value = self.eval(node.value, env) if node.value else None
        raise _Return(value)

    def _s_Pass(self, node, env):
        return None

    def _s_Break(self, node, env):
        raise _Break()

    def _s_Continue(self, node, env):
        raise _Continue()

    def _s_FunctionDef(self, node, env):
        value: Any = FuncVal(node.name, node, env)
        # apply decorators conservatively (innermost first): Pallas
        # kernels predicate code with ``@pl.when(cond)`` on NESTED defs,
        # which must execute-or-skip at interpretation time exactly like
        # trace time. A decorator the domain cannot model (``Unk``
        # result) keeps the undecorated FuncVal — @jax.custom_vjp et al
        # stay callable.
        for dec in reversed(node.decorator_list):
            try:
                dec_val = self.eval(dec, env)
                applied = self.call_value(dec_val, [value], {}, node)
            except _Abort:
                raise
            except Exception:
                break
            if is_unknown(applied):
                break
            value = applied
        env.set(node.name, value)

    def _s_AsyncFunctionDef(self, node, env):
        env.set(node.name, UNKNOWN)

    def _s_ClassDef(self, node, env):
        env.set(node.name, UNKNOWN)

    def _s_Import(self, node, env):
        for alias in node.names:
            name = alias.asname or alias.name.split(".")[0]
            env.set(name, ModVal(alias.name if alias.asname else name))

    def _s_ImportFrom(self, node, env):
        if not node.module:
            return
        for alias in node.names:
            if alias.name == "*":
                continue
            env.set(
                alias.asname or alias.name,
                ModVal(f"{node.module}.{alias.name}"),
            )

    def bind_target(self, target, value, env: Env) -> None:
        if isinstance(target, ast.Name):
            env.set(target.id, value)
        elif isinstance(target, (ast.Tuple, ast.List)):
            elts = target.elts
            if isinstance(value, (tuple, list)) and len(value) == len(elts):
                for t, v in zip(elts, value):
                    self.bind_target(t, v, env)
            else:
                for t in elts:
                    self.bind_target(t, Unk(tainted=taint_of(value)), env)
        elif isinstance(target, ast.Attribute):
            base = self.eval(target.value, env)
            if isinstance(base, SelfVal):
                base.attrs[target.attr] = value
        elif isinstance(target, ast.Subscript):
            base = self.eval(target.value, env)
            idx = self.eval(target.slice, env)
            if isinstance(base, dict) and isinstance(
                idx, (str, int, bool, float, tuple)
            ):
                base[idx] = value
        elif isinstance(target, ast.Starred):
            self.bind_target(target.value, UNKNOWN, env)

    def _s_Assign(self, node, env):
        value = self.eval(node.value, env)
        if isinstance(node.value, ast.ListComp) and isinstance(
            value, (Unk, PermPattern)
        ):
            pat = _ring_perm_pattern(node.value)
            if pat:
                value = PermPattern(pat)
        for target in node.targets:
            self.bind_target(target, value, env)

    def _s_AnnAssign(self, node, env):
        if node.value is not None:
            self.bind_target(
                node.target, self.eval(node.value, env), env
            )

    def _s_AugAssign(self, node, env):
        cur = self.eval(node.target, env)
        rhs = self.eval(node.value, env)
        fake = ast.BinOp(left=ast.Constant(value=0), op=node.op,
                         right=ast.Constant(value=0))
        concrete = (int, float, bool, str, tuple, list)
        if isinstance(cur, concrete) and isinstance(rhs, concrete):
            fn = self._BINOPS.get(type(node.op))
            if fn is not None:
                try:
                    self.bind_target(node.target, fn(cur, rhs), env)
                    return
                # a concrete fold that raises (div-by-zero, bad
                # operand mix) falls back to the symbolic binding
                # below — exactly the abstract-domain widening
                except Exception:  # ddlb: ignore[DDLB107]
                    pass
        del fake
        if isinstance(cur, Arr) or isinstance(rhs, Arr):
            shape = _broadcast(_shape_of(cur), _shape_of(rhs))
            self.bind_target(
                node.target,
                Arr(shape, _dtype_of(cur) or _dtype_of(rhs),
                    taint_of(cur) or taint_of(rhs)),
                env,
            )
        else:
            self.bind_target(
                node.target, Unk(taint_of(cur) or taint_of(rhs)), env
            )

    def _s_If(self, node, env):
        cond = self.eval(node.test, env)
        if isinstance(cond, (bool, int, float, str)) or cond is None:
            self.exec_block(node.body if cond else node.orelse, env)
            return
        tainted = taint_of(cond)
        trace = self.tracer.current()
        arms: List[list] = []
        forks: List[Env] = []
        for arm_i, block in enumerate((node.body, node.orelse)):
            fork = env.fork()
            frame = Frame("if", "if", tainted=tainted, arm=arm_i,
                          line=node.lineno)
            self.tracer.push_frame(frame)
            start = len(trace.entries) if trace else 0
            returned = False
            try:
                self.exec_block(block, fork)
            except _Return:
                returned = True
            except (_Break, _Continue):
                pass
            finally:
                self.tracer.pop_frame()
            arms.append(list(trace.entries[start:]) if trace else [])
            if not returned:
                forks.append(fork)
        self.tracer.record_divergences(
            arms, Frame("if", "if", tainted=tainted, line=node.lineno)
        )
        # merge forked bindings back into env
        if not forks:
            return
        names = set()
        for f in forks:
            names.update(f.vars)
        for name in names:
            vals = [f.vars.get(name, _MISSING) for f in forks]
            present = [v for v in vals if v is not _MISSING]
            if not present:
                continue
            first = present[0]
            if all(v is first for v in present) and len(present) == len(
                forks
            ):
                env.set(name, first)
            elif len(present) == 1 and len(forks) == 1:
                env.set(name, present[0])
            else:
                distinct = []
                for v in present:
                    if not any(v is d for d in distinct):
                        distinct.append(v)
                env.set(
                    name,
                    distinct[0] if len(distinct) == 1
                    else UnionVal(distinct),
                )

    def _s_For(self, node, env):
        it = self.eval(node.iter, env)
        if isinstance(it, (list, tuple, range)) and len(
            list(it)
        ) <= _MAX_CONCRETE_ITERS:
            items = list(it)
            label = f"{ast.unparse(node.target)} in {len(items)} items"
            frame = Frame("loop", label, line=node.lineno)
            self.tracer.push_frame(frame)
            try:
                for item in items:
                    self.bind_target(node.target, item, env)
                    try:
                        self.exec_block(node.body, env)
                    except _Continue:
                        continue
                    except _Break:
                        break
            finally:
                self.tracer.pop_frame()
            self.exec_block(node.orelse, env)
            return
        frame = Frame("loop", "for(?)", tainted=taint_of(it),
                      line=node.lineno)
        self.tracer.push_frame(frame)
        try:
            self.bind_target(node.target, Unk(taint_of(it)), env)
            try:
                self.exec_block(node.body, env)
            except (_Break, _Continue):
                pass
        finally:
            self.tracer.pop_frame()

    def _s_While(self, node, env):
        cond = self.eval(node.test, env)
        if isinstance(cond, (bool, int)) and not cond:
            self.exec_block(node.orelse, env)
            return
        frame = Frame("while", "while", tainted=taint_of(cond),
                      line=node.lineno)
        self.tracer.push_frame(frame)
        try:
            try:
                self.exec_block(node.body, env)
            except (_Break, _Continue):
                pass
        finally:
            self.tracer.pop_frame()

    def _s_With(self, node, env):
        for item in node.items:
            v = self.eval(item.context_expr, env)
            if item.optional_vars is not None:
                self.bind_target(item.optional_vars, v, env)
        self.exec_block(node.body, env)

    def _s_Try(self, node, env):
        try:
            self.exec_block(node.body, env)
        except (_Return, _Break, _Continue, _Abort):
            raise
        self.exec_block(node.finalbody, env)

    def _s_Raise(self, node, env):
        raise _Return(UNKNOWN)

    def _s_Assert(self, node, env):
        return None

    def _s_Delete(self, node, env):
        return None

    def _s_Global(self, node, env):
        return None

    def _s_Nonlocal(self, node, env):
        return None


class PermPattern:
    """Marker for a symbolic ring permutation (bijective for any d)."""

    __slots__ = ("pattern",)

    def __init__(self, pattern: str) -> None:
        self.pattern = pattern


class _ArrMethod:
    """Bound shape-level method on a symbolic array."""

    __slots__ = ("arr", "name", "interp")

    def __init__(self, arr, name, interp) -> None:
        self.arr = arr
        self.name = name
        self.interp = interp

    def __call__(self, args, kwargs, node, interp):
        a = self.arr
        if self.name == "reshape":
            return interp.reshape(a, args, kwargs)
        if self.name == "transpose":
            axes = args if len(args) > 1 else (args[0] if args else None)
            return interp.transpose(a, axes)
        if self.name == "astype":
            dt = _as_dtype(args[0]) if args else None
            return Arr(a.shape, dt or a.dtype, a.tainted)
        if self.name in ("sum", "max", "min", "mean", "prod"):
            return interp.reduce(a, args, kwargs)
        if self.name in ("copy",):
            return a
        if self.name in ("flatten", "ravel"):
            n = a.elems()
            return Arr((n,) if n is not None else None, a.dtype, a.tainted)
        if self.name == "squeeze":
            if a.shape is None:
                return a
            return a.with_shape(tuple(d for d in a.shape if d != 1))
        return UNKNOWN


class _AtVal:
    """``arr.at[idx].set/add`` → same shape as the base array."""

    __slots__ = ("arr",)

    def __init__(self, arr) -> None:
        self.arr = arr


class _DictMethod:
    __slots__ = ("d", "name")

    def __init__(self, d, name) -> None:
        self.d = d
        self.name = name

    def __call__(self, args, kwargs, node, interp):
        if self.name == "get":
            key = args[0] if args else None
            default = args[1] if len(args) > 1 else None
            if isinstance(key, (str, int, bool, float, tuple)):
                return self.d.get(key, default)
            return UNKNOWN
        if self.name == "items":
            return tuple(self.d.items())
        if self.name == "keys":
            return tuple(self.d.keys())
        if self.name == "values":
            return tuple(self.d.values())
        if self.name == "setdefault" and args:
            key = args[0]
            if isinstance(key, (str, int, bool, float, tuple)):
                return self.d.setdefault(
                    key, args[1] if len(args) > 1 else None
                )
        return UNKNOWN


class _ListMethod:
    __slots__ = ("lst", "name")

    def __init__(self, lst, name) -> None:
        self.lst = lst
        self.name = name

    def __call__(self, args, kwargs, node, interp):
        if self.name == "append" and args:
            self.lst.append(args[0])
        elif self.name == "extend" and args and isinstance(
            args[0], (list, tuple)
        ):
            self.lst.extend(args[0])
        elif self.name == "insert" and len(args) > 1 and isinstance(
            args[0], int
        ):
            self.lst.insert(args[0], args[1])
        return None


class _SelfSummary:
    """A summarized self-method (e.g. ``_make_int8_gemm``)."""

    __slots__ = ("handler", "selfval")

    def __init__(self, handler, selfval) -> None:
        self.handler = handler
        self.selfval = selfval

    def __call__(self, args, kwargs, node, interp):
        return self.handler(self.selfval, args, kwargs, node, interp)


def wrap_real(value) -> Any:
    """Wrap a real host value into the abstract domain."""
    if isinstance(value, (int, float, bool, str)) or value is None:
        return value
    if isinstance(value, (tuple, list)):
        wrapped = [wrap_real(v) for v in value]
        return tuple(wrapped) if isinstance(value, tuple) else wrapped
    if isinstance(value, dict):
        return {
            k: wrap_real(v)
            for k, v in value.items()
            if isinstance(k, (str, int, bool, float, tuple))
        }
    shape = getattr(value, "shape", None)
    if shape is not None and isinstance(shape, tuple):
        dt = str(getattr(value, "dtype", "") or "") or None
        if dt is not None and dt not in (
            "float32", "float64", "float16", "bfloat16", "int32",
            "int64", "int8", "bool",
        ):
            dt = {"int": "int64", "uint8": "int8"}.get(dt, None)
        return Arr(tuple(int(d) for d in shape), dt)
    return OpaqueReal(value)


# ---------------------------------------------------------------------------
# super()._input_setup() detection + per-file tracing
# ---------------------------------------------------------------------------


def is_super_call(node: ast.Call) -> Optional[str]:
    """``super().<name>(...)`` → the method name, else None."""
    fn = node.func
    if (
        isinstance(fn, ast.Attribute)
        and isinstance(fn.value, ast.Call)
        and isinstance(fn.value.func, ast.Name)
        and fn.value.func.id == "super"
    ):
        return fn.attr
    return None


def _contains_spmd_marker(fn_node) -> bool:
    for sub in ast.walk(fn_node):
        if isinstance(sub, ast.Call):
            f = sub.func
            name = (
                f.id
                if isinstance(f, ast.Name)
                else f.attr if isinstance(f, ast.Attribute) else ""
            )
            if name in (
                "shard_map", "shard_map_compat", "make_async_remote_copy",
                "psum", "pmean", "ppermute", "all_gather", "psum_scatter",
                "all_to_all", "axis_index",
            ):
                return True
    return False


def build_module_env(
    tree: ast.Module, interp: "Interpreter", rel: str = ""
) -> Env:
    """A module's interpretation env: imports as ``ModVal`` paths plus
    module-level simple constants and function defs (shared by the
    per-file tracer and the cross-module resolver). ``rel`` stamps each
    ``FuncVal`` with its defining file so cross-module findings (the
    Pallas kernel census above all) anchor at the right path."""
    env = module_alias_env(tree)
    for stmt in tree.body:
        try:
            if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                interp.exec_stmt(stmt, env)
            elif isinstance(stmt, ast.FunctionDef):
                env.set(
                    stmt.name, FuncVal(stmt.name, stmt, env, path=rel)
                )
        except (_Abort, _Return, _Break, _Continue):
            break
    return env


def trace_file(ctx) -> List[ShardMapTrace]:
    """Best-effort per-file collective tracing (fixtures + the repo
    sweep): every function/method containing a ``shard_map`` (or remote
    DMA) marker is interpreted with unknown parameters; traces are
    cached on the ``FileContext``."""
    cached = getattr(ctx, "_ddlb_spmd_traces", None)
    if cached is not None:
        return cached
    traces: List[ShardMapTrace] = []
    if ctx.tree is not None:
        tracer = Tracer(ctx.rel, mode="file")
        budget = Budget()
        interp = Interpreter(tracer, budget=budget)
        module_env = build_module_env(ctx.tree, interp, rel=ctx.rel)
        candidates: List[Tuple[ast.FunctionDef, Optional[str]]] = []
        for stmt in ctx.tree.body:
            if isinstance(stmt, ast.FunctionDef) and _contains_spmd_marker(
                stmt
            ):
                candidates.append((stmt, None))
            elif isinstance(stmt, ast.ClassDef):
                for sub in stmt.body:
                    if isinstance(
                        sub, ast.FunctionDef
                    ) and _contains_spmd_marker(sub):
                        candidates.append((sub, stmt.name))
        for fdef, _cls in candidates:
            fv = FuncVal(fdef.name, fdef, module_env)
            params = [
                a.arg for a in fdef.args.posonlyargs + fdef.args.args
            ]
            args: List[Any] = []
            for p in params:
                if p == "self":
                    args.append(SelfVal())
                else:
                    args.append(UNKNOWN)
            try:
                interp.call_function(fv, args, {})
            except (_Abort, _Return):
                pass
            except RecursionError:  # pragma: no cover - deep fixture
                pass
        traces = tracer.traces
    ctx._ddlb_spmd_traces = traces
    return traces
