"""Family-mode tracing: drive each member's measured fn symbolically.

Where ``interp.trace_file`` walks one file with unknown parameters (the
per-file DDLB120-122 surface), this module reconstructs each registered
primitive member the way the benchmark worker would — canonical shapes,
default + per-member options, a concrete partition count — and interprets
its ``_input_setup`` and measured ``_fn`` end to end, WITHOUT importing
any of it (the analysis tier stays accelerator-free): classes resolve
statically from source (``StaticClass``), cross-module helpers interpret
from their own files (``ModuleResolver``), and the host-only pieces the
interpreter cannot model (seeded operand construction, device placement)
are summarized by shape.

The result per (member, config) is a ``MemberReport``: the collective
trace of the measured region, the trace-derived per-device wire bytes
under the canonical axis sizes, and the family's ``wire_bytes()`` formula
evaluated over the same shapes — the DDLB123 drift comparison, and the
``scripts/analyze.py --spmd-trace`` debugging surface.

Verification statuses:

- ``verified``: the trace sized every collective and the totals agree
  within ``WIRE_RTOL``;
- ``drift``: both sides resolved and DISAGREE — the DDLB123 finding;
- ``opaque``: the measured region shows no collectives but the formula
  expects wire (compiler-scheduled members: xla_gspmd's implicit GSPMD
  collectives, pallas kernel-body DMAs) — statically uncheckable, listed
  but not a finding;
- ``unresolved``: the trace truncated or a payload would not size;
- ``skipped``: compute-only members (no wire by contract) and the
  families whose cost model declares no wire term at all
  (transformer_step / transformer_decode price compute/HBM only).
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ddlb_tpu.analysis.spmd import interp as interp_mod
from ddlb_tpu.analysis.spmd.interp import (
    _MISSING,
    Budget,
    Env,
    HostNS,
    Interpreter,
    SelfVal,
    module_alias_env,
)
from ddlb_tpu.analysis.spmd.trace import (
    Arr,
    FuncVal,
    MeshVal,
    OpaqueReal,
    ShardMapVal,
    Tracer,
    UnionVal,
)

#: relative drift tolerated between trace wire bytes and the formula —
#: the formulas are exact closed forms, so this only absorbs float noise
WIRE_RTOL = 0.02

#: canonical per-family shapes: small, every divisibility constraint of
#: every member satisfied at d partitions (the shapes DDLB123 evaluates
#: under; mirrors the tier-1 test shapes, not the sweep shapes)
FAMILY_SHAPES: Dict[str, Dict[str, int]] = {
    "tp_columnwise": {"m": 256, "n": 128, "k": 64, "d": 4},
    "tp_rowwise": {"m": 256, "n": 128, "k": 64, "d": 4},
    "dp_allreduce": {"m": 128, "n": 64, "k": 64, "d": 4},
    "ep_alltoall": {"m": 256, "n": 64, "k": 64, "d": 4},
    "cp_ring_attention": {"m": 128, "n": 64, "k": 16, "d": 4},
    "pp_pipeline": {"m": 128, "n": 64, "k": 64, "d": 4},
    "collectives": {"m": 256, "n": 1, "k": 64, "d": 4},
    "transformer_step": {"m": 64, "n": 64, "k": 64, "d": 4},
    "transformer_decode": {"m": 64, "n": 64, "k": 64, "d": 4},
    "serving_load": {"m": 16, "n": 32, "k": 64, "d": 4},
}

#: families whose registered cost model prices no wire term at all —
#: their wire_bytes (when any) is not a claim DDLB123 can hold them to
NO_WIRE_TERM_FAMILIES = (
    "transformer_step",
    "transformer_decode",
    "serving_load",
)

#: the REGISTERED opaque set: every (family, member) whose wire is
#: statically uncheckable must carry a justification here, and DDLB123
#: fails on any member that goes opaque WITHOUT one — a new member can
#: no longer land unverifiable with a shrug (same shrink-only
#: discipline as the findings baseline). The pallas members left this
#: set when the kernel model (``analysis.pallas``) began tracing their
#: in-kernel RDMA rings; only the compiler-scheduled class remains.
OPAQUE_JUSTIFIED: Dict[Tuple[str, str], str] = {
    (family, "xla_gspmd"): (
        "GSPMD inserts the collectives during XLA partitioning; the "
        "measured fn contains only the sharded computation, so no "
        "source-level trace can see the wire"
    )
    for family in (
        "tp_columnwise", "tp_rowwise", "dp_allreduce", "ep_alltoall",
        "pp_pipeline", "collectives",
    )
}

#: per-(family, member) option matrices where the defaults don't cover
#: the wire-relevant behavior; one MemberReport per entry
MEMBER_CONFIGS: Dict[Tuple[str, str], List[Dict[str, Any]]] = {
    ("collectives", "jax_spmd"): [
        {"op": "all_gather"},
        {"op": "all_reduce", "strategy": "psum"},
        {"op": "all_reduce", "strategy": "rs_ag"},
        {"op": "reduce_scatter"},
        {"op": "all_to_all"},
        {"op": "ppermute"},
    ],
    ("collectives", "xla_gspmd"): [
        {"op": "all_gather"},
        {"op": "all_reduce"},
        {"op": "reduce_scatter"},
        {"op": "all_to_all"},
        {"op": "ppermute"},
    ],
    # only the ops the member's ALLOWED_VALUES admits: the ring kernels
    # cover the gather/reduce shapes; a2a/ppermute live with the lax
    # members (driving an unsupported op would silently fall through to
    # the all_reduce path and "drift" against the wrong formula)
    ("collectives", "pallas"): [
        {"op": "all_gather"},
        {"op": "all_reduce"},
        {"op": "reduce_scatter"},
    ],
    # the fused RDMA kernels: default (xla_collective) plus the whole-
    # primitive Pallas program, whose in-kernel ring the kernel model
    # traces hop by hop (the de-opaqued members)
    ("tp_columnwise", "pallas"): [
        {},
        {"algorithm": "ring_rdma"},
    ],
    ("tp_rowwise", "pallas"): [
        {},
        {"algorithm": "ring_rdma"},
    ],
    ("dp_allreduce", "pallas"): [
        {},
        {"algorithm": "ring_rdma"},
    ],
    ("ep_alltoall", "pallas"): [
        {},
        {"algorithm": "a2a_rdma"},
    ],
    ("tp_columnwise", "overlap"): [
        {"algorithm": "default"},
        {"algorithm": "coll_pipeline", "s": 8},
        {"algorithm": "p2p_pipeline", "direction": "unidirectional"},
        {"algorithm": "p2p_pipeline", "direction": "bidirectional"},
        {"algorithm": "chunked", "chunk_count": 1},
        {"algorithm": "chunked", "chunk_count": 2},
    ],
    # the chunked-fusion engine members: chunking must not change the
    # total wire, only the schedule (ISSUE 10 zero-drift invariant) —
    # checked at two pipeline depths per family
    ("tp_rowwise", "overlap"): [
        {},
        {"algorithm": "chunked", "chunk_count": 1},
        {"algorithm": "chunked", "chunk_count": 2},
    ],
    ("dp_allreduce", "overlap"): [
        {},
        {"algorithm": "chunked", "chunk_count": 1},
        {"algorithm": "chunked", "chunk_count": 2},
    ],
    ("ep_alltoall", "overlap"): [
        {},
        {"algorithm": "chunked", "chunk_count": 1},
        {"algorithm": "chunked", "chunk_count": 2},
    ],
    # both quantization modes move wire (static: pre-quantized shard
    # gathered; dynamic: quantize-in-step then gather) — check each
    ("tp_columnwise", "quantized"): [
        {"quantize": "static"},
        {"quantize": "dynamic"},
    ],
    ("tp_rowwise", "quantized"): [
        {"quantize": "static"},
        {"quantize": "dynamic"},
    ],
    ("dp_allreduce", "quantized"): [
        {"quantize": "static"},
        {"quantize": "dynamic"},
    ],
    ("ep_alltoall", "quantized"): [
        {"quantize": "static"},
        {"quantize": "dynamic"},
    ],
    # the topology-adaptive members (ISSUE 16): every decomposition the
    # member can resolve to, pinned — ``auto`` consults live-world
    # signals (fault plan, health bank) the static tier must not read
    ("collectives", "jax_spmd_hier"): [
        {"op": "all_gather", "composition": "hierarchical"},
        {"op": "all_reduce", "composition": "hierarchical"},
        {"op": "reduce_scatter", "composition": "hierarchical"},
        {"op": "all_to_all", "composition": "hierarchical"},
        {"op": "all_reduce", "composition": "flat"},
    ],
    ("collectives", "jax_spmd_striped"): [{}],
    ("dp_allreduce", "jax_spmd_hier"): [
        {"composition": "hierarchical"},
        {"composition": "flat"},
    ],
    ("dp_allreduce", "jax_spmd_striped"): [{}],
    ("ep_alltoall", "jax_spmd_hier"): [
        {"composition": "hierarchical"},
        {"composition": "flat"},
    ],
    ("ep_alltoall", "jax_spmd_striped"): [{}],
}


# ---------------------------------------------------------------------------
# static class resolution (no imports — classes from source)
# ---------------------------------------------------------------------------


class StaticClass:
    """A class resolved purely from its AST: methods, properties and
    class attributes looked up through an approximate (left-to-right
    DFS, deduplicated) linearization of its package-local bases."""

    def __init__(
        self,
        name: str,
        node: ast.ClassDef,
        env: Env,
        bases: List["StaticClass"],
        rel: str,
    ) -> None:
        self.name = name
        self.node = node
        self.env = env  # defining module's env
        self.bases = bases
        self.rel = rel
        self._mro: Optional[List["StaticClass"]] = None
        self._attr_cache: Dict[str, Any] = {}

    def mro(self) -> List["StaticClass"]:
        if self._mro is None:
            out: List[StaticClass] = []
            seen: set = set()

            def visit(cls: StaticClass) -> None:
                if id(cls) in seen:
                    return
                seen.add(id(cls))
                out.append(cls)
                for b in cls.bases:
                    visit(b)

            visit(self)
            self._mro = out
        return self._mro

    def _method_in(self, cls: "StaticClass", name: str):
        for stmt in cls.node.body:
            if isinstance(stmt, ast.FunctionDef) and stmt.name == name:
                return stmt
        return None

    def _class_assign_in(self, cls: "StaticClass", name: str):
        for stmt in cls.node.body:
            if isinstance(stmt, ast.Assign):
                for t in stmt.targets:
                    if isinstance(t, ast.Name) and t.id == name:
                        return stmt.value
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                if (
                    isinstance(stmt.target, ast.Name)
                    and stmt.target.id == name
                ):
                    return stmt.value
        return None

    def find_method(
        self, name: str, after: Optional["StaticClass"] = None
    ) -> Optional[Tuple["StaticClass", ast.FunctionDef]]:
        chain = self.mro()
        if after is not None and after in chain:
            chain = chain[chain.index(after) + 1:]
        for cls in chain:
            fdef = self._method_in(cls, name)
            if fdef is not None:
                return cls, fdef
        return None

    def class_attr(self, name: str, interp: Interpreter) -> Any:
        """First class-level assignment of ``name`` in the mro,
        evaluated in its defining module's env."""
        for cls in self.mro():
            value = self._class_assign_in(cls, name)
            if value is not None:
                return interp.eval(value, cls.env)
        return _MISSING

    @staticmethod
    def _is_property(fdef: ast.FunctionDef) -> bool:
        return any(
            isinstance(dec, ast.Name) and dec.id == "property"
            for dec in fdef.decorator_list
        )

    def _bind(
        self, owner: "StaticClass", fdef: ast.FunctionDef, selfval: SelfVal
    ) -> FuncVal:
        return FuncVal(
            fdef.name, fdef, owner.env, self_val=selfval, path=owner.rel,
            owner=owner,
        )

    def resolve_attr(
        self, attr: str, selfval: SelfVal, interp: Interpreter
    ) -> Any:
        """The ``Interpreter.self_attr`` hook: method (bound), property
        (evaluated now), or class attribute; ``_MISSING`` otherwise."""
        found = self.find_method(attr)
        if found is not None:
            owner, fdef = found
            fv = self._bind(owner, fdef, selfval)
            if self._is_property(fdef):
                try:
                    return interp.call_function(fv, [], {})
                except Exception:
                    return interp_mod.UNKNOWN
            return fv
        value = self.class_attr(attr, interp)
        if value is not _MISSING:
            return value
        return _MISSING

    def super_method(
        self, name: str, after: "StaticClass", selfval: SelfVal
    ) -> Optional[FuncVal]:
        found = self.find_method(name, after=after)
        if found is None:
            return None
        owner, fdef = found
        return self._bind(owner, fdef, selfval)


class ClassRegistry:
    """Dotted class path -> ``StaticClass``, parsing files on demand."""

    def __init__(self, root: Path) -> None:
        self.root = Path(root)
        self._modules: Dict[str, Tuple[Optional[ast.Module], Env]] = {}
        self._classes: Dict[Tuple[str, str], Optional[StaticClass]] = {}
        self._interp = Interpreter(Tracer("<registry>", mode="family"))

    def module(self, dotted: str) -> Tuple[Optional[ast.Module], Env]:
        """Parse ``ddlb_tpu.x.y`` into (tree, module env) once."""
        if dotted in self._modules:
            return self._modules[dotted]
        rel = dotted.replace(".", "/")
        tree: Optional[ast.Module] = None
        for cand in (
            self.root / (rel + ".py"), self.root / rel / "__init__.py"
        ):
            if cand.is_file():
                # the engine's mtime-keyed parse cache: repeated sweeps
                # (DDLB123 + the pallas census + tests in one process)
                # parse each ops/primitives module once, not per driver
                from ddlb_tpu.analysis.core import build_context

                try:
                    tree = build_context(cand, root=self.root).tree
                except (OSError, UnicodeDecodeError):
                    tree = None
                break
        if tree is None:
            self._modules[dotted] = (None, Env())
            return self._modules[dotted]
        env = interp_mod.build_module_env(
            tree, self._interp, rel=cand.relative_to(self.root).as_posix()
        )
        self._modules[dotted] = (tree, env)
        return self._modules[dotted]

    def resolve(self, module: str, name: str) -> Optional[StaticClass]:
        key = (module, name)
        if key in self._classes:
            return self._classes[key]
        self._classes[key] = None  # cycle guard
        tree, env = self.module(module)
        if tree is None:
            return None
        node = next(
            (
                stmt
                for stmt in tree.body
                if isinstance(stmt, ast.ClassDef) and stmt.name == name
            ),
            None,
        )
        if node is None:
            # re-exported class: follow the module's import of the name
            bound = env.get(name)
            if isinstance(bound, interp_mod.ModVal):
                mod, _, sym = bound.path.rpartition(".")
                if mod and mod != module:
                    got = self.resolve(mod, sym)
                    self._classes[key] = got
                    return got
            return None
        bases: List[StaticClass] = []
        for b in node.bases:
            base_name = (
                b.id if isinstance(b, ast.Name)
                else b.attr if isinstance(b, ast.Attribute) else None
            )
            if base_name in (None, "ABC", "object", "Protocol"):
                continue
            bound = env.get(base_name)
            if isinstance(bound, interp_mod.ModVal):
                mod, _, sym = bound.path.rpartition(".")
                if mod.startswith("ddlb_tpu"):
                    sub = self.resolve(mod, sym)
                    if sub is not None:
                        bases.append(sub)
            else:
                # same-module base class
                sub = self.resolve(module, base_name)
                if sub is not None:
                    bases.append(sub)
        rel = module.replace(".", "/") + ".py"
        if not (self.root / rel).is_file():
            rel = module.replace(".", "/") + "/__init__.py"
        cls = StaticClass(name, node, env, bases, rel)
        self._classes[key] = cls
        return cls


class ModuleResolver:
    """Dotted ``ddlb_tpu.*`` value path -> interpretable value.

    ``ddlb_tpu.ops.flash_attention.flash_attention`` resolves to a
    ``FuncVal`` carrying that module's own import env (so intra-module
    helpers and constants resolve); re-exports follow one hop per call.
    Unknown / non-function symbols return ``None`` (the caller falls
    back to the shape-op table).
    """

    def __init__(self, registry: ClassRegistry) -> None:
        self.registry = registry

    def __call__(self, path: str, _depth: int = 0) -> Any:
        if _depth > 4 or not path.startswith("ddlb_tpu"):
            return None
        module, _, symbol = path.rpartition(".")
        if not module or not symbol:
            return None
        tree, env = self.registry.module(module)
        if tree is None:
            # ``pkg.module.ClassName.method`` — the explicit
            # parent-class call idiom (``JaxSPMDCollectives.
            # _input_setup(self)``, the composed members' flat
            # delegation): resolve the class statically and return the
            # unbound method; the call site passes self positionally
            mod2, _, cls_name = module.rpartition(".")
            if mod2:
                klass = self.registry.resolve(mod2, cls_name)
                if klass is not None:
                    found = klass.find_method(symbol)
                    if found is not None:
                        owner, fdef = found
                        return FuncVal(
                            fdef.name, fdef, owner.env, path=owner.rel,
                            owner=owner,
                        )
            return None
        bound = env.get(symbol)
        if bound is _MISSING:
            return None
        if isinstance(bound, interp_mod.ModVal):
            if bound.path == path:
                return None
            return self(bound.path, _depth + 1)
        if isinstance(bound, FuncVal):
            return bound
        # concrete module-level constant (itemsize tables etc.) — return
        # a host closure so call sites still work, values pass through
        return None


# ---------------------------------------------------------------------------
# the member driver
# ---------------------------------------------------------------------------


class MemberReport:
    """One (member, config) verification record."""

    def __init__(
        self, family: str, member: str, options: Dict[str, Any]
    ) -> None:
        self.family = family
        self.member = member
        self.options = dict(options)
        self.rel = ""  # member module repo-relative path
        self.traces: List[Any] = []
        self.wire_traced: Optional[float] = None
        self.wire_formula: Optional[float] = None
        self.status = "unresolved"
        self.reason = ""
        #: anchor for DDLB123 findings: the defining wire_bytes() line
        self.formula_rel = ""
        self.formula_line = 0
        #: schedule-export metadata (the simulator front-end's inputs):
        #: the statically evaluated ``flops()`` census, the member's
        #: ``COST_SCHEDULE``, and the chunked-engine pipeline depth
        self.flops_formula: Optional[float] = None
        self.cost_schedule = "sequential"
        self.chunk_count: Optional[int] = None

    def label(self) -> str:
        opts = ",".join(f"{k}={v}" for k, v in sorted(self.options.items()))
        return f"{self.family}/{self.member}" + (f"[{opts}]" if opts else "")

    def describe(self) -> List[str]:
        traced = (
            "?" if self.wire_traced is None else f"{self.wire_traced:.0f}"
        )
        formula = (
            "?" if self.wire_formula is None else f"{self.wire_formula:.0f}"
        )
        head = (
            f"{self.label()}: {self.status} "
            f"(trace={traced} B, formula={formula} B"
            + (f"; {self.reason}" if self.reason else "")
            + ")"
        )
        lines = [head]
        for t in self.traces:
            lines.extend("  " + ln for ln in t.describe())
        return lines


def _registry_table() -> Dict[str, Dict[str, Tuple[str, str]]]:
    """The primitive registry's (module, class) table — imported, not
    parsed: ``ddlb_tpu.primitives.registry`` is stdlib-only by design."""
    from ddlb_tpu.primitives.registry import _REGISTRY

    return _REGISTRY


def _axis_sizes_for(
    family: str, d: int, explicit: Optional[Dict[str, int]] = None
) -> Dict[str, int]:
    """Canonical hybrid/torus axis sizes for a ``d``-device trace.

    ``explicit`` (extra ``dcn``/``ici``/``sx``/``sy`` keys riding on a
    shapes dict) pins the split instead of the near-square default —
    the simulator's twin check traces members at the axis sizes of the
    topology it replays them on (``pods``/``ici_mesh``), not at the
    canonical census split."""
    sizes = {"tp": d, "_barrier": d}
    explicit = explicit or {}
    # the hierarchical members build a 2-D (dcn, ici) mesh
    ici = explicit.get("ici")
    dcn = explicit.get("dcn")
    if ici is None and dcn:
        ici = max(1, d // int(dcn))
    if ici is None:
        ici = max(1, int(round(d ** 0.5)))
    sizes["ici"] = int(ici)
    sizes["dcn"] = int(dcn) if dcn else max(1, d // sizes["ici"])
    # the striped members additionally split the slice into its torus
    # factorization (runtime.torus_mesh / cost.torus_factors)
    from ddlb_tpu.perfmodel.cost import torus_factors

    sx, sy = explicit.get("sx"), explicit.get("sy")
    if sx is None or sy is None:
        sx, sy = torus_factors(sizes["ici"])
    sizes["sx"], sizes["sy"] = int(sx), int(sy)
    return sizes


def _self_summaries(shapes: Dict[str, int]) -> Dict[str, Any]:
    """Host-only Primitive methods summarized by shape: seeded operand
    construction and device placement never execute for real."""

    def _host_operands(selfval, args, kwargs, node, interp):
        m = selfval.attrs.get("m")
        n = selfval.attrs.get("n")
        k = selfval.attrs.get("k")
        dt = selfval.attrs.get("dtype")
        return (Arr((m, k), dt), Arr((k, n), dt))

    def _host_qkv(selfval, args, kwargs, node, interp):
        m = selfval.attrs.get("m")
        dt = selfval.attrs.get("dtype")
        klass = selfval.klass
        heads = kvh = None
        if klass is not None:
            heads = klass.resolve_attr("num_heads", selfval, interp)
            kvh = klass.resolve_attr("kv_heads", selfval, interp)
        k = selfval.attrs.get("k")
        heads = heads if isinstance(heads, int) else None
        kvh = kvh if isinstance(kvh, int) else heads
        return (
            Arr((m, heads, k), dt),
            Arr((m, kvh, k), dt),
            Arr((m, kvh, k), dt),
        )

    def _device_put(selfval, args, kwargs, node, interp):
        dt = selfval.attrs.get("dtype")
        host = args[0] if args else None
        if isinstance(host, Arr):
            return Arr(host.shape, dt)
        return Arr(None, dt)

    def _host_tokens_experts(selfval, args, kwargs, node, interp):
        # ep_alltoall: seeded tokens [m, k] + per-partition expert
        # weights [d, k, n] (host arrays; _device_put casts)
        m = selfval.attrs.get("m")
        n = selfval.attrs.get("n")
        k = selfval.attrs.get("k")
        d = selfval.attrs.get("num_partitions")
        return (
            Arr((m, k), "float32"),
            Arr((d, k, n) if isinstance(d, int) else None, "float32"),
        )

    def _host_chain_operands(selfval, args, kwargs, node, interp):
        # pp_pipeline: seeded tokens [m, k] + stage weights [S, k, n];
        # host arrays are float32/float64 generators, _device_put casts
        m = selfval.attrs.get("m")
        n = selfval.attrs.get("n")
        k = selfval.attrs.get("k")
        stages = None
        if selfval.klass is not None:
            stages = selfval.klass.resolve_attr("num_stages", selfval, interp)
        stages = stages if isinstance(stages, int) else None
        return (
            Arr((m, k), "float32"),
            Arr((stages, k, n) if stages is not None else None, "float32"),
        )

    # the ComposedMember (primitives/topo_compose.py) topology helpers,
    # summarized from the SAME canonical axis sizes the trace resolves
    # under: the live policy reads env state (fault plan, health bank,
    # degraded stamp) the static tier must not consult, so the summary
    # is the healthy-world restriction of select_composition — pinned
    # compositions pass through, ``auto`` follows the topology alone

    def _resolved_composition(selfval, args, kwargs, node, interp):
        options = selfval.attrs.get("options")
        requested = "auto"
        if isinstance(options, dict):
            requested = options.get("composition", "auto")
        if requested != "auto":
            return requested
        return (
            "hierarchical"
            if interp.axis_sizes.get("dcn", 1) > 1
            else "flat"
        )

    def _two_level(selfval, args, kwargs, node, interp):
        d = selfval.attrs.get("num_partitions")
        inter = interp.axis_sizes.get("dcn", 1)
        if not isinstance(d, int) or inter > d or d % inter:
            return (d, 1)
        return (d // inter, inter)

    def _torus(selfval, args, kwargs, node, interp):
        return (
            interp.axis_sizes.get("sx", 1),
            interp.axis_sizes.get("sy", 1),
        )

    def _stripe_count(selfval, args, kwargs, node, interp):
        sizes = _torus(selfval, args, kwargs, node, interp)
        return max(1, sum(1 for a in sizes if a > 1))

    return {
        "_host_operands": _host_operands,
        "_host_qkv": _host_qkv,
        "_device_put": _device_put,
        "_host_chain_operands": _host_chain_operands,
        "_host_tokens_experts": _host_tokens_experts,
        "_resolved_composition": _resolved_composition,
        "_two_level": _two_level,
        "_torus": _torus,
        "_stripe_count": _stripe_count,
    }


def _path_summaries() -> Dict[str, Any]:
    """Dotted-path handlers for host-only helpers the interpreter should
    run FOR REAL: the pipeline schedule builder is pure host numpy (no
    jax), and its dense tables — ``ticks`` above all — are exactly what
    sizes the schedules member's unconditional per-tick ppermutes."""

    def _build_schedule(args, kwargs, node, interp):
        from ddlb_tpu.utils.pipeline_schedule import build_schedule

        try:
            return OpaqueReal(build_schedule(*args, **kwargs))
        except Exception:
            return interp_mod.UNKNOWN

    return {
        "ddlb_tpu.utils.pipeline_schedule.build_schedule": _build_schedule,
    }


def _runtime_ns(shapes: Dict[str, int], axis_sizes: Dict[str, int]) -> HostNS:
    d = shapes["d"]

    def _mesh(args, kwargs, node, interp):
        axes = args[0] if args else ("tp",)
        if isinstance(axes, str):
            axes = (axes,)
        if isinstance(axes, (tuple, list)) and all(
            isinstance(a, str) for a in axes
        ):
            return MeshVal(
                tuple(axes),
                {a: axis_sizes.get(a, d) for a in axes},
            )
        return interp_mod.UNKNOWN

    def _hybrid_mesh(args, kwargs, node, interp):
        return MeshVal(
            ("dcn", "ici"),
            {"dcn": axis_sizes["dcn"], "ici": axis_sizes["ici"]},
        )

    def _torus_mesh(args, kwargs, node, interp):
        return MeshVal(
            ("dcn", "sx", "sy"),
            {
                "dcn": axis_sizes["dcn"],
                "sx": axis_sizes.get("sx", 1),
                "sy": axis_sizes.get("sy", 1),
            },
        )

    return HostNS(
        {
            "mesh": _mesh,
            "transport_mesh": _mesh,
            "hybrid_mesh": _hybrid_mesh,
            "torus_mesh": _torus_mesh,
            # the static world has as many slices as the dcn axis the
            # hybrid/torus members factor over — one number, both sides
            # (formula and trace) of the DDLB123 comparison
            "num_slices": axis_sizes.get("dcn", 1),
            "num_devices": d,
            "local_devices": (interp_mod.UNKNOWN,),
            "process_id": 0,
            "num_processes": 1,
            "platform": "cpu",
        }
    )


def _static_options(
    klass: StaticClass, interp: Interpreter, overrides: Dict[str, Any]
) -> Dict[str, Any]:
    """``option_schema`` semantics statically: ``BASE_OPTIONS`` under
    ``DEFAULT_OPTIONS``, each merged base-first across the mro — the
    subclass idiom ``{**Parent.DEFAULT_OPTIONS, ...}`` spreads a
    cross-module attribute the static evaluator cannot expand, so the
    reverse-mro walk recovers those inherited defaults from the
    parents' own literals (a subclass that deliberately DROPS a parent
    key is approximated as keeping it; options are additive here)."""
    merged: Dict[str, Any] = {}
    for name in ("BASE_OPTIONS", "DEFAULT_OPTIONS"):
        for cls in reversed(klass.mro()):
            value = cls._class_assign_in(cls, name)
            if value is None:
                continue
            try:
                table = interp.eval(value, cls.env)
            except Exception:
                continue
            if isinstance(table, dict):
                merged.update(
                    {k: v for k, v in table.items() if isinstance(k, str)}
                )
    merged.update(overrides)
    return merged


def _measured_wire(
    traces: Sequence[Any], axis_sizes: Dict[str, int]
) -> Tuple[Optional[float], int, str]:
    """(total bytes | None, collective entry count, failure reason) over
    the measured-phase traces."""
    total = 0.0
    entries = 0
    for t in traces:
        if t.phase != "measured":
            continue
        if t.truncated:
            return None, entries, "trace truncated (budget)"
        if t.unresolved:
            return None, entries, "shard_map body unresolved"
        sub = t.wire_bytes(axis_sizes)
        if sub is None:
            return None, entries, "collective payload would not size"
        from ddlb_tpu.analysis.spmd.trace import COLLECTIVE_OPS, P2P_OPS

        entries += sum(
            1 for e in t.entries if e.op in COLLECTIVE_OPS + P2P_OPS
        )
        total += sub
    return total, entries, ""


def trace_member(
    family: str,
    member: str,
    overrides: Dict[str, Any],
    registry: ClassRegistry,
    table: Optional[Dict[str, Dict[str, Tuple[str, str]]]] = None,
    shapes: Optional[Dict[str, int]] = None,
) -> MemberReport:
    """Drive one member under the canonical shapes; see module docstring
    for the status vocabulary. ``table``/``shapes`` default to the real
    primitive registry and ``FAMILY_SHAPES`` (fixture tests inject
    synthetic ones)."""
    shapes = shapes or FAMILY_SHAPES[family]
    report = MemberReport(family, member, overrides)
    table = table or _registry_table()
    module_name, class_name = table[family][member]
    report.rel = module_name.replace(".", "/") + ".py"
    klass = registry.resolve(module_name, class_name)
    if klass is None:
        report.reason = f"class {class_name} did not resolve statically"
        return report

    axis_sizes = _axis_sizes_for(family, shapes["d"], shapes)
    tracer = Tracer(report.rel, mode="family")
    # the kernel model rides along so pallas members trace their
    # in-kernel DMA rings instead of stopping opaque at pallas_call
    from ddlb_tpu.analysis.pallas.model import PallasModel

    interp = Interpreter(
        tracer,
        budget=Budget(),
        summaries=_path_summaries(),
        self_summaries=_self_summaries(shapes),
        module_resolver=ModuleResolver(registry),
        axis_sizes=axis_sizes,
        pallas_model=PallasModel(),
    )

    options = _static_options(klass, interp, overrides)
    schedule = klass.class_attr("COST_SCHEDULE", interp)
    if isinstance(schedule, str):
        report.cost_schedule = schedule
    if options.get("algorithm") == "chunked":
        # the chunked-fusion engine's contract (Primitive.overlap_chunks)
        chunks = options.get("chunk_count")
        if isinstance(chunks, int) and chunks >= 1:
            report.chunk_count = chunks
    if schedule == "compute_only":
        report.status = "skipped"
        report.reason = "compute_only member (no wire by contract)"
        return report
    if family in NO_WIRE_TERM_FAMILIES:
        report.status = "skipped"
        report.reason = (
            "cost model prices no wire term for this family "
            "(perfmodel/cost.py)"
        )
        return report

    selfval = SelfVal(
        attrs={
            "m": shapes["m"],
            "n": shapes["n"],
            "k": shapes["k"],
            "dtype": overrides.get("dtype", "bfloat16"),
            "seed": 42,
            "options": options,
            "num_partitions": shapes["d"],
            "mesh": MeshVal(("tp",), {"tp": shapes["d"]}),
            "runtime": _runtime_ns(shapes, axis_sizes),
        },
        klass=klass,
    )

    # the wire_bytes() formula over the same static instance — and the
    # DDLB123 finding anchor: the defining def's own line
    formula_owner = klass.find_method("wire_bytes")
    if formula_owner is not None:
        owner, fdef = formula_owner
        report.formula_rel = owner.rel
        report.formula_line = fdef.lineno
        try:
            value = interp.call_function(
                FuncVal(
                    "wire_bytes", fdef, owner.env, self_val=selfval,
                    path=owner.rel, owner=owner,
                ),
                [],
                {},
            )
        except Exception:
            value = None
        if isinstance(value, (int, float)):
            report.wire_formula = float(value)

    # the FLOP census over the same static instance — the compute side
    # of the simulator's schedule export (wire alone cannot place the
    # GEMM stream the collective overlaps with)
    flops_owner = klass.find_method("flops")
    if flops_owner is not None:
        owner, fdef = flops_owner
        try:
            value = interp.call_function(
                FuncVal(
                    "flops", fdef, owner.env, self_val=selfval,
                    path=owner.rel, owner=owner,
                ),
                [],
                {},
            )
        except Exception:
            value = None
        if isinstance(value, (int, float)):
            report.flops_formula = float(value)

    setup = klass.find_method("_input_setup")
    if setup is None:
        report.reason = "_input_setup did not resolve"
        return report
    owner, fdef = setup
    interp.phase_override = "init"
    try:
        interp.call_function(
            FuncVal(
                "_input_setup", fdef, owner.env, self_val=selfval,
                path=owner.rel, owner=owner,
            ),
            [],
            {},
        )
    # best-effort abstract interpretation: a setup body the value domain
    # cannot model still binds the shape attrs the drive below needs —
    # an unmodelable member surfaces as status="unresolved", never a
    # crash of the whole analyzer sweep
    except Exception:  # ddlb: ignore[DDLB107]
        pass

    fn = selfval.attrs.get("_fn")
    call_args = klass.resolve_attr("_call_args", selfval, interp)
    if not isinstance(call_args, (tuple, list)):
        call_args = (
            selfval.attrs.get("a", interp_mod.UNKNOWN),
            selfval.attrs.get("b", interp_mod.UNKNOWN),
        )
    interp.phase_override = "measured"
    fns = fn.options if isinstance(fn, UnionVal) else [fn]
    drove = False
    for f in fns:
        if isinstance(f, (FuncVal, ShardMapVal)):
            try:
                interp.call_value(f, list(call_args), {}, None)
            # partial traces are the product here: whatever the drive
            # recorded before the model gave up still feeds the wire
            # comparison, and an unsizeable trace reports "unresolved"
            except Exception:  # ddlb: ignore[DDLB107]
                pass
            drove = True
    interp.phase_override = None
    report.traces = [t for t in tracer.traces if t.phase == "measured"]
    if not drove:
        report.reason = "measured _fn did not resolve to a traceable value"
        return report

    if report.chunk_count is None and report.cost_schedule == "overlap":
        # a pallas ring kernel's schedule is one hop + one GEMM chunk
        # per step: exporting its hop count as the pipeline depth lets
        # the simulator replay the kernel exactly like the chunked
        # shard_map engine (one stage per hop), where the
        # max(C, W) + min(C, W)/c law emerges from arbitration
        from ddlb_tpu.analysis.spmd.trace import COLLECTIVE_OPS, P2P_OPS

        wire_entries = [
            e
            for t in report.traces
            for e in t.entries
            if e.op in COLLECTIVE_OPS + P2P_OPS
        ]
        if wire_entries and all(
            e.op == "remote_copy" for e in wire_entries
        ):
            report.chunk_count = len(wire_entries)

    wire, n_entries, why = _measured_wire(tracer.traces, axis_sizes)
    report.wire_traced = wire
    if wire is None:
        report.reason = why
        return report
    formula = report.wire_formula
    if formula is None:
        report.reason = "wire_bytes() formula did not evaluate statically"
        return report
    if n_entries == 0 and formula > 0.0:
        report.status = "opaque"
        report.reason = (
            "no collectives visible to the tracer (compiler-scheduled "
            "or kernel-internal wire)"
        )
        return report
    if abs(wire - formula) <= WIRE_RTOL * max(abs(formula), 1.0):
        report.status = "verified"
    else:
        report.status = "drift"
        report.reason = (
            f"trace moves {wire:.0f} B/device but wire_bytes() claims "
            f"{formula:.0f} B"
        )
    return report


def member_schedule(
    family: str,
    member: str,
    overrides: Optional[Dict[str, Any]] = None,
    registry: Optional[ClassRegistry] = None,
    shapes: Optional[Dict[str, int]] = None,
) -> Dict[str, Any]:
    """The schedule-export API: one member's traced collective schedule
    as a plain dict the static performance simulator replays
    (``ddlb_tpu.simulator.frontends.program_from_schedule``).

    Runs ``trace_member`` under the canonical (or supplied) shapes and
    flattens the result: ordered per-entry collective dicts
    (``ShardMapTrace.export_entries``), the statically evaluated
    ``flops()``/``wire_bytes()`` censuses, the member's cost schedule
    and chunk depth, and the axis sizes everything was resolved under.
    Purely static — no JAX import, so 4096-chip replays stay bookable
    from the analysis tier.
    """
    if registry is None:
        from ddlb_tpu.analysis.core import repo_root

        registry = ClassRegistry(repo_root())
    shapes = shapes or FAMILY_SHAPES[family]
    report = trace_member(
        family, member, dict(overrides or {}), registry, shapes=shapes
    )
    axis_sizes = _axis_sizes_for(family, shapes["d"], shapes)
    entries: List[Dict[str, Any]] = []
    for t in report.traces:
        entries.extend(t.export_entries(axis_sizes))
    return {
        "family": family,
        "member": member,
        "options": dict(report.options),
        "status": report.status,
        "reason": report.reason,
        "shapes": dict(shapes),
        "partitions": shapes["d"],
        "axis_sizes": axis_sizes,
        "entries": entries,
        "flops": report.flops_formula,
        "wire_traced": report.wire_traced,
        "wire_formula": report.wire_formula,
        "schedule": report.cost_schedule,
        "chunks": report.chunk_count,
        # striped members: concurrent ring families per slice (the
        # count of non-degenerate torus axes) — the simulator front-end
        # splits the ici stream across them
        "stripes": max(
            1,
            sum(
                1
                for a in ("sx", "sy")
                if axis_sizes.get(a, 1) > 1
            ),
        ),
    }


def member_matrix(family: str) -> List[Tuple[str, List[Dict[str, Any]]]]:
    table = _registry_table()
    out: List[Tuple[str, List[Dict[str, Any]]]] = []
    for member in table[family]:
        out.append(
            (member, MEMBER_CONFIGS.get((family, member), [{}]))
        )
    return out


def verify_families(
    root: Optional[Path] = None,
    families: Optional[Sequence[str]] = None,
) -> List[MemberReport]:
    """Every registered family's members under canonical shapes — the
    DDLB123 input and the ``--spmd-trace`` document."""
    from ddlb_tpu.analysis.core import repo_root

    registry = ClassRegistry(root or repo_root())
    reports: List[MemberReport] = []
    for family in FAMILY_SHAPES:
        if families is not None and family not in families:
            continue
        for member, configs in member_matrix(family):
            for overrides in configs:
                reports.append(
                    trace_member(family, member, overrides, registry)
                )
    return reports
