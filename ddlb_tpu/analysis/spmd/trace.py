"""Value domain and collective-trace model for the SPMD interpreter.

The interpreter (``interp.py``) evaluates ``shard_map`` bodies over a
small abstract value domain defined here:

- concrete Python scalars / tuples / lists / dicts pass through, so
  canonical-shape evaluation is mostly *concrete* execution (loops run
  their real trip counts, reshapes produce real dims);
- ``Arr`` is a symbolic device array carrying only ``(shape, dtype,
  tainted)`` — shape dims are ints or ``None`` (unknown); ``tainted``
  marks values derived from ``lax.axis_index`` (rank-dependent data,
  the DDLB121 divergence signal);
- ``Unk`` is the don't-know element (with taint), absorbing everything
  the interpreter does not model;
- ``FuncVal`` / ``ShardMapVal`` / ``MeshVal`` / ``SpecVal`` / ``ModVal``
  model the JAX program-construction layer far enough to find every
  collective call inside a mapped body.

A ``Tracer`` collects ``TraceEntry`` rows — op, axis names, payload
size, surrounding branch/loop frames — into ``ShardMapTrace`` objects,
one per traced ``shard_map`` site (plus "floating" traces for Pallas
kernel bodies reached outside any ``shard_map``). The DDLB120-123 rules
and ``scripts/analyze.py --spmd-trace`` consume these traces.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

#: collective ops recorded into traces; the wire-relevant subset powers
#: DDLB123 and the deadlock-relevant subset powers DDLB121
COLLECTIVE_OPS = (
    "psum",
    "pmean",
    "ppermute",
    "all_gather",
    "psum_scatter",
    "all_to_all",
)
#: rank-asymmetric by protocol (point-to-point DMA), excluded from the
#: DDLB121 divergence check but SIZED like a ppermute hop: under the
#: SPMD-symmetric model every device sends one payload per recorded
#: remote copy, which is exactly what the Pallas kernel rings move —
#: the de-opaquing contract that lets DDLB123 hold ``ring_all_gather``
#: et al to their ``wire_bytes()`` formulas
P2P_OPS = ("remote_copy",)

#: wire/HBM itemsize per dtype name, mirroring perfmodel.cost._ITEMSIZE
#: (f64 counts 4: device arrays are f32 unless x64 is enabled). Stated
#: here too so the analysis tier never imports the perfmodel at module
#: import time; DDLB123 cross-checks against the real formulas at run
#: time, which is exactly its job.
ITEMSIZE = {
    "float32": 4,
    "float64": 4,
    "float16": 2,
    "bfloat16": 2,
    "int32": 4,
    "int64": 8,
    "int8": 1,
    "bool": 1,
}


class Unk:
    """The don't-know element; ``tainted`` marks rank-dependence."""

    __slots__ = ("tainted",)

    def __init__(self, tainted: bool = False) -> None:
        self.tainted = tainted

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return "Unk(tainted)" if self.tainted else "Unk"


UNKNOWN = Unk()


def is_unknown(v: Any) -> bool:
    return isinstance(v, Unk)


def taint_of(v: Any) -> bool:
    """Whether a value is (transitively) derived from rank identity."""
    if isinstance(v, (Unk, Arr)):
        return v.tainted
    if isinstance(v, (tuple, list)):
        return any(taint_of(x) for x in v)
    return False


class Arr:
    """Symbolic array: shape dims are ints or None (unknown)."""

    __slots__ = ("shape", "dtype", "tainted")

    def __init__(
        self,
        shape: Optional[Tuple],
        dtype: Optional[str] = None,
        tainted: bool = False,
    ) -> None:
        self.shape = tuple(shape) if shape is not None else None
        self.dtype = dtype
        self.tainted = tainted

    def elems(self) -> Optional[int]:
        if self.shape is None:
            return None
        total = 1
        for dim in self.shape:
            if not isinstance(dim, int):
                return None
            total *= dim
        return total

    def nbytes(self) -> Optional[float]:
        n = self.elems()
        if n is None:
            return None
        isz = ITEMSIZE.get(self.dtype or "", None)
        if isz is None:
            return None
        return float(n * isz)

    def with_shape(self, shape) -> "Arr":
        return Arr(shape, self.dtype, self.tainted)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        dims = (
            "?"
            if self.shape is None
            else ",".join("?" if d is None else str(d) for d in self.shape)
        )
        return f"Arr[{dims}]{self.dtype or '?'}"


class FuncVal:
    """An interpretable function: AST node + defining environment."""

    __slots__ = ("name", "node", "env", "self_val", "path", "owner")

    def __init__(
        self, name, node, env, self_val=None, path="", owner=None
    ) -> None:
        self.name = name
        self.node = node  # ast.FunctionDef | ast.Lambda
        self.env = env
        self.self_val = self_val  # bound receiver for methods
        self.path = path  # defining file (for cross-module bodies)
        self.owner = owner  # defining StaticClass (super() dispatch)


class ShardMapVal:
    """The value ``shard_map(fn, mesh=..., in_specs=..., out_specs=...)``
    evaluates to; calling it shards the args and interprets ``fn``."""

    __slots__ = ("fn", "mesh_axes", "in_specs", "out_specs", "node")

    def __init__(self, fn, mesh_axes, in_specs, out_specs, node) -> None:
        self.fn = fn
        self.mesh_axes = mesh_axes  # tuple of names, or None (unknown)
        self.in_specs = in_specs  # tuple of SpecVal/Unk
        self.out_specs = out_specs
        self.node = node  # the shard_map call site


class MeshVal:
    """A mesh whose axis names (and optionally sizes) are known."""

    __slots__ = ("axes", "sizes")

    def __init__(self, axes, sizes=None) -> None:
        self.axes = tuple(axes) if axes is not None else None
        self.sizes = dict(sizes or {})


class SpecVal:
    """``PartitionSpec`` literal: entries are str | None | tuple."""

    __slots__ = ("entries",)

    def __init__(self, entries) -> None:
        self.entries = tuple(entries)

    def axis_names(self) -> Tuple[str, ...]:
        out = []
        for e in self.entries:
            if isinstance(e, str):
                out.append(e)
            elif isinstance(e, (tuple, list)):
                out.extend(x for x in e if isinstance(x, str))
        return tuple(out)


class ModVal:
    """A dotted module/attribute path ("jax.lax") pending resolution."""

    __slots__ = ("path",)

    def __init__(self, path: str) -> None:
        self.path = path


class OpaqueReal:
    """A real host object (e.g. a schedule-table dataclass) whose plain
    attributes the interpreter may read; never called."""

    __slots__ = ("obj",)

    def __init__(self, obj) -> None:
        self.obj = obj


class UnionVal:
    """A bounded set of alternative values (post-branch merges)."""

    __slots__ = ("options",)

    MAX = 4

    def __init__(self, options) -> None:
        flat: List[Any] = []
        for o in options:
            if isinstance(o, UnionVal):
                flat.extend(o.options)
            else:
                flat.append(o)
        self.options = flat[: self.MAX]


class Frame:
    """One branch/loop context surrounding a trace entry."""

    __slots__ = ("kind", "label", "tainted", "arm", "line")

    def __init__(self, kind, label, tainted=False, arm=None, line=0) -> None:
        self.kind = kind  # "if" | "cond" | "switch" | "loop" | "while"
        self.label = label
        self.tainted = tainted
        self.arm = arm
        self.line = line

    def describe(self) -> str:
        arm = f"#arm{self.arm}" if self.arm is not None else ""
        taint = " rank-dependent" if self.tainted else ""
        return f"{self.kind}({self.label}){arm}{taint}"


class TraceEntry:
    """One collective occurrence inside a traced body."""

    __slots__ = (
        "op", "axes", "line", "col", "payload", "frames", "perm",
        "perm_pattern",
    )

    def __init__(
        self, op, axes, line, col, payload, frames, perm=None,
        perm_pattern=None,
    ) -> None:
        self.op = op
        self.axes = tuple(axes)
        self.line = line
        self.col = col
        self.payload = payload  # Arr | None
        self.frames = list(frames)  # Frame snapshots
        self.perm = perm  # concrete [(src, dst), ...] when resolvable
        self.perm_pattern = perm_pattern  # "ring" for the ±1 comprehension

    def payload_bytes(self) -> Optional[float]:
        if isinstance(self.payload, Arr):
            return self.payload.nbytes()
        return None

    def describe(self) -> str:
        where = "/".join(f.describe() for f in self.frames)
        pay = repr(self.payload) if self.payload is not None else "?"
        ax = ",".join(self.axes) or "-"
        loc = f":{self.line}"
        return f"{self.op}[{ax}] payload={pay}{loc}" + (
            f" in {where}" if where else ""
        )


#: per-device ring-algorithm wire bytes each collective contributes,
#: given its local payload bytes and the axis size d — the same
#: bandwidth-optimal formulas perfmodel/cost.py states per family
def wire_contribution(op: str, nbytes: float, d: int) -> float:
    if op == "remote_copy":
        # one kernel-level RDMA hop: every device sends the payload
        # once (the symmetric ring/all-pairs protocols of ops/); the
        # axis product does not divide it — the kernel already sliced
        # the payload, and the entry often carries no axis names at all
        return nbytes
    if d <= 1:
        return 0.0
    if op == "all_gather":
        return nbytes * (d - 1)
    if op == "psum_scatter":
        return nbytes * (d - 1) / d
    if op in ("psum", "pmean"):
        return 2.0 * nbytes * (d - 1) / d
    if op == "all_to_all":
        return nbytes * (d - 1) / d
    if op == "ppermute":
        return nbytes
    return 0.0


class Divergence:
    """A DDLB121 record: a collective present on one arm only."""

    __slots__ = ("entry", "branch_line", "branch_kind")

    def __init__(self, entry, branch_line, branch_kind) -> None:
        self.entry = entry
        self.branch_line = branch_line
        self.branch_kind = branch_kind


class ShardMapTrace:
    """Everything traced from one ``shard_map`` site (or floating body)."""

    __slots__ = (
        "rel", "line", "col", "fn_name", "mesh_axes", "spec_axes",
        "entries", "divergences", "phase", "unresolved", "truncated",
        "site_name",
    )

    def __init__(
        self, rel, line, col, fn_name, mesh_axes, spec_axes,
        phase="measured",
    ) -> None:
        self.rel = rel
        self.line = line
        self.col = col
        self.fn_name = fn_name
        self.mesh_axes = mesh_axes  # tuple | None
        self.spec_axes = tuple(spec_axes)
        self.entries: List[TraceEntry] = []
        self.divergences: List[Divergence] = []
        self.phase = phase  # "measured" | "init" | "kernel" | "floating"
        self.unresolved = False
        self.truncated = False
        self.site_name = ""  # flightrec site joined by flight_report

    def declared_axes(self) -> Optional[Tuple[str, ...]]:
        """The axis names a collective may legally use here: the mesh
        axes (widened by the spec axes), or None — rule skips — when the
        mesh is not statically known. Spec axes alone are a LOWER bound
        on the mesh, never the axis universe: ``models/`` maps bodies
        over ``P("dp", ...)`` specs inside (dp, tp, pp) meshes passed as
        parameters, and their tp/pp collectives are legal."""
        if self.mesh_axes is None:
            return None
        axes = set(self.spec_axes)
        axes.update(self.mesh_axes)
        return tuple(sorted(axes))

    def wire_bytes(self, axis_sizes: Dict[str, int]) -> Optional[float]:
        """Total per-device wire bytes of the trace's collectives — and
        kernel-level remote-DMA hops — under the given axis sizes; None
        when any payload is unsizeable."""
        total = 0.0
        for e in self.entries:
            if e.op not in COLLECTIVE_OPS + P2P_OPS:
                continue
            if e.op == "axis_index":  # pragma: no cover - not collective
                continue
            nbytes = e.payload_bytes()
            if nbytes is None:
                return None
            d = 1
            for ax in e.axes:
                if ax not in axis_sizes:
                    return None
                d *= axis_sizes[ax]
            total += wire_contribution(e.op, nbytes, d)
        return total

    def export_entries(
        self, axis_sizes: Dict[str, int]
    ) -> List[Dict[str, Any]]:
        """The trace's wire-relevant collectives as plain dicts, in
        traced order — the simulator's schedule-export surface
        (``ddlb_tpu.simulator.frontends`` replays these step-by-step).

        Each dict carries ``op``, the ``axes`` tuple, the resolved axis
        product ``axis`` (None when a name is missing from
        ``axis_sizes``), the LOCAL payload ``nbytes`` (None when the
        payload would not size), and the source ``line``. Entries stay
        un-collapsed: a chunked ring's ``c*(d-1)`` ppermutes export as
        ``c*(d-1)`` dicts, which is exactly what step-by-step replay
        needs."""
        out: List[Dict[str, Any]] = []
        for e in self.entries:
            if e.op not in COLLECTIVE_OPS + P2P_OPS:
                continue
            d: Optional[int] = 1
            for ax in e.axes:
                if ax not in axis_sizes:
                    d = None
                    break
                d *= axis_sizes[ax]
            out.append(
                {
                    "op": e.op,
                    "axes": tuple(e.axes),
                    "axis": d,
                    "nbytes": e.payload_bytes(),
                    "line": e.line,
                }
            )
        return out

    def describe(self) -> List[str]:
        head = (
            f"shard_map @ {self.rel}:{self.line} fn={self.fn_name or '?'} "
            f"mesh_axes={self.mesh_axes or '?'} specs={self.spec_axes} "
            f"phase={self.phase}"
        )
        lines = [head]
        if self.unresolved:
            lines.append("  (body unresolved statically)")
        # collapse identical (op, line, axes) repeats from concrete loops
        counts: Dict[Tuple, int] = {}
        order: List[Tuple] = []
        by_key: Dict[Tuple, TraceEntry] = {}
        for e in self.entries:
            key = (e.op, e.line, e.axes, repr(e.payload))
            if key not in counts:
                order.append(key)
                by_key[key] = e
            counts[key] = counts.get(key, 0) + 1
        for key in order:
            e = by_key[key]
            n = counts[key]
            mult = f" x{n}" if n > 1 else ""
            lines.append(f"  {e.describe()}{mult}")
        return lines


class Tracer:
    """Collects entries into a stack of open traces.

    ``mode`` selects site behavior: in ``"file"`` mode a ``ShardMapVal``
    is traced at *creation* (the per-file sweep can rarely see the call);
    in ``"family"`` mode tracing happens when the value is called (init
    helpers) or driven explicitly with the member's canonical args.
    """

    def __init__(self, rel: str, mode: str = "file") -> None:
        self.rel = rel
        self.mode = mode
        self.traces: List[ShardMapTrace] = []
        self._stack: List[ShardMapTrace] = []
        self._frames: List[Frame] = []

    # -- trace lifecycle ---------------------------------------------------

    def open_trace(self, trace: ShardMapTrace) -> ShardMapTrace:
        self.traces.append(trace)
        self._stack.append(trace)
        return trace

    def close_trace(self) -> None:
        self._stack.pop()

    def current(self) -> Optional[ShardMapTrace]:
        return self._stack[-1] if self._stack else None

    def ensure_floating(self, fn_name: str, line: int) -> ShardMapTrace:
        """Open a floating (kernel-body) trace when an entry lands with
        no shard_map context — Pallas kernels reached directly."""
        if not self._stack:
            t = ShardMapTrace(
                self.rel, line, 1, fn_name, None, (), phase="kernel"
            )
            self.open_trace(t)
        return self._stack[-1]

    # -- frames ------------------------------------------------------------

    def push_frame(self, frame: Frame) -> None:
        self._frames.append(frame)

    def pop_frame(self) -> Frame:
        return self._frames.pop()

    def frames(self) -> Sequence[Frame]:
        return tuple(self._frames)

    # -- recording ---------------------------------------------------------

    def record(
        self, op, axes, node, payload=None, perm=None, perm_pattern=None,
        fn_name="",
    ) -> Optional[TraceEntry]:
        trace = self.current()
        if trace is None:
            trace = self.ensure_floating(fn_name, getattr(node, "lineno", 0))
        entry = TraceEntry(
            op,
            axes,
            getattr(node, "lineno", 0),
            getattr(node, "col_offset", 0) + 1,
            payload,
            self.frames(),
            perm=perm,
            perm_pattern=perm_pattern,
        )
        trace.entries.append(entry)
        return entry

    def record_divergences(
        self, arm_entries: List[List[TraceEntry]], frame: Frame
    ) -> None:
        """Compare branch arms: a collective (op, axes) multiset present
        in one arm but unmatched in another, under a rank-dependent
        condition, is a DDLB121 divergence."""
        if not frame.tainted:
            return
        trace = self.current()
        if trace is None:
            return

        def keyset(entries):
            out: Dict[Tuple, int] = {}
            for e in entries:
                if e.op in COLLECTIVE_OPS:
                    key = (e.op, e.axes)
                    out[key] = out.get(key, 0) + 1
            return out

        keysets = [keyset(arm) for arm in arm_entries]
        for i, entries in enumerate(arm_entries):
            others = [k for j, k in enumerate(keysets) if j != i]
            seen: Dict[Tuple, int] = {}
            for e in entries:
                if e.op not in COLLECTIVE_OPS:
                    continue
                key = (e.op, e.axes)
                seen[key] = seen.get(key, 0) + 1
                if any(o.get(key, 0) < seen[key] for o in others):
                    trace.divergences.append(
                        Divergence(e, frame.line, frame.kind)
                    )
