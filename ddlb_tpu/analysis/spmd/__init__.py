"""Semantic SPMD analysis: collective-trace abstract interpretation.

Where ``ddlb_tpu/analysis/rules_domain.py`` is syntactic (it can see a
``jax.shard_map`` *call*, not what the mapped body does), this package
walks every ``shard_map`` / ``runtime.shard_map_compat`` body (and the
Pallas kernel bodies) with a small abstract interpreter and produces a
per-function **collective trace**: ordered ``(op, axis_names, payload)``
entries with branch/loop structure preserved. Four rules read the trace:

- **DDLB120** axis-name validity — every collective's axis must appear
  in the enclosing mesh axes / partition specs;
- **DDLB121** static divergence — a collective reachable on one side of
  a rank-dependent branch but not the other (the static twin of the
  PR 8 flight recorder);
- **DDLB122** ppermute permutation totality — ring perms must be a
  bijection over the axis size (the silent-wrong-answer class);
- **DDLB123** wire-bytes drift — the trace's per-step payload evaluated
  under each family's canonical shapes, cross-checked against the
  ``perfmodel/cost.py`` ``wire_bytes()`` formula every roofline column
  depends on.

Modules: ``trace`` (value domain + trace model + tracer), ``interp``
(the AST interpreter + per-file tracing), ``families`` (canonical
per-family evaluation for DDLB123 and ``--spmd-trace``), ``rules_spmd``
(the rule battery, registered with the engine via ``core.all_rules``).
"""
