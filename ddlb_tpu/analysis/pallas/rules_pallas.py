"""The DDLB13x Pallas kernel rules — the kernel-resource battery.

Where DDLB120-123 read the collective traces of ``shard_map`` bodies,
these read the per-``pallas_call`` resource censuses the kernel model
extracts (``analysis.pallas.model`` driven by ``analysis.pallas.census``
at canonical sweep shapes):

- **DDLB130 vmem-over-budget**: a kernel's resident VMEM working set
  (pipelined blocks x2, scratch, inner-pipeline peak) exceeds a
  registered chip's ``vmem_bytes`` (``perfmodel/specs.py``) — on
  hardware this is a Mosaic allocation failure at compile time, found
  today only by booking the chip. The rule also closes coverage: a
  ``pallas_call`` site no census reaches, a kernel spec that failed to
  drive, and a census that would not size are all findings, so a new
  kernel cannot land unmodeled.
- **DDLB131 tile-misalignment**: a VMEM block whose last dim exceeds
  the 128 lane and is not a multiple of it, or whose second-to-last dim
  exceeds the dtype sublane granule ((8,128)/f32, (16,128)/bf16,
  (32,128)/int8) without dividing it — Mosaic inserts relayouts and the
  MXU runs partially masked, the silent perf-cliff class. Dims at or
  under the granule pad (legal, deliberate: ``[bq, 1]`` flash
  accumulators), so only true misalignment fires.
- **DDLB132 dma-semaphore-leak**: per-semaphore DMA start/wait balance
  over the interpreted kernel (concrete ring trip counts, concrete
  ``pl.when`` predicates): a start that never meets a wait wedges the
  NEXT kernel invocation on a dirty semaphore — the cross-invocation
  cousin of the flight recorder's in-flight hang.
- **DDLB133 grid-block-mismatch**: a block shape that does not divide
  the operand it tiles under canonical shapes — Pallas pads the tail
  block and the kernel reads unmasked garbage, the
  wrong-answer-without-an-error class.
- **DDLB134 direct-compiler-params** (style, per file): a direct
  ``pltpu.CompilerParams`` / ``TPUCompilerParams`` reference outside
  ``ops/pallas_compat.py`` — the jax-0.4.x rename bridge PR 9
  installed; one un-bridged reference breaks every interpret-mode test
  on the 0.4.x fleet.
"""

from __future__ import annotations

import ast
from typing import Any, Iterable, List, Sequence, Set, Tuple

from ddlb_tpu.analysis.core import FileContext, Finding, ProjectRule, Rule
from ddlb_tpu.analysis.pallas.model import LANE, SUBLANE, KernelCensus

#: the subtrees whose presence in a sweep turns the project rules on
#: (same contract as the DDLB12x semantic scope)
_KERNEL_DIRS = ("ops", "primitives")

_CENSUS_REL = "ddlb_tpu/analysis/pallas/census.py"


def _in_kernel_scope(ctx: FileContext) -> bool:
    return ctx.in_package() and any(d in ctx.parts for d in _KERNEL_DIRS)


def _line_of(rel: str, line: int) -> str:
    from ddlb_tpu.analysis.core import repo_root

    try:
        lines = (repo_root() / rel).read_text(
            encoding="utf-8"
        ).splitlines()
        return lines[line - 1].strip() if 1 <= line <= len(lines) else ""
    except OSError:
        return ""


class _CensusRule(ProjectRule):
    """Shared plumbing: run (or receive) the census sweep, emit
    findings via ``findings_from`` (fixture tests drive that directly,
    mirroring the DDLB123 pattern)."""

    def check_project(
        self, contexts: Sequence[FileContext]
    ) -> Iterable[Finding]:
        if not any(_in_kernel_scope(ctx) for ctx in contexts):
            return []
        from ddlb_tpu.analysis.pallas import census as census_mod

        try:
            run = census_mod.shared_run()
        except Exception as exc:
            return [
                Finding(
                    self.id, _CENSUS_REL, 1, 1,
                    f"pallas census failed to run: "
                    f"{type(exc).__name__}: {exc}",
                )
            ]
        return self.findings_from(run, contexts)

    def findings_from(
        self, run: Any, contexts: Sequence[FileContext] = ()
    ) -> List[Finding]:
        raise NotImplementedError

    def census_finding(
        self, census: KernelCensus, message: str
    ) -> Finding:
        return Finding(
            self.id, census.rel, census.line or 1, 1, message,
            severity=self.severity,
            snippet=_line_of(census.rel, census.line or 1),
        )


class VmemBudgetRule(_CensusRule):
    """Kernel working set vs every registered chip's VMEM capacity."""

    id = "DDLB130"
    name = "vmem-over-budget"
    rationale = (
        "a kernel whose resident blocks + scratch exceed a chip's VMEM "
        "fails Mosaic allocation only on real hardware; the census "
        "catches it at analyze time, and its coverage check keeps every "
        "pallas_call site modeled"
    )

    def findings_from(
        self, run: Any, contexts: Sequence[FileContext] = ()
    ) -> List[Finding]:
        from ddlb_tpu.analysis.pallas.census import pallas_call_sites
        from ddlb_tpu.perfmodel.specs import CHIP_SPECS

        out: List[Finding] = []
        for label, reason in run.errors:
            out.append(
                Finding(
                    self.id, _CENSUS_REL, 1, 1,
                    f"kernel spec {label!r} failed to drive: {reason} — "
                    f"its pallas_call sites are unmodeled",
                )
            )
        covered: Set[Tuple[str, int]] = set()
        for census in run.censuses:
            covered.add((census.rel, census.line))
            if census.incomplete is not None:
                # a partially-interpreted body may have missed
                # run_scoped allocations and DMA events entirely — a
                # green gate over an undercounted census would be a lie
                out.append(
                    self.census_finding(
                        census,
                        f"kernel {census.name}: body did not interpret "
                        f"to completion ({census.incomplete}) — the "
                        f"census may undercount; simplify the kernel "
                        f"or extend the model before relying on "
                        f"DDLB130-133 here",
                    )
                )
                continue
            total = census.vmem_bytes()
            if total is None:
                out.append(
                    self.census_finding(
                        census,
                        f"kernel {census.name}: VMEM working set would "
                        f"not size statically "
                        f"({'; '.join(census.notes) or 'unknown'}) — "
                        f"the budget check cannot run",
                    )
                )
                continue
            over = [
                (spec.name, spec.vmem_bytes)
                for spec in CHIP_SPECS.values()
                if total > spec.vmem_bytes
            ]
            if over:
                chips = ", ".join(
                    f"{name} ({cap / (1 << 20):.0f} MiB)"
                    for name, cap in sorted(over)
                )
                out.append(
                    self.census_finding(
                        census,
                        f"kernel {census.name}: VMEM working set "
                        f"{total / (1 << 20):.2f} MiB exceeds {chips} "
                        f"at canonical sweep shapes — shrink the blocks "
                        f"or gate the config per chip",
                    )
                )
        for rel, line in pallas_call_sites(contexts):
            if (rel, line) not in covered:
                out.append(
                    Finding(
                        self.id, rel, line, 1,
                        "pallas_call site reached by no kernel census — "
                        "register a KernelSpec in "
                        "analysis/pallas/census.py so DDLB130-133 can "
                        "model it",
                        snippet=_line_of(rel, line),
                    )
                )
        return out


class TileAlignmentRule(_CensusRule):
    """VMEM block last-two-dims vs the dtype tiling granules."""

    id = "DDLB131"
    name = "tile-misalignment"
    rationale = (
        "a VMEM block whose trailing dims exceed but do not divide the "
        "(sublane, 128) granule for its dtype forces Mosaic relayouts "
        "and masked MXU lanes — a silent perf cliff the compiler "
        "accepts without a diagnostic"
    )

    def findings_from(
        self, run: Any, contexts: Sequence[FileContext] = ()
    ) -> List[Finding]:
        out: List[Finding] = []
        seen: Set[Tuple] = set()
        for census in run.censuses:
            for rec in census.blocks:
                if rec.space != "vmem" or rec.block_shape is None:
                    continue
                dims = [
                    d for d in rec.block_shape if isinstance(d, int)
                ]
                if len(dims) < 2 or len(dims) != len(rec.block_shape):
                    continue
                sub = SUBLANE.get(rec.dtype or "", None)
                if sub is None:
                    continue
                problems = []
                last, second = dims[-1], dims[-2]
                if last > LANE and last % LANE:
                    problems.append(
                        f"last dim {last} > {LANE} lanes but not a "
                        f"multiple of {LANE}"
                    )
                if second > sub and second % sub:
                    problems.append(
                        f"second-to-last dim {second} > sublane {sub} "
                        f"({rec.dtype}) but not a multiple of {sub}"
                    )
                if not problems:
                    continue
                key = (census.rel, census.line, rec.label,
                       rec.block_shape)
                if key in seen:
                    continue
                seen.add(key)
                out.append(
                    self.census_finding(
                        census,
                        f"kernel {census.name} block {rec.label} "
                        f"{list(rec.block_shape)} ({rec.dtype}): "
                        f"{'; '.join(problems)} — pad or resize to the "
                        f"({sub}, {LANE}) granule",
                    )
                )
        return out


class DmaSemaphoreRule(_CensusRule):
    """Per-semaphore start/wait balance across the interpreted kernel."""

    id = "DDLB132"
    name = "dma-semaphore-leak"
    rationale = (
        "a DMA start whose semaphore is never awaited leaves the next "
        "kernel invocation waiting on a dirty semaphore (or racing a "
        "live copy) — the ring protocols drain every credit for exactly "
        "this reason, and the interpreter's concrete trip counts make "
        "the balance checkable per path"
    )

    def findings_from(
        self, run: Any, contexts: Sequence[FileContext] = ()
    ) -> List[Finding]:
        out: List[Finding] = []
        for census in run.censuses:
            for name, rec in census.unbalanced_sems():
                delta = rec["starts"] - rec["waits"]
                kind = (
                    "unwaited start(s)" if delta > 0
                    else "wait(s) with no matching start"
                )
                out.append(
                    self.census_finding(
                        census,
                        f"kernel {census.name} semaphore {name} "
                        f"({rec['kind']}): {rec['starts']} start(s) / "
                        f"{rec['waits']} wait(s) — {abs(delta)} "
                        f"{kind} on the interpreted paths; the kernel "
                        f"exits with a dirty semaphore",
                    )
                )
        return out


class GridBlockRule(_CensusRule):
    """Block shapes must divide their operands at canonical shapes."""

    id = "DDLB133"
    name = "grid-block-mismatch"
    rationale = (
        "a block that does not divide its operand makes Pallas pad the "
        "tail tile; kernels that reduce over it read unmasked garbage "
        "— wrong answers with no error, caught here under the canonical "
        "sweep shapes every kernel must serve"
    )

    def findings_from(
        self, run: Any, contexts: Sequence[FileContext] = ()
    ) -> List[Finding]:
        out: List[Finding] = []
        seen: Set[Tuple] = set()
        for census in run.censuses:
            for rec in census.blocks:
                if rec.block_shape is None or rec.operand_shape is None:
                    continue
                if len(rec.block_shape) != len(rec.operand_shape):
                    continue
                bad = [
                    (i, o, b)
                    for i, (o, b) in enumerate(
                        zip(rec.operand_shape, rec.block_shape)
                    )
                    if isinstance(o, int) and isinstance(b, int)
                    and b > 0 and o % b
                ]
                if not bad:
                    continue
                key = (census.rel, census.line, rec.label,
                       rec.block_shape, rec.operand_shape)
                if key in seen:
                    continue
                seen.add(key)
                dims = ", ".join(
                    f"dim {i}: {o} % {b} != 0" for i, o, b in bad
                )
                out.append(
                    self.census_finding(
                        census,
                        f"kernel {census.name} block {rec.label} "
                        f"{list(rec.block_shape)} does not divide "
                        f"operand {list(rec.operand_shape)} ({dims}) "
                        f"at canonical shapes — the padded tail tile "
                        f"is read unmasked",
                    )
                )
        return out


class DirectCompilerParamsRule(Rule):
    """Direct pltpu compiler-params references outside the bridge."""

    id = "DDLB134"
    name = "direct-compiler-params"
    rationale = (
        "jax >= 0.5 spells it pltpu.CompilerParams, the 0.4.x fleet "
        "only has TPUCompilerParams; ops/pallas_compat.py is the one "
        "version bridge — a direct reference breaks one side of the "
        "fleet (the rename class PR 9 fixed once)"
    )

    _BANNED = ("CompilerParams", "TPUCompilerParams")

    def scope(self, ctx: FileContext) -> bool:
        return ctx.in_package() and ctx.path.name != "pallas_compat.py"

    @staticmethod
    def _is_jax_pallas(module: str) -> bool:
        """The jax pallas namespace itself — NOT the repo's own bridge
        (``ddlb_tpu.ops.pallas_compat`` is the sanctioned import)."""
        return module.startswith("jax") and "pallas" in module

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        out: List[Finding] = []
        pallas_aliases: Set[str] = set()
        for node in ctx.nodes(ast.Import):
            for alias in node.names:
                if self._is_jax_pallas(alias.name):
                    pallas_aliases.add(
                        alias.asname or alias.name.split(".")[0]
                    )
        for node in ctx.nodes(ast.ImportFrom):
            if not node.module:
                continue
            if not self._is_jax_pallas(node.module):
                continue
            for alias in node.names:
                if alias.name in ("tpu", "pallas"):
                    pallas_aliases.add(alias.asname or alias.name)
                if alias.name in self._BANNED:
                    out.append(
                        self.finding(
                            ctx, node.lineno, node.col_offset + 1,
                            f"direct import of {alias.name} from "
                            f"{node.module} — resolve it through "
                            f"ddlb_tpu.ops.pallas_compat (the jax-0.4.x "
                            f"rename bridge)",
                        )
                    )
        for node in ctx.nodes(ast.Attribute):
            if (
                node.attr in self._BANNED
                and isinstance(node.value, ast.Name)
                and node.value.id in pallas_aliases
            ):
                out.append(
                    self.finding(
                        ctx, node.lineno, node.col_offset + 1,
                        f"direct {node.value.id}.{node.attr} reference "
                        f"— use ddlb_tpu.ops.pallas_compat."
                        f"CompilerParams (the jax-0.4.x rename bridge)",
                    )
                )
        return out


RULES = [
    VmemBudgetRule(),
    TileAlignmentRule(),
    DmaSemaphoreRule(),
    GridBlockRule(),
    DirectCompilerParamsRule(),
]
