"""Static Pallas kernel analysis: model, canonical census, DDLB13x rules.

``model`` extends the semantic SPMD interpreter into ``pallas_call``
kernel bodies (Refs, BlockSpecs, DMA semaphores, remote-copy wire);
``census`` drives every registered ops kernel at canonical sweep shapes;
``rules_pallas`` turns the censuses into findings DDLB130-134. See
``docs/source/static_analysis.rst`` ("Pallas kernel rules").
"""

from ddlb_tpu.analysis.pallas.census import (  # noqa: F401
    KERNEL_SPECS,
    KernelSpec,
    pallas_call_sites,
    run_census,
    shared_run,
)
from ddlb_tpu.analysis.pallas.model import (  # noqa: F401
    KernelCensus,
    PallasModel,
)
