"""Canonical-shape census driver: every ops kernel through the model.

``KERNEL_SPECS`` registers every Pallas kernel entry point in ``ops/``
with the canonical sweep-scale arguments the benchmark actually runs
(the 8192-class shapes the kernel docstrings quote their measured
numbers at — ``FAMILY_SHAPES`` scaled to sweep size, kept small-d so the
ring protocols unroll concretely). ``run_census`` drives each entry
through the abstract interpreter with a ``PallasModel`` installed and
returns one ``KernelCensus`` per ``pallas_call`` invocation — the input
to rules DDLB130 (VMEM budget), DDLB131 (tile alignment), DDLB132 (DMA
semaphore balance), and DDLB133 (grid/block divisibility), and the
``scripts/analyze.py --pallas-census`` dump.

Coverage is CLOSED over the repo: ``pallas_call_sites`` enumerates every
``pallas_call`` in ``ddlb_tpu/ops`` + ``ddlb_tpu/primitives`` from the
AST, and DDLB130 reports any site no census reached — a new kernel
cannot land unmodeled (the same shrink-only discipline as the DDLB123
opaque registry).

Fixture tests inject synthetic spec lists and roots; the real registry
is only the default.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ddlb_tpu.analysis.pallas.model import KernelCensus, PallasModel
from ddlb_tpu.analysis.spmd.families import ClassRegistry, ModuleResolver
from ddlb_tpu.analysis.spmd.interp import Budget, Interpreter
from ddlb_tpu.analysis.spmd.trace import Arr, Tracer

#: canonical sweep-scale shapes (the benchmark's measured operating
#: points, not the tiny tier-1 FAMILY_SHAPES): GEMM-family kernels at
#: the 8192^3 bf16 sweep shape over a d=4 ring; attention at seq 8192,
#: 8 heads x dh=128 (the flash docstring's v5e baseline); decode at the
#: serving engine's batch/cache geometry.
SWEEP = {"m": 8192, "n": 8192, "k": 8192, "d": 4}
ATTN = {"s": 8192, "h": 8, "h_kv": 2, "dh": 128}
DECODE = {"b": 8, "s": 8192, "h": 8, "h_kv": 2, "dh": 128}

BF16 = "bfloat16"
F32 = "float32"


class KernelSpec:
    """One registered kernel entry point + its canonical drive."""

    def __init__(
        self,
        label: str,
        path: str,
        build: Callable[[], Tuple[Sequence[Any], Dict[str, Any]]],
        family: str = "",
    ) -> None:
        self.label = label
        self.path = path  # dotted ddlb_tpu.* function path
        self.build = build
        self.family = family


def _gemm(m, k, n, dtype=BF16):
    return (Arr((m, k), dtype), Arr((k, n), dtype))


def _specs() -> List[KernelSpec]:
    m, n, k, d = SWEEP["m"], SWEEP["n"], SWEEP["k"], SWEEP["d"]
    s, h, h_kv, dh = ATTN["s"], ATTN["h"], ATTN["h_kv"], ATTN["dh"]
    b = DECODE["b"]
    scale = 0.088  # 1/sqrt(dh); any float works — never used for sizing

    def qkv(seq=s, heads=h, kv=h_kv):
        return (
            Arr((seq, heads, dh), BF16),
            Arr((seq, kv, dh), BF16),
            Arr((seq, kv, dh), BF16),
        )

    return [
        KernelSpec(
            "matmul", "ddlb_tpu.ops.matmul.matmul",
            lambda: (_gemm(m, k, n), {}), "tp_columnwise",
        ),
        KernelSpec(
            "int8_matmul_pallas",
            "ddlb_tpu.ops.quantized_matmul.int8_matmul_pallas",
            lambda: (
                (
                    Arr((m, k), "int8"), Arr((k, n), "int8"),
                    Arr((m, 1), F32), Arr((1, n), F32),
                ),
                {},
            ),
            "tp_columnwise",
        ),
        KernelSpec(
            "ring_ag_matmul",
            "ddlb_tpu.ops.collective_matmul.ring_ag_matmul",
            lambda: (
                (Arr((m // d, k), BF16), Arr((k, n), BF16)),
                {"axis_size": d},
            ),
            "tp_columnwise",
        ),
        KernelSpec(
            "ring_matmul_rs",
            "ddlb_tpu.ops.collective_matmul.ring_matmul_rs",
            lambda: (
                (Arr((m, k // d), BF16), Arr((k // d, n), BF16)),
                {"axis_size": d},
            ),
            "tp_rowwise",
        ),
        KernelSpec(
            "ring_all_gather",
            "ddlb_tpu.ops.ring_collectives.ring_all_gather",
            lambda: ((Arr((m // d, k), BF16),), {"axis_size": d}),
            "collectives",
        ),
        KernelSpec(
            "ring_reduce_scatter",
            "ddlb_tpu.ops.ring_collectives.ring_reduce_scatter",
            lambda: ((Arr((m // d, k), BF16),), {"axis_size": d}),
            "collectives",
        ),
        KernelSpec(
            "alltoall_expert_matmul",
            "ddlb_tpu.ops.alltoall_matmul.alltoall_expert_matmul",
            lambda: (
                (Arr((m // d, k), BF16), Arr((k, n), BF16)),
                {"axis_size": d},
            ),
            "ep_alltoall",
        ),
        # flash forward: literal row_offset=0 takes the triangular grid
        # (one pallas_call site), a traced offset takes the rectangular
        # masked grid (the other site) — both censused
        KernelSpec(
            "flash_attention[tri]",
            "ddlb_tpu.ops.flash_attention.flash_attention",
            lambda: (qkv(), {"scale": scale}),
            "cp_ring_attention",
        ),
        KernelSpec(
            "flash_attention[rect]",
            "ddlb_tpu.ops.flash_attention._flash_forward",
            lambda: (
                qkv() + (Arr((), "int32"), scale, 1024, 1024, False),
                {},
            ),
            "cp_ring_attention",
        ),
        KernelSpec(
            "flash_attention_chunk",
            "ddlb_tpu.ops.flash_attention.flash_attention_chunk",
            lambda: (
                qkv() + (
                    (
                        Arr((h, s, dh), F32),
                        Arr((h, s, 1), F32),
                        Arr((h, s, 1), F32),
                    ),
                ),
                {
                    "scale": scale,
                    "row_offset": Arr((), "int32"),
                    "col_offset": Arr((), "int32"),
                },
            ),
            "cp_ring_attention",
        ),
        KernelSpec(
            "flash_attention_bwd[tri]",
            "ddlb_tpu.ops.flash_attention.flash_attention_bwd",
            lambda: (
                (
                    Arr((s, h, dh), BF16), Arr((s, h_kv, dh), BF16),
                    Arr((s, h_kv, dh), BF16), Arr((s, h, dh), BF16),
                    Arr((h, s, 1), F32), Arr((s, h, dh), BF16),
                ),
                {"scale": scale, "row_offset": 0, "col_offset": 0},
            ),
            "cp_ring_attention",
        ),
        KernelSpec(
            "flash_attention_bwd[rect]",
            "ddlb_tpu.ops.flash_attention.flash_attention_bwd",
            lambda: (
                (
                    Arr((s, h, dh), BF16), Arr((s, h_kv, dh), BF16),
                    Arr((s, h_kv, dh), BF16), Arr((s, h, dh), BF16),
                    Arr((h, s, 1), F32), Arr((s, h, dh), BF16),
                ),
                {
                    "scale": scale,
                    "row_offset": Arr((), "int32"),
                    "col_offset": Arr((), "int32"),
                },
            ),
            "cp_ring_attention",
        ),
        KernelSpec(
            "decode_attention",
            "ddlb_tpu.ops.decode_attention.decode_attention",
            lambda: (
                (
                    Arr((b, h, dh), BF16),
                    Arr((b, DECODE["s"], h_kv, dh), BF16),
                    Arr((b, DECODE["s"], h_kv, dh), BF16),
                    Arr((b,), "int32"),
                ),
                {},
            ),
            "transformer_decode",
        ),
        KernelSpec(
            "paged_decode_attention",
            "ddlb_tpu.ops.decode_attention.paged_decode_attention",
            lambda: (
                (
                    Arr((b, h, dh), BF16),
                    Arr((512, 256, h_kv, dh), BF16),
                    Arr((512, 256, h_kv, dh), BF16),
                    Arr((b, 32), "int32"),
                    Arr((b,), "int32"),
                ),
                {},
            ),
            "transformer_decode",
        ),
    ]


KERNEL_SPECS: List[KernelSpec] = _specs()


class CensusRun:
    """One census sweep: all censuses plus per-spec drive failures."""

    def __init__(self) -> None:
        self.censuses: List[KernelCensus] = []
        self.errors: List[Tuple[str, str]] = []  # (spec label, reason)


def run_census(
    root: Optional[Path] = None,
    specs: Optional[Sequence[KernelSpec]] = None,
) -> CensusRun:
    """Drive every registered kernel under its canonical sweep shapes."""
    from ddlb_tpu.analysis.core import repo_root

    root = Path(root or repo_root())
    registry = ClassRegistry(root)
    resolver = ModuleResolver(registry)
    run = CensusRun()
    for spec in specs if specs is not None else KERNEL_SPECS:
        fn = resolver(spec.path)
        if fn is None:
            run.errors.append(
                (spec.label, f"{spec.path} did not resolve statically")
            )
            continue
        model = PallasModel()
        tracer = Tracer(f"<census:{spec.label}>", mode="family")
        interp = Interpreter(
            tracer,
            budget=Budget(),
            module_resolver=resolver,
            axis_sizes={"tp": SWEEP["d"]},
            pallas_model=model,
        )
        try:
            args, kwargs = spec.build()
            interp.call_value(fn, list(args), dict(kwargs), None)
        # best-effort: whatever censuses the drive produced before the
        # domain gave up still feed the rules; a spec that produced
        # NOTHING surfaces through the uncovered-site check
        except Exception as exc:
            run.errors.append(
                (spec.label, f"{type(exc).__name__}: {exc}")
            )
        for census in model.censuses:
            census.notes.insert(0, f"driven by {spec.label}")
        run.censuses.extend(model.censuses)
    return run


#: process-level memo: the four DDLB13x rules share one sweep per root
_RUN_CACHE: Dict[str, CensusRun] = {}


def shared_run(root: Optional[Path] = None) -> CensusRun:
    from ddlb_tpu.analysis.core import repo_root

    key = str(Path(root or repo_root()).resolve())
    if key not in _RUN_CACHE:
        _RUN_CACHE[key] = run_census(root=root)
    return _RUN_CACHE[key]


def pallas_call_sites(contexts: Sequence[Any]) -> List[Tuple[str, int]]:
    """Every ``pallas_call`` call site in the kernel-bearing subtrees of
    the supplied contexts — the coverage universe DDLB130 closes over."""
    sites: List[Tuple[str, int]] = []
    for ctx in contexts:
        if ctx.tree is None or not ctx.in_package():
            continue
        if not ({"ops", "primitives"} & set(ctx.parts)):
            continue
        for node in ctx.nodes(ast.Call):
            fn = node.func
            name = (
                fn.attr if isinstance(fn, ast.Attribute)
                else fn.id if isinstance(fn, ast.Name) else ""
            )
            if name == "pallas_call":
                sites.append((ctx.rel, node.lineno))
    return sorted(set(sites))
