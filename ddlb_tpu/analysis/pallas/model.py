"""Static Pallas kernel model: the value domain behind the DDLB13x rules.

The PR 9 abstract interpreter stops at every ``pallas_call`` and returns
its ``out_shape`` — which is why DDLB123 lists the pallas members as
*opaque* and why nothing checks a kernel's VMEM working set, tile
alignment, or DMA-semaphore protocol before XLA does (as a compile
error) or the hardware does (as a perf cliff). This module extends the
interpreter INTO the kernel body: when an ``Interpreter`` carries a
``PallasModel``, the ``pl``/``pltpu`` program-construction surface
(``pallas_call``, ``BlockSpec``, ``PrefetchScalarGridSpec``, VMEM/SMEM
scratch, DMA/REGULAR/BARRIER semaphores, ``make_async_copy`` /
``make_async_remote_copy``, ``emit_pipeline``, ``run_scoped``,
``pl.when``, ``program_id``/``num_programs``, ``pl.ds``) evaluates to
model values, the kernel function is interpreted over symbolic ``Ref``s,
and one ``KernelCensus`` per ``pallas_call`` invocation records:

- the **VMEM working set**: every VMEM-resident block (pipelined blocks
  count their double-buffer multiplicity x2 — Pallas's implicit grid
  pipeline keeps the in-flight and the in-use copy resident), scratch
  allocations, and the peak over inner ``emit_pipeline`` tile sets
  (inner pipelines are scoped, so they max rather than sum);
- every **block record** (block shape, operand shape, dtype, memory
  space) — the DDLB131 tile-alignment and DDLB133 divisibility inputs;
- per-semaphore **DMA start/wait balance** under the SPMD-symmetric
  model (a remote copy's send increments locally AND its recv
  increments locally, because the left neighbor runs the same program)
  — the DDLB132 input. Concrete ``fori_loop`` bounds and concrete
  ``pl.when`` predicates make the counts path-exact for the ring
  kernels;
- **remote-DMA wire**: every ``make_async_remote_copy(...).start()``
  records a ``remote_copy`` trace entry sized from its source Ref, so a
  kernel ring exports the same per-hop schedule a ``shard_map`` ring
  does — the DDLB123 de-opaquing and the simulator's pallas frontend;
- **MXU tiles**: every dot over Ref-backed tiles, for the census dump.

Everything here is source-level: no JAX import, same contract as the
rest of the analysis tier.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from ddlb_tpu.analysis.spmd import interp as interp_mod
from ddlb_tpu.analysis.spmd.interp import _MISSING, Frame, FuncVal, PartialVal
from ddlb_tpu.analysis.spmd.trace import (
    ITEMSIZE,
    UNKNOWN,
    Arr,
    ModVal,
    UnionVal,
    taint_of,
)

#: sublane granule of the second-to-last dim per dtype; the last dim's
#: lane granule is always 128 (pallas_guide.md "Tiling Constraints")
SUBLANE = {
    "float32": 8,
    "float64": 8,
    "int32": 8,
    "int64": 8,
    "bfloat16": 16,
    "float16": 16,
    "int8": 32,
    "bool": 32,
}
LANE = 128


def _prod(dims) -> Optional[int]:
    total = 1
    for d in dims:
        if not isinstance(d, int):
            return None
        total *= d
    return total


def _nbytes(shape, dtype) -> Optional[float]:
    n = _prod(shape) if shape is not None else None
    isz = ITEMSIZE.get(dtype or "", None)
    if n is None or isz is None:
        return None
    return float(n * isz)


class VmemItem:
    """One VMEM-resident allocation in a kernel's working set."""

    __slots__ = ("label", "shape", "dtype", "mult", "origin")

    def __init__(self, label, shape, dtype, mult, origin) -> None:
        self.label = label
        self.shape = tuple(shape) if shape is not None else None
        self.dtype = dtype
        self.mult = mult  # 1 resident, 2 double-buffered pipeline block
        self.origin = origin  # "block" | "scratch" | "pipeline"

    def nbytes(self) -> Optional[float]:
        base = _nbytes(self.shape, self.dtype)
        return None if base is None else base * self.mult

    def describe(self) -> str:
        dims = (
            "?" if self.shape is None
            else "x".join(str(d) for d in self.shape)
        )
        size = self.nbytes()
        size_s = "?" if size is None else f"{size / (1 << 20):.2f} MiB"
        return (
            f"{self.label:24s} [{dims}] {self.dtype or '?'} "
            f"x{self.mult} ({self.origin}) = {size_s}"
        )


class BlockRecord:
    """One BlockSpec binding: block shape vs the operand it tiles."""

    __slots__ = (
        "label", "block_shape", "operand_shape", "dtype", "space", "line",
    )

    def __init__(
        self, label, block_shape, operand_shape, dtype, space, line
    ) -> None:
        self.label = label
        self.block_shape = (
            tuple(block_shape) if block_shape is not None else None
        )
        self.operand_shape = (
            tuple(operand_shape) if operand_shape is not None else None
        )
        self.dtype = dtype
        self.space = space
        self.line = line


class KernelCensus:
    """Everything the DDLB130-133 rules need about ONE ``pallas_call``."""

    def __init__(self, name: str, rel: str, line: int) -> None:
        self.name = name
        self.rel = rel
        self.line = line
        self.grid: Optional[Tuple] = None
        self.grid_steps: Optional[int] = 1
        self.vmem_items: List[VmemItem] = []
        #: peak over inner emit_pipeline invocations (scoped: max not sum)
        self.pipeline_bytes = 0.0
        self.blocks: List[BlockRecord] = []
        #: sem name -> {"kind", "starts", "waits", "unknown"}
        self.sems: Dict[str, Dict[str, Any]] = {}
        self.remote_hops = 0
        self.remote_bytes = 0.0
        self.local_dma_bytes = 0.0
        self.mxu_tiles: List[Tuple] = []
        self.notes: List[str] = []
        #: set when the kernel body did not interpret to completion —
        #: the census may UNDERCOUNT (missed run_scoped allocations,
        #: missed DMA events), so the budget rule must fail it rather
        #: than pass a partially-modeled kernel
        self.incomplete: Optional[str] = None

    def sem(self, name: str, kind: str) -> Dict[str, Any]:
        return self.sems.setdefault(
            name, {"kind": kind, "starts": 0, "waits": 0, "unknown": False}
        )

    def vmem_bytes(self) -> Optional[float]:
        """Total resident working set; None when any item is unsizeable
        (the budget rule reports the unresolved census instead of a
        silently-low number)."""
        total = self.pipeline_bytes
        for item in self.vmem_items:
            size = item.nbytes()
            if size is None:
                return None
            total += size
        return total

    def unbalanced_sems(self) -> List[Tuple[str, Dict[str, Any]]]:
        out = []
        for name, rec in sorted(self.sems.items()):
            if rec["unknown"]:
                continue
            if rec["starts"] != rec["waits"]:
                out.append((name, rec))
        return out

    def describe(self) -> List[str]:
        grid = self.grid if self.grid is not None else "-"
        total = self.vmem_bytes()
        total_s = "?" if total is None else f"{total / (1 << 20):.2f} MiB"
        lines = [
            f"{self.rel}:{self.line} kernel={self.name} grid={grid} "
            f"vmem={total_s} remote_hops={self.remote_hops} "
            f"remote_bytes={self.remote_bytes:.0f}"
        ]
        for item in self.vmem_items:
            lines.append("  vmem  " + item.describe())
        if self.pipeline_bytes:
            lines.append(
                f"  vmem  inner-pipeline peak = "
                f"{self.pipeline_bytes / (1 << 20):.2f} MiB"
            )
        for name, rec in sorted(self.sems.items()):
            bal = rec["starts"] - rec["waits"]
            flag = "?" if rec["unknown"] else (
                "ok" if bal == 0 else f"UNBALANCED {bal:+d}"
            )
            lines.append(
                f"  sem   {name:24s} {rec['kind']:8s} "
                f"starts={rec['starts']} waits={rec['waits']} {flag}"
            )
        for tile in sorted(set(self.mxu_tiles)):
            m, k, n, dt = tile
            lines.append(f"  mxu   {m}x{k} @ {k}x{n} {dt}")
        for note in self.notes:
            lines.append(f"  note  {note}")
        return lines


# ---------------------------------------------------------------------------
# model values (the ddlb_attr / ddlb_subscript protocol of spmd.interp)
# ---------------------------------------------------------------------------


class DSVal:
    """``pl.ds(start, size)`` — a dynamic slice of known length."""

    __slots__ = ("start", "size")

    def __init__(self, start, size) -> None:
        self.start = start
        self.size = size


def _translate_idx(idx) -> Any:
    """Map DSVal items to plain slices so ``Interpreter.index_arr`` can
    size the result; everything else passes through."""

    def one(it):
        if isinstance(it, DSVal):
            if isinstance(it.size, int):
                return slice(0, it.size)
            return slice(None)
        return it

    if isinstance(idx, tuple):
        return tuple(one(i) for i in idx)
    return one(idx)


class RefVal:
    """A kernel Ref: shape/dtype plus the memory space it lives in."""

    __slots__ = ("shape", "dtype", "space", "name", "kind")

    def __init__(self, shape, dtype, space, name="", kind="in") -> None:
        self.shape = tuple(shape) if shape is not None else None
        self.dtype = dtype
        self.space = space  # "vmem" | "any" | "smem"
        self.name = name
        self.kind = kind  # "in" | "out" | "scratch" | "prefetch"

    def arr(self) -> Arr:
        return Arr(self.shape, self.dtype)

    def ddlb_attr(self, attr, interp, node):
        if attr == "shape":
            return self.shape if self.shape is not None else UNKNOWN
        if attr == "dtype":
            return self.dtype or UNKNOWN
        if attr == "ndim":
            return len(self.shape) if self.shape is not None else UNKNOWN
        if attr == "at":
            return _RefAt(self)
        return UNKNOWN

    def ddlb_subscript(self, idx, interp, node):
        # a Ref READ produces a symbolic array of the indexed shape
        return interp.index_arr(self.arr(), _translate_idx(idx))

    def sliced(self, idx, interp) -> "RefVal":
        out = interp.index_arr(self.arr(), _translate_idx(idx))
        return RefVal(out.shape, self.dtype, self.space, self.name,
                      self.kind)


class _RefAt:
    """``ref.at[...]`` — a sub-Ref view (still a Ref, still DMA-able)."""

    __slots__ = ("ref",)

    def __init__(self, ref: RefVal) -> None:
        self.ref = ref

    def ddlb_subscript(self, idx, interp, node):
        return self.ref.sliced(idx, interp)


class SemVal:
    """One kernel semaphore (or semaphore array — slots collapse to one
    identity for balance accounting, which keeps counts exact even when
    the slot index is symbolic)."""

    __slots__ = ("name", "kind", "census")

    def __init__(self, name, kind, census) -> None:
        self.name = name
        self.kind = kind  # "dma" | "regular" | "barrier"
        self.census = census

    def ddlb_attr(self, attr, interp, node):
        if attr == "at":
            return _SemAt(self)
        return UNKNOWN


class _SemAt:
    __slots__ = ("sem",)

    def __init__(self, sem: SemVal) -> None:
        self.sem = sem

    def ddlb_subscript(self, idx, interp, node):
        return self.sem


class DmaVal:
    """A ``make_async_copy`` / ``make_async_remote_copy`` handle.

    ``start()`` records the transfer (a sized ``remote_copy`` trace
    entry for RDMA — the wire the rings move) and increments the DMA
    semaphores; ``wait()`` decrements them. The wait-only idiom
    (``make_async_copy(x, x, sem).wait()``) therefore decrements without
    a matching local start, exactly as the hardware semantics pair a
    wait against SOME earlier start on that semaphore.
    """

    __slots__ = ("model", "src", "dst", "sems", "remote", "node")

    def __init__(self, model, src, dst, sems, remote, node) -> None:
        self.model = model
        self.src = src
        self.dst = dst
        self.sems = [s for s in sems if isinstance(s, SemVal)]
        self.remote = remote
        self.node = node

    def ddlb_attr(self, attr, interp, node):
        if attr == "start":
            return self._start
        if attr == "wait":
            return self._wait
        return UNKNOWN

    def _payload(self) -> Optional[Arr]:
        if isinstance(self.src, RefVal):
            return self.src.arr()
        if isinstance(self.src, Arr):
            return self.src
        return None

    def _start(self, args, kwargs, node, interp):
        census = self.model.current()
        payload = self._payload()
        nbytes = payload.nbytes() if payload is not None else None
        if self.remote:
            interp.tracer.record(
                "remote_copy", (), self.node, payload=payload
            )
            if census is not None:
                census.remote_hops += 1
                if nbytes is not None:
                    census.remote_bytes += nbytes
                else:
                    census.notes.append(
                        "remote copy payload would not size"
                    )
        elif census is not None:
            if nbytes is not None:
                census.local_dma_bytes += nbytes
        for sem in self.sems:
            self.model.sem_event(sem, +1)
        return None

    def _wait(self, args, kwargs, node, interp):
        for sem in self.sems:
            self.model.sem_event(sem, -1)
        return None


class WhenVal:
    """``pl.when(cond)`` — execute-or-skip at interpretation time: a
    concrete False predicate skips the body (path-exact ring protocol
    counting), anything else interprets it once under an ``if`` frame."""

    __slots__ = ("model", "cond", "line")

    def __init__(self, model, cond, line) -> None:
        self.model = model
        self.cond = cond
        self.line = line

    def __call__(self, args, kwargs, node, interp):
        fn = args[0] if args else None
        if fn is None:
            return None
        cond = self.cond
        if isinstance(cond, (bool, int, float)) and not cond:
            return None
        concrete = isinstance(cond, (bool, int, float))
        if concrete:
            interp.call_value(fn, [], {}, node)
            return None
        frame = Frame(
            "if", "pl.when", tainted=taint_of(cond), line=self.line
        )
        interp.tracer.push_frame(frame)
        try:
            interp.call_value(fn, [], {}, node)
        finally:
            interp.tracer.pop_frame()
        return None


class BlockSpecVal:
    """``pl.BlockSpec`` literal: block shape, index map, memory space."""

    __slots__ = ("block_shape", "index_map", "space")

    def __init__(self, block_shape, index_map, space) -> None:
        self.block_shape = block_shape
        self.index_map = index_map
        self.space = space  # "vmem" | "any" | "smem" | None (default)


class ScratchVal:
    """``pltpu.VMEM(shape, dtype)`` / ``pltpu.SMEM(...)`` allocation."""

    __slots__ = ("shape", "dtype", "space")

    def __init__(self, shape, dtype, space) -> None:
        self.shape = shape
        self.dtype = dtype
        self.space = space


class SemSpecVal:
    """``pltpu.SemaphoreType.DMA((2,))`` etc. (bare names arrive as
    ``ModVal`` and are resolved by ``_scratch_to_ref``)."""

    __slots__ = ("kind", "slots")

    def __init__(self, kind, slots=None) -> None:
        self.kind = kind
        self.slots = slots


class GridSpecVal:
    """``pltpu.PrefetchScalarGridSpec`` / ``pl.GridSpec`` literal."""

    __slots__ = (
        "num_scalar_prefetch", "grid", "in_specs", "out_specs", "scratch",
    )

    def __init__(
        self, num_scalar_prefetch, grid, in_specs, out_specs, scratch
    ) -> None:
        self.num_scalar_prefetch = num_scalar_prefetch
        self.grid = grid
        self.in_specs = in_specs
        self.out_specs = out_specs
        self.scratch = scratch


class EmitPipelineVal:
    """``pltpu.emit_pipeline(body, grid=..., in_specs=..., out_specs=...)``
    — on call, charges the tile set (x2: the inner pipeline double
    buffers its blocks) against the enclosing census's pipeline peak and
    interprets the body once over tile Refs."""

    __slots__ = ("model", "body", "grid", "in_specs", "out_specs")

    def __init__(self, model, body, grid, in_specs, out_specs) -> None:
        self.model = model
        self.body = body
        self.grid = grid
        self.in_specs = list(in_specs or [])
        self.out_specs = list(out_specs or [])

    def __call__(self, args, kwargs, node, interp):
        census = self.model.current()
        specs = self.in_specs + self.out_specs
        operands = list(args)
        tiles: List[RefVal] = []
        total = 0.0
        sizeable = True
        for i, spec in enumerate(specs):
            operand = operands[i] if i < len(operands) else UNKNOWN
            dtype = None
            oshape = None
            if isinstance(operand, RefVal):
                dtype, oshape = operand.dtype, operand.shape
            elif isinstance(operand, Arr):
                dtype, oshape = operand.dtype, operand.shape
            block = (
                spec.block_shape if isinstance(spec, BlockSpecVal) else None
            )
            tiles.append(RefVal(block, dtype, "vmem", kind="in"))
            if census is not None:
                census.blocks.append(
                    BlockRecord(
                        f"{self._label(node)}#{i}", block, oshape, dtype,
                        "vmem", getattr(node, "lineno", 0),
                    )
                )
            size = _nbytes(block, dtype)
            if size is None:
                sizeable = False
            else:
                total += 2.0 * size
        if census is not None:
            if sizeable:
                census.pipeline_bytes = max(census.pipeline_bytes, total)
            else:
                census.notes.append(
                    "emit_pipeline tile set would not size"
                )
        frame = Frame("loop", "emit_pipeline",
                      line=getattr(node, "lineno", 0))
        interp.tracer.push_frame(frame)
        try:
            interp.call_value(self.body, tiles, {}, node)
        finally:
            interp.tracer.pop_frame()
        return None

    @staticmethod
    def _label(node) -> str:
        return f"emit_pipeline@{getattr(node, 'lineno', 0)}"


# ---------------------------------------------------------------------------
# pallas_call modeling
# ---------------------------------------------------------------------------


def _space_name(value, default="vmem") -> str:
    """Resolve a memory_space operand: ``pltpu.VMEM``/``ANY``/``SMEM``
    ModVals, or a UnionVal from ``vmem if interpret else any`` — the
    hardware (ANY) branch wins, because the census models the real-chip
    path, not the interpreter's park-everything-in-VMEM emulation."""
    if isinstance(value, UnionVal):
        names = [_space_name(o, default="") for o in value.options]
        if "any" in names:
            return "any"
        for n in names:
            if n:
                return n
        return default
    if isinstance(value, ModVal):
        tail = value.path.rsplit(".", 1)[-1].lower()
        if tail in ("vmem", "any", "smem", "hbm"):
            return "any" if tail == "hbm" else tail
    return default


def _as_seq(value) -> List[Any]:
    if isinstance(value, (list, tuple)):
        return list(value)
    if value is None:
        return []
    return [value]


class PallasCallVal:
    """The value ``pl.pallas_call(kernel, ...)`` evaluates to: calling
    it with operands builds a ``KernelCensus``, interprets the kernel
    body over Refs, and returns the declared ``out_shape`` arrays."""

    __slots__ = (
        "model", "kernel", "out_shape", "grid", "in_specs", "out_specs",
        "scratch", "num_prefetch", "node",
    )

    def __init__(
        self, model, kernel, out_shape, grid, in_specs, out_specs,
        scratch, num_prefetch, node,
    ) -> None:
        self.model = model
        self.kernel = kernel
        self.out_shape = out_shape
        self.grid = grid
        self.in_specs = in_specs
        self.out_specs = out_specs
        self.scratch = scratch
        self.num_prefetch = num_prefetch
        self.node = node

    # -- kernel identity ----------------------------------------------------

    def _kernel_fn(self) -> Optional[FuncVal]:
        fn = self.kernel
        while isinstance(fn, PartialVal):
            fn = fn.fn
        return fn if isinstance(fn, FuncVal) else None

    def _site(self, interp) -> Tuple[str, int]:
        line = getattr(self.node, "lineno", 0)
        for fv in reversed(interp._fn_stack):
            if fv.path:
                return fv.path, line
        return interp.tracer.rel, line

    # -- ref construction ---------------------------------------------------

    def _block_ref(
        self, census, operand, spec, kind, label, pipelined
    ) -> RefVal:
        dtype = operand.dtype if isinstance(operand, Arr) else None
        oshape = operand.shape if isinstance(operand, Arr) else None
        block = None
        space = "vmem"
        if isinstance(spec, BlockSpecVal):
            block = spec.block_shape
            space = spec.space or "vmem"
        if block is None:
            shape = oshape
            mult = 1
        else:
            shape = tuple(block)
            mult = 2 if pipelined else 1
        census.blocks.append(
            BlockRecord(label, block, oshape, dtype, space, census.line)
        )
        if space == "vmem":
            if shape is None or dtype is None:
                census.notes.append(
                    f"{label}: operand/block would not size"
                )
            census.vmem_items.append(
                VmemItem(label, shape, dtype, mult, "block")
            )
        return RefVal(shape, dtype, space, kind=kind)

    def _scratch_to_ref(self, census, alloc, index) -> Any:
        label = f"scratch[{index}]"
        if isinstance(alloc, ScratchVal):
            if alloc.space == "vmem":
                census.vmem_items.append(
                    VmemItem(label, alloc.shape, alloc.dtype, 1, "scratch")
                )
            return RefVal(
                alloc.shape, alloc.dtype, alloc.space, name=label,
                kind="scratch",
            )
        if isinstance(alloc, SemSpecVal):
            return SemVal(label, alloc.kind, census)
        if isinstance(alloc, ModVal) and "SemaphoreType" in alloc.path:
            kind = alloc.path.rsplit(".", 1)[-1].lower()
            return SemVal(label, kind, census)
        census.notes.append(f"{label}: unmodeled scratch allocation")
        return UNKNOWN

    # -- the call -----------------------------------------------------------

    def __call__(self, args, kwargs, node, interp):
        rel, line = self._site(interp)
        kfn = self._kernel_fn()
        census = KernelCensus(
            kfn.name if kfn is not None else "<kernel>", rel, line
        )
        self.model.censuses.append(census)
        census.grid = (
            tuple(self.grid) if isinstance(self.grid, (tuple, list))
            else None
        )
        census.grid_steps = (
            _prod(census.grid) if census.grid is not None else 1
        )
        pipelined = census.grid is not None

        operands = list(args)
        refs: List[Any] = []
        n_pre = self.num_prefetch or 0
        for i in range(min(n_pre, len(operands))):
            op = operands[i]
            shape = op.shape if isinstance(op, Arr) else None
            dtype = op.dtype if isinstance(op, Arr) else "int32"
            refs.append(
                RefVal(shape, dtype, "smem", kind="prefetch")
            )
        ins = operands[n_pre:]
        in_specs = _as_seq(self.in_specs)
        for i, op in enumerate(ins):
            spec = in_specs[i] if i < len(in_specs) else None
            refs.append(
                self._block_ref(
                    census, op, spec, "in", f"in[{i}]", pipelined
                )
            )
        outs = _as_seq(self.out_shape)
        out_specs = _as_seq(self.out_specs)
        for i, out in enumerate(outs):
            spec = out_specs[i] if i < len(out_specs) else None
            refs.append(
                self._block_ref(
                    census, out, spec, "out", f"out[{i}]", pipelined
                )
            )
        for i, alloc in enumerate(_as_seq(self.scratch)):
            refs.append(self._scratch_to_ref(census, alloc, i))

        # name refs after the kernel's own parameters (readable censuses
        # and sem findings: "send_sem", not "scratch[0]")
        if kfn is not None:
            params = kfn.node.args
            names = [a.arg for a in params.posonlyargs + params.args]
            for name, ref in zip(names, refs):
                if isinstance(ref, (RefVal, SemVal)):
                    ref.name = name

        self.model.stack.append(census)
        try:
            if self.kernel is None or (
                kfn is None and not callable(self.kernel)
            ):
                census.incomplete = "kernel did not resolve statically"
            else:
                interp.call_value(self.kernel, refs, {}, self.node)
        except interp_mod._Abort:
            census.incomplete = "interpretation budget exhausted"
        except Exception as exc:  # pragma: no cover - defensive
            census.incomplete = (
                f"kernel body failed: {type(exc).__name__}"
            )
        finally:
            self.model.stack.pop()
        if census.incomplete is not None:
            census.notes.append(census.incomplete)

        if isinstance(self.out_shape, (tuple, list)):
            return tuple(
                o if isinstance(o, Arr) else UNKNOWN
                for o in self.out_shape
            )
        return (
            self.out_shape
            if isinstance(self.out_shape, Arr)
            else UNKNOWN
        )


# ---------------------------------------------------------------------------
# the model: dispatch + accounting
# ---------------------------------------------------------------------------


class PallasModel:
    """Per-run pallas state: the census list and the pl/pltpu handlers
    the interpreter consults (``Interpreter(pallas_model=...)``)."""

    def __init__(self) -> None:
        self.censuses: List[KernelCensus] = []
        self.stack: List[KernelCensus] = []

    def current(self) -> Optional[KernelCensus]:
        return self.stack[-1] if self.stack else None

    def sem_event(self, sem: SemVal, delta) -> None:
        census = sem.census or self.current()
        if census is None:
            return
        rec = census.sem(sem.name or "<sem>", sem.kind)
        if not isinstance(delta, int):
            rec["unknown"] = True
            return
        if delta > 0:
            rec["starts"] += delta
        else:
            rec["waits"] += -delta

    def note_dot(self, a, b) -> None:
        census = self.current()
        if census is None:
            return
        sa = a.shape if isinstance(a, Arr) else None
        sb = b.shape if isinstance(b, Arr) else None
        if (
            sa is not None and sb is not None
            and len(sa) >= 2 and len(sb) >= 2
        ):
            census.mxu_tiles.append(
                (sa[-2], sa[-1], sb[-1],
                 a.dtype if isinstance(a, Arr) else None)
            )

    # -- the pl/pltpu call surface ------------------------------------------

    def dispatch(self, path, tail, args, kwargs, node, interp):
        """Handle one dotted call; ``_MISSING`` means "not mine"."""
        if "pallas" not in path:
            return _MISSING
        if tail == "pallas_call":
            return self._pallas_call(args, kwargs, node)
        if tail == "BlockSpec":
            block = args[0] if args else kwargs.get("block_shape")
            index_map = (
                args[1] if len(args) > 1 else kwargs.get("index_map")
            )
            block = tuple(block) if isinstance(block, (tuple, list)) else None
            space = kwargs.get("memory_space")
            return BlockSpecVal(
                block, index_map,
                None if space is None else _space_name(space),
            )
        if tail in ("PrefetchScalarGridSpec", "GridSpec"):
            grid = kwargs.get("grid", args[0] if args else None)
            return GridSpecVal(
                kwargs.get("num_scalar_prefetch", 0) or 0,
                tuple(grid) if isinstance(grid, (tuple, list)) else None,
                _as_seq(kwargs.get("in_specs")),
                _as_seq(kwargs.get("out_specs")),
                _as_seq(kwargs.get("scratch_shapes")),
            )
        if tail in ("VMEM", "SMEM"):
            shape = args[0] if args else kwargs.get("shape")
            dtype = interp_mod._as_dtype(
                args[1] if len(args) > 1 else kwargs.get("dtype")
            )
            shape = (
                tuple(shape) if isinstance(shape, (tuple, list)) else None
            )
            return ScratchVal(shape, dtype, tail.lower())
        if tail in ("DMA", "REGULAR", "BARRIER") and "SemaphoreType" in path:
            return SemSpecVal(tail.lower(), args[0] if args else None)
        if tail == "make_async_copy":
            src = args[0] if args else kwargs.get("src_ref")
            dst = args[1] if len(args) > 1 else kwargs.get("dst_ref")
            sem = args[2] if len(args) > 2 else kwargs.get("sem")
            return DmaVal(self, src, dst, [sem], remote=False, node=node)
        if tail == "make_async_remote_copy":
            src = args[0] if args else kwargs.get("src_ref")
            send = kwargs.get("send_sem")
            recv = kwargs.get("recv_sem")
            # symmetric SPMD model: our send increments our send_sem,
            # and our recv_sem is incremented by the neighbor running
            # the same program — both count as this device's starts
            return DmaVal(
                self, src, kwargs.get("dst_ref"), [send, recv],
                remote=True, node=node,
            )
        if tail == "get_barrier_semaphore":
            census = self.current()
            return SemVal("<barrier>", "barrier", census)
        if tail == "semaphore_signal":
            sem = args[0] if args else kwargs.get("sem")
            inc = kwargs.get("inc", args[1] if len(args) > 1 else 1)
            if isinstance(sem, SemVal):
                self.sem_event(sem, inc)
            return None
        if tail == "semaphore_wait":
            sem = args[0] if args else kwargs.get("sem")
            dec = args[1] if len(args) > 1 else kwargs.get(
                "decrement", 1
            )
            if isinstance(sem, SemVal):
                self.sem_event(sem, -dec if isinstance(dec, int) else dec)
            return None
        if tail == "emit_pipeline":
            return EmitPipelineVal(
                self,
                args[0] if args else None,
                kwargs.get("grid"),
                _as_seq(kwargs.get("in_specs")),
                _as_seq(kwargs.get("out_specs")),
            )
        if tail == "run_scoped":
            return self._run_scoped(args, kwargs, node, interp)
        if tail == "when":
            return WhenVal(
                self, args[0] if args else UNKNOWN,
                getattr(node, "lineno", 0),
            )
        if tail == "program_id":
            return Arr((), "int32")
        if tail == "num_programs":
            census = self.current()
            axis = args[0] if args else None
            if (
                census is not None
                and census.grid is not None
                and isinstance(axis, int)
                and axis < len(census.grid)
                and isinstance(census.grid[axis], int)
            ):
                return census.grid[axis]
            return UNKNOWN
        if tail in ("ds", "dslice"):
            start = args[0] if args else None
            size = args[1] if len(args) > 1 else kwargs.get("size")
            return DSVal(start, size)
        if tail == "with_memory_space_constraint":
            return args[0] if args else UNKNOWN
        if tail in (
            "CompilerParams", "TPUCompilerParams", "CostEstimate",
            "InterpretParams",
        ):
            return UNKNOWN
        # pl.cdiv falls through to the interpreter's generic
        # concrete-int rem/cdiv handler (one ceiling-division source)
        return _MISSING

    def _pallas_call(self, args, kwargs, node) -> PallasCallVal:
        kernel = args[0] if args else kwargs.get("kernel")
        grid_spec = kwargs.get("grid_spec")
        grid = kwargs.get("grid")
        in_specs = kwargs.get("in_specs")
        out_specs = kwargs.get("out_specs")
        scratch = kwargs.get("scratch_shapes")
        num_prefetch = 0
        if isinstance(grid_spec, GridSpecVal):
            grid = grid_spec.grid
            in_specs = grid_spec.in_specs
            out_specs = grid_spec.out_specs
            scratch = grid_spec.scratch
            num_prefetch = grid_spec.num_scalar_prefetch
        return PallasCallVal(
            self, kernel, kwargs.get("out_shape"), grid, in_specs,
            out_specs, scratch, num_prefetch, node,
        )

    def _run_scoped(self, args, kwargs, node, interp):
        """``pltpu.run_scoped(body, *allocs)``: allocate, run, free —
        the allocations join the census working set (they are live for
        the body's whole extent) and the body interprets over them."""
        census = self.current()
        body = args[0] if args else None
        refs: List[Any] = []
        for i, alloc in enumerate(list(args[1:]) + sorted(
            kwargs.items()
        )):
            name = f"run_scoped[{i}]"
            if isinstance(alloc, tuple) and len(alloc) == 2:
                name, alloc = f"run_scoped[{alloc[0]}]", alloc[1]
            if isinstance(alloc, ScratchVal):
                if census is not None and alloc.space == "vmem":
                    census.vmem_items.append(
                        VmemItem(name, alloc.shape, alloc.dtype, 1,
                                 "scratch")
                    )
                refs.append(
                    RefVal(alloc.shape, alloc.dtype, alloc.space,
                           name=name, kind="scratch")
                )
            elif isinstance(alloc, (SemSpecVal, ModVal)):
                kind = (
                    alloc.kind if isinstance(alloc, SemSpecVal)
                    else alloc.path.rsplit(".", 1)[-1].lower()
                )
                refs.append(SemVal(name, kind, census))
            else:
                refs.append(UNKNOWN)
        if body is None:
            return UNKNOWN
        return interp.call_value(body, refs, {}, node)
