"""The DDLB1xx invariant rules: six PRs of hardening, machine-checked.

Each rule encodes one invariant the repo learned the hard way, with the
PR that motivated it:

- **DDLB101 legacy-shard-map**: ``jax.shard_map(`` (or the experimental
  import) outside ``runtime.py`` — the fleet's jax 0.4.x lacks
  ``jax.shard_map``, so every legacy site is a family that silently
  fails there. Findings feed the per-family migration inventory the
  ROADMAP item tracks (PRs 3-6 established ``runtime.shard_map_compat``).
- **DDLB102 wall-clock-deadline**: ``time.time()`` in deadline/timeout
  code (pool, heartbeat, benchmark await loops) — PR 5's NTP-step
  hardening made these paths monotonic end to end; one wall-clock
  deadline reintroduces the multi-hour-capture kill bug.
- **DDLB103 raw-env-read**: ``os.environ``/``os.getenv`` reads of
  ``DDLB_TPU_*`` outside ``envs.py`` — the env surface is the sweep
  resume/signature contract; stray reads dodge the accessor docs, the
  pool's signature keys, and test monkeypatching.
- **DDLB104 unknown-fault-site**: ``faults.inject("site")`` literals and
  fault-plan ``site`` globs cross-checked against
  ``faults.plan.SITES`` — a typo'd site means a seeded chaos plan
  silently injects nothing (PR 4's whole point inverted).
- **DDLB105 locked-sync-primitive**: ``multiprocessing`` ``Value``/
  ``Array`` without ``lock=False`` — a child SIGKILLed mid-beat orphans
  the lock and deadlocks the parent's next read (the PR 5 heartbeat
  lesson; ``heartbeat.new_channel`` is the one blessed constructor).
- **DDLB106 unregistered-telemetry-name**: span/instant/metric name
  literals must appear in ``telemetry.names`` — ``trace_report`` /
  ``observatory.fold()`` join by name, and a rename used to just make
  reports silently emptier (PRs 2/6).
- **DDLB107 silent-swallow**: broad ``except`` whose body swallows
  without telemetry — the failure class the fault harness exists to
  provoke (ported from the PR 4 lint satellite).
"""

from __future__ import annotations

import ast
import fnmatch
from typing import Iterable, List, Optional

from ddlb_tpu.analysis.core import FileContext, Finding, Rule
from ddlb_tpu.faults.plan import SITES as FAULT_SITES
from ddlb_tpu.telemetry.names import all_names as telemetry_names


def _rel_endswith(ctx: FileContext, suffixes: tuple) -> bool:
    rel = ctx.rel.replace("\\", "/")
    return any(rel == s or rel.endswith("/" + s) for s in suffixes)


def _const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


class LegacyShardMapRule(Rule):
    """``jax.shard_map`` call sites pending the compat migration."""

    id = "DDLB101"
    name = "legacy-shard-map"
    rationale = (
        "jax 0.4.x has no jax.shard_map; runtime.shard_map_compat is "
        "the one version bridge, and each legacy site is a family dead "
        "on the old-jax fleet (ROADMAP: finish the migration)"
    )

    def scope(self, ctx: FileContext) -> bool:
        return ctx.in_package() and ctx.path.name != "runtime.py"

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        out: List[Finding] = []
        for node in ctx.nodes(ast.Call):
            fn = node.func
            if (
                isinstance(fn, ast.Attribute)
                and fn.attr == "shard_map"
                and isinstance(fn.value, ast.Name)
                and fn.value.id == "jax"
            ):
                out.append(
                    self.finding(
                        ctx, node.lineno, node.col_offset + 1,
                        f"legacy jax.shard_map call in "
                        f"{family_of(ctx.rel)} — migrate to "
                        f"runtime.shard_map_compat (jax 0.4.x "
                        f"compatibility)",
                    )
                )
        for node in ctx.nodes(ast.ImportFrom):
            if node.module == "jax.experimental.shard_map":
                out.append(
                    self.finding(
                        ctx, node.lineno, node.col_offset + 1,
                        "direct jax.experimental.shard_map import — only "
                        "runtime.shard_map_compat may touch the legacy "
                        "entry point",
                    )
                )
        return out


def family_of(rel: str) -> str:
    """The migration-inventory bucket for a path: the primitive family
    dir, the model module, or the module stem."""
    parts = rel.replace("\\", "/").split("/")
    if "primitives" in parts:
        i = parts.index("primitives")
        if i + 1 < len(parts) - 1:
            return parts[i + 1]
    if "models" in parts:
        return "models/" + parts[-1].removesuffix(".py")
    return parts[-1].removesuffix(".py")


#: the deadline/timeout code paths PR 5 made monotonic end to end —
#: plus the distributed-resilience layer (the supervised launcher's
#: watchdog math and the flight recorder's cross-rank-comparable
#: stamps, ISSUE 8), which compares instants across processes on one
#: host and therefore MUST stay on the system-wide monotonic clock
_DEADLINE_FILES = (
    "ddlb_tpu/pool.py",
    "ddlb_tpu/faults/heartbeat.py",
    "ddlb_tpu/faults/flightrec.py",
    "ddlb_tpu/cli/launch.py",
    "ddlb_tpu/benchmark.py",
    "ddlb_tpu/utils/timing.py",
    # the clock-alignment layer (ISSUE 14) compares monotonic stamps
    # across processes — a wall-clock stamp there would fold NTP steps
    # straight into the offset fit it exists to make trustworthy
    "ddlb_tpu/telemetry/clocksync.py",
    "ddlb_tpu/observatory/timeline.py",
)


class WallClockDeadlineRule(Rule):
    """``time.time()`` in deadline code: NTP steps break the kill math."""

    id = "DDLB102"
    name = "wall-clock-deadline"
    rationale = (
        "heartbeat ages and worker deadlines compare instants hours "
        "apart; an NTP step under a wall clock kills a healthy worker "
        "or spares a hung one (PR 5 hardening) — observatory "
        "timestamping stays wall-clock by design and is out of scope"
    )

    def scope(self, ctx: FileContext) -> bool:
        return ctx.in_package() and _rel_endswith(ctx, _DEADLINE_FILES)

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        out: List[Finding] = []
        for node in ctx.nodes(ast.Call):
            fn = node.func
            if (
                isinstance(fn, ast.Attribute)
                and fn.attr == "time"
                and isinstance(fn.value, ast.Name)
                and fn.value.id == "time"
            ):
                out.append(
                    self.finding(
                        ctx, node.lineno, node.col_offset + 1,
                        "wall clock time.time() in a deadline/timeout "
                        "path — use time.monotonic() (NTP-step immune; "
                        "PR 5 heartbeat hardening)",
                    )
                )
        return out


#: files allowed to read DDLB_TPU_* raw: the accessor layer itself, and
#: the launcher (which assembles whole child environments)
_ENV_EXEMPT = ("ddlb_tpu/envs.py", "ddlb_tpu/cli/launch.py")


class RawEnvReadRule(Rule):
    """Raw ``DDLB_TPU_*`` env reads outside the ``envs.py`` accessors."""

    id = "DDLB103"
    name = "raw-env-read"
    rationale = (
        "envs.py is the documented, monkeypatchable accessor surface "
        "and the pool's signature-key contract; a stray raw read is a "
        "knob that resume keys and tests cannot see"
    )

    def scope(self, ctx: FileContext) -> bool:
        return ctx.in_package() and not _rel_endswith(ctx, _ENV_EXEMPT)

    def _is_environ(self, node: ast.AST) -> bool:
        """``os.environ`` (attribute) or a bare ``environ`` import."""
        return (
            isinstance(node, ast.Attribute)
            and node.attr == "environ"
            and isinstance(node.value, ast.Name)
            and node.value.id == "os"
        ) or (isinstance(node, ast.Name) and node.id == "environ")

    def _module_str_constants(self, ctx: FileContext) -> dict:
        """Module-level ``NAME = "DDLB_TPU_X"`` bindings, so the
        ``CHIP_ENV = "DDLB_TPU_CHIP"`` indirection class is caught
        too (one assignment only; rebound names are skipped)."""
        consts: dict = {}
        rebound: set = set()
        tree = ctx.tree
        assert tree is not None
        for node in tree.body:
            if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Constant
            ) and isinstance(node.value.value, str):
                for target in node.targets:
                    if isinstance(target, ast.Name):
                        if target.id in consts:
                            rebound.add(target.id)
                        consts[target.id] = node.value.value
        return {
            k: v
            for k, v in consts.items()
            if k not in rebound and v.startswith("DDLB_TPU_")
        }

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        out: List[Finding] = []
        consts = self._module_str_constants(ctx)

        def env_name(node: Optional[ast.AST]) -> Optional[str]:
            value = _const_str(node) if node is not None else None
            if value is None and isinstance(node, ast.Name):
                value = consts.get(node.id)
            if value is not None and value.startswith("DDLB_TPU_"):
                return value
            return None

        def hit(node: ast.AST, var: str) -> None:
            out.append(
                self.finding(
                    ctx, node.lineno, node.col_offset + 1,
                    f"raw read of {var} — add/use an accessor in "
                    f"ddlb_tpu/envs.py (the documented, monkeypatchable "
                    f"env surface)",
                )
            )

        for node in ctx.nodes(ast.Call):
            fn = node.func
            name = env_name(node.args[0]) if node.args else None
            if name is None:
                continue
            # os.environ.get(...) / os.getenv(...)
            if isinstance(fn, ast.Attribute) and (
                (fn.attr == "get" and self._is_environ(fn.value))
                or (
                    fn.attr == "getenv"
                    and isinstance(fn.value, ast.Name)
                    and fn.value.id == "os"
                )
            ):
                hit(node, name)
        for node in ctx.nodes(ast.Subscript):
            if not isinstance(node.ctx, ast.Load):
                continue  # writes/deletes configure the env; reads leak
            name = env_name(node.slice)
            if name is not None and self._is_environ(node.value):
                hit(node, name)
        for node in ctx.nodes(ast.Compare):
            if len(node.ops) == 1 and isinstance(
                node.ops[0], (ast.In, ast.NotIn)
            ):
                name = env_name(node.left)
                if name is not None and self._is_environ(
                    node.comparators[0]
                ):
                    hit(node, name)
        return out


class UnknownFaultSiteRule(Rule):
    """Injection-site literals and plan globs must hit the registry."""

    id = "DDLB104"
    name = "unknown-fault-site"
    rationale = (
        "a typo'd site (or a plan glob matching zero sites) makes a "
        "seeded chaos plan silently inject NOTHING — the battery passes "
        "without testing anything (PR 4's contract inverted)"
    )

    #: call attrs whose first string arg is a site name
    _SITE_CALLS = ("inject", "corrupt", "corrupt_row")

    def scope(self, ctx: FileContext) -> bool:
        # the faults package defines the sites; tests exercise fake ones
        return (
            ctx.in_package() or "scripts" in ctx.parts
        ) and "faults" not in ctx.parts and "tests" not in ctx.parts

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        out: List[Finding] = []
        for node in ctx.nodes(ast.Call):
            fn = node.func
            if not (
                isinstance(fn, ast.Attribute)
                and fn.attr in self._SITE_CALLS
                and isinstance(fn.value, ast.Name)
                and fn.value.id in ("faults", "plan")
            ):
                continue
            site = _const_str(node.args[0]) if node.args else None
            if site is not None and site not in FAULT_SITES:
                out.append(
                    self.finding(
                        ctx, node.lineno, node.col_offset + 1,
                        f"fault site '{site}' is not registered in "
                        f"ddlb_tpu/faults/plan.py SITES — a plan "
                        f"targeting it would nominally exist but the "
                        f"analyzer cannot prove it; register the site",
                    )
                )
        # fault-plan dict literals: {"site": <glob>, "kind": ...}
        for node in ctx.nodes(ast.Dict):
            keys = {
                _const_str(k): v
                for k, v in zip(node.keys, node.values)
                if k is not None
            }
            if "site" not in keys or "kind" not in keys:
                continue
            glob = _const_str(keys["site"])
            if glob is None:
                continue
            if not fnmatch.filter(FAULT_SITES, glob):
                out.append(
                    self.finding(
                        ctx, keys["site"].lineno,
                        keys["site"].col_offset + 1,
                        f"fault-plan site glob '{glob}' matches zero "
                        f"registered injection sites — the rule would "
                        f"never fire (see faults/plan.py SITES)",
                    )
                )
        return out


class LockedSyncPrimitiveRule(Rule):
    """``mp.Value``/``Array`` without ``lock=False``: SIGKILL-orphanable."""

    id = "DDLB105"
    name = "locked-sync-primitive"
    rationale = (
        "a child SIGKILLed mid-write orphans the primitive's lock and "
        "the parent's next read deadlocks forever — the exact unbounded "
        "hang the heartbeat channel exists to eliminate; "
        "heartbeat.new_channel is the blessed lock-free constructor"
    )

    def scope(self, ctx: FileContext) -> bool:
        return ctx.in_package()

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        out: List[Finding] = []
        for node in ctx.nodes(ast.Call):
            fn = node.func
            named = (
                fn.attr
                if isinstance(fn, ast.Attribute)
                else fn.id
                if isinstance(fn, ast.Name)
                else None
            )
            if named not in ("Value", "Array"):
                continue
            # the mp signature starts with a 1-2 char typecode string
            typecode = _const_str(node.args[0]) if node.args else None
            if typecode is None or len(typecode) > 2:
                continue
            lock_false = any(
                kw.arg == "lock"
                and isinstance(kw.value, ast.Constant)
                and kw.value.value is False
                for kw in node.keywords
            )
            if not lock_false:
                out.append(
                    self.finding(
                        ctx, node.lineno, node.col_offset + 1,
                        f"multiprocessing {named}() without lock=False — "
                        f"a SIGKILLed child can orphan the lock and "
                        f"deadlock the parent; use "
                        f"faults.heartbeat.new_channel or pass "
                        f"lock=False explicitly",
                    )
                )
        return out


class UnregisteredTelemetryNameRule(Rule):
    """Span/metric name literals must be in ``telemetry.names``."""

    id = "DDLB106"
    name = "unregistered-telemetry-name"
    rationale = (
        "trace_report and observatory.fold() join spans/metrics by "
        "name; an unregistered (or renamed) name makes those joins "
        "silently miss instead of failing loudly"
    )

    _NAME_CALLS = (
        "span", "instant", "record", "record_max", "completed_event",
    )

    def scope(self, ctx: FileContext) -> bool:
        # the telemetry package itself (registry + logger mirror) is the
        # implementation layer the registry describes
        return (
            ctx.in_package() or "scripts" in ctx.parts
        ) and "telemetry" not in ctx.parts and "tests" not in ctx.parts

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        registry = telemetry_names()
        out: List[Finding] = []
        for node in ctx.nodes(ast.Call):
            fn = node.func
            if not (
                isinstance(fn, ast.Attribute)
                and fn.attr in self._NAME_CALLS
                and isinstance(fn.value, ast.Name)
                and fn.value.id == "telemetry"
            ):
                continue
            if not node.args:
                continue
            arg = node.args[0]
            # a conditional of two literals checks both arms
            candidates = (
                [arg.body, arg.orelse]
                if isinstance(arg, ast.IfExp)
                else [arg]
            )
            for cand in candidates:
                name = _const_str(cand)
                if name is not None and name not in registry:
                    out.append(
                        self.finding(
                            ctx, node.lineno, node.col_offset + 1,
                            f"telemetry name '{name}' is not registered "
                            f"in ddlb_tpu/telemetry/names.py — report "
                            f"joins would silently miss it",
                        )
                    )
        return out


class SilentSwallowRule(Rule):
    """Broad ``except`` whose body swallows without telemetry."""

    id = "DDLB107"
    name = "silent-swallow"
    rationale = (
        "an 'except Exception: pass' turns a real failure into an "
        "invisible one — exactly the class the fault-injection harness "
        "exists to provoke; narrow exception types remain legitimate "
        "control flow"
    )

    def scope(self, ctx: FileContext) -> bool:
        return ctx.in_package()

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        def _names(node):
            if node is None:
                return ["<bare>"]
            elts = node.elts if isinstance(node, ast.Tuple) else [node]
            out = []
            for e in elts:
                if isinstance(e, ast.Name):
                    out.append(e.id)
                elif isinstance(e, ast.Attribute):
                    out.append(e.attr)
                else:
                    out.append("?")
            return out

        problems: List[Finding] = []
        for node in ctx.nodes(ast.ExceptHandler):
            silent = all(
                isinstance(stmt, ast.Pass)
                or (
                    isinstance(stmt, ast.Expr)
                    and isinstance(stmt.value, ast.Constant)
                    and stmt.value.value is Ellipsis
                )
                for stmt in node.body
            )
            names = _names(node.type)
            broad = node.type is None or any(
                n in ("Exception", "BaseException") for n in names
            )
            if silent and broad:
                problems.append(
                    self.finding(
                        ctx, node.lineno, node.col_offset + 1,
                        f"swallow: silent 'except {', '.join(names)}: "
                        f"pass' — re-raise, return an error row, or log "
                        f"via ddlb_tpu.telemetry",
                    )
                )
        return problems


RULES = [
    LegacyShardMapRule(),
    WallClockDeadlineRule(),
    RawEnvReadRule(),
    UnknownFaultSiteRule(),
    LockedSyncPrimitiveRule(),
    UnregisteredTelemetryNameRule(),
    SilentSwallowRule(),
]
