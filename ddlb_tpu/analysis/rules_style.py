"""Style/correctness rules ported from the old ``scripts/lint.py``.

Same findings, same scopes, now as engine rules with stable ids so they
participate in suppression, baselining, and SARIF output:

- **DDLB002 undefined-name**: module-global references nothing binds —
  the pyflakes-floor check (``make lint`` must never degrade to a bare
  syntax check). Files with wildcard imports are skipped.
- **DDLB003 forbidden-call**: the bandit-lite battery — string
  ``eval``/``exec``, pickle deserialization, ``os.system``,
  ``shell=True``.
- **DDLB004 bare-print**: package diagnostics go through
  ``ddlb_tpu.telemetry.log`` (rank-tagged, machine-parseable); ``cli/``
  and ``telemetry/`` are the exempt stdout surfaces.
- **DDLB005 missing-docstring**: pydocstyle-lite floor for package
  modules and public classes (sole-public-class modules carry the prose
  at module level).
- **DDLB006 process-spawn**: worker processes come from
  ``ddlb_tpu/pool.py`` only, so row execution cannot silently regress
  to cold spawn-per-row.

(DDLB001 syntax-error is emitted by the engine itself; DDLB107/DDLB108
— the swallow and row-schema ports — live with the domain rules they
became.)
"""

from __future__ import annotations

import ast
import builtins
import symtable
from typing import Iterable, List

from ddlb_tpu.analysis.core import FileContext, Finding, Rule

_MODULE_DUNDERS = {
    "__file__", "__name__", "__doc__", "__package__", "__spec__",
    "__builtins__", "__loader__", "__path__", "__annotations__",
    "__all__", "__debug__", "__class__",
}


def _module_bindings(tree: ast.Module) -> set:
    """Every name the module's global namespace can bind at runtime."""
    names: set = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                names.add(a.asname or a.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom):
            for a in node.names:
                if a.name != "*":
                    names.add(a.asname or a.name)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
            names.add(node.name)
        elif isinstance(node, ast.Name) and isinstance(
            node.ctx, (ast.Store, ast.Del)
        ):
            names.add(node.id)
        elif isinstance(node, (ast.Global, ast.Nonlocal)):
            names.update(node.names)
        elif isinstance(node, (ast.MatchAs, ast.MatchStar)):
            if node.name:  # match-case capture patterns bind raw strings
                names.add(node.name)
        elif isinstance(node, ast.MatchMapping) and node.rest:
            names.add(node.rest)
        elif hasattr(ast, "TypeAlias") and isinstance(
            node, ast.TypeAlias
        ):  # PEP 695 `type X = ...`
            names.add(node.name.id)
    return names


def _global_refs(table: symtable.SymbolTable, out: set) -> None:
    """Names referenced as globals anywhere in the scope tree; scope
    resolution is symtable's, so parameters, locals, closures and class
    scopes are never reported."""
    is_module = table.get_type() == "module"
    for sym in table.get_symbols():
        if not sym.is_referenced() or sym.is_imported():
            continue
        if is_module:
            if not sym.is_assigned():
                out.add(sym.get_name())
        elif sym.is_global() and not sym.is_assigned():
            out.add(sym.get_name())
    for child in table.get_children():
        _global_refs(child, out)


class UndefinedNameRule(Rule):
    """Module-global references that nothing binds (pyflakes floor)."""

    id = "DDLB002"
    name = "undefined-name"
    rationale = (
        "an undefined name fails the build even on a checkout without "
        "pyflakes (the lint tier must never degrade to compileall)"
    )

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        tree = ctx.tree
        assert tree is not None
        if any(
            isinstance(n, ast.ImportFrom)
            and any(a.name == "*" for a in n.names)
            for n in ctx.nodes(ast.ImportFrom)
        ):
            return []  # wildcard import: globals unknowable statically
        try:
            table = symtable.symtable(ctx.source, str(ctx.path), "exec")
        except SyntaxError:  # pragma: no cover - ast parsed, so unlikely
            return []
        known = _module_bindings(tree) | _MODULE_DUNDERS | set(dir(builtins))
        refs: set = set()
        _global_refs(table, refs)
        lines = {}
        cols = {}
        for node in ctx.nodes(ast.Name):
            if isinstance(node.ctx, ast.Load) and node.id not in lines:
                lines[node.id] = node.lineno
                cols[node.id] = node.col_offset + 1
        return [
            self.finding(
                ctx, lines.get(name, 1), cols.get(name, 1),
                f"undefined name '{name}'",
            )
            for name in sorted(refs - known)
        ]


_FORBIDDEN_CALLS = {
    "eval": "eval() on a string",
    "exec": "exec() on a string",
}
_FORBIDDEN_ATTRS = {
    ("pickle", "load"): "pickle.load (arbitrary code on untrusted data)",
    ("pickle", "loads"): "pickle.loads (arbitrary code on untrusted data)",
    ("os", "system"): "os.system (shell injection; use subprocess lists)",
}


class ForbiddenCallRule(Rule):
    """Dangerous-call patterns with no legitimate use in this codebase."""

    id = "DDLB003"
    name = "forbidden-call"
    rationale = (
        "subprocess always runs argv lists here; nothing evals strings "
        "or loads pickles — a new hit is either a bug or needs an "
        "explicit suppression with a justification"
    )

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        out: List[Finding] = []
        for node in ctx.nodes(ast.Call):
            fn = node.func
            if isinstance(fn, ast.Name) and fn.id in _FORBIDDEN_CALLS:
                out.append(
                    self.finding(
                        ctx, node.lineno, node.col_offset + 1,
                        f"security: {_FORBIDDEN_CALLS[fn.id]}",
                    )
                )
            if isinstance(fn, ast.Attribute) and isinstance(
                fn.value, ast.Name
            ):
                why = _FORBIDDEN_ATTRS.get((fn.value.id, fn.attr))
                if why:
                    out.append(
                        self.finding(
                            ctx, node.lineno, node.col_offset + 1,
                            f"security: {why}",
                        )
                    )
            for kw in node.keywords:
                if (
                    kw.arg == "shell"
                    and isinstance(kw.value, ast.Constant)
                    and kw.value.value is True
                ):
                    out.append(
                        self.finding(
                            ctx, node.lineno, node.col_offset + 1,
                            "security: shell=True (use an argv list)",
                        )
                    )
        return out


#: package subtrees exempt from the bare-print ban: the CLI is the
#: user-facing stdout surface, and the telemetry logger is the one place
#: a print legitimately lives (it is what everything else must call)
_PRINT_EXEMPT_DIRS = {"cli", "telemetry"}


class BarePrintRule(Rule):
    """Bare ``print(`` in package code interleaves unattributably."""

    id = "DDLB004"
    name = "bare-print"
    rationale = (
        "on a multi-process pod untagged prints interleave "
        "unattributably and the capture pipelines substring-match free "
        "text; package diagnostics go through ddlb_tpu.telemetry.log"
    )

    def scope(self, ctx: FileContext) -> bool:
        return ctx.in_package() and not (
            set(ctx.parts) & _PRINT_EXEMPT_DIRS
        )

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        return [
            self.finding(
                ctx, node.lineno, node.col_offset + 1,
                "print: bare print() in package code — use "
                "ddlb_tpu.telemetry.log (rank-tagged, machine-parseable)",
            )
            for node in ctx.nodes(ast.Call)
            if isinstance(node.func, ast.Name) and node.func.id == "print"
        ]


class DocstringRule(Rule):
    """pydocstyle-lite presence floor for package modules/classes."""

    id = "DDLB005"
    name = "missing-docstring"
    rationale = (
        "every package module needs a docstring; every public class "
        "needs one unless it is its module's only public class (the "
        "one-member-class-per-file pattern carries the prose at module "
        "level)"
    )

    def scope(self, ctx: FileContext) -> bool:
        return ctx.in_package()

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        tree = ctx.tree
        assert tree is not None
        out: List[Finding] = []
        module_doc = ast.get_docstring(tree)
        if not module_doc:
            out.append(
                self.finding(ctx, 1, 1, "docstring: module has no docstring")
            )
        public_classes = [
            n
            for n in ctx.nodes(ast.ClassDef)
            if not n.name.startswith("_")
        ]
        sole = len(public_classes) == 1 and bool(module_doc)
        for node in public_classes:
            if not ast.get_docstring(node) and not sole:
                out.append(
                    self.finding(
                        ctx, node.lineno, node.col_offset + 1,
                        f"docstring: public class '{node.name}' has no "
                        f"docstring",
                    )
                )
        return out


class ProcessSpawnRule(Rule):
    """Direct ``Process()`` construction outside the warm-worker pool."""

    id = "DDLB006"
    name = "process-spawn"
    rationale = (
        "the warm-worker pool is the one spawner for row/worker "
        "processes — every spawn inherits its heartbeat channel, daemon "
        "flag, and queue-release discipline"
    )

    def scope(self, ctx: FileContext) -> bool:
        return ctx.in_package() and ctx.path.name != "pool.py"

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        out: List[Finding] = []
        for node in ctx.nodes(ast.Call):
            fn = node.func
            named = (
                fn.attr
                if isinstance(fn, ast.Attribute)
                else fn.id
                if isinstance(fn, ast.Name)
                else None
            )
            if named == "Process":
                out.append(
                    self.finding(
                        ctx, node.lineno, node.col_offset + 1,
                        "process: direct Process() construction — worker "
                        "processes must come from ddlb_tpu/pool.py "
                        "(WorkerPool), so row execution cannot regress "
                        "to cold spawn-per-row",
                    )
                )
        return out


RULES = [
    UndefinedNameRule(),
    ForbiddenCallRule(),
    BarePrintRule(),
    DocstringRule(),
    ProcessSpawnRule(),
]
