"""Analysis engine: one parse per file, pluggable rules, suppressions.

The engine's contract:

- **Parse once.** ``build_context`` turns a file into a ``FileContext``
  holding the source, its lines, the AST, a by-type node index, and the
  inline-suppression map. Every rule reads the same context — adding a
  rule never adds a parse.
- **Rules are small objects.** A rule declares an ``id`` (``DDLB101``),
  a kebab ``name`` (SARIF), a severity, a one-line rationale, a
  ``scope(ctx)`` predicate, and ``check(ctx)`` yielding findings.
  Project rules implement ``check_project(contexts)`` instead and run
  once per invocation (cross-file invariants).
- **Suppression.** ``# ddlb: ignore[DDLB101]`` (comma lists allowed) on
  the finding's line suppresses it; a suppression that suppressed
  nothing is itself a finding (``DDLB100``) so dead ignores can't
  accumulate.
- **Severity.** ``error`` findings fail the build unless suppressed or
  baselined (``ddlb_tpu.analysis.baseline``); ``warn`` findings are
  advisory.

Scope conventions mirror the old lint: *package* rules apply to files
whose path contains a ``ddlb_tpu`` component (so fixture trees under a
tmp dir behave like the real package); universal rules apply everywhere
``scripts/analyze.py`` is pointed.
"""

from __future__ import annotations

import ast
import io
import re
import tokenize
from pathlib import Path
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Set, Tuple

#: suppression-comment pattern; the marker is the word "ddlb", a colon,
#: "ignore", then one or more bracketed comma-separated rule ids
_SUPPRESS_RE = re.compile(r"#\s*ddlb:\s*ignore\[([A-Za-z0-9_,\s]+)\]")

SEVERITIES = ("error", "warn")


class Finding:
    """One rule violation at a location.

    ``snippet`` is the stripped source line — the line-drift-stable key
    the baseline matches on (a finding survives unrelated edits above
    it). ``suppressed``/``baselined`` are set by the engine/baseline
    layers; both keep the finding visible to ``--json``/SARIF consumers
    while excluding it from the exit code.
    """

    def __init__(
        self,
        rule: str,
        path: str,
        line: int,
        col: int,
        message: str,
        severity: str = "error",
        snippet: str = "",
    ) -> None:
        self.rule = rule
        self.path = path
        self.line = int(line)
        self.col = int(col)
        self.message = message
        self.severity = severity
        self.snippet = snippet
        self.suppressed = False
        self.baselined = False

    @property
    def counts(self) -> bool:
        """True when this finding should fail the build."""
        return (
            self.severity == "error"
            and not self.suppressed
            and not self.baselined
        )

    def key(self) -> Tuple[str, str, str]:
        """Baseline identity: rule + path + stripped source line."""
        return (self.rule, self.path, self.snippet)

    def legacy_str(self) -> str:
        """The old ``scripts/lint.py`` one-line format (shim compat)."""
        return f"{self.path}:{self.line}: {self.message}"

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (
            f"Finding({self.rule} {self.path}:{self.line}:{self.col} "
            f"{self.severity} {self.message!r})"
        )


class FileContext:
    """Everything the rules need about one file, computed exactly once.

    ``parsed`` (when supplied by the ``build_context`` mtime cache)
    short-circuits the expensive immutable work — the AST parse and the
    suppression-comment tokenization — while the mutable per-run state
    (``used_suppressions``, rule caches hung off the instance) always
    starts fresh.
    """

    def __init__(
        self,
        path: Path,
        rel: str,
        source: str,
        parsed: Optional[Tuple] = None,
    ) -> None:
        self.path = path
        self.rel = rel  # repo-relative posix path (or the input as given)
        self.source = source
        self.lines = source.splitlines()
        self.tree: Optional[ast.Module] = None
        self.syntax_error: Optional[SyntaxError] = None
        #: line -> rule ids a ``# ddlb: ignore[...]`` comment names there
        self.suppressions: Dict[int, Set[str]] = {}
        #: (line, rule) pairs that actually suppressed a finding
        self.used_suppressions: Set[Tuple[int, str]] = set()
        self._index: Optional[Dict[type, List[ast.AST]]] = None
        if parsed is not None:
            self.tree, self.syntax_error, cached_supp = parsed
            self.suppressions = {
                line: set(ids) for line, ids in cached_supp.items()
            }
            return
        try:
            self.tree = ast.parse(source, filename=str(path))
        except SyntaxError as exc:
            self.syntax_error = exc
        self._collect_suppressions()

    @property
    def parts(self) -> Tuple[str, ...]:
        return self.path.parts

    def in_package(self) -> bool:
        """Whether this file belongs to the ``ddlb_tpu`` package tree
        (true for fixture trees containing a ``ddlb_tpu`` component)."""
        return "ddlb_tpu" in self.parts

    def nodes(self, *types: type) -> Iterator[ast.AST]:
        """All AST nodes of the given types, from the shared one-walk
        index (empty when the file failed to parse)."""
        if self.tree is None:
            return iter(())
        if self._index is None:
            index: Dict[type, List[ast.AST]] = {}
            for node in ast.walk(self.tree):
                index.setdefault(type(node), []).append(node)
            self._index = index
        out: List[ast.AST] = []
        for t in types:
            for bucket_type, bucket in self._index.items():
                if issubclass(bucket_type, t):
                    out.extend(bucket)
        return iter(out)

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def _collect_suppressions(self) -> None:
        """Comment tokens only (a suppression spelled inside a string
        literal must not suppress anything); regex fallback if the
        tokenizer chokes on a file that nevertheless parsed."""
        comments: List[Tuple[int, str]] = []
        try:
            for tok in tokenize.generate_tokens(
                io.StringIO(self.source).readline
            ):
                if tok.type == tokenize.COMMENT:
                    comments.append((tok.start[0], tok.string))
        except (tokenize.TokenError, IndentationError, SyntaxError):
            comments = [
                (i + 1, line)
                for i, line in enumerate(self.lines)
                if "#" in line
            ]
        for lineno, text in comments:
            m = _SUPPRESS_RE.search(text)
            if m:
                ids = {
                    part.strip()
                    for part in m.group(1).split(",")
                    if part.strip()
                }
                self.suppressions.setdefault(lineno, set()).update(ids)


class Rule:
    """Base class for per-file rules; subclasses override ``check``."""

    id: str = "DDLB000"
    name: str = "unnamed-rule"
    severity: str = "error"
    rationale: str = ""

    def scope(self, ctx: FileContext) -> bool:
        """Whether this rule runs on ``ctx`` (default: every file)."""
        return True

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        raise NotImplementedError

    def finding(
        self, ctx: FileContext, line: int, col: int, message: str
    ) -> Finding:
        return Finding(
            self.id,
            ctx.rel,
            line,
            col,
            message,
            severity=self.severity,
            snippet=ctx.line_text(line),
        )


class ProjectRule(Rule):
    """A repo-level rule: runs once over every context (cross-file
    state), not per file. ``check_project`` replaces ``check``."""

    def check(self, ctx: FileContext) -> Iterable[Finding]:  # pragma: no cover
        return ()

    def check_project(
        self, contexts: Sequence[FileContext]
    ) -> Iterable[Finding]:
        raise NotImplementedError


UNUSED_SUPPRESSION_ID = "DDLB100"
UNUSED_SUPPRESSION_NAME = "unused-suppression"


def all_rules() -> List[Rule]:
    """Every registered rule instance, stable-ordered by id. Imported
    lazily so ``core`` has no import cycle with the rule modules."""
    from ddlb_tpu.analysis import rules_domain, rules_project, rules_style
    from ddlb_tpu.analysis.pallas import rules_pallas
    from ddlb_tpu.analysis.spmd import rules_spmd

    rules: List[Rule] = []
    for module in (
        rules_style, rules_domain, rules_project, rules_spmd, rules_pallas
    ):
        rules.extend(module.RULES)
    return sorted(rules, key=lambda r: r.id)


def repo_root() -> Path:
    """The repository root (the directory holding ``ddlb_tpu/``)."""
    return Path(__file__).resolve().parent.parent.parent


def relativize(path: Path, root: Optional[Path] = None) -> str:
    """The repo-relative posix path when the file lives under ``root``,
    else the path as given (fixture trees keep their tmp prefix)."""
    path = Path(path)
    root = root or repo_root()
    try:
        return path.resolve().relative_to(root.resolve()).as_posix()
    except ValueError:
        return path.as_posix()


#: (resolved path) -> (mtime_ns, size, tree, syntax_error, suppressions)
#: — the in-process parse cache. One ``analyze`` invocation builds the
#: same FileContext several times (the project rules re-anchor findings,
#: DDLB123/DDLB130 drive the ClassRegistry over the same files, the
#: test suite runs dozens of sweeps per process); keying on
#: (mtime_ns, size) keeps a stale AST impossible while making every
#: re-parse after the first a dict hit. Mutable per-run state is NOT
#: cached — ``FileContext`` rebuilds it fresh (see its docstring).
_PARSE_CACHE: Dict[str, Tuple[int, int, object, object, Dict]] = {}

_PARSE_CACHE_MAX = 2048


def build_context(path: Path, root: Optional[Path] = None) -> FileContext:
    """Parse ``path`` once into a ``FileContext`` (mtime-keyed cache)."""
    path = Path(path)
    rel = relativize(path, root)
    try:
        stat = path.stat()
        key = str(path.resolve())
    except OSError:
        key = None
    if key is not None:
        hit = _PARSE_CACHE.get(key)
        if hit is not None and hit[0] == stat.st_mtime_ns and (
            hit[1] == stat.st_size
        ):
            source = path.read_text(encoding="utf-8")
            return FileContext(
                path, rel, source, parsed=(hit[2], hit[3], hit[4])
            )
    source = path.read_text(encoding="utf-8")
    ctx = FileContext(path, rel, source)
    if key is not None:
        if len(_PARSE_CACHE) >= _PARSE_CACHE_MAX:
            _PARSE_CACHE.clear()
        _PARSE_CACHE[key] = (
            stat.st_mtime_ns, stat.st_size, ctx.tree, ctx.syntax_error,
            {line: set(ids) for line, ids in ctx.suppressions.items()},
        )
    return ctx


def _apply_suppressions(ctx: FileContext, findings: List[Finding]) -> None:
    for f in findings:
        ids = ctx.suppressions.get(f.line, ())
        if f.rule in ids:
            f.suppressed = True
            ctx.used_suppressions.add((f.line, f.rule))


def _unused_suppression_findings(ctx: FileContext) -> List[Finding]:
    out = []
    for lineno, ids in sorted(ctx.suppressions.items()):
        for rule_id in sorted(ids):
            if (lineno, rule_id) not in ctx.used_suppressions:
                out.append(
                    Finding(
                        UNUSED_SUPPRESSION_ID,
                        ctx.rel,
                        lineno,
                        1,
                        f"unused suppression: no {rule_id} finding on "
                        f"this line — remove the '# ddlb: ignore' comment",
                        severity="error",
                        snippet=ctx.line_text(lineno),
                    )
                )
    return out


def analyze(
    paths: Sequence[Path],
    rules: Optional[Sequence[Rule]] = None,
    root: Optional[Path] = None,
    project_rules: bool = True,
    contexts_out: Optional[List[FileContext]] = None,
) -> List[Finding]:
    """Run the rule battery over ``paths`` (files, pre-expanded).

    Returns every finding — including suppressed ones — sorted by
    location; callers filter on ``Finding.counts`` / render as needed.
    ``project_rules=False`` skips the repo-level rules (the
    ``--changed-only`` fast path still runs them by default because
    they are cheap and their state is global). ``contexts_out``, when a
    list, receives every parsed ``FileContext`` so callers (the DDLB101
    migrated/total inventory) can reuse the one-parse-per-file ASTs.
    """
    rules = list(rules if rules is not None else all_rules())
    per_file = [r for r in rules if not isinstance(r, ProjectRule)]
    project = [r for r in rules if isinstance(r, ProjectRule)]
    contexts: List[FileContext] = []
    findings: List[Finding] = []
    for path in paths:
        ctx = build_context(Path(path), root=root)
        contexts.append(ctx)
        if contexts_out is not None:
            contexts_out.append(ctx)
        file_findings: List[Finding] = []
        if ctx.syntax_error is not None:
            exc = ctx.syntax_error
            file_findings.append(
                Finding(
                    "DDLB001",
                    ctx.rel,
                    exc.lineno or 1,
                    (exc.offset or 1),
                    f"syntax error: {exc.msg}",
                    severity="error",
                    snippet=ctx.line_text(exc.lineno or 1),
                )
            )
        else:
            for rule in per_file:
                if rule.scope(ctx):
                    file_findings.extend(rule.check(ctx))
        _apply_suppressions(ctx, file_findings)
        findings.extend(file_findings)
    if project_rules:
        project_findings: List[Finding] = []
        for rule in project:
            project_findings.extend(rule.check_project(contexts))
        by_rel = {ctx.rel: ctx for ctx in contexts}
        root_dir = root or repo_root()
        for f in project_findings:
            ctx = by_rel.get(f.path)
            if ctx is None:
                # a project rule may anchor findings at files outside
                # this sweep (e.g. a row-writer file on a changed-only
                # run) — their inline suppressions still apply, but
                # their unused suppressions are only the FULL sweep's
                # business (the context is not appended to `contexts`)
                candidate = root_dir / f.path
                if candidate.is_file():
                    ctx = by_rel[f.path] = build_context(
                        candidate, root=root_dir
                    )
            if ctx is not None:
                _apply_suppressions(ctx, [f])
        findings.extend(project_findings)
    for ctx in contexts:
        findings.extend(_unused_suppression_findings(ctx))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def expand_targets(targets: Sequence[str]) -> List[Path]:
    """Directories recurse to ``*.py`` (skipping ``__pycache__``); file
    arguments must exist. Raises ``FileNotFoundError`` for a missing
    target — analyzing nothing must never look like a clean pass."""
    out: List[Path] = []
    for arg in targets:
        p = Path(arg)
        if p.is_dir():
            out.extend(
                f
                for f in sorted(p.rglob("*.py"))
                if "__pycache__" not in f.parts
            )
        elif p.suffix == ".py" and p.exists():
            out.append(p)
        else:
            raise FileNotFoundError(arg)
    return out
