"""Finding renderers: text, JSON, SARIF 2.1.0, and the DDLB101 inventory.

Pure functions from findings to strings/documents — the CLI
(``scripts/analyze.py``) owns stdout and exit codes. SARIF output
targets the 2.1.0 schema (one run, one driver, per-rule metadata from
the registered rule objects; suppressed/baselined results carry SARIF
``suppressions`` entries so code-scanning UIs show them greyed instead
of dropped).
"""

from __future__ import annotations

import ast
import json
from collections import Counter
from typing import Any, Dict, List, Sequence

from ddlb_tpu.analysis.core import Finding, Rule, all_rules
from ddlb_tpu.analysis.rules_domain import family_of

SARIF_SCHEMA = (
    "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/"
    "Schemata/sarif-schema-2.1.0.json"
)


def text_line(f: Finding) -> str:
    mark = ""
    if f.suppressed:
        mark = " (suppressed)"
    elif f.baselined:
        mark = " (baselined)"
    return (
        f"{f.path}:{f.line}:{f.col}: {f.severity}[{f.rule}] "
        f"{f.message}{mark}"
    )


def render_text(
    findings: Sequence[Finding], show_masked: bool = False
) -> List[str]:
    """One line per ACTIONABLE finding (masked ones only on request)."""
    return [
        text_line(f)
        for f in findings
        if show_masked or not (f.suppressed or f.baselined)
    ]


def render_json(findings: Sequence[Finding]) -> Dict[str, Any]:
    return {
        "findings": [
            {
                "rule": f.rule,
                "path": f.path,
                "line": f.line,
                "col": f.col,
                "severity": f.severity,
                "message": f.message,
                "snippet": f.snippet,
                "suppressed": f.suppressed,
                "baselined": f.baselined,
            }
            for f in findings
        ],
        "counts": {
            "total": len(findings),
            "errors": sum(1 for f in findings if f.counts),
            "suppressed": sum(1 for f in findings if f.suppressed),
            "baselined": sum(1 for f in findings if f.baselined),
        },
    }


def _rule_metadata() -> List[Dict[str, Any]]:
    rules_meta = []
    for rule in all_rules():
        rules_meta.append(
            {
                "id": rule.id,
                "name": rule.name,
                "shortDescription": {"text": rule.rationale or rule.name},
                "defaultConfiguration": {
                    "level": "error" if rule.severity == "error" else "warning"
                },
            }
        )
    return rules_meta


def render_sarif(findings: Sequence[Finding]) -> Dict[str, Any]:
    """A single-run SARIF 2.1.0 document."""
    results = []
    for f in findings:
        result: Dict[str, Any] = {
            "ruleId": f.rule,
            "level": "error" if f.severity == "error" else "warning",
            "message": {"text": f.message},
            "locations": [
                {
                    "physicalLocation": {
                        "artifactLocation": {
                            "uri": f.path,
                            "uriBaseId": "SRCROOT",
                        },
                        "region": {
                            "startLine": max(1, f.line),
                            "startColumn": max(1, f.col),
                        },
                    }
                }
            ],
        }
        suppressions = []
        if f.suppressed:
            suppressions.append(
                {"kind": "inSource", "justification": "ddlb: ignore comment"}
            )
        if f.baselined:
            suppressions.append(
                {
                    "kind": "external",
                    "justification": "analysis_baseline.json",
                }
            )
        if suppressions:
            result["suppressions"] = suppressions
        results.append(result)
    known_ids = {r.id for r in all_rules()}
    extra_ids = sorted(
        {f.rule for f in findings if f.rule not in known_ids}
    )
    rules_meta = _rule_metadata() + [
        {
            "id": rule_id,
            "name": {
                "DDLB001": "syntax-error",
                "DDLB100": "unused-suppression",
                "DDLB110": "stale-baseline",
            }.get(rule_id, rule_id.lower()),
            "shortDescription": {"text": rule_id},
            "defaultConfiguration": {"level": "error"},
        }
        for rule_id in extra_ids
    ]
    return {
        "$schema": SARIF_SCHEMA,
        "version": "2.1.0",
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": "ddlb-analyze",
                        "informationUri": (
                            "docs/source/static_analysis.rst"
                        ),
                        "version": "1.0.0",
                        "rules": rules_meta,
                    }
                },
                # SRCROOT deliberately unresolved (SARIF §3.14.14): the
                # consumer roots the repo-relative URIs at its checkout
                "results": results,
            }
        ],
    }


def compat_call_sites(contexts: Sequence[Any]) -> Counter:
    """Per-family count of ``shard_map_compat(`` call-through sites —
    the MIGRATED side of the DDLB101 ledger. ``runtime.py`` (the compat
    shim's own definition and internal uses) is excluded exactly like
    the DDLB101 rule excludes it from the remaining side."""
    counts: Counter = Counter()
    for ctx in contexts:
        if (
            ctx.tree is None
            or not ctx.in_package()
            or ctx.path.name == "runtime.py"
        ):
            continue
        for node in ctx.nodes(ast.Call):
            fn = node.func
            name = (
                fn.id
                if isinstance(fn, ast.Name)
                else fn.attr if isinstance(fn, ast.Attribute) else ""
            )
            if name == "shard_map_compat":
                counts[family_of(ctx.rel)] += 1
    return counts


def shard_map_inventory(
    findings: Sequence[Finding], contexts: Sequence[Any] = (),
) -> List[str]:
    """The DDLB101 per-family migration inventory the ROADMAP item
    needs: counts INCLUDE baselined findings (they are the backlog),
    sorted largest-first. When ``contexts`` are supplied (the full
    sweep), each line shows migrated/total progress — the
    ``shard_map_compat`` call-through sites next to the legacy
    remainder — instead of just the remaining count."""
    counts: Counter = Counter()
    for f in findings:
        if f.rule == "DDLB101" and not f.suppressed:
            counts[family_of(f.path)] += 1
    migrated = compat_call_sites(contexts) if contexts else Counter()
    if not counts and not migrated:
        return []
    remaining = sum(counts.values())
    if not migrated:
        lines = [
            f"shard_map migration inventory: {remaining} legacy site(s) "
            f"remaining (DDLB101, incl. baselined):"
        ]
        for family, n in sorted(
            counts.items(), key=lambda kv: (-kv[1], kv[0])
        ):
            lines.append(f"  {family:32s} {n}")
        return lines
    done = sum(migrated.values())
    lines = [
        f"shard_map migration inventory: {remaining} legacy site(s) "
        f"remaining, {done}/{done + remaining} migrated (DDLB101, "
        f"incl. baselined):"
    ]
    families = sorted(
        set(counts) | set(migrated),
        key=lambda fam: (-counts.get(fam, 0), fam),
    )
    for family in families:
        n = counts.get(family, 0)
        m = migrated.get(family, 0)
        lines.append(
            f"  {family:32s} {n} remaining, {m}/{m + n} migrated"
        )
    return lines


def dump_json(doc: Dict[str, Any]) -> str:
    return json.dumps(doc, indent=2, sort_keys=False) + "\n"


# re-exported for the CLI's --list-rules mode
__all__ = [
    "Rule",
    "dump_json",
    "render_json",
    "render_sarif",
    "render_text",
    "shard_map_inventory",
    "text_line",
]
