"""Committed baseline of grandfathered findings — shrink-only by design.

``analysis_baseline.json`` at the repo root records the error findings
that predate a rule (today: the ~37 legacy ``jax.shard_map`` sites the
DDLB101 migration inventory tracks). The contract:

- **Masking.** A current finding whose ``(rule, path, stripped source
  line)`` key appears in the baseline (with remaining count) is marked
  ``baselined`` — visible in every output mode, excluded from the exit
  code. Keying on the stripped source line instead of the line NUMBER
  means unrelated edits above a grandfathered site don't un-mask it.
- **Stale entries are errors.** A baseline entry that matches no
  current finding (the site was fixed, moved, or rewritten) is itself
  reported (``DDLB110 stale-baseline``) — the fix and the baseline
  shrink land in the same commit, so the file can only ever shrink.
- **Growth is refused.** ``scripts/analyze.py --update-baseline``
  rewrites the file from the current findings but refuses any key whose
  count would GROW unless ``--allow-baseline-growth`` is passed — new
  violations get fixed or suppressed with a reviewed inline comment,
  never silently grandfathered.
"""

from __future__ import annotations

import json
from collections import Counter
from pathlib import Path
from typing import List, Optional, Sequence, Set, Tuple

from ddlb_tpu.analysis.core import Finding

BASELINE_NAME = "analysis_baseline.json"
STALE_BASELINE_ID = "DDLB110"
STALE_BASELINE_NAME = "stale-baseline"

Key = Tuple[str, str, str]  # (rule, path, snippet)


def load(path: Path) -> Counter:
    """The baseline as a Counter of finding keys; empty when the file
    does not exist (a new checkout starts strict). A malformed file
    raises — a silently ignored baseline would un-mask everything."""
    if not path.exists():
        return Counter()
    doc = json.loads(path.read_text(encoding="utf-8"))
    counts: Counter = Counter()
    for entry in doc.get("findings", []):
        key = (
            str(entry["rule"]),
            str(entry["path"]),
            str(entry.get("snippet", "")),
        )
        counts[key] += int(entry.get("count", 1))
    return counts


def apply(
    findings: Sequence[Finding],
    baseline: Counter,
    path: Path,
    analyzed: Optional[Set[str]] = None,
) -> List[Finding]:
    """Mark baselined error findings in place; return stale-baseline
    findings for entries nothing matched (shrink enforcement).

    ``analyzed`` restricts staleness to baseline entries whose file was
    actually in this sweep — a ``--changed-only`` run must not report
    the untouched backlog as stale (only the full sweep, where
    ``analyzed=None``, can prove an entry dead — including entries for
    deleted files)."""
    remaining = Counter(baseline)
    for f in findings:
        if f.severity != "error" or f.suppressed:
            continue
        if remaining.get(f.key(), 0) > 0:
            remaining[f.key()] -= 1
            f.baselined = True
    stale: List[Finding] = []
    for (rule, rel, snippet), count in sorted(remaining.items()):
        if count > 0 and (analyzed is None or rel in analyzed):
            stale.append(
                Finding(
                    STALE_BASELINE_ID,
                    path.name,
                    1,
                    1,
                    f"stale baseline entry: {rule} at {rel} "
                    f"({snippet!r} x{count}) matches no current finding "
                    f"— the site was fixed; shrink the baseline "
                    f"(scripts/analyze.py --update-baseline)",
                )
            )
    return stale


#: meta-findings about the analysis itself — never baselineable. A
#: stale entry appended by ``apply`` must not re-enter the file the
#: update is about to shrink, and a dead suppression is fixed by
#: deleting the comment, not by grandfathering it.
_META_RULES = (STALE_BASELINE_ID, "DDLB100")


def _aggregate(findings: Sequence[Finding]) -> Counter:
    counts: Counter = Counter()
    for f in findings:
        if (
            f.severity == "error"
            and not f.suppressed
            and f.rule not in _META_RULES
        ):
            counts[f.key()] += 1
    return counts


def update(
    findings: Sequence[Finding], path: Path, allow_growth: bool = False
) -> List[str]:
    """Rewrite the baseline from the current unsuppressed error
    findings. Returns the list of GROWN keys when growth was refused
    (and writes nothing); an empty list means the file was written."""
    new = _aggregate(findings)
    old = load(path)
    grown = sorted(
        f"{rule} {rel} ({snippet!r}): {old.get((rule, rel, snippet), 0)} "
        f"-> {count}"
        for (rule, rel, snippet), count in new.items()
        if count > old.get((rule, rel, snippet), 0)
    )
    if grown and not allow_growth and old:
        return grown
    entries = [
        {"rule": rule, "path": rel, "snippet": snippet, "count": count}
        for (rule, rel, snippet), count in sorted(new.items())
    ]
    doc = {
        "version": 1,
        "comment": (
            "Grandfathered static-analysis findings (ddlb_tpu/analysis). "
            "Shrink-only: stale entries are errors (DDLB110), growth "
            "needs --allow-baseline-growth. Regenerate with "
            "scripts/analyze.py --update-baseline."
        ),
        "findings": entries,
    }
    tmp = path.with_suffix(path.suffix + ".tmp")
    tmp.write_text(
        json.dumps(doc, indent=1, sort_keys=False) + "\n", encoding="utf-8"
    )
    tmp.replace(path)
    return []


