"""Repo-level rules: invariants that span files, run once per sweep.

- **DDLB007 cost-model-coverage**: every registered primitive family
  must resolve a cost model, so a newly added family can never ship
  rows with a silent ``predicted_s=None`` (PR 3 satellite). Both
  modules are JAX-free by design, so the import is safe from the lint
  tier; an import failure is itself a finding.
- **DDLB108 row-schema-coverage**: every column a runner path writes
  must appear in the ``ddlb_tpu/schema.py`` registry with a non-empty
  docstring (PR 6 satellite) — the column set was previously re-stated
  ad hoc in benchmark.py, pool.py, hw_common.py and tests, with nothing
  keeping the statements in agreement.

Project rules run whenever the analyzed file set touches the package
(the Makefile targets always do); their findings anchor at the file
that owns the invariant so suppressions/baselines behave normally.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Sequence

from ddlb_tpu.analysis.core import (
    FileContext,
    Finding,
    ProjectRule,
    build_context,
    repo_root,
)


def _covers_package(contexts: Sequence[FileContext]) -> bool:
    return any(ctx.in_package() for ctx in contexts)


class CostModelCoverageRule(ProjectRule):
    """Every registered primitive family resolves a perfmodel."""

    id = "DDLB007"
    name = "cost-model-coverage"
    rationale = (
        "a family missing from FAMILY_COST_MODELS ships rows with "
        "silent predicted_s defaults — the roofline gate then never "
        "fires for it"
    )

    def check_project(
        self, contexts: Sequence[FileContext]
    ) -> Iterable[Finding]:
        if not _covers_package(contexts):
            return []
        anchor = "ddlb_tpu/perfmodel/cost.py"
        try:
            from ddlb_tpu.perfmodel.cost import FAMILY_COST_MODELS
            from ddlb_tpu.primitives.registry import ALLOWED_PRIMITIVES
        except Exception as exc:
            return [
                Finding(
                    self.id, anchor, 1, 1,
                    f"perfmodel: cost-model coverage check failed to "
                    f"import: {type(exc).__name__}: {exc}",
                )
            ]
        return [
            Finding(
                self.id, anchor, 1, 1,
                f"perfmodel: primitive family '{fam}' has no cost model "
                f"in ddlb_tpu/perfmodel/cost.py FAMILY_COST_MODELS "
                f"(rows would carry silent predicted_s defaults)",
            )
            for fam in ALLOWED_PRIMITIVES
            if fam not in FAMILY_COST_MODELS
        ]


#: the runner-path files whose row-column writes the schema check scans:
#: the one row constructor + every site that amends rows after the fact
#: (repo-relative). A new runner path that writes columns must be added
#: here — and its columns to ddlb_tpu/schema.py.
ROW_WRITER_FILES = (
    "ddlb_tpu/benchmark.py",
    "ddlb_tpu/pool.py",
    "ddlb_tpu/telemetry/metrics.py",
    "ddlb_tpu/telemetry/clocksync.py",
    "ddlb_tpu/observatory/attribution.py",
    "scripts/hw_common.py",
)


def written_row_columns(tree: ast.Module) -> Dict[str, int]:
    """Every row-column name a file writes, statically, with the line of
    the first write:

    - keys of the dict literal ``make_result_row`` returns (the one
      row constructor);
    - keys of module-level ``*_ROW_DEFAULTS`` / ``ROW_METRIC_DEFAULTS``
      dict literals (merged into every row);
    - every ``row["<name>"] = ...`` subscript assignment (the
      amend-after-build sites: pool reuse columns, hbm peak, bank key).
    """
    columns: Dict[str, int] = {}

    def _dict_keys(node):
        return {
            key.value: key.lineno
            for key in getattr(node, "keys", [])
            if isinstance(key, ast.Constant) and isinstance(key.value, str)
        }

    def _add(mapping: Dict[str, int]) -> None:
        for name, lineno in mapping.items():
            columns.setdefault(name, lineno)

    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and node.name == "make_result_row":
            for ret in ast.walk(node):
                if isinstance(ret, ast.Return) and isinstance(
                    ret.value, ast.Dict
                ):
                    _add(_dict_keys(ret.value))
        elif isinstance(node, (ast.Assign, ast.AnnAssign)):
            targets = (
                node.targets if isinstance(node, ast.Assign) else [node.target]
            )
            # one node can be BOTH cases at once (`row["x"] = {...}`):
            # check the defaults-dict names and the row subscripts
            # independently, never as an either/or
            if isinstance(node.value, ast.Dict):
                names = [t.id for t in targets if isinstance(t, ast.Name)]
                if any(
                    n.endswith("_ROW_DEFAULTS") or n == "ROW_METRIC_DEFAULTS"
                    for n in names
                ):
                    _add(_dict_keys(node.value))
            for target in targets:
                if (
                    isinstance(target, ast.Subscript)
                    and isinstance(target.value, ast.Name)
                    and target.value.id == "row"
                    and isinstance(target.slice, ast.Constant)
                    and isinstance(target.slice.value, str)
                ):
                    columns.setdefault(target.slice.value, target.lineno)
    return columns


class RowSchemaCoverageRule(ProjectRule):
    """Every written row column is registered and documented."""

    id = "DDLB108"
    name = "row-schema-coverage"
    rationale = (
        "an unregistered column is a CSV contract change nothing "
        "reviews; the schema registry is what keeps benchmark.py, "
        "pool.py, hw_common.py and the tests stating the same row shape"
    )

    def check_project(
        self, contexts: Sequence[FileContext]
    ) -> Iterable[Finding]:
        if not _covers_package(contexts):
            return []
        try:
            from ddlb_tpu.schema import ROW_COLUMNS
        except Exception as exc:
            return [
                Finding(
                    self.id, "ddlb_tpu/schema.py", 1, 1,
                    f"schema: row-column registry failed to import: "
                    f"{type(exc).__name__}: {exc}",
                )
            ]
        root = repo_root()
        by_rel = {ctx.rel: ctx for ctx in contexts}
        problems: List[Finding] = []
        for rel in ROW_WRITER_FILES:
            ctx = by_rel.get(rel)
            if ctx is None:
                path = root / rel
                if not path.exists():
                    problems.append(
                        Finding(
                            self.id, rel, 1, 1,
                            f"schema: row-writer file {rel} is missing",
                        )
                    )
                    continue
                ctx = build_context(path, root=root)
            if ctx.tree is None:
                continue  # the per-file pass reports the syntax error
            for column, lineno in sorted(
                written_row_columns(ctx.tree).items()
            ):
                doc = ROW_COLUMNS.get(column)
                if doc is None:
                    problems.append(
                        Finding(
                            self.id, rel, lineno, 1,
                            f"schema: {rel} writes row column {column!r} "
                            f"that is not registered in "
                            f"ddlb_tpu/schema.py ROW_COLUMNS",
                            snippet=ctx.line_text(lineno),
                        )
                    )
                elif not str(doc).strip():
                    problems.append(
                        Finding(
                            self.id, rel, lineno, 1,
                            f"schema: ddlb_tpu/schema.py "
                            f"ROW_COLUMNS[{column!r}] has an empty "
                            f"docstring",
                            snippet=ctx.line_text(lineno),
                        )
                    )
        return problems


class KnobSpaceCoverageRule(ProjectRule):
    """Every registered family declares a knob space or is knob-free."""

    id = "DDLB140"
    name = "knob-space-coverage"
    rationale = (
        "a family absent from both tuner SPACES and KNOB_FREE has no "
        "tuning story at all — the autotuner silently skips it and "
        "nothing records whether that was a decision or an omission"
    )

    def check_project(
        self, contexts: Sequence[FileContext]
    ) -> Iterable[Finding]:
        if not _covers_package(contexts):
            return []
        anchor = "ddlb_tpu/tuner/space.py"
        try:
            from ddlb_tpu.primitives.registry import ALLOWED_PRIMITIVES
            from ddlb_tpu.tuner.space import KNOB_FREE, SPACES
        except Exception as exc:
            return [
                Finding(
                    self.id, anchor, 1, 1,
                    f"tuner: knob-space coverage check failed to "
                    f"import: {type(exc).__name__}: {exc}",
                )
            ]
        declared = {family for family, _impl in SPACES}
        problems = [
            Finding(
                self.id, anchor, 1, 1,
                f"tuner: primitive family '{fam}' declares no knob "
                f"space in SPACES and is not listed knob-free in "
                f"KNOB_FREE (ddlb_tpu/tuner/space.py) — the autotuner "
                f"silently skips it",
            )
            for fam in ALLOWED_PRIMITIVES
            if fam not in declared and fam not in KNOB_FREE
        ]
        # a family both searchable and declared knob-free is a
        # contradiction the registry must not carry
        problems.extend(
            Finding(
                self.id, anchor, 1, 1,
                f"tuner: primitive family '{fam}' appears in BOTH "
                f"SPACES and KNOB_FREE — pick one",
            )
            for fam in sorted(declared & set(KNOB_FREE))
        )
        return problems


RULES = [
    CostModelCoverageRule(),
    RowSchemaCoverageRule(),
    KnobSpaceCoverageRule(),
]
