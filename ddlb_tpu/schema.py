"""The ONE registry of result-row columns, with a docstring per column.

Before this module the row column set was re-stated ad hoc wherever
rows are built or amended — ``benchmark.make_result_row``'s literal,
``telemetry.ROW_METRIC_DEFAULTS``, ``benchmark.PERF_ROW_DEFAULTS`` (and
the observatory's attribution defaults folded into it), the pool's
reuse columns, hw_common's bank key, and the expectations hard-coded in
tests — with nothing forcing the restatements to agree (ISSUE 6
satellite). This registry is the source of truth: ``scripts/lint.py``
statically collects every column the runner paths write (the
``make_result_row`` literal, the ``*_ROW_DEFAULTS`` dicts, and every
``row["..."] = ...`` assignment in benchmark.py / pool.py /
scripts/hw_common.py) and fails when one is missing here or documented
with an empty string — a new column cannot ship undocumented.

Stdlib-only and import-free by design, so the lint tier, tests, and
JAX-free drivers can all read it.
"""

from __future__ import annotations

from typing import Dict

#: column name -> one-line docstring. Grouped by the subsystem that
#: writes the column; ordering is documentation, not the CSV order (the
#: CSV header is fixed by the first row written).
ROW_COLUMNS: Dict[str, str] = {
    # -- identity (make_result_row) ------------------------------------
    "implementation": "sweep impl id (base implementation + position)",
    "primitive": "primitive family name (registry.ALLOWED_PRIMITIVES)",
    "base_implementation": "implementation class key within the family",
    "option": "DEFAULT-merged option string, ';'-joined k=v pairs",
    "m": "GEMM/problem M dimension",
    "n": "GEMM/problem N dimension",
    "k": "GEMM/problem K dimension",
    "dtype": "operand dtype name",
    "unit": "what the Throughput column measures (TFLOPS or GB/s)",
    # -- measurement statistics (native robust_stats over times_ms) ----
    "mean time (ms)": "mean per-call latency over the timing loop",
    "std time (ms)": "standard deviation of per-call latency",
    "min time (ms)": "fastest timed call",
    "max time (ms)": "slowest timed call",
    "median time (ms)": "median per-call latency — the pinned statistic",
    "p95 time (ms)": "95th-percentile per-call latency",
    "Throughput (TFLOPS)": "mean flops()/time throughput (family unit)",
    "Throughput std (TFLOPS)": "std of the per-sample throughput",
    # -- environment ----------------------------------------------------
    "world_size": "device count the row ran across (-1: died unreported)",
    "num_processes": "participating host processes",
    "hostname": "host that produced the row",
    "platform": "JAX backend platform (tpu / cpu / 'unknown' on death)",
    "time_measurement_backend": "host_clock or device_loop",
    "barrier_at_each_iteration": "whether each timed call barriered first",
    # -- compile-ahead engine (PR 1) ------------------------------------
    "compile_time_s": "XLA compile seconds attributed to this row",
    "compile_cache_hit": "persistent compile cache served this row",
    # -- telemetry metric snapshot (telemetry.ROW_METRIC_DEFAULTS) ------
    "barrier_wait_s": "summed Runtime.barrier() wait during the row",
    "loop_overhead_s": "device_loop dispatch/fence slack (two-window est)",
    "hbm_high_water_bytes": "allocator peak when THIS config raised it",
    "collective_bytes": "per-device wire bytes/op from wire_bytes()",
    "hbm_peak_gib": "allocator peak in GiB (only when raised by this row)",
    # -- analytical perfmodel (PR 3) ------------------------------------
    "predicted_s": "closed-form lower bound for this config (seconds)",
    "roofline_frac": "predicted_s / measured median, clamped to (0, 1]",
    "bound": "dominating roofline term: compute / comm / hbm",
    "chip": "hardware spec the prediction was made against",
    # -- calibrated perfmodel (ISSUE 17: perfmodel/calib.py constants
    #    fitted from banked history; all three sit at their defaults —
    #    NaN / NaN / "" — whenever DDLB_TPU_CALIB is unset, keeping the
    #    uncalibrated row byte-identical) -------------------------------
    "predicted_cal_s": (
        "calibrated absolute prediction: the analytical bound plus"
        " fitted per-hop latency / per-step overhead / dispatch"
        " constants through the schedule law (NaN when uncalibrated)"
    ),
    "cal_residual_frac": (
        "(measured median - predicted_cal_s) / predicted_cal_s —"
        " positive means slower than the fitted model; the drift metric"
        " regress.detect_calibration gates (NaN when uncalibrated)"
    ),
    "cal_version": (
        "calibration-table fingerprint the row was priced against"
        " (perfmodel.calib.table_version); '' when uncalibrated —"
        " residual baselines never mix across refits"
    ),
    # -- tuning-table consult (ISSUE 20: ddlb_tpu/tuner; all three sit
    #    at their defaults — False / "" / NaN — whenever DDLB_TPU_TUNING
    #    is unset, keeping the untuned row byte-identical) --------------
    "tuned": (
        "an active tuning-table hit applied banked knobs to this"
        " construction (Primitive._consult_tuning_table); False on"
        " untuned rows and table misses"
    ),
    "tuning_version": (
        "tuning-table fingerprint the applied knobs came from"
        " (tuner.table.table_version); '' when untuned — regression"
        " baselines never mix across re-tunes"
    ),
    "prior_rank": (
        "the applied winner's 1-based rank in the search's prior order"
        " (rank 1 = the cost model called it); NaN when untuned"
    ),
    # -- observatory measured-overlap attribution (ISSUE 6) -------------
    "measured_overlap_frac": (
        "achieved overlap fraction: (serial floor - measured) / hideable,"
        " in [0, 1]; NaN off overlap members AND on rows with no hideable"
        " window at the schedule's granularity (1-device collective, zero"
        " comm/compute term, chunked engine at chunk_count=1) — never inf"
    ),
    "phase_compute_s": "model compute-phase floor (MXU term, seconds)",
    "phase_comm_s": "model comm-phase floor (wire term, seconds)",
    "phase_idle_s": "measured time no roofline term explains (overhead)",
    # -- cross-rank skew attribution (ISSUE 14: telemetry/clocksync.py
    #    fold over the row's collective entry/exit stamps, clocks
    #    aligned on the row's own barrier exchanges; defaults on
    #    single-process rows) ---------------------------------------------
    "skew_enter_s": (
        "summed arrival skew: per collective, how long it waited on its"
        " last-arriving rank (aligned max enter - min enter), seconds"
    ),
    "skew_exit_s": "summed collective exit spread (aligned), seconds",
    "straggler_rank": (
        "process id that caused the most arrival-skew seconds as the"
        " last arrival; -1 when no skew / single-process"
    ),
    "straggler_frac": (
        "skew_enter_s / total collective time: the share of the row's"
        " collective wall time spent waiting on last arrivals, in [0,1]"
    ),
    "clock_unc_s": (
        "worst-rank clock-alignment uncertainty bound (midpoint"
        " estimator, telemetry/clocksync.py) the skew columns carry"
    ),
    # -- robustness / self-healing (PR 4) -------------------------------
    "retries": "retry attempts this row consumed before its final state",
    "fault_injected": "fault-plan sites that fired under this row (csv)",
    "error_class": (
        "transient / degraded / deterministic / quarantined / '' (clean)"
    ),
    "quarantined": "row skipped because its impl was quarantined",
    # -- degraded worlds (ISSUE 15) --------------------------------------
    "world_degraded": (
        "row measured on a DEGRADED world: the supervised launcher"
        " relaunched shrunk/remapped around an indicted rank"
        " (DDLB_TPU_WORLD_DEGRADED) — banked history must tell limp-mode"
        " measurements from full-world ones"
    ),
    # -- warm-worker pool (PR 5) ----------------------------------------
    "worker_reused": "row ran on an already-warm pool worker",
    "worker_setup_s": "child init cost when this row paid the spawn",
    # -- validation / outcome -------------------------------------------
    "valid": "validation verdict (soft: recorded, never fatal)",
    "error": "error string; empty on measured rows",
    # -- hardware-batch banking (scripts/hw_common.py) ------------------
    "bank_key": "caller-config identity JSON for hwlogs/rows.jsonl dedup",
    # -- family extras (impl.extra_row_fields; only on measured rows of
    #    the family, never part of the fixed CSV header contract) -------
    "composition": (
        "resolved collective composition (flat / hierarchical / striped)"
        " stamped by the topology-adaptive members; 'auto' resolves via"
        " primitives.topo_compose against the live topology, fault plan,"
        " degraded-world stamp and health verdict"
    ),
    "spec_accept_rate": "speculative decoding measured acceptance rate",
    "spec_rounds": "speculative decoding verify rounds measured",
    "spec_proposals": "speculative decoding proposed-token count",
    "serve_occupancy": "serving engine mean batch-slot occupancy",
    "serve_admissions_deferred": "serving admissions deferred by HBM gate",
    "serve_peak_pages": "serving paged-KV peak pages in use",
    "serve_pages_capacity": "serving paged-KV pool capacity",
    "serve_prefix_hits": "serving shared-prefix cache hits",
    # -- serving_load SLO telemetry (ISSUE 11: open-loop traffic drains;
    #    percentiles are streaming estimates within 0.4% relative —
    #    workload/slo.py; NaN marks "no sample", e.g. TPOT with every
    #    request generating one token) ----------------------------------
    "slo_offered_rps": "realized offered load: requests / arrival horizon",
    "slo_completed": "completions pooled over the row's post-warmup drains",
    "slo_ttft_p50_ms": "median time-to-first-token incl. queueing wait",
    "slo_ttft_p95_ms": "p95 time-to-first-token incl. queueing wait",
    "slo_ttft_p99_ms": "p99 time-to-first-token incl. queueing wait",
    "slo_tpot_p50_ms": "median per-output-token latency (steady decode)",
    "slo_tpot_p95_ms": "p95 per-output-token latency",
    "slo_tpot_p99_ms": "p99 per-output-token latency",
    "slo_e2e_p95_ms": "p95 end-to-end request latency (arrival to done)",
    "slo_goodput_rps": "completions meeting BOTH SLO bounds per second",
    "slo_attainment": "fraction of completions meeting both SLO bounds",
    "serve_queue_peak": "peak admission-queue depth over the drain",
    "serve_queue_mean": "mean admission-queue depth over the drain",
    "serve_preemptions": "requests preempted (requeued, KV evicted)",
    "serve_kv_evicted_tokens": "KV cache rows abandoned by preemptions",
    # -- serving cluster ledger (ISSUE 18: ddlb_tpu/serve — routed dp>1
    #    and disaggregated prefill/decode members; single-engine rows
    #    carry "single" / zeros so a mixed sweep keeps one CSV header) --
    "serve_topology": "cluster composition stamp (single, router:dp=N, disagg:pP+dD; :degraded=K after a drill; :elastic=R after pool resizes)",
    "serve_shards": "engines in the serving cluster (1 = single engine)",
    "serve_shards_excluded": "decode shards indicted and drained this row",
    "serve_rejected": "requests shed at the admission-control door",
    "serve_handoffs": "prefill->decode / drain KV-bundle handoffs",
    "serve_handoff_bytes": "KV bytes moved across engine handoffs (priced census)",
    "serve_handoff_ms": "priced cumulative handoff latency (not slept on CPU-sim)",
    "serve_drained": "in-flight/queued requests migrated off indicted or resized shards",
    "serve_affinity_hits": "router dispatches that honored prefix affinity",
    # -- elastic serving cluster (ISSUE 19: pools that breathe) --
    "serve_resizes": "elastic pool transitions this row (promote + demote)",
    "serve_pool_history": "semicolon-joined transition journal (promote:3@120;exonerate:1@300)",
    "serve_readmitted": "indicted shards exonerated and re-admitted after probation",
}
