"""Multi-process launcher: the framework's supervised ``mpirun`` analogue.

The reference's L5 entry is ``mpirun -np N python scripts/run_benchmark.py``
(/root/reference/scripts/run_benchmark.py:10-32, README.md:80-153) — the
launcher's only real job there is fanning out N processes and handing each
its rank env vars. The TPU-native equivalent does the same with the
``jax.distributed`` bootstrap env this runtime reads (``envs.py``):
``DDLB_TPU_NUM_PROCESSES`` / ``DDLB_TPU_PROCESS_ID`` /
``DDLB_TPU_COORD_ADDR``, picking a free coordinator port automatically.

On real pods one process per HOST is started by the pod tooling and this
launcher is unnecessary; its value is local: an N-process × M-device
CPU-sim world on one machine, so the cross-process collective paths (the
DCN stand-in, runtime.transport_mesh) run without hardware. Example::

    python -m ddlb_tpu.cli.launch --processes 2 --devices-per-process 4 -- \
        python -m ddlb_tpu.cli.benchmark --primitive tp_columnwise \
        --impl jax_spmd -m 1024 -n 256 -k 512

Two modes:

- **Plain** (default): child stdout/stderr are drained concurrently (a
  blocked pipe would stall the lock-step collective world) and printed
  with a ``[p{rank}]`` prefix once all children exit, rank 0 last so its
  result table ends the output. The exit code is the first non-zero
  child code, with signal deaths mapped to ``128 + signum`` and the
  signal named in the summary line.
- **Supervised** (``--supervise``): the distributed-resilience layer.
  One rank dying or wedging leaves every peer blocked in a collective
  forever, so the supervisor watches each rank's *signs of life* — its
  file-based progress beats (``DDLB_TPU_BEAT_FILE``, written by
  ``faults.heartbeat``) and its streamed output (printed live with the
  ``[p{rank}]`` prefix) — and on ``--silence-timeout`` seconds of world
  silence, or an asymmetric rank death, performs a **coordinated
  abort**: SIGTERM to every rank (the flight recorder's dump-on-signal
  trigger, ``faults.flightrec``), a bounded grace, then SIGKILL. The
  per-attempt flight files are joined (``flightrec.analyze_run``) to
  name the lagging rank and divergence site, the attempt is persisted
  to ``<run-dir>/attempts.json``, and — when the failure classifies
  *transient* (``faults.classify``: silence kills, asymmetric deaths,
  coordinator/bootstrap flaps in the output tail) — the **whole world
  is relaunched** with backoff on a fresh coordinator port, up to
  ``--world-retries`` times, with ``DDLB_TPU_WORLD_ATTEMPT`` exported
  so seeded fault plans can model world-level transient recovery.

Monotonic clocks only in the watchdog math (this file is on the static
analyzer's wall-clock ban list, DDLB102): beat stamps are CLOCK_MONOTONIC
on the same host by construction.
"""

from __future__ import annotations

import argparse
import collections
import json
import os
import signal as signal_mod
import socket
import subprocess
import sys
import tempfile
import threading
import time
from typing import Any, Dict, List, Optional

#: seconds between watchdog polls, and the SIGTERM->SIGKILL grace in
#: which a wedged rank may still flush its flight-recorder dump
POLL_S = 0.25
TERM_GRACE_S = 5.0
#: after the first non-zero rank death, how long peers get to exit on
#: their own before the death is called ASYMMETRIC and the world is
#: aborted — a bad config kills every rank within this window
#: (symmetric: classify, don't relaunch blindly), while peers wedged in
#: a collective the dead rank never joins stay alive past it forever
DEATH_GRACE_S = 2.0


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _rc_info(rc: Optional[int]) -> tuple:
    """(mapped exit code, human summary) for one child's returncode —
    a signal-killed child has a NEGATIVE returncode, which must become
    a truthful nonzero exit (``128 + signum``, the shell convention)
    with the signal named, never the raw number."""
    if rc is None:
        return 1, "still running"
    if rc < 0:
        try:
            name = signal_mod.Signals(-rc).name
        except ValueError:
            name = f"signal {-rc}"
        return 128 - rc, f"terminated by {name} (exit code {128 - rc})"
    return rc, f"exit code {rc}"


def _child_env(
    rank: int,
    processes: int,
    coordinator: str,
    devices_per_process: int,
    slices: int,
    env: Optional[dict],
    attempt_dir: Optional[str] = None,
    attempt: int = 0,
) -> dict:
    """One rank's environment: the bootstrap vars every mode sets, the
    CPU-sim world when requested, and — under supervision — the beat
    file, flight-recorder dir and world-attempt counter."""
    child_env = dict(os.environ if env is None else env)
    child_env.update(
        {
            "DDLB_TPU_NUM_PROCESSES": str(processes),
            "DDLB_TPU_PROCESS_ID": str(rank),
            "DDLB_TPU_COORD_ADDR": coordinator,
        }
    )
    if devices_per_process:
        # CPU-sim world: force the cpu platform in every child (the
        # reference parent also never touches the accelerator,
        # cli/benchmark.py:126)
        child_env.update(
            {
                "JAX_PLATFORMS": "cpu",
                "PALLAS_AXON_POOL_IPS": "",
                "DDLB_TPU_SIM_DEVICES": "0",  # flag set directly:
                "XLA_FLAGS": (
                    child_env.get("XLA_FLAGS", "")
                    + f" --xla_force_host_platform_device_count="
                    f"{devices_per_process}"
                ).strip(),
            }
        )
    if slices:
        child_env["DDLB_TPU_SIM_SLICES"] = str(slices)
    if attempt_dir:
        child_env.update(
            {
                "DDLB_TPU_FLIGHTREC": attempt_dir,
                "DDLB_TPU_BEAT_FILE": os.path.join(
                    attempt_dir, f"beat-p{rank}"
                ),
                "DDLB_TPU_WORLD_ATTEMPT": str(attempt),
                # live streaming is a supervision feature: a child whose
                # stdout sits in a 4 KB block buffer looks silent (and
                # prints nothing useful) right up to the abort
                "PYTHONUNBUFFERED": "1",
            }
        )
    return child_env


def launch(
    command: List[str],
    processes: int,
    devices_per_process: int = 0,
    slices: int = 0,
    coordinator: Optional[str] = None,
    env: Optional[dict] = None,
) -> int:
    """Plain mode: fan ``command`` out over ``processes`` local
    processes; returns the first non-zero child exit code (0 if all
    succeed), signal deaths mapped to ``128 + signum``."""
    if processes < 1:
        raise ValueError(f"processes must be >= 1, got {processes}")
    coordinator = coordinator or f"127.0.0.1:{_free_port()}"
    procs = []
    for rank in range(processes):
        procs.append(
            subprocess.Popen(
                command,
                env=_child_env(
                    rank, processes, coordinator, devices_per_process,
                    slices, env,
                ),
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
            )
        )
    # Drain every pipe CONCURRENTLY: the children advance in lock-step
    # through collectives, so one child blocked on a full 64 KB pipe
    # (rank 0 prints per-row tables) stalls every other rank and a
    # sequential communicate() would deadlock the whole launch.
    buffers: List[List[str]] = [[] for _ in range(processes)]

    def _drain(rank: int) -> None:
        for line in procs[rank].stdout:
            buffers[rank].append(line.rstrip("\n"))

    threads = [
        threading.Thread(target=_drain, args=(rank,), daemon=True)
        for rank in range(processes)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    rc = 0
    # print non-zero ranks first, rank 0 (the result-table rank) last
    for rank in list(range(1, processes)) + [0]:
        procs[rank].wait()
        for line in buffers[rank]:
            print(f"[p{rank}] {line}")
        mapped, summary = _rc_info(procs[rank].returncode)
        if mapped:
            print(f"[p{rank}] {summary}")
        if mapped and rc == 0:
            rc = mapped
    return rc


# ---------------------------------------------------------------------------
# Supervised mode: cross-rank watchdog + classifier-gated world relaunch
# ---------------------------------------------------------------------------


class _Rank:
    """One supervised rank: its process, streamed-output bookkeeping,
    and the beat file the watchdog reads."""

    def __init__(self, rank: int, proc, beat_path: str) -> None:
        self.rank = rank
        self.proc = proc
        self.beat_path = beat_path
        self.spawned = time.monotonic()
        #: monotonic stamp of the last streamed output line
        self.last_output = self.spawned
        self.tail: collections.deque = collections.deque(maxlen=80)

    def last_sign(self) -> float:
        """The rank's most recent sign of life: spawn, output, or file
        beat — the same max-of-signals rule the pool's heartbeat kill
        policy uses, cross-process."""
        from ddlb_tpu.faults import heartbeat

        return max(
            self.spawned,
            self.last_output,
            heartbeat.read_file_beat(self.beat_path),
        )


def _stream_output(state: _Rank) -> None:
    """Live prefixed streaming (the supervised replacement for plain
    mode's after-exit printing): every child line is printed the moment
    it arrives — a wedged world's partial output is often the only
    diagnostic — and counts as a sign of life."""
    for line in state.proc.stdout:
        line = line.rstrip("\n")
        state.last_output = time.monotonic()
        state.tail.append(line)
        print(f"[p{state.rank}] {line}", flush=True)


def _abort_world(ranks: List[_Rank]) -> None:
    """Coordinated abort: SIGTERM everyone (the flight recorder's
    dump-on-signal trigger), one bounded grace for handlers/teardown,
    then SIGKILL whatever is left. The whole world dies together — a
    half-aborted world would leave survivors wedged in collectives."""
    for state in ranks:
        if state.proc.poll() is None:
            state.proc.terminate()
    deadline = time.monotonic() + TERM_GRACE_S
    for state in ranks:
        while state.proc.poll() is None and time.monotonic() < deadline:
            time.sleep(0.05)
    for state in ranks:
        if state.proc.poll() is None:
            state.proc.kill()
            state.proc.wait()


def _watch_world(
    ranks: List[_Rank], silence_timeout: float
) -> tuple:
    """The cross-rank watchdog loop: returns ``(abort_error,
    culprit_rank, silence_age_s)`` — all None/0 when every rank exited
    on its own. Two abort triggers:

    - **asymmetric death**: a rank exited non-zero while peers are
      still in flight — those peers are (or will be) blocked in a
      collective the dead rank never joins;
    - **world silence**: a rank produced no beat and no output for
      ``silence_timeout`` seconds — the wedged-collective signature
      (every rank's beats stop together; the flight recorder, not the
      watchdog, says who diverged).
    """
    first_death: Optional[float] = None
    while True:
        running = [s for s in ranks if s.proc.poll() is None]
        if not running:
            return None, None, 0.0
        failed = [
            s for s in ranks
            if s.proc.poll() is not None and s.proc.returncode != 0
        ]
        if failed:
            if first_death is None:
                first_death = time.monotonic()
            if time.monotonic() - first_death > DEATH_GRACE_S:
                state = failed[0]
                _, summary = _rc_info(state.proc.returncode)
                return (
                    f"WorkerDied: rank {state.rank} {summary} with "
                    f"{len(running)} rank(s) still in flight",
                    state.rank,
                    0.0,
                )
        if silence_timeout:
            now = time.monotonic()
            ages = [(now - s.last_sign(), s) for s in running]
            age, state = max(ages, key=lambda pair: pair[0])
            if age > silence_timeout:
                return (
                    f"TimeoutError: rank {state.rank} silent for "
                    f"{age:.1f}s (no beat, no output) — aborting the "
                    f"world",
                    state.rank,
                    age,
                )
        time.sleep(POLL_S)


def _classify_attempt(
    abort_error: Optional[str], ranks: List[_Rank]
) -> tuple:
    """(error string, error class) for a failed attempt. Abort errors
    carry their own classifiable shape (TimeoutError / WorkerDied →
    transient). A symmetric failure (every rank exited, some non-zero,
    no abort) is classified from the failing ranks' output tails — a
    coordinator/bootstrap flap leaves its transient signature there,
    while a bad config's ValueError matches nothing and parks."""
    from ddlb_tpu.faults.classify import classify_error

    if abort_error:
        return abort_error, classify_error(abort_error)
    failed = [s for s in ranks if s.proc.returncode != 0]
    if not failed:
        return "", ""
    state = failed[0]
    _, summary = _rc_info(state.proc.returncode)
    error = f"rank {state.rank} {summary}"
    # classify from each failing rank's FINAL non-empty output line —
    # the exception line a Python traceback ends with — not the whole
    # 80-line tail: a broad transient pattern ('coordinator', 'failed
    # to connect') matching benign earlier text (a logged-and-recovered
    # reconnect warning, a traceback frame quoting
    # coordinator_address=...) must not relaunch a world that failed
    # deterministically
    tail = "\n".join(
        next((ln for ln in reversed(s.tail) if ln.strip()), "")
        for s in failed
    )
    return error, classify_error(tail.strip() or error)


def _persist_attempts(run_dir: str, records: List[Dict[str, Any]]) -> None:
    """Atomic write of the world-attempt record (crash-safe: a killed
    supervisor leaves the previous complete record, never a torn one)."""
    path = os.path.join(run_dir, "attempts.json")
    tmp = f"{path}.tmp{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(records, f, indent=1, default=str)
    os.replace(tmp, path)


def launch_supervised(
    command: List[str],
    processes: int,
    devices_per_process: int = 0,
    slices: int = 0,
    env: Optional[dict] = None,
    silence_timeout: float = 60.0,
    world_retries: int = 2,
    relaunch_backoff_s: float = 1.0,
    run_dir: Optional[str] = None,
) -> int:
    """Supervised mode: launch, watch, abort, attribute, relaunch.
    Returns 0 when an attempt completes cleanly, else the mapped exit
    code of the final failed attempt. Every attempt gets its own
    ``<run_dir>/attempt-N`` flight/beat directory and a line in
    ``<run_dir>/attempts.json``."""
    from ddlb_tpu import telemetry
    from ddlb_tpu.faults import flightrec
    from ddlb_tpu.faults.classify import TRANSIENT
    from ddlb_tpu.faults.plan import backoff_delays

    if processes < 1:
        raise ValueError(f"processes must be >= 1, got {processes}")
    run_dir = run_dir or tempfile.mkdtemp(prefix="ddlb_launch_")
    os.makedirs(run_dir, exist_ok=True)
    delays = backoff_delays(
        relaunch_backoff_s, world_retries, seed=os.path.basename(run_dir)
    )
    records: List[Dict[str, Any]] = []
    rc = 1
    for attempt in range(world_retries + 1):
        attempt_dir = os.path.join(run_dir, f"attempt-{attempt}")
        os.makedirs(attempt_dir, exist_ok=True)
        coordinator = f"127.0.0.1:{_free_port()}"
        print(
            f"[launcher] attempt {attempt}: {processes} rank(s), "
            f"coordinator {coordinator}, run dir {attempt_dir}",
            flush=True,
        )
        started = time.monotonic()
        ranks: List[_Rank] = []
        for rank in range(processes):
            proc = subprocess.Popen(
                command,
                env=_child_env(
                    rank, processes, coordinator, devices_per_process,
                    slices, env, attempt_dir=attempt_dir, attempt=attempt,
                ),
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
            )
            ranks.append(
                _Rank(rank, proc, os.path.join(attempt_dir, f"beat-p{rank}"))
            )
        threads = [
            threading.Thread(target=_stream_output, args=(s,), daemon=True)
            for s in ranks
        ]
        for t in threads:
            t.start()
        telemetry.record("launch.world_attempts")
        abort_error, culprit, silence_age = _watch_world(
            ranks, silence_timeout
        )
        if abort_error:
            print(f"[launcher] {abort_error}", flush=True)
            telemetry.instant(
                "launch.abort", cat="launch", rank=culprit,
                error=abort_error[:200],
            )
            _abort_world(ranks)
        for t in threads:
            t.join(timeout=5.0)
        error, error_class = _classify_attempt(abort_error, ranks)
        if error and culprit is None:
            failed = [
                s.rank for s in ranks
                if s.proc.returncode not in (0, None)
            ]
            culprit = failed[0] if failed else None
        report = flightrec.analyze_run(attempt_dir, expected_ranks=processes)
        if error and report.get("lagging_ranks"):
            # the flight recorder's sequence join beats the watchdog's
            # beat-age guess at naming the diverging rank (every rank's
            # beats stop together once the world wedges in a collective)
            culprit = report["lagging_ranks"][0]
        rank_rcs = []
        rc = 0
        for state in ranks:
            mapped, summary = _rc_info(state.proc.returncode)
            if mapped:
                print(f"[p{state.rank}] {summary}", flush=True)
            if mapped and rc == 0:
                rc = mapped
            rank_rcs.append(
                {"rank": state.rank, "returncode": state.proc.returncode,
                 "exit": mapped}
            )
        if culprit is not None:
            # the culprit's own exit code is the informative one — the
            # supervisor SIGTERMed the innocent peers itself, and their
            # 143s would otherwise shadow it in rank order
            for entry in rank_rcs:
                if entry["rank"] == culprit and entry["exit"]:
                    rc = entry["exit"]
                    break
        if error and not rc:
            rc = 1  # an aborted world must never report success
        records.append(
            {
                "attempt": attempt,
                "outcome": "ok" if not error else "failed",
                "error": error,
                "error_class": error_class,
                "culprit_rank": culprit,
                "silence_age_s": round(silence_age, 2),
                "silence_timeout_s": silence_timeout,
                "duration_s": round(time.monotonic() - started, 2),
                "coordinator": coordinator,
                "ranks": rank_rcs,
                "flight_headline": report.get("headline"),
                "divergence_site": report.get("divergence_site"),
            }
        )
        _persist_attempts(run_dir, records)
        if not error:
            print(
                f"[launcher] attempt {attempt} completed cleanly "
                f"({records[-1]['duration_s']}s)",
                flush=True,
            )
            return 0
        print(
            f"[launcher] post-mortem: {report.get('headline')}",
            flush=True,
        )
        if error_class != TRANSIENT:
            print(
                f"[launcher] failure classified "
                f"{error_class or 'deterministic'} — not relaunching "
                f"(a relaunch would re-pay the world for the same answer)",
                flush=True,
            )
            return rc
        if attempt == world_retries:
            print(
                f"[launcher] world retries exhausted "
                f"({world_retries + 1} attempts)",
                flush=True,
            )
            return rc
        delay = delays[attempt]
        print(
            f"[launcher] transient world failure — relaunching in "
            f"{delay:.1f}s (attempt {attempt + 1}/{world_retries + 1})",
            flush=True,
        )
        telemetry.instant(
            "launch.relaunch", cat="launch", attempt=attempt + 1,
            error_class=error_class,
        )
        time.sleep(delay)
    return rc


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(
        prog="ddlb_tpu.cli.launch",
        description="Fan a command out over N coordinated local processes "
        "(the mpirun analogue; see module docstring).",
    )
    parser.add_argument("--processes", type=int, required=True)
    parser.add_argument(
        "--devices-per-process",
        type=int,
        default=0,
        help="N virtual CPU devices per process (0 = use the real platform)",
    )
    parser.add_argument(
        "--slices",
        type=int,
        default=0,
        help="DDLB_TPU_SIM_SLICES for every child (simulated DCN topology)",
    )
    parser.add_argument(
        "--coordinator",
        default=None,
        help="host:port for jax.distributed (default: free local port; "
        "supervised mode always picks a fresh port per attempt)",
    )
    parser.add_argument(
        "--supervise",
        action="store_true",
        help="cross-rank watchdog: file beats + live output streaming, "
        "coordinated abort on silence/asymmetric death, flight-recorder "
        "post-mortem, classifier-gated world relaunch",
    )
    parser.add_argument(
        "--silence-timeout",
        type=float,
        default=60.0,
        help="supervised: seconds without any beat/output from a rank "
        "before the world is aborted (0 disables the silence trigger)",
    )
    parser.add_argument(
        "--world-retries",
        type=int,
        default=2,
        help="supervised: transient world failures relaunched up to this "
        "many times with backoff",
    )
    parser.add_argument(
        "--relaunch-backoff",
        type=float,
        default=1.0,
        help="supervised: base seconds for the relaunch backoff schedule",
    )
    parser.add_argument(
        "--run-dir",
        default=None,
        help="supervised: directory for per-attempt flight/beat files and "
        "attempts.json (default: a fresh temp dir, printed)",
    )
    parser.add_argument(
        "command",
        nargs=argparse.REMAINDER,
        help="command to run in every process (prefix with --)",
    )
    args = parser.parse_args(argv)
    command = args.command
    if command and command[0] == "--":
        command = command[1:]
    if not command:
        parser.error("no command given (append: -- python -m ...)")
    if args.supervise:
        sys.exit(
            launch_supervised(
                command,
                processes=args.processes,
                devices_per_process=args.devices_per_process,
                slices=args.slices,
                silence_timeout=args.silence_timeout,
                world_retries=args.world_retries,
                relaunch_backoff_s=args.relaunch_backoff,
                run_dir=args.run_dir,
            )
        )
    sys.exit(
        launch(
            command,
            processes=args.processes,
            devices_per_process=args.devices_per_process,
            slices=args.slices,
            coordinator=args.coordinator,
        )
    )


if __name__ == "__main__":
    main()
