"""Multi-process launcher: the framework's supervised ``mpirun`` analogue.

The reference's L5 entry is ``mpirun -np N python scripts/run_benchmark.py``
(/root/reference/scripts/run_benchmark.py:10-32, README.md:80-153) — the
launcher's only real job there is fanning out N processes and handing each
its rank env vars. The TPU-native equivalent does the same with the
``jax.distributed`` bootstrap env this runtime reads (``envs.py``):
``DDLB_TPU_NUM_PROCESSES`` / ``DDLB_TPU_PROCESS_ID`` /
``DDLB_TPU_COORD_ADDR``, picking a free coordinator port automatically.

On real pods one process per HOST is started by the pod tooling and this
launcher is unnecessary; its value is local: an N-process × M-device
CPU-sim world on one machine, so the cross-process collective paths (the
DCN stand-in, runtime.transport_mesh) run without hardware. Example::

    python -m ddlb_tpu.cli.launch --processes 2 --devices-per-process 4 -- \
        python -m ddlb_tpu.cli.benchmark --primitive tp_columnwise \
        --impl jax_spmd -m 1024 -n 256 -k 512

Two modes:

- **Plain** (default): child stdout/stderr are drained concurrently (a
  blocked pipe would stall the lock-step collective world) and printed
  with a ``[p{rank}]`` prefix once all children exit, rank 0 last so its
  result table ends the output. The exit code is the first non-zero
  child code, with signal deaths mapped to ``128 + signum`` and the
  signal named in the summary line.
- **Supervised** (``--supervise``): the distributed-resilience layer.
  One rank dying or wedging leaves every peer blocked in a collective
  forever, so the supervisor watches each rank's *signs of life* — its
  file-based progress beats (``DDLB_TPU_BEAT_FILE``, written by
  ``faults.heartbeat``) and its streamed output (printed live with the
  ``[p{rank}]`` prefix) — and on ``--silence-timeout`` seconds of world
  silence, or an asymmetric rank death, performs a **coordinated
  abort**: SIGTERM to every rank (the flight recorder's dump-on-signal
  trigger, ``faults.flightrec``), a bounded grace, then SIGKILL. The
  per-attempt flight files are joined (``flightrec.analyze_run``) to
  name the lagging rank and divergence site, the attempt is persisted
  to ``<run-dir>/attempts.json``, and — when the failure classifies
  *transient* (``faults.classify``: silence kills, asymmetric deaths,
  coordinator/bootstrap flaps in the output tail) — the **whole world
  is relaunched** with backoff on a fresh coordinator port, up to
  ``--world-retries`` times, with ``DDLB_TPU_WORLD_ATTEMPT`` exported
  so seeded fault plans can model world-level transient recovery.

Monotonic clocks only in the watchdog math (this file is on the static
analyzer's wall-clock ban list, DDLB102): beat stamps are CLOCK_MONOTONIC
on the same host by construction.
"""

from __future__ import annotations

import argparse
import collections
import json
import os
import signal as signal_mod
import socket
import subprocess
import sys
import tempfile
import threading
import time
from typing import Any, Dict, List, Optional

#: seconds between watchdog polls, and the SIGTERM->SIGKILL grace in
#: which a wedged rank may still flush its flight-recorder dump
POLL_S = 0.25
TERM_GRACE_S = 5.0
#: after the first non-zero rank death, how long peers get to exit on
#: their own before the death is called ASYMMETRIC and the world is
#: aborted — a bad config kills every rank within this window
#: (symmetric: classify, don't relaunch blindly), while peers wedged in
#: a collective the dead rank never joins stay alive past it forever
DEATH_GRACE_S = 2.0
#: asymmetric-silence split: when the silence deadline fires on one
#: rank while some peer showed life within this fraction of the
#: deadline, the world is NOT lock-step-wedged (a wedge stops every
#: rank's beats together) — the silent rank is a slow/degraded PEER,
#: and the error classifies DEGRADED (barrier-timeout-with-surviving-
#: peers), the mitigating relaunch's trigger, instead of transient
PEER_FRESH_FRAC = 0.5


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _rc_info(rc: Optional[int]) -> tuple:
    """(mapped exit code, human summary) for one child's returncode —
    a signal-killed child has a NEGATIVE returncode, which must become
    a truthful nonzero exit (``128 + signum``, the shell convention)
    with the signal named, never the raw number."""
    if rc is None:
        return 1, "still running"
    if rc < 0:
        try:
            name = signal_mod.Signals(-rc).name
        except ValueError:
            name = f"signal {-rc}"
        return 128 - rc, f"terminated by {name} (exit code {128 - rc})"
    return rc, f"exit code {rc}"


def _child_env(
    rank: int,
    processes: int,
    coordinator: str,
    devices_per_process: int,
    slices: int,
    env: Optional[dict],
    attempt_dir: Optional[str] = None,
    attempt: int = 0,
    phys_rank: Optional[int] = None,
    phys_world: Optional[int] = None,
    degraded: bool = False,
) -> dict:
    """One rank's environment: the bootstrap vars every mode sets, the
    CPU-sim world when requested, and — under supervision — the beat
    file, flight-recorder dir, world-attempt counter, and (on a
    degraded relaunch) the PHYSICAL slot id + world_degraded stamp."""
    child_env = dict(os.environ if env is None else env)
    child_env.update(
        {
            "DDLB_TPU_NUM_PROCESSES": str(processes),
            "DDLB_TPU_PROCESS_ID": str(rank),
            "DDLB_TPU_COORD_ADDR": coordinator,
        }
    )
    if phys_rank is not None:
        # the rank's PHYSICAL world slot: jax.distributed needs dense
        # process ids 0..N-1, but fault-plan topo/rank selectors key on
        # the slot (envs.get_physical_rank) so a shrunken world's
        # survivors keep dodging the hardware that indicted the
        # excluded slot instead of re-rolling its faults onto whoever
        # inherited its process id
        child_env["DDLB_TPU_PHYS_RANK"] = str(phys_rank)
        # ...and ring-neighbor math (an rx-direction link fault's
        # receiver) must wrap the FULL physical ring, not the shrunken
        # process count (envs.get_physical_world)
        child_env["DDLB_TPU_PHYS_WORLD"] = str(phys_world or processes)
    if degraded:
        # stamped onto every result row (the world_degraded schema
        # column): banked history must tell limp-mode measurements
        # from full-world ones
        child_env["DDLB_TPU_WORLD_DEGRADED"] = "1"
    else:
        child_env.pop("DDLB_TPU_WORLD_DEGRADED", None)
    if devices_per_process:
        # CPU-sim world: force the cpu platform in every child (the
        # reference parent also never touches the accelerator,
        # cli/benchmark.py:126)
        child_env.update(
            {
                "JAX_PLATFORMS": "cpu",
                "PALLAS_AXON_POOL_IPS": "",
                "DDLB_TPU_SIM_DEVICES": "0",  # flag set directly:
                "XLA_FLAGS": (
                    child_env.get("XLA_FLAGS", "")
                    + f" --xla_force_host_platform_device_count="
                    f"{devices_per_process}"
                ).strip(),
            }
        )
    if slices:
        child_env["DDLB_TPU_SIM_SLICES"] = str(slices)
    if attempt_dir:
        child_env.update(
            {
                "DDLB_TPU_FLIGHTREC": attempt_dir,
                "DDLB_TPU_BEAT_FILE": os.path.join(
                    attempt_dir, f"beat-p{rank}"
                ),
                "DDLB_TPU_WORLD_ATTEMPT": str(attempt),
                # live streaming is a supervision feature: a child whose
                # stdout sits in a 4 KB block buffer looks silent (and
                # prints nothing useful) right up to the abort
                "PYTHONUNBUFFERED": "1",
            }
        )
    return child_env


def launch(
    command: List[str],
    processes: int,
    devices_per_process: int = 0,
    slices: int = 0,
    coordinator: Optional[str] = None,
    env: Optional[dict] = None,
) -> int:
    """Plain mode: fan ``command`` out over ``processes`` local
    processes; returns the first non-zero child exit code (0 if all
    succeed), signal deaths mapped to ``128 + signum``."""
    if processes < 1:
        raise ValueError(f"processes must be >= 1, got {processes}")
    coordinator = coordinator or f"127.0.0.1:{_free_port()}"
    procs = []
    for rank in range(processes):
        procs.append(
            subprocess.Popen(
                command,
                env=_child_env(
                    rank, processes, coordinator, devices_per_process,
                    slices, env,
                ),
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
            )
        )
    # Drain every pipe CONCURRENTLY: the children advance in lock-step
    # through collectives, so one child blocked on a full 64 KB pipe
    # (rank 0 prints per-row tables) stalls every other rank and a
    # sequential communicate() would deadlock the whole launch.
    buffers: List[List[str]] = [[] for _ in range(processes)]

    def _drain(rank: int) -> None:
        for line in procs[rank].stdout:
            buffers[rank].append(line.rstrip("\n"))

    threads = [
        threading.Thread(target=_drain, args=(rank,), daemon=True)
        for rank in range(processes)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    rc = 0
    # print non-zero ranks first, rank 0 (the result-table rank) last
    for rank in list(range(1, processes)) + [0]:
        procs[rank].wait()
        for line in buffers[rank]:
            print(f"[p{rank}] {line}")
        mapped, summary = _rc_info(procs[rank].returncode)
        if mapped:
            print(f"[p{rank}] {summary}")
        if mapped and rc == 0:
            rc = mapped
    return rc


# ---------------------------------------------------------------------------
# Supervised mode: cross-rank watchdog + classifier-gated world relaunch
# ---------------------------------------------------------------------------


class _Rank:
    """One supervised rank: its process, streamed-output bookkeeping,
    and the beat file the watchdog reads."""

    def __init__(self, rank: int, proc, beat_path: str) -> None:
        self.rank = rank
        self.proc = proc
        self.beat_path = beat_path
        self.spawned = time.monotonic()
        #: monotonic stamp of the last streamed output line
        self.last_output = self.spawned
        self.tail: collections.deque = collections.deque(maxlen=80)

    def last_sign(self) -> float:
        """The rank's most recent sign of life: spawn, output, or file
        beat — the same max-of-signals rule the pool's heartbeat kill
        policy uses, cross-process."""
        from ddlb_tpu.faults import heartbeat

        return max(
            self.spawned,
            self.last_output,
            heartbeat.read_file_beat(self.beat_path),
        )


def _stream_output(state: _Rank) -> None:
    """Live prefixed streaming (the supervised replacement for plain
    mode's after-exit printing): every child line is printed the moment
    it arrives — a wedged world's partial output is often the only
    diagnostic — and counts as a sign of life."""
    for line in state.proc.stdout:
        line = line.rstrip("\n")
        state.last_output = time.monotonic()
        state.tail.append(line)
        print(f"[p{state.rank}] {line}", flush=True)


def _abort_world(ranks: List[_Rank]) -> None:
    """Coordinated abort: SIGTERM everyone (the flight recorder's
    dump-on-signal trigger), one bounded grace for handlers/teardown,
    then SIGKILL whatever is left. The whole world dies together — a
    half-aborted world would leave survivors wedged in collectives."""
    for state in ranks:
        if state.proc.poll() is None:
            state.proc.terminate()
    deadline = time.monotonic() + TERM_GRACE_S
    for state in ranks:
        while state.proc.poll() is None and time.monotonic() < deadline:
            time.sleep(0.05)
    for state in ranks:
        if state.proc.poll() is None:
            state.proc.kill()
            state.proc.wait()


def _watch_world(
    ranks: List[_Rank], silence_timeout: float
) -> tuple:
    """The cross-rank watchdog loop: returns ``(abort_error,
    culprit_rank, silence_age_s)`` — all None/0 when every rank exited
    on its own. Two abort triggers:

    - **asymmetric death**: a rank exited non-zero while peers are
      still in flight — those peers are (or will be) blocked in a
      collective the dead rank never joins;
    - **world silence**: a rank produced no beat and no output for
      ``silence_timeout`` seconds — the wedged-collective signature
      (every rank's beats stop together; the flight recorder, not the
      watchdog, says who diverged).
    """
    first_death: Optional[float] = None
    while True:
        running = [s for s in ranks if s.proc.poll() is None]
        if not running:
            return None, None, 0.0
        failed = [
            s for s in ranks
            if s.proc.poll() is not None and s.proc.returncode != 0
        ]
        if failed:
            if first_death is None:
                first_death = time.monotonic()
            if time.monotonic() - first_death > DEATH_GRACE_S:
                state = failed[0]
                _, summary = _rc_info(state.proc.returncode)
                return (
                    f"WorkerDied: rank {state.rank} {summary} with "
                    f"{len(running)} rank(s) still in flight",
                    state.rank,
                    0.0,
                )
        if silence_timeout:
            now = time.monotonic()
            ages = [(now - s.last_sign(), s) for s in running]
            age, state = max(ages, key=lambda pair: pair[0])
            if age > silence_timeout:
                freshest = min(pair[0] for pair in ages)
                if (
                    len(ages) > 1
                    and freshest < PEER_FRESH_FRAC * silence_timeout
                ):
                    # peers kept beating while this rank went dark: a
                    # slow/wedged PEER, not a world wedge — the
                    # degraded-component signature (classify: DEGRADED)
                    return (
                        f"SlowPeer: rank {state.rank} silent for "
                        f"{age:.1f}s while {len(ages) - 1} peer(s) kept "
                        f"beating (freshest {freshest:.1f}s ago) — "
                        f"aborting the degraded world",
                        state.rank,
                        age,
                    )
                return (
                    f"TimeoutError: rank {state.rank} silent for "
                    f"{age:.1f}s (no beat, no output) — aborting the "
                    f"world",
                    state.rank,
                    age,
                )
        time.sleep(POLL_S)


def _classify_attempt(
    abort_error: Optional[str], ranks: List[_Rank]
) -> tuple:
    """(error string, error class) for a failed attempt. Abort errors
    carry their own classifiable shape (TimeoutError / WorkerDied →
    transient). A symmetric failure (every rank exited, some non-zero,
    no abort) is classified from the failing ranks' output tails — a
    coordinator/bootstrap flap leaves its transient signature there,
    while a bad config's ValueError matches nothing and parks."""
    from ddlb_tpu.faults.classify import classify_error

    if abort_error:
        return abort_error, classify_error(abort_error)
    failed = [s for s in ranks if s.proc.returncode != 0]
    if not failed:
        return "", ""
    state = failed[0]
    _, summary = _rc_info(state.proc.returncode)
    error = f"rank {state.rank} {summary}"
    # classify from each failing rank's FINAL non-empty output line —
    # the exception line a Python traceback ends with — not the whole
    # 80-line tail: a broad transient pattern ('coordinator', 'failed
    # to connect') matching benign earlier text (a logged-and-recovered
    # reconnect warning, a traceback frame quoting
    # coordinator_address=...) must not relaunch a world that failed
    # deterministically
    tail = "\n".join(
        next((ln for ln in reversed(s.tail) if ln.strip()), "")
        for s in failed
    )
    return error, classify_error(tail.strip() or error)


def _persist_attempts(run_dir: str, records: List[Dict[str, Any]]) -> None:
    """Atomic write of the world-attempt record (crash-safe: a killed
    supervisor leaves the previous complete record, never a torn one)."""
    path = os.path.join(run_dir, "attempts.json")
    tmp = f"{path}.tmp{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(records, f, indent=1, default=str)
    os.replace(tmp, path)


def launch_supervised(
    command: List[str],
    processes: int,
    devices_per_process: int = 0,
    slices: int = 0,
    env: Optional[dict] = None,
    silence_timeout: float = 60.0,
    world_retries: int = 2,
    relaunch_backoff_s: float = 1.0,
    run_dir: Optional[str] = None,
    exclude_ranks: Any = (),
    health_gate: bool = False,
) -> int:
    """Supervised mode: launch, watch, abort, attribute, relaunch.
    Returns 0 when an attempt completes cleanly, else the mapped exit
    code of the final failed attempt. Every attempt gets its own
    ``<run_dir>/attempt-N`` flight/beat directory and a line in
    ``<run_dir>/attempts.json``.

    **Degraded worlds** (ISSUE 15): ``processes`` is the FULL world;
    ``exclude_ranks`` names physical slots to launch without (the
    operator's pre-indictment), and the launcher itself excludes more
    when a failure classifies DEGRADED (a ``link_down`` transport
    error, a slow peer whose silence aborted a still-beating world) or
    — with ``health_gate=True`` — when the attempt's clock-aligned
    timeline produces a persistent-straggler indictment
    (``observatory.health``). A degraded relaunch shrinks the world
    around the indicted slot (survivors keep their physical slot id
    via ``DDLB_TPU_PHYS_RANK``; rows are stamped ``world_degraded``),
    but only while ``health.relaunch_policy`` says shrinking still
    leaves a real multi-rank world — a 2-rank world's link failure is
    fatal-not-degraded."""
    from ddlb_tpu import telemetry
    from ddlb_tpu.faults import flightrec
    from ddlb_tpu.faults.classify import DEGRADED, TRANSIENT
    from ddlb_tpu.faults.plan import backoff_delays
    from ddlb_tpu.observatory import health

    if processes < 1:
        raise ValueError(f"processes must be >= 1, got {processes}")
    excluded = set(int(r) for r in exclude_ranks or ())
    bad = [r for r in excluded if not (0 <= r < processes)]
    if bad:
        raise ValueError(
            f"exclude_ranks {sorted(bad)} outside the world 0..{processes - 1}"
        )
    run_dir = run_dir or tempfile.mkdtemp(prefix="ddlb_launch_")
    os.makedirs(run_dir, exist_ok=True)
    delays = backoff_delays(
        relaunch_backoff_s, world_retries, seed=os.path.basename(run_dir)
    )
    records: List[Dict[str, Any]] = []
    rc = 1
    for attempt in range(world_retries + 1):
        #: surviving physical slots; process id i runs slot slots[i]
        slots = [r for r in range(processes) if r not in excluded]
        n = len(slots)
        if n < 1:
            print("[launcher] every rank excluded — nothing to launch",
                  flush=True)
            return rc
        degraded = bool(excluded)
        attempt_dir = os.path.join(run_dir, f"attempt-{attempt}")
        os.makedirs(attempt_dir, exist_ok=True)
        coordinator = f"127.0.0.1:{_free_port()}"
        print(
            f"[launcher] attempt {attempt}: {n} rank(s)"
            + (
                f" (DEGRADED world: slots {slots}, excluded "
                f"{sorted(excluded)})"
                if degraded
                else ""
            )
            + f", coordinator {coordinator}, run dir {attempt_dir}",
            flush=True,
        )
        started = time.monotonic()
        ranks: List[_Rank] = []
        for rank in range(n):
            proc = subprocess.Popen(
                command,
                env=_child_env(
                    rank, n, coordinator, devices_per_process,
                    slices, env, attempt_dir=attempt_dir, attempt=attempt,
                    phys_rank=slots[rank], phys_world=processes,
                    degraded=degraded,
                ),
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
            )
            ranks.append(
                _Rank(rank, proc, os.path.join(attempt_dir, f"beat-p{rank}"))
            )
        threads = [
            threading.Thread(target=_stream_output, args=(s,), daemon=True)
            for s in ranks
        ]
        for t in threads:
            t.start()
        telemetry.record("launch.world_attempts")
        abort_error, culprit, silence_age = _watch_world(
            ranks, silence_timeout
        )
        if abort_error:
            print(f"[launcher] {abort_error}", flush=True)
            telemetry.instant(
                "launch.abort", cat="launch", rank=culprit,
                error=abort_error[:200],
            )
            _abort_world(ranks)
        for t in threads:
            t.join(timeout=5.0)
        error, error_class = _classify_attempt(abort_error, ranks)
        if error and culprit is None:
            failed = [
                s.rank for s in ranks
                if s.proc.returncode not in (0, None)
            ]
            culprit = failed[0] if failed else None
        report = flightrec.analyze_run(attempt_dir, expected_ranks=n)
        if error and report.get("lagging_ranks"):
            # the flight recorder's sequence join beats the watchdog's
            # beat-age guess at naming the diverging rank (every rank's
            # beats stop together once the world wedges in a collective)
            culprit = report["lagging_ranks"][0]
        rank_rcs = []
        rc = 0
        for state in ranks:
            mapped, summary = _rc_info(state.proc.returncode)
            if mapped:
                print(f"[p{state.rank}] {summary}", flush=True)
            if mapped and rc == 0:
                rc = mapped
            rank_rcs.append(
                {"rank": state.rank, "returncode": state.proc.returncode,
                 "exit": mapped}
            )
        if culprit is not None:
            # the culprit's own exit code is the informative one — the
            # supervisor SIGTERMed the innocent peers itself, and their
            # 143s would otherwise shadow it in rank order
            for entry in rank_rcs:
                if entry["rank"] == culprit and entry["exit"]:
                    rc = entry["exit"]
                    break
        if error and not rc:
            rc = 1  # an aborted world must never report success

        # -- health gate: a clean-but-limping attempt can still indict a
        # persistently-straggling rank from its own clock-aligned
        # timeline (the detect -> indict -> mitigate loop's trigger when
        # nothing crashed — a slow link doesn't kill anyone)
        verdict = None
        if health_gate and not error:
            from ddlb_tpu.observatory import timeline as timeline_mod

            doc = timeline_mod.build_world_timeline(
                attempt_dir, expected_ranks=n
            )
            verdict = health.verdict_from_observations(
                health.observations_from_timeline(doc), world=n
            )
        indicted = (
            verdict["rank"]
            if verdict is not None
            and verdict["status"] == health.PERSISTENT
            else (culprit if error and error_class == DEGRADED else None)
        )
        outcome = "ok" if not error else "failed"
        if indicted is not None:
            outcome = "degraded"
        records.append(
            {
                "attempt": attempt,
                "outcome": outcome,
                "error": error,
                "error_class": error_class,
                "culprit_rank": culprit,
                "silence_age_s": round(silence_age, 2),
                "silence_timeout_s": silence_timeout,
                "duration_s": round(time.monotonic() - started, 2),
                "coordinator": coordinator,
                "ranks": rank_rcs,
                "world_slots": slots,
                "excluded_ranks": sorted(excluded),
                "world_degraded": degraded,
                "health": verdict,
                "flight_headline": report.get("headline"),
                "divergence_site": report.get("divergence_site"),
            }
        )
        _persist_attempts(run_dir, records)

        if indicted is not None:
            # indicted is a PROCESS id of this attempt; the hardware to
            # exclude is its physical slot
            phys = slots[indicted] if 0 <= indicted < n else indicted
            policy = health.relaunch_policy(n)
            reason = (
                verdict["reason"]
                if verdict is not None
                else f"{error_class}: {error[:120]}"
            )
            print(
                f"[launcher] rank {indicted} (physical slot {phys}) "
                f"indicted: {reason}",
                flush=True,
            )
            if policy != "exclude":
                print(
                    f"[launcher] {n}-rank world cannot shrink around the "
                    f"indicted rank (a degraded relaunch needs >= 2 "
                    f"survivors) — fatal, not degraded",
                    flush=True,
                )
                records[-1]["mitigation"] = "fatal"
                _persist_attempts(run_dir, records)
                # a completed-but-indicted attempt keeps its result; a
                # failed one keeps its truthful exit code
                return 0 if not error else rc
            if attempt == world_retries:
                print(
                    f"[launcher] world retries exhausted before the "
                    f"degraded relaunch ({world_retries + 1} attempts)",
                    flush=True,
                )
                records[-1]["mitigation"] = "exhausted"
                _persist_attempts(run_dir, records)
                return 0 if not error else rc
            excluded.add(phys)
            records[-1]["mitigation"] = f"exclude slot {phys}"
            _persist_attempts(run_dir, records)
            print(
                f"[launcher] relaunching DEGRADED without slot {phys} "
                f"({n - 1} rank(s); attempt "
                f"{attempt + 1}/{world_retries + 1})",
                flush=True,
            )
            telemetry.instant(
                "launch.degraded", cat="launch", slot=phys,
                attempt=attempt + 1,
            )
            time.sleep(min(delays[attempt], 2.0))
            continue

        if not error:
            print(
                f"[launcher] attempt {attempt} completed cleanly "
                f"({records[-1]['duration_s']}s)",
                flush=True,
            )
            return 0
        print(
            f"[launcher] post-mortem: {report.get('headline')}",
            flush=True,
        )
        if error_class != TRANSIENT:
            print(
                f"[launcher] failure classified "
                f"{error_class or 'deterministic'} — not relaunching "
                f"(a relaunch would re-pay the world for the same answer)",
                flush=True,
            )
            return rc
        if attempt == world_retries:
            print(
                f"[launcher] world retries exhausted "
                f"({world_retries + 1} attempts)",
                flush=True,
            )
            return rc
        delay = delays[attempt]
        print(
            f"[launcher] transient world failure — relaunching in "
            f"{delay:.1f}s (attempt {attempt + 1}/{world_retries + 1})",
            flush=True,
        )
        telemetry.instant(
            "launch.relaunch", cat="launch", attempt=attempt + 1,
            error_class=error_class,
        )
        time.sleep(delay)
    return rc


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(
        prog="ddlb_tpu.cli.launch",
        description="Fan a command out over N coordinated local processes "
        "(the mpirun analogue; see module docstring).",
    )
    parser.add_argument("--processes", type=int, required=True)
    parser.add_argument(
        "--devices-per-process",
        type=int,
        default=0,
        help="N virtual CPU devices per process (0 = use the real platform)",
    )
    parser.add_argument(
        "--slices",
        type=int,
        default=0,
        help="DDLB_TPU_SIM_SLICES for every child (simulated DCN topology)",
    )
    parser.add_argument(
        "--coordinator",
        default=None,
        help="host:port for jax.distributed (default: free local port; "
        "supervised mode always picks a fresh port per attempt)",
    )
    parser.add_argument(
        "--supervise",
        action="store_true",
        help="cross-rank watchdog: file beats + live output streaming, "
        "coordinated abort on silence/asymmetric death, flight-recorder "
        "post-mortem, classifier-gated world relaunch",
    )
    parser.add_argument(
        "--silence-timeout",
        type=float,
        default=60.0,
        help="supervised: seconds without any beat/output from a rank "
        "before the world is aborted (0 disables the silence trigger)",
    )
    parser.add_argument(
        "--world-retries",
        type=int,
        default=2,
        help="supervised: transient world failures relaunched up to this "
        "many times with backoff",
    )
    parser.add_argument(
        "--relaunch-backoff",
        type=float,
        default=1.0,
        help="supervised: base seconds for the relaunch backoff schedule",
    )
    parser.add_argument(
        "--run-dir",
        default=None,
        help="supervised: directory for per-attempt flight/beat files and "
        "attempts.json (default: a fresh temp dir, printed)",
    )
    parser.add_argument(
        "--exclude-rank",
        type=int,
        action="append",
        default=None,
        metavar="SLOT",
        help="supervised: launch the world WITHOUT this physical slot "
        "(repeatable) — the operator's pre-indictment; rows are stamped "
        "world_degraded and survivors keep their slot id in "
        "DDLB_TPU_PHYS_RANK",
    )
    parser.add_argument(
        "--health-gate",
        action="store_true",
        help="supervised: run the persistent-straggler health verdict "
        "(observatory.health) over each attempt's clock-aligned "
        "timeline; a persistent indictment triggers a degraded relaunch "
        "with the indicted slot excluded",
    )
    parser.add_argument(
        "command",
        nargs=argparse.REMAINDER,
        help="command to run in every process (prefix with --)",
    )
    args = parser.parse_args(argv)
    command = args.command
    if command and command[0] == "--":
        command = command[1:]
    if not command:
        parser.error("no command given (append: -- python -m ...)")
    if args.supervise:
        sys.exit(
            launch_supervised(
                command,
                processes=args.processes,
                devices_per_process=args.devices_per_process,
                slices=args.slices,
                silence_timeout=args.silence_timeout,
                world_retries=args.world_retries,
                relaunch_backoff_s=args.relaunch_backoff,
                run_dir=args.run_dir,
                exclude_ranks=args.exclude_rank or (),
                health_gate=args.health_gate,
            )
        )
    sys.exit(
        launch(
            command,
            processes=args.processes,
            devices_per_process=args.devices_per_process,
            slices=args.slices,
            coordinator=args.coordinator,
        )
    )


if __name__ == "__main__":
    main()
