"""Multi-process launcher: the framework's `mpirun` analogue.

The reference's L5 entry is ``mpirun -np N python scripts/run_benchmark.py``
(/root/reference/scripts/run_benchmark.py:10-32, README.md:80-153) — the
launcher's only real job there is fanning out N processes and handing each
its rank env vars. The TPU-native equivalent does the same with the
``jax.distributed`` bootstrap env this runtime reads (``envs.py``):
``DDLB_TPU_NUM_PROCESSES`` / ``DDLB_TPU_PROCESS_ID`` /
``DDLB_TPU_COORD_ADDR``, picking a free coordinator port automatically.

On real pods one process per HOST is started by the pod tooling and this
launcher is unnecessary; its value is local: an N-process × M-device
CPU-sim world on one machine, so the cross-process collective paths (the
DCN stand-in, runtime.transport_mesh) run without hardware. Example::

    python -m ddlb_tpu.cli.launch --processes 2 --devices-per-process 4 -- \
        python -m ddlb_tpu.cli.benchmark --primitive tp_columnwise \
        --impl jax_spmd -m 1024 -n 256 -k 512

Child stdout/stderr are drained concurrently (a blocked pipe would
stall the lock-step collective world) and printed with a ``[p{rank}]``
prefix once all children exit, rank 0 last so its result table ends the
output; the launcher's exit code is the first non-zero child code.
"""

from __future__ import annotations

import argparse
import os
import socket
import subprocess
import sys
from typing import List, Optional


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def launch(
    command: List[str],
    processes: int,
    devices_per_process: int = 0,
    slices: int = 0,
    coordinator: Optional[str] = None,
    env: Optional[dict] = None,
) -> int:
    """Fan ``command`` out over ``processes`` local processes; returns the
    first non-zero child exit code (0 if all succeed)."""
    if processes < 1:
        raise ValueError(f"processes must be >= 1, got {processes}")
    coordinator = coordinator or f"127.0.0.1:{_free_port()}"
    procs = []
    for rank in range(processes):
        child_env = dict(os.environ if env is None else env)
        child_env.update(
            {
                "DDLB_TPU_NUM_PROCESSES": str(processes),
                "DDLB_TPU_PROCESS_ID": str(rank),
                "DDLB_TPU_COORD_ADDR": coordinator,
            }
        )
        if devices_per_process:
            # CPU-sim world: force the cpu platform in every child (the
            # reference parent also never touches the accelerator,
            # cli/benchmark.py:126)
            child_env.update(
                {
                    "JAX_PLATFORMS": "cpu",
                    "PALLAS_AXON_POOL_IPS": "",
                    "DDLB_TPU_SIM_DEVICES": "0",  # flag set directly:
                    "XLA_FLAGS": (
                        child_env.get("XLA_FLAGS", "")
                        + f" --xla_force_host_platform_device_count="
                        f"{devices_per_process}"
                    ).strip(),
                }
            )
        if slices:
            child_env["DDLB_TPU_SIM_SLICES"] = str(slices)
        procs.append(
            subprocess.Popen(
                command,
                env=child_env,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
            )
        )
    # Drain every pipe CONCURRENTLY: the children advance in lock-step
    # through collectives, so one child blocked on a full 64 KB pipe
    # (rank 0 prints per-row tables) stalls every other rank and a
    # sequential communicate() would deadlock the whole launch.
    import threading

    buffers: List[List[str]] = [[] for _ in range(processes)]

    def _drain(rank: int) -> None:
        for line in procs[rank].stdout:
            buffers[rank].append(line.rstrip("\n"))

    threads = [
        threading.Thread(target=_drain, args=(rank,), daemon=True)
        for rank in range(processes)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    rc = 0
    # print non-zero ranks first, rank 0 (the result-table rank) last
    for rank in list(range(1, processes)) + [0]:
        procs[rank].wait()
        for line in buffers[rank]:
            print(f"[p{rank}] {line}")
        if procs[rank].returncode and rc == 0:
            rc = procs[rank].returncode
    return rc


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(
        prog="ddlb_tpu.cli.launch",
        description="Fan a command out over N coordinated local processes "
        "(the mpirun analogue; see module docstring).",
    )
    parser.add_argument("--processes", type=int, required=True)
    parser.add_argument(
        "--devices-per-process",
        type=int,
        default=0,
        help="N virtual CPU devices per process (0 = use the real platform)",
    )
    parser.add_argument(
        "--slices",
        type=int,
        default=0,
        help="DDLB_TPU_SIM_SLICES for every child (simulated DCN topology)",
    )
    parser.add_argument(
        "--coordinator",
        default=None,
        help="host:port for jax.distributed (default: free local port)",
    )
    parser.add_argument(
        "command",
        nargs=argparse.REMAINDER,
        help="command to run in every process (prefix with --)",
    )
    args = parser.parse_args(argv)
    command = args.command
    if command and command[0] == "--":
        command = command[1:]
    if not command:
        parser.error("no command given (append: -- python -m ...)")
    sys.exit(
        launch(
            command,
            processes=args.processes,
            devices_per_process=args.devices_per_process,
            slices=args.slices,
            coordinator=args.coordinator,
        )
    )


if __name__ == "__main__":
    main()
