"""CLI / config expansion: the three front doors of the framework.

Rebuild of /root/reference/ddlb/cli/benchmark.py:14-320 — JSON config,
``name;k=v,v`` impl-spec flags, and programmatic dict all normalize into one
config that is cartesian-expanded over per-implementation option lists and
over (m, n, k) shape lists. Differences from the reference:

- both primitives are accepted from the flag CLI (the reference restricts
  ``choices=["tp_columnwise"]`` at cli/benchmark.py:232 even though its JSON
  path supports tp_rowwise — SURVEY.md section 3.3 flags this as a bug);
- a ``--sim N`` flag enables the N-device CPU simulation before JAX boots.
"""

from __future__ import annotations

import argparse
import itertools
import json
import time
from typing import Any, Dict, List, Tuple

# ---------------------------------------------------------------------------
# Impl-spec parsing (reference cli/benchmark.py:14-83)
# ---------------------------------------------------------------------------


def _infer_scalar(text: str) -> Any:
    """'true'/'false' -> bool, then int, then float, else str."""
    low = text.strip().lower()
    if low == "true":
        return True
    if low == "false":
        return False
    try:
        return int(text)
    except ValueError:
        pass
    try:
        return float(text)
    except ValueError:
        pass
    return text.strip()


def _parse_value_list(text: str) -> List[Any]:
    return [_infer_scalar(v) for v in text.split(",") if v.strip() != ""]


def _parse_int_list(values: List[str]) -> List[int]:
    out: List[int] = []
    for v in values:
        out.extend(int(x) for x in str(v).split(",") if x.strip() != "")
    return out


def parse_impl_spec(spec: str) -> Tuple[str, Dict[str, List[Any]]]:
    """``'overlap;algorithm=coll_pipeline,p2p_pipeline;s=4'`` ->
    ``('overlap', {'algorithm': [...], 's': [4]})``."""
    parts = [p for p in spec.split(";") if p.strip() != ""]
    if not parts:
        raise ValueError(f"Empty implementation spec: {spec!r}")
    name = parts[0].strip()
    options: Dict[str, List[Any]] = {}
    for part in parts[1:]:
        if "=" not in part:
            raise ValueError(
                f"Bad option {part!r} in spec {spec!r} (expected key=value[,value])"
            )
        key, _, value = part.partition("=")
        options[key.strip()] = _parse_value_list(value)
    return name, options


# ---------------------------------------------------------------------------
# Cartesian expansion (reference generate_config_combinations,
# cli/benchmark.py:85-118, and impl_id assignment, :166-177)
# ---------------------------------------------------------------------------


def generate_config_combinations(
    implementations: Dict[str, List[Dict[str, Any]]],
) -> Dict[str, List[Dict[str, Any]]]:
    """Expand list-valued options into the cartesian product per block."""
    expanded: Dict[str, List[Dict[str, Any]]] = {}
    for impl_name, blocks in implementations.items():
        expanded[impl_name] = []
        for block in blocks:
            list_params = {k: v for k, v in block.items() if isinstance(v, list)}
            if not list_params:
                expanded[impl_name].append(dict(block))
                continue
            keys = list(list_params)
            for combo in itertools.product(*(list_params[k] for k in keys)):
                cfg = dict(block)
                cfg.update(zip(keys, combo))
                expanded[impl_name].append(cfg)
    return expanded


def assign_impl_ids(
    expanded: Dict[str, List[Dict[str, Any]]],
) -> Dict[str, Dict[str, Any]]:
    """``{name: [cfg, ...]}`` -> ``{f'{name}_{i}': cfg + implementation key}``."""
    out: Dict[str, Dict[str, Any]] = {}
    for name, configs in expanded.items():
        for i, cfg in enumerate(configs):
            cfg = dict(cfg)
            cfg["implementation"] = name
            out[f"{name}_{i}"] = cfg
    return out


# ---------------------------------------------------------------------------
# run_benchmark (reference cli/benchmark.py:120-223)
# ---------------------------------------------------------------------------


def _normalize(config: Dict[str, Any]) -> Dict[str, Any]:
    cfg = dict(config.get("benchmark", config))
    impls = cfg.get("implementations")
    if isinstance(impls, list):
        # JSON list form [{"name": n, ...opts}, ...] (the shipped
        # scripts/config_*.json shape) -> canonical {name: [opts, ...]}
        as_dict: Dict[str, List[Dict[str, Any]]] = {}
        for block in impls:
            block = dict(block)
            name = block.pop("name", None)
            if not name:
                raise ValueError(
                    f"implementation list entries need a 'name': {block!r}"
                )
            as_dict.setdefault(name, []).append(block)
        cfg["implementations"] = as_dict
    return cfg


def _as_list(value) -> List[int]:
    return [int(v) for v in (value if isinstance(value, list) else [value])]


def run_benchmark(config: Dict[str, Any]):
    """Run the full sweep described by ``config``; returns a DataFrame."""
    import pandas as pd

    from ddlb_tpu.benchmark import PrimitiveBenchmarkRunner
    from ddlb_tpu.envs import get_process_id

    cfg = _normalize(config)
    primitive = cfg.get("primitive", "tp_columnwise")
    dtype = cfg.get("dtype", "bfloat16")
    sim = cfg.get("sim")
    if sim:
        from ddlb_tpu.runtime import enable_simulation

        enable_simulation(int(sim))

    expanded = generate_config_combinations(cfg.get("implementations", {}))
    impl_map = assign_impl_ids(expanded)
    if not impl_map:
        raise ValueError("Config contains no implementations")

    ms, ns, ks = _as_list(cfg.get("m", 8192)), _as_list(cfg.get("n", 8192)), _as_list(cfg.get("k", 8192))
    shapes = list(itertools.product(ms, ns, ks))

    # CSV path with {timestamp} token and shape-derived default
    # (reference cli/benchmark.py:179-188)
    timestamp = time.strftime("%Y%m%d_%H%M%S")
    output_csv = cfg.get("output_csv")
    if cfg.get("resume") and (output_csv is None or "{timestamp}" in output_csv):
        # a per-run path can never contain previous rows: resuming against
        # it would silently re-run everything while scattering results
        raise ValueError(
            "resume requires a fixed output_csv path (no {timestamp} token)"
        )
    if output_csv is None:
        m0, n0, k0 = shapes[0]
        output_csv = (
            f"results/{primitive}_{m0}x{k0}x{n0}_{dtype}_{timestamp}.csv"
        )
    output_csv = output_csv.replace("{timestamp}", timestamp)

    frames = []
    for m, n, k in shapes:
        runner = PrimitiveBenchmarkRunner(
            primitive=primitive,
            m=m,
            n=n,
            k=k,
            implementations=impl_map,
            dtype=dtype,
            num_iterations=cfg.get("num_iterations", 50),
            num_warmups=cfg.get("num_warmups", 5),
            validate=cfg.get("validate", True),
            time_measurement_backend=cfg.get(
                "time_measurement_backend", "host_clock"
            ),
            barrier_at_each_iteration=cfg.get("barrier_at_each_iteration", True),
            output_csv=output_csv,
            profile_dir=cfg.get("profile_dir"),
            isolation=cfg.get("isolation", "none"),
            progress=cfg.get("progress", True),
            worker_timeout=cfg.get("worker_timeout"),
            resume=cfg.get("resume", False),
            device_loop_windows=cfg.get("device_loop_windows", 5),
            device_loop_min_window_ms=cfg.get(
                "device_loop_min_window_ms", 100.0
            ),
            # compile-ahead engine knobs (benchmark.py): both default on;
            # compile_ahead only engages when DDLB_TPU_COMPILE_CACHE is
            # set and isolation is in-process
            compile_ahead=cfg.get("compile_ahead", True),
            group_by_signature=cfg.get("group_by_signature", True),
            # self-healing knobs (ISSUE 4): None defers to the
            # DDLB_TPU_MAX_RETRIES / DDLB_TPU_QUARANTINE_AFTER env
            # defaults resolved in the runner
            max_retries=cfg.get("max_retries"),
            retry_backoff_s=cfg.get("retry_backoff_s", 0.5),
            quarantine_after=cfg.get("quarantine_after"),
            # warm-worker-pool knobs (ISSUE 5): None defers to the
            # DDLB_TPU_WORKER_POOL / DDLB_TPU_POOL_MAX_ROWS env
            # defaults (pool on; unlimited rows per worker)
            worker_pool=cfg.get("worker_pool"),
            pool_max_rows=cfg.get("pool_max_rows"),
        )
        frames.append(runner.run())

    df = pd.concat(frames, ignore_index=True)
    if get_process_id() == 0:
        # final aggregated table, fixed column order
        # (reference cli/benchmark.py:214-223)
        columns = [
            "implementation",
            "option",
            "m",
            "n",
            "k",
            "dtype",
            "mean time (ms)",
            "std time (ms)",
            "Throughput (TFLOPS)",
            "world_size",
            "valid",
        ]
        print("\n=== Benchmark results ===")
        print(df[[c for c in columns if c in df]].to_string(index=False))
        print(f"\nResults written to {output_csv}")
    return df


# ---------------------------------------------------------------------------
# argparse entry (reference cli/benchmark.py:226-320)
# ---------------------------------------------------------------------------


def main(argv=None) -> None:
    from ddlb_tpu.primitives.registry import ALLOWED_PRIMITIVES

    parser = argparse.ArgumentParser(
        description="TPU-native tensor-parallel GEMM primitive benchmark"
    )
    parser.add_argument(
        "--primitive",
        default="tp_columnwise",
        choices=list(ALLOWED_PRIMITIVES),  # both allowed (reference bug fixed)
    )
    parser.add_argument(
        "--impl",
        action="append",
        default=None,
        metavar="NAME[;OPT=V1,V2...]",
        help="implementation spec; repeatable",
    )
    parser.add_argument("-m", action="append", default=None)
    parser.add_argument("-n", action="append", default=None)
    parser.add_argument("-k", action="append", default=None)
    parser.add_argument("--dtype", default="bfloat16")
    parser.add_argument("--num-iterations", type=int, default=50)
    parser.add_argument("--num-warmups", type=int, default=5)
    parser.add_argument("--no-validate", action="store_true")
    parser.add_argument(
        "--timing", default="host_clock", choices=["host_clock", "device_loop"]
    )
    parser.add_argument("--no-barrier", action="store_true")
    parser.add_argument("--csv", default=None, help="output CSV ({timestamp} token)")
    parser.add_argument("--profile-dir", default=None)
    parser.add_argument(
        "--isolation", default="none", choices=["none", "subprocess"]
    )
    parser.add_argument(
        "--sim", type=int, default=None, metavar="N",
        help="run on an N-device CPU simulation",
    )
    parser.add_argument(
        "--topology", default=None, metavar="SPEC",
        help="synthetic topology for the static performance simulator "
        "(spec '<chip>:<pods>x<dims>' or a preset name, e.g. "
        "'v5p:4x16x16'); exported as DDLB_TPU_TOPOLOGY so "
        "scripts/sim_report.py and the perfmodel consumers of this run "
        "see one world (envs.get_topology_override)",
    )
    parser.add_argument(
        "--worker-timeout", type=float, default=None, metavar="SECONDS",
        help="kill a hung worker after this many seconds and record an "
        "error row (requires --isolation subprocess)",
    )
    parser.add_argument(
        "--resume", action="store_true",
        help="skip configs already recorded in --csv, keyed by primitive "
        "+ implementation + merged options + shape + dtype + world size; "
        "crashed rows are retried (give --csv a fixed path, not a "
        "{timestamp} one)",
    )
    parser.add_argument(
        "--no-compile-ahead", action="store_true",
        help="disable background AOT compilation of the next config "
        "(compile-ahead otherwise engages when DDLB_TPU_COMPILE_CACHE "
        "is set and isolation is in-process)",
    )
    parser.add_argument(
        "--worker-pool", dest="worker_pool", action="store_true",
        default=None,
        help="run subprocess-isolation rows on the persistent warm-"
        "worker pool (default: on, env DDLB_TPU_WORKER_POOL) — one "
        "long-lived child per environment signature instead of a fresh "
        "spawn per row",
    )
    parser.add_argument(
        "--no-worker-pool", dest="worker_pool", action="store_false",
        help="force spawn-per-row (equivalent to --pool-max-rows 1); "
        "use when suspecting cross-row state leakage",
    )
    parser.add_argument(
        "--pool-max-rows", type=int, default=None, metavar="N",
        help="recycle a pool worker after N rows (default 0 = "
        "unlimited, env DDLB_TPU_POOL_MAX_ROWS; 1 = spawn-per-row)",
    )
    parser.add_argument(
        "--no-signature-grouping", action="store_true",
        help="keep the sweep's literal config order instead of grouping "
        "configs that share an executable signature (grouping lets the "
        "runner clear caches once per signature, not per row)",
    )
    args = parser.parse_args(argv)

    if args.topology:
        # validate before exporting: a typo'd world must fail the launch,
        # not silently skew every downstream simulator read
        import os

        from ddlb_tpu.perfmodel.topology import resolve_topology

        try:
            resolve_topology(args.topology)
        except (KeyError, ValueError) as exc:
            parser.error(f"bad --topology {args.topology!r}: {exc}")
        os.environ["DDLB_TPU_TOPOLOGY"] = args.topology

    impl_specs = args.impl or ["jax_spmd"]
    implementations: Dict[str, List[Dict[str, Any]]] = {}
    for spec in impl_specs:
        name, options = parse_impl_spec(spec)
        implementations.setdefault(name, []).append(options)

    config = {
        "primitive": args.primitive,
        "m": _parse_int_list(args.m or ["1024"]),
        "n": _parse_int_list(args.n or ["1024"]),
        "k": _parse_int_list(args.k or ["1024"]),
        "dtype": args.dtype,
        "num_iterations": args.num_iterations,
        "num_warmups": args.num_warmups,
        "validate": not args.no_validate,
        "time_measurement_backend": args.timing,
        "barrier_at_each_iteration": not args.no_barrier,
        "implementations": implementations,
        "output_csv": args.csv,
        "profile_dir": args.profile_dir,
        "isolation": args.isolation,
        "sim": args.sim,
        "worker_timeout": args.worker_timeout,
        "resume": args.resume,
        "compile_ahead": not args.no_compile_ahead,
        "group_by_signature": not args.no_signature_grouping,
        "worker_pool": args.worker_pool,
        "pool_max_rows": args.pool_max_rows,
    }
    run_benchmark(config)


def load_config(path: str) -> Dict[str, Any]:
    with open(path) as f:
        return json.load(f)


if __name__ == "__main__":
    main()
