"""CLI package (reference re-export pattern, ddlb/cli/__init__.py:3-5)."""

from ddlb_tpu.cli.benchmark import (  # noqa: F401
    generate_config_combinations,
    load_config,
    main,
    parse_impl_spec,
    run_benchmark,
)
