"""Seeded open-loop workload generation for the serving engine.

An **open-loop** generator: arrival times are drawn from the offered
process independently of how fast the server drains them — the shape
under which queueing delay, saturation knees and SLO attainment are
actually defined (a closed loop self-throttles and can never show the
knee). Three generation axes, every one seeded and deterministic:

- **arrival process** — ``poisson`` (memoryless, the classic open-loop
  baseline) or ``bursty`` (a 2-state Markov-modulated Poisson process:
  fixed-length burst/quiet windows whose rates average to the offered
  ``rate_rps``, so sweeps over the process axis hold load constant and
  vary only its burstiness);
- **length mix** — prompt lengths are lognormal around ``prompt_mean``
  (the long-tail shape real prompt populations show), generated-token
  budgets exponential around ``out_mean``; both clipped to explicit
  bounds so an engine's ``max_len`` can be sized from the spec alone;
- **shared-prefix population** — ``prefix_pop`` distinct prefixes with
  Zipf(``prefix_alpha``) popularity, each ``prefix_len`` tokens drawn
  per-id deterministically. Rank 0 is the hot prefix (the "system
  prompt" case the engine's ``set_shared_prefix`` cache serves);
  ``prefix_id`` on each request says which population member it leads
  with, so a driver can measure hit rates against any caching policy.

Determinism contract (pinned in tests/test_serving_load.py): two calls
of ``generate_trace`` with equal specs produce identical traces —
arrival times, prompts, budgets, prefix assignments, all of it. Every
random stream derives from ``numpy.random.SeedSequence`` spawns of the
spec's single ``seed``, so adding a stream later cannot perturb the
existing ones.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import List, Tuple

import numpy as np

#: SeedSequence lane ids, one per independent stream (appending a new
#: stream appends a lane — existing traces never move)
_LANE_ARRIVAL = 0
_LANE_PROMPT_LEN = 1
_LANE_OUT_LEN = 2
_LANE_PREFIX_PICK = 3
_LANE_BODY = 4
_LANE_PREFIX_TOKENS = 5


@dataclass(frozen=True)
class TimedRequest:
    """One generated request: when it arrives and what it asks for."""

    index: int
    arrival_s: float            # offset from trace start
    prompt: np.ndarray          # [S0] int32 (prefix tokens included)
    max_new: int
    prefix_id: int              # population rank, -1 = no shared prefix


@dataclass(frozen=True)
class WorkloadSpec:
    """Everything that determines a trace. Equal specs (seed included)
    generate equal traces — the spec IS the workload's identity."""

    n_requests: int
    rate_rps: float             # offered load, requests/second
    process: str = "poisson"    # "poisson" | "bursty"
    #: bursty (MMPP-2): in-burst rate multiplier and the fraction of
    #: time spent bursting; the quiet rate is solved so the long-run
    #: mean stays ``rate_rps`` (requires burst_duty * burst_factor < 1)
    burst_factor: float = 4.0
    burst_duty: float = 0.2
    burst_len_s: float = 2.0
    #: prompt-length mix: lognormal(mean=prompt_mean, sigma) clipped
    prompt_mean: int = 64
    prompt_sigma: float = 0.6
    prompt_min: int = 4
    prompt_max: int = 512
    #: output budget: exponential(out_mean) clipped
    out_mean: int = 16
    out_min: int = 1
    out_max: int = 128
    vocab: int = 512
    #: Zipf shared-prefix population (0 disables; prefix_len tokens
    #: prepended to every prompt, id drawn by popularity rank)
    prefix_pop: int = 0
    prefix_alpha: float = 1.1
    prefix_len: int = 0
    seed: int = 0

    def __post_init__(self) -> None:
        if self.n_requests < 1:
            raise ValueError(f"n_requests must be >= 1, got {self.n_requests}")
        if self.rate_rps <= 0.0:
            raise ValueError(f"rate_rps must be > 0, got {self.rate_rps}")
        if self.process not in ("poisson", "bursty"):
            raise ValueError(
                f"unknown arrival process {self.process!r} "
                f"(poisson | bursty)"
            )
        if self.process == "bursty":
            if not 0.0 < self.burst_duty < 1.0:
                raise ValueError(
                    f"burst_duty must be in (0, 1), got {self.burst_duty}"
                )
            if self.burst_factor * self.burst_duty >= 1.0:
                raise ValueError(
                    "burst_factor * burst_duty must be < 1 so the quiet "
                    f"rate stays positive (got {self.burst_factor} * "
                    f"{self.burst_duty})"
                )
        if not 1 <= self.prompt_min <= self.prompt_max:
            raise ValueError("need 1 <= prompt_min <= prompt_max")
        if not 1 <= self.out_min <= self.out_max:
            raise ValueError("need 1 <= out_min <= out_max")
        if self.prefix_pop and self.prefix_len < 1:
            raise ValueError("prefix_pop > 0 needs prefix_len >= 1")
        if self.vocab < 2:
            raise ValueError("vocab must be >= 2")

    @property
    def max_total_tokens(self) -> int:
        """Upper bound on prompt + generated per request — what an
        engine's ``max_len`` must cover."""
        return self.prefix_len + self.prompt_max + self.out_max


def _rng(spec: WorkloadSpec, lane: int, extra: Tuple[int, ...] = ()):
    return np.random.default_rng(
        np.random.SeedSequence((spec.seed, lane) + extra)
    )


def prefix_tokens(spec: WorkloadSpec, prefix_id: int) -> np.ndarray:
    """The population member's tokens, generated per-id so a driver can
    materialize any prefix without the whole trace (rank 0 is the hot
    one ``set_shared_prefix`` wants)."""
    if not (0 <= prefix_id < spec.prefix_pop):
        raise ValueError(
            f"prefix_id {prefix_id} outside population [0, {spec.prefix_pop})"
        )
    rng = _rng(spec, _LANE_PREFIX_TOKENS, (prefix_id,))
    return rng.integers(1, spec.vocab, spec.prefix_len).astype(np.int32)


def _arrival_times(spec: WorkloadSpec) -> np.ndarray:
    """Arrival offsets for ``n_requests``, by integrating unit-rate
    exponentials through the (piecewise-constant) rate function — exact
    for both processes, no thinning loss."""
    rng = _rng(spec, _LANE_ARRIVAL)
    work = rng.exponential(1.0, spec.n_requests)  # unit-rate exponentials
    if spec.process == "poisson":
        return np.cumsum(work) / spec.rate_rps
    # bursty: fixed-length burst/quiet windows; the quiet rate solves
    # duty*f + (1-duty)*q = 1 so the long-run mean stays rate_rps
    f = spec.burst_factor
    duty = spec.burst_duty
    q = (1.0 - duty * f) / (1.0 - duty)
    burst_len = spec.burst_len_s
    quiet_len = burst_len * (1.0 - duty) / duty
    out = np.empty(spec.n_requests, np.float64)
    t = 0.0
    in_burst = True
    boundary = burst_len
    for i, w in enumerate(work):
        while True:
            rate = spec.rate_rps * (f if in_burst else q)
            # rate integral available before the next state boundary
            capacity = (boundary - t) * rate
            if w <= capacity:
                t += w / rate
                break
            w -= capacity
            t = boundary
            in_burst = not in_burst
            boundary += burst_len if in_burst else quiet_len
        out[i] = t
    return out


def _lognormal_lengths(
    rng, n: int, mean: float, sigma: float, lo: int, hi: int
) -> np.ndarray:
    mu = math.log(max(mean, 1.0)) - 0.5 * sigma * sigma
    raw = rng.lognormal(mu, sigma, n)
    return np.clip(np.rint(raw), lo, hi).astype(np.int64)


def generate_trace(spec: WorkloadSpec) -> List[TimedRequest]:
    """The full trace, arrival-ordered. Identical per spec (seed
    included); prompts carry their prefix tokens inline so a consumer
    that ignores prefixes still replays the same byte stream."""
    arrivals = _arrival_times(spec)
    prompt_lens = _lognormal_lengths(
        _rng(spec, _LANE_PROMPT_LEN), spec.n_requests,
        spec.prompt_mean, spec.prompt_sigma,
        spec.prompt_min, spec.prompt_max,
    )
    raw_out = _rng(spec, _LANE_OUT_LEN).exponential(
        spec.out_mean, spec.n_requests
    )
    out_lens = np.clip(
        np.rint(raw_out), spec.out_min, spec.out_max
    ).astype(np.int64)
    if spec.prefix_pop:
        ranks = np.arange(1, spec.prefix_pop + 1, dtype=np.float64)
        weights = ranks ** (-spec.prefix_alpha)
        weights /= weights.sum()
        prefix_ids = _rng(spec, _LANE_PREFIX_PICK).choice(
            spec.prefix_pop, size=spec.n_requests, p=weights
        )
        prefixes = [
            prefix_tokens(spec, i) for i in range(spec.prefix_pop)
        ]
    else:
        prefix_ids = np.full(spec.n_requests, -1, np.int64)
    body_rng = _rng(spec, _LANE_BODY)
    trace: List[TimedRequest] = []
    for i in range(spec.n_requests):
        body = body_rng.integers(1, spec.vocab, int(prompt_lens[i])).astype(
            np.int32
        )
        pid = int(prefix_ids[i])
        prompt = (
            np.concatenate([prefixes[pid], body]) if pid >= 0 else body
        )
        trace.append(
            TimedRequest(
                index=i,
                arrival_s=float(arrivals[i]),
                prompt=prompt,
                max_new=int(out_lens[i]),
                prefix_id=pid,
            )
        )
    return trace
