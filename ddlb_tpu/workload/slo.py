"""Streaming SLO statistics for load-driven serving runs.

Two pieces, both O(1) per observation so the drain loop they instrument
stays unperturbed:

- ``StreamingQuantile``: a sparse log-bucketed histogram (the
  HDR-histogram idea) with bounded RELATIVE error — bucket boundaries
  grow geometrically by ``1 + 2*rel_err``, a sample lands in one
  integer bucket via a log, and any reported quantile is the geometric
  midpoint of the bucket holding that rank, hence within ``rel_err`` of
  the true order statistic. The accuracy contract (within 1% of exact
  ``numpy.quantile`` on a 10k-sample reference at the default
  ``rel_err``) is pinned in tests/test_serving_load.py. Memory is one
  dict entry per occupied bucket (~a few hundred over µs→minutes).
- ``SLOTracker``: the per-request timeline ledger
  (arrival → admit/first-token → completion; the engine's admission
  computes the first token, so TTFT ends at admit) plus queue-depth
  gauges, folded into the ``slo_*`` row columns: TTFT/TPOT/E2E
  percentiles, goodput under the configured SLO bound (completed
  requests meeting BOTH bounds per second of drain), attainment, and
  preemption/eviction counters forwarded from the engine.

Definitions (the column semantics docs/source/observability.rst
documents):

- **TTFT**: arrival → first generated token, queueing wait included —
  the user-visible "time to first token", not the prefill's device
  time.
- **TPOT**: (completion − first token) / (generated − 1) per request —
  steady-state per-token latency; requests generating one token have
  no TPOT sample.
- **goodput**: completed requests whose TTFT ≤ ``ttft_slo_ms`` AND
  TPOT ≤ ``tpot_slo_ms`` (one-token requests: TTFT alone), divided by
  the drain's makespan — the rate the service DELIVERS within its SLO,
  the number the Big Send-off says load sweeps must report instead of
  raw throughput.
- **attainment**: the same SLO predicate as a fraction of completed
  requests.
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional

#: default relative error of the streaming quantile buckets (0.4% —
#: comfortably inside the 1%-of-exact test contract)
DEFAULT_REL_ERR = 0.004


class StreamingQuantile:
    """Sparse log-bucketed streaming quantile estimator."""

    def __init__(
        self, rel_err: float = DEFAULT_REL_ERR, min_value: float = 1e-6
    ) -> None:
        if not 0.0 < rel_err < 0.5:
            raise ValueError(f"rel_err must be in (0, 0.5), got {rel_err}")
        if min_value <= 0.0:
            raise ValueError(f"min_value must be > 0, got {min_value}")
        self._growth = 1.0 + 2.0 * rel_err
        self._log_growth = math.log(self._growth)
        self._min_value = min_value
        self._counts: Dict[int, int] = {}
        self._n = 0
        self._lo = math.inf
        self._hi = -math.inf

    def __len__(self) -> int:
        return self._n

    def add(self, value: float) -> None:
        """Count one sample (values below ``min_value`` — including any
        non-positive measurement artifact — clamp into bucket 0)."""
        value = float(value)
        if not math.isfinite(value):
            return
        self._n += 1
        self._lo = min(self._lo, value)
        self._hi = max(self._hi, value)
        if value <= self._min_value:
            bucket = 0
        else:
            bucket = int(
                math.log(value / self._min_value) / self._log_growth
            ) + 1
        self._counts[bucket] = self._counts.get(bucket, 0) + 1

    def quantile(self, q: float) -> float:
        """The ``q``-quantile (nearest-rank over buckets, geometric
        bucket midpoint, clamped to the exact observed min/max). NaN on
        an empty estimator."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"q must be in [0, 1], got {q}")
        if self._n == 0:
            return float("nan")
        rank = q * (self._n - 1)
        cum = 0
        for bucket in sorted(self._counts):
            cum += self._counts[bucket]
            if cum > rank:
                if bucket == 0:
                    mid = self._min_value
                else:
                    lo = self._min_value * self._growth ** (bucket - 1)
                    mid = lo * math.sqrt(self._growth)
                return float(min(max(mid, self._lo), self._hi))
        return float(self._hi)


class _Timeline:
    """One request's timestamps (all offsets from the drain's t0)."""

    __slots__ = ("arrival_s", "first_token_s", "done_s", "new_tokens")

    def __init__(self, arrival_s: float) -> None:
        self.arrival_s = arrival_s
        self.first_token_s: Optional[float] = None
        self.done_s: Optional[float] = None
        self.new_tokens = 0


class SLOTracker:
    """Per-request timeline ledger + the ``slo_*`` row-column fold."""

    def __init__(
        self,
        ttft_slo_ms: float,
        tpot_slo_ms: float,
        rel_err: float = DEFAULT_REL_ERR,
    ) -> None:
        self.ttft_slo_ms = float(ttft_slo_ms)
        self.tpot_slo_ms = float(tpot_slo_ms)
        self._timelines: Dict[int, _Timeline] = {}
        self._ttft = StreamingQuantile(rel_err)
        self._tpot = StreamingQuantile(rel_err)
        self._e2e = StreamingQuantile(rel_err)
        self._slo_met = 0
        self._completed = 0
        self._queue_sum = 0.0
        self._queue_samples = 0
        self.queue_peak = 0
        #: recent queue-depth gauge ring (the dashboard sparkline feed)
        self.queue_recent: List[int] = []

    def new_drain(self) -> None:
        """Start another drain of the same trace: per-request timelines
        and the sparkline ring reset, while the percentile estimators,
        SLO counters and queue aggregates keep accumulating — a row's
        distributions POOL across its drains (one drain's p95 over a
        small trace is max-dominated noise; pooled order statistics are
        what make the SLO gate's baselines stable)."""
        self._timelines.clear()
        self.queue_recent = []

    # -- timeline events ----------------------------------------------------

    def arrived(self, index: int, arrival_s: float) -> None:
        self._timelines[index] = _Timeline(arrival_s)

    def first_token(self, index: int, t_s: float) -> None:
        """The request produced its first generated token (admission's
        prefill does this synchronously). Idempotent across preemptions:
        only the FIRST call counts — a preempted request's re-admission
        is a scheduling event, not a new first token."""
        tl = self._timelines[index]
        if tl.first_token_s is None:
            tl.first_token_s = t_s

    def finished(self, index: int, t_s: float, new_tokens: int) -> None:
        tl = self._timelines[index]
        tl.done_s = t_s
        tl.new_tokens = int(new_tokens)
        self._completed += 1
        ttft_ms = (tl.first_token_s - tl.arrival_s) * 1e3
        e2e_ms = (t_s - tl.arrival_s) * 1e3
        self._ttft.add(ttft_ms)
        self._e2e.add(e2e_ms)
        tpot_ms = None
        if tl.new_tokens > 1:
            tpot_ms = (t_s - tl.first_token_s) * 1e3 / (tl.new_tokens - 1)
            self._tpot.add(tpot_ms)
        met = ttft_ms <= self.ttft_slo_ms and (
            tpot_ms is None or tpot_ms <= self.tpot_slo_ms
        )
        if met:
            self._slo_met += 1

    def observe_queue(self, depth: int, recent_cap: int = 120) -> None:
        depth = int(depth)
        self._queue_sum += depth
        self._queue_samples += 1
        self.queue_peak = max(self.queue_peak, depth)
        self.queue_recent.append(depth)
        del self.queue_recent[:-recent_cap]

    # -- the fold -----------------------------------------------------------

    @property
    def completed(self) -> int:
        return self._completed

    def row_fields(
        self, makespan_s: float, offered_rps: float
    ) -> Dict[str, Any]:
        """The ``slo_*`` columns for one drained run (schema.py is the
        registry; NaN marks 'no sample', same convention as every other
        measured column)."""
        nan = float("nan")
        queue_mean = (
            self._queue_sum / self._queue_samples
            if self._queue_samples
            else nan
        )
        goodput = (
            self._slo_met / makespan_s if makespan_s > 0.0 else nan
        )
        attainment = (
            self._slo_met / self._completed if self._completed else nan
        )
        return {
            "slo_offered_rps": round(float(offered_rps), 4),
            "slo_completed": self._completed,
            "slo_ttft_p50_ms": self._ttft.quantile(0.50),
            "slo_ttft_p95_ms": self._ttft.quantile(0.95),
            "slo_ttft_p99_ms": self._ttft.quantile(0.99),
            "slo_tpot_p50_ms": self._tpot.quantile(0.50),
            "slo_tpot_p95_ms": self._tpot.quantile(0.95),
            "slo_tpot_p99_ms": self._tpot.quantile(0.99),
            "slo_e2e_p95_ms": self._e2e.quantile(0.95),
            "slo_goodput_rps": (
                round(goodput, 4) if goodput == goodput else goodput
            ),
            "slo_attainment": (
                round(attainment, 4) if attainment == attainment else attainment
            ),
            "serve_queue_peak": self.queue_peak,
            "serve_queue_mean": (
                round(queue_mean, 3) if queue_mean == queue_mean else queue_mean
            ),
        }
