"""Traffic-scale serving workloads: seeded generation + SLO telemetry.

The serving engine (``models/serving.py``) schedules whatever it is
given; this package supplies the "millions of users"-shaped traffic the
north star asks it to be judged under, plus the streaming statistics
that turn a drained trace into SLO columns:

- ``generator``: a seeded, deterministic **open-loop** workload
  generator — Poisson and bursty (MMPP-2) arrival processes, mixed
  prompt/output-length distributions, and a Zipf-popular shared-prefix
  population. The same seed replays the identical trace, byte for
  byte, which is what makes load-driven measurements bankable in the
  observatory's history store.
- ``slo``: streaming percentile estimation (log-bucketed histogram,
  bounded relative error, O(1) per sample) and the per-request
  timeline accounting (arrival → admit → first token → completion)
  behind the ``slo_*`` row columns: TTFT/TPOT percentiles, goodput
  under an SLO bound, attainment, and queue-depth gauges.

Consumed by the ``serving_load`` primitive family
(``primitives/serving_load``) and ``scripts/serving_load_report.py``.
NumPy-only by design (no JAX import), so trace generation and report
tooling run in the JAX-free process tiers.
"""

from __future__ import annotations

from ddlb_tpu.workload.generator import (  # noqa: F401
    TimedRequest,
    WorkloadSpec,
    generate_trace,
    prefix_tokens,
)
from ddlb_tpu.workload.slo import (  # noqa: F401
    SLOTracker,
    StreamingQuantile,
)

__all__ = [
    "SLOTracker",
    "StreamingQuantile",
    "TimedRequest",
    "WorkloadSpec",
    "generate_trace",
    "prefix_tokens",
]
