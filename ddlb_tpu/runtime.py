"""Distributed runtime bootstrap: devices, mesh, and cross-host barrier.

TPU-native replacement for the reference's ``Communicator`` singleton
(/root/reference/ddlb/communicator.py:36-81). Where the reference parses
launcher env vars, binds a CUDA device per rank and wraps
``torch.distributed.barrier``, this runtime:

- optionally initializes ``jax.distributed`` (coordinator + process id from
  ``ddlb_tpu.envs``) for multi-host TPU pods — the analogue of the TCP
  rendezvous at /root/reference/ddlb/primitives/TPColumnwise/pytorch.py:53-59,
  done once per process instead of once per implementation because the TPU
  runtime owns all local chips for the process lifetime;
- exposes the global device list and builds ``jax.sharding.Mesh`` instances
  (device binding is implicit: XLA addresses all local chips);
- implements ``barrier()`` as a tiny all-device ``psum`` +
  ``block_until_ready`` — the reference's dummy-allreduce trick
  (/root/reference/ddlb/benchmark.py:133-137) expressed in XLA collectives;
- supports a CPU-simulation mode (``enable_simulation``) with N virtual host
  devices, the testing capability SURVEY.md section 4 identifies as missing
  upstream.
"""

from __future__ import annotations

import os
import threading
from typing import Optional, Sequence, Tuple

from ddlb_tpu import envs

_SIM_FLAG = "--xla_force_host_platform_device_count"


def enable_simulation(num_devices: int) -> None:
    """Force the CPU platform with ``num_devices`` virtual devices.

    Must run before the first JAX backend use in the process (XLA clients are
    created lazily on first device query). Safe to call repeatedly with the
    same count.
    """
    import jax

    flags = os.environ.get("XLA_FLAGS", "")
    if _SIM_FLAG not in flags:
        os.environ["XLA_FLAGS"] = f"{flags} {_SIM_FLAG}={num_devices}".strip()
    jax.config.update("jax_platforms", "cpu")


class Runtime:
    """Process-wide singleton (reference Communicator.__new__, communicator.py:36-43)."""

    _instance: Optional["Runtime"] = None
    _lock = threading.Lock()

    def __new__(cls) -> "Runtime":
        with cls._lock:
            if cls._instance is None:
                inst = super().__new__(cls)
                inst._initialize()
                cls._instance = inst
            return cls._instance

    @classmethod
    def reset(cls) -> None:
        """Drop the singleton (test helper; no reference analogue)."""
        with cls._lock:
            cls._instance = None

    def _initialize(self) -> None:
        sim = envs.get_sim_device_count()
        if sim > 0:
            enable_simulation(sim)

        import jax

        self.process_id = envs.get_process_id()
        self.num_processes = envs.get_num_processes()
        self._distributed = False
        if self.num_processes > 1 and not jax.distributed.is_initialized():
            jax.distributed.initialize(
                coordinator_address=envs.get_coordinator_address(),
                num_processes=self.num_processes,
                process_id=self.process_id,
            )
            self._distributed = True

        self.devices = tuple(jax.devices())
        self.local_devices = tuple(jax.local_devices())
        self.num_devices = len(self.devices)
        self.platform = self.devices[0].platform if self.devices else "none"

    # -- mesh construction ---------------------------------------------------

    def mesh(
        self,
        axis_names: Sequence[str] = ("tp",),
        shape: Optional[Tuple[int, ...]] = None,
    ):
        """Build a ``jax.sharding.Mesh`` over all global devices.

        Defaults to a 1-D ``('tp',)`` mesh spanning every device — the
        reference's single tensor-parallel process group
        (/root/reference/ddlb/primitives/TPColumnwise/jax_tp.py:43-45).
        """
        import jax

        if shape is None:
            shape = (self.num_devices,) if len(axis_names) == 1 else None
        if shape is None:
            raise ValueError("shape required for multi-axis meshes")
        return jax.make_mesh(shape, tuple(axis_names), devices=self.devices)

    # -- synchronization -----------------------------------------------------

    def barrier(self) -> None:
        """Cross-device/-host barrier.

        A one-element replicated ``psum`` over every device followed by
        ``block_until_ready`` — the XLA-native form of the reference's dummy
        NCCL allreduce + ``cuda.synchronize``
        (/root/reference/ddlb/benchmark.py:133-137,
        /root/reference/ddlb/communicator.py:65-74).
        """
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = self.mesh(("_barrier",))
        ones = jax.device_put(
            jnp.ones((self.num_devices,), jnp.int32),
            NamedSharding(mesh, P("_barrier")),
        )

        def _sum(x):
            return jax.shard_map(
                lambda v: jax.lax.psum(v, "_barrier"),
                mesh=mesh,
                in_specs=P("_barrier"),
                out_specs=P(),
            )(x)

        jax.jit(_sum)(ones).block_until_ready()

    def __repr__(self) -> str:
        return (
            f"Runtime(process={self.process_id}/{self.num_processes}, "
            f"devices={self.num_devices}, platform={self.platform})"
        )


def as_auto_mesh(mesh):
    """Rebuild a mesh with all axes in ``Auto`` mode for GSPMD implicit
    propagation (JAX 0.9 defaults to Explicit sharding-in-types, which
    rejects mid-function ``with_sharding_constraint``); operands and jit
    shardings must then use this mesh consistently."""
    from jax.sharding import AxisType, Mesh

    return Mesh(
        mesh.devices,
        mesh.axis_names,
        axis_types=(AxisType.Auto,) * len(mesh.axis_names),
    )
