"""Distributed runtime bootstrap: devices, mesh, and cross-host barrier.

TPU-native replacement for the reference's ``Communicator`` singleton
(/root/reference/ddlb/communicator.py:36-81). Where the reference parses
launcher env vars, binds a CUDA device per rank and wraps
``torch.distributed.barrier``, this runtime:

- optionally initializes ``jax.distributed`` (coordinator + process id from
  ``ddlb_tpu.envs``) for multi-host TPU pods — the analogue of the TCP
  rendezvous at /root/reference/ddlb/primitives/TPColumnwise/pytorch.py:53-59,
  done once per process instead of once per implementation because the TPU
  runtime owns all local chips for the process lifetime;
- exposes the global device list and builds ``jax.sharding.Mesh`` instances
  (device binding is implicit: XLA addresses all local chips);
- implements ``barrier()`` as a tiny all-device ``psum`` +
  ``block_until_ready`` — the reference's dummy-allreduce trick
  (/root/reference/ddlb/benchmark.py:133-137) expressed in XLA collectives;
- supports a CPU-simulation mode (``enable_simulation``) with N virtual host
  devices, the testing capability SURVEY.md section 4 identifies as missing
  upstream.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional, Sequence, Tuple

from ddlb_tpu import envs, faults, telemetry
from ddlb_tpu.faults import flightrec
from ddlb_tpu.telemetry import clocksync

_SIM_FLAG = "--xla_force_host_platform_device_count"


def enable_simulation(num_devices: int) -> None:
    """Force the CPU platform with ``num_devices`` virtual devices.

    Must run before the first JAX backend use in the process (XLA clients are
    created lazily on first device query). Safe to call repeatedly with the
    same count.
    """
    import jax

    flags = os.environ.get("XLA_FLAGS", "")
    if _SIM_FLAG not in flags:
        os.environ["XLA_FLAGS"] = f"{flags} {_SIM_FLAG}={num_devices}".strip()
    jax.config.update("jax_platforms", "cpu")


def configure_compile_cache() -> Optional[str]:
    """Point JAX's persistent compilation cache at ``DDLB_TPU_COMPILE_CACHE``.

    Returns the configured directory, or None when the knob is unset.
    Idempotent and safe to call at any point in the process lifetime
    (the cache is consulted per compile, not captured at backend init).
    The thresholds are lowered so EVERY executable is banked: the sweep
    engine's win comes from re-paying nothing on a resumed or repeated
    sweep, and on the CPU sim (where compiles are fast) the default
    1-second / 1-KB floors would silently cache nothing at test shapes.
    """
    path = envs.get_compile_cache_dir()
    if not path:
        return None
    import jax

    path = os.path.abspath(path)
    os.makedirs(path, exist_ok=True)
    changed = getattr(jax.config, "jax_compilation_cache_dir", None) != path
    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    if changed:
        # the cache subsystem memoizes its backing store at first
        # compile: a process that already compiled something with the
        # cache unset has it pinned DISABLED, and the config update
        # alone would be silently ignored — force re-initialization
        try:
            from jax._src import compilation_cache

            compilation_cache.reset_cache()
        except Exception as exc:
            # older/newer layouts re-read the config themselves; logged
            # (not swallowed) so a layout where they DON'T is visible
            telemetry.log(
                f"compilation cache reset unavailable "
                f"({type(exc).__name__}: {exc}); relying on config re-read"
            )
    return path


def distributed_initialized() -> bool:
    """Whether ``jax.distributed`` is already connected — version
    bridge: ``jax.distributed.is_initialized`` arrived after the 0.4.x
    line the relay fleet runs, where the only signal is the private
    global client (absent/None = not initialized). Without this shim a
    launched multi-process world crashes at bootstrap on old jax
    instead of forming the joint mesh."""
    import jax

    fn = getattr(jax.distributed, "is_initialized", None)
    if fn is not None:
        return bool(fn())
    try:
        from jax._src.distributed import global_state

        return getattr(global_state, "client", None) is not None
    except ImportError:
        # no such layout: nothing to ask, assume uninitialized (the
        # initialize call itself raises if double-connected)
        return False


def shard_map_compat(fn, mesh, in_specs, out_specs, check_vma=None):
    """``jax.shard_map`` where available, the pre-0.5 experimental entry
    point otherwise — so the runtime's own collectives (barrier) and the
    queue's parity harness work across the JAX versions the relay fleet
    actually runs. ``check_vma`` maps to the old API's ``check_rep``."""
    import jax

    if hasattr(jax, "shard_map"):
        kwargs = {} if check_vma is None else {"check_vma": check_vma}
        return jax.shard_map(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
        )
    from jax.experimental.shard_map import shard_map

    kwargs = {} if check_vma is None else {"check_rep": check_vma}
    return shard_map(
        fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
    )


def set_mesh_compat(mesh):
    """``jax.set_mesh(mesh)`` where available; on pre-0.5 JAX the mesh
    itself (``Mesh`` is a context manager there, and the legacy global
    mesh context is the analogous "make this the ambient mesh" form).
    The model layer's ``with set_mesh_compat(mesh):`` blocks work on
    both — the version bridge the shard_map_compat migration rides."""
    import jax

    if hasattr(jax, "set_mesh"):
        return jax.set_mesh(mesh)
    return mesh


def reshard_compat(x, sharding):
    """``jax.sharding.reshard`` where available, falling back to
    ``with_sharding_constraint`` on pre-0.5 JAX. The two agree for the
    serving paths' use: pinning one explicit layout on a traced value
    under jit. (reshard exists because with_sharding_constraint is a
    no-op under Explicit axis types; 0.4.x has no Explicit axis types,
    so the constraint is the real thing there.)"""
    import jax

    if hasattr(jax.sharding, "reshard"):
        return jax.sharding.reshard(x, sharding)
    return jax.lax.with_sharding_constraint(x, sharding)


class Runtime:
    """Process-wide singleton (reference Communicator.__new__, communicator.py:36-43)."""

    _instance: Optional["Runtime"] = None
    _lock = threading.Lock()

    def __new__(cls) -> "Runtime":
        with cls._lock:
            if cls._instance is None:
                inst = super().__new__(cls)
                inst._initialize()
                cls._instance = inst
            return cls._instance

    @classmethod
    def reset(cls) -> None:
        """Drop the singleton (test helper; no reference analogue)."""
        with cls._lock:
            cls._instance = None

    def _initialize(self) -> None:
        sim = envs.get_sim_device_count()
        if sim > 0:
            enable_simulation(sim)
        # persistent executable reuse across runs/processes (no-op when
        # DDLB_TPU_COMPILE_CACHE is unset); before the first backend use
        # so even bootstrap-time compiles land in the cache
        configure_compile_cache()

        import jax

        self.process_id = envs.get_process_id()
        self.num_processes = envs.get_num_processes()
        self._distributed = False
        if self.num_processes > 1:
            # a multi-process CPU world needs a real cross-process
            # collectives backend: the CPU client's default ('none')
            # makes every multiprocess computation fail with
            # INVALID_ARGUMENT, so the launched CPU-sim worlds (the DCN
            # stand-in, test_multiprocess, chaos_launch) would form a
            # mesh they can never compute on. Harmless on TPU (the flag
            # only configures the CPU client); respected if the
            # operator already chose an implementation.
            if not os.environ.get("JAX_CPU_COLLECTIVES_IMPLEMENTATION"):
                try:
                    jax.config.update(
                        "jax_cpu_collectives_implementation", "gloo"
                    )
                except (AttributeError, KeyError, ValueError):
                    pass  # a jax without the flag: nothing to configure
        # launched-world bootstrap injection site: a fault here models a
        # rank that died/hung BEFORE the distributed rendezvous — the
        # flapped-bootstrap class the supervised launcher's world-level
        # relaunch must absorb (classified transient, faults/classify)
        faults.inject("launch.child")
        if self.num_processes > 1 and not distributed_initialized():
            # flight-recorded as a sequenced entry: a rank wedged in the
            # rendezvous shows "runtime.init begun, never completed"
            # while its peers' entries say whether they even got here
            with flightrec.record(
                "runtime.init", processes=self.num_processes
            ):
                jax.distributed.initialize(
                    coordinator_address=envs.get_coordinator_address(),
                    num_processes=self.num_processes,
                    process_id=self.process_id,
                )
            self._distributed = True

        #: (jitted psum, operand) built lazily by the first barrier();
        #: cached so repeat barriers time only execution, never re-trace
        self._barrier_call = None
        self.devices = tuple(jax.devices())
        self.local_devices = tuple(jax.local_devices())
        self.num_devices = len(self.devices)
        self.platform = self.devices[0].platform if self.devices else "none"
        #: PJRT chip identity string ("TPU v5 lite", ...) — the input to
        #: the perfmodel spec registry's auto-detection (chip_spec)
        self.device_kind = (
            str(getattr(self.devices[0], "device_kind", ""))
            if self.devices
            else ""
        )
        self.slice_ids = self._slice_assignment()
        self.num_slices = len(set(self.slice_ids)) if self.slice_ids else 1

    def _slice_assignment(self):
        """Per-device slice id — the DCN topology layer.

        Priority: the simulation knob (DDLB_TPU_SIM_SLICES partitions the
        device list into equal contiguous blocks); on the CPU sim the
        owning process (cross-process collectives ride the network — the
        sim stand-in for DCN; CPU devices report ``slice_index == 0``
        everywhere, so the process boundary is the only topology signal);
        on real TPU the multi-slice id PJRT exposes
        (``device.slice_index`` on megascale pods — a multi-host
        single-slice pod is genuinely one ICI domain, so process index
        must NOT split it). Analogue of the reference's transport layers
        (nccl vs ucc/tl/* — SURVEY.md section 2.4): here the layer
        boundary is ICI inside a slice, DCN across.
        """
        n = self.num_devices
        sim_slices = envs.get_sim_slice_count()
        if sim_slices > 1:
            if n % sim_slices:
                raise ValueError(
                    f"DDLB_TPU_SIM_SLICES={sim_slices} does not divide "
                    f"{n} devices"
                )
            per = n // sim_slices
            return tuple(i // per for i in range(n))
        if self.platform != "tpu":
            return tuple(int(d.process_index) for d in self.devices)
        return tuple(
            int(getattr(d, "slice_index", None) or 0) for d in self.devices
        )

    def info(self) -> dict:
        """Plain-data snapshot of this runtime's world — what a warm
        pool worker reports in its ready message so a JAX-free parent
        (bench.py, the queue driver) can probe the backend without ever
        creating one itself."""
        return {
            "platform": self.platform,
            "num_devices": self.num_devices,
            "num_processes": self.num_processes,
            "device_kind": self.device_kind,
        }

    @property
    def chip_spec(self):
        """The perfmodel hardware spec for this runtime's chips
        (``perfmodel.specs.ChipSpec``): the ``DDLB_TPU_CHIP`` env
        override when set, else auto-detected from the PJRT
        ``device_kind``; non-TPU platforms (the CPU sim) resolve to the
        calibrated ``cpu-sim`` entry. Resolved per access so a test's
        env override takes effect without resetting the singleton."""
        from ddlb_tpu.perfmodel.specs import detect_spec

        return detect_spec(
            device_kind=self.device_kind, platform=self.platform
        )

    # -- mesh construction ---------------------------------------------------

    def mesh(
        self,
        axis_names: Sequence[str] = ("tp",),
        shape: Optional[Tuple[int, ...]] = None,
    ):
        """Build a ``jax.sharding.Mesh`` over all global devices.

        Defaults to a 1-D ``('tp',)`` mesh spanning every device — the
        reference's single tensor-parallel process group
        (/root/reference/ddlb/primitives/TPColumnwise/jax_tp.py:43-45).
        """
        import jax

        # collective-infrastructure injection site: a mesh build is the
        # first thing every impl's setup does, so a fault here models a
        # backend that died before any collective ran
        faults.inject("runtime.mesh")
        if shape is None:
            shape = (self.num_devices,) if len(axis_names) == 1 else None
        if shape is None:
            raise ValueError("shape required for multi-axis meshes")
        with telemetry.span(
            "runtime.mesh_build", cat="runtime", axes=",".join(axis_names)
        ), flightrec.record(
            "runtime.mesh_build", axes=",".join(axis_names)
        ):
            return jax.make_mesh(
                shape, tuple(axis_names), devices=self.devices
            )

    def transport_mesh(self, axis_names=("tp",), transport: str = "ici"):
        """1-D mesh whose ring-neighbor structure rides the chosen
        transport — the TPU form of the reference's collective-backend
        sweep axis (nccl/ucc/tl-*, /root/reference/ddlb/primitives/
        TPColumnwise/pytorch.py:32-45; SURVEY.md section 2.4 maps it to
        {ici, dcn}):

        - ``'ici'``: devices grouped by slice, so ring hops and collective
          stages stay on intra-slice ICI except at slice boundaries (the
          best-case layout, and the identity order on one slice);
        - ``'dcn'``: slices interleaved round-robin, so EVERY neighbor hop
          crosses the slice boundary — collectives are forced onto the
          DCN/cross-process transport (the stress layout; on the CPU sim
          this exercises the cross-"slice" code paths).
        """
        import numpy as np

        import jax

        # the DCN stand-in's construction is a flight-recorder entry:
        # hierarchical/multi-pod scenarios diverge here first when a
        # rank's topology view disagrees with its peers'
        flightrec.mark(
            "runtime.transport_mesh", transport=transport,
            slices=self.num_slices,
        )
        if transport not in ("ici", "dcn"):
            raise ValueError(f"transport must be 'ici' or 'dcn', got {transport!r}")
        if transport == "dcn" and self.num_slices == 1:
            # visible topology has one slice (possibly because this PJRT
            # runtime exposes no device.slice_index): the dcn and ici
            # layouts are identical, so say so rather than let a sweep
            # record a 'dcn' row that silently measured the ici ordering
            telemetry.warn(
                "transport='dcn' requested but the device topology shows "
                "a single slice — dcn and ici mesh layouts are identical "
                "here"
            )
        n = self.num_devices
        order = sorted(range(n), key=lambda i: (self.slice_ids[i], i))
        if transport == "dcn" and self.num_slices > 1:
            by_slice = [
                [i for i in order if self.slice_ids[i] == s]
                for s in sorted(set(self.slice_ids))
            ]
            order = [
                grp[j]
                for j in range(max(len(g) for g in by_slice))
                for grp in by_slice
                if j < len(grp)
            ]
        devices = np.array([self.devices[i] for i in order])
        return jax.sharding.Mesh(devices, tuple(axis_names))

    def hybrid_mesh(self, axis_names=("dcn", "ici")):
        """2-D ``(num_slices, per_slice)`` mesh separating the cross-slice
        (DCN) axis from the intra-slice (ICI) axis — the hierarchical form
        ``mesh_utils.create_hybrid_device_mesh`` builds on real multi-slice
        pods, with a grouped-reshape fallback for the simulated topology.
        """
        import numpy as np

        import jax

        per = self.num_devices // self.num_slices
        if self.num_slices > 1:
            try:
                from jax.experimental import mesh_utils

                arr = mesh_utils.create_hybrid_device_mesh(
                    (1, per), (self.num_slices, 1), devices=self.devices
                )
                return jax.sharding.Mesh(arr, tuple(axis_names))
            except Exception as exc:
                # simulated slices: PJRT lacks real slice topology, so
                # fall through to the grouped reshape — logged so a
                # REAL pod landing here (losing the hierarchical
                # layout) is diagnosable
                telemetry.log(
                    f"hybrid mesh fell back to grouped reshape "
                    f"({type(exc).__name__}: {exc})"
                )
        order = sorted(
            range(self.num_devices), key=lambda i: (self.slice_ids[i], i)
        )
        arr = np.array([self.devices[i] for i in order]).reshape(
            self.num_slices, per
        )
        return jax.sharding.Mesh(arr, tuple(axis_names))

    def torus_mesh(self, axis_names=("dcn", "sx", "sy")):
        """3-D ``(num_slices, sx, sy)`` mesh splitting each slice into
        its squarest 2-D torus factorization (``perfmodel.cost
        .torus_factors``) — the striped composition's world view: one
        independent ring family per intra-slice axis, the DCN axis
        kept separate. Device order matches ``hybrid_mesh`` (slices
        are contiguous blocks), so the two views agree on which chips
        share a slice."""
        import numpy as np

        import jax

        from ddlb_tpu.perfmodel.cost import torus_factors

        per = self.num_devices // self.num_slices
        sx, sy = torus_factors(per)
        order = sorted(
            range(self.num_devices), key=lambda i: (self.slice_ids[i], i)
        )
        arr = np.array([self.devices[i] for i in order]).reshape(
            self.num_slices, sx, sy
        )
        return jax.sharding.Mesh(arr, tuple(axis_names))

    # -- synchronization -----------------------------------------------------

    def barrier(self) -> None:
        """Cross-device/-host barrier.

        A one-element replicated ``psum`` over every device followed by
        ``block_until_ready`` — the XLA-native form of the reference's dummy
        NCCL allreduce + ``cuda.synchronize``
        (/root/reference/ddlb/benchmark.py:133-137,
        /root/reference/ddlb/communicator.py:65-74).
        """
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        # collective-entry injection site: the barrier is the one
        # collective EVERY timing path crosses, so a fault here models a
        # wedged transport mid-sweep (e.g. hang = a peer that never
        # arrives; the subprocess parent's heartbeat kill recovers it).
        # payload_bytes feeds the topology fault kinds' payload-
        # proportional delay (the degraded-link realization)
        faults.inject(
            "runtime.barrier", payload_bytes=4 * self.num_devices
        )
        # the clock-sync exchange stamps bracket everything AFTER the
        # injection site: a fault-delayed rank arrives late on its own
        # stamp, exactly what the skew fold must attribute. Monotonic
        # stamps (system-wide on one host; the offset fit is what makes
        # them comparable across hosts).
        t_enter = time.monotonic()
        # flight-recorded AFTER the injection site: a rank the plan
        # hangs/kills here never begins the entry, so the post-mortem
        # join shows it lagging while its peers sit in-flight in the
        # barrier — the attribution scripts/chaos_launch.py asserts
        with telemetry.span("runtime.barrier", cat="barrier"), \
                flightrec.record(
                    "runtime.barrier", axes="_barrier",
                    payload_bytes=4 * self.num_devices,
                ):
            if self._barrier_call is None:
                # built once per process: a fresh closure would re-trace
                # on every barrier, and its jit/compile cost would land
                # in barrier_wait_s — which must measure WAIT (the
                # cross-process skew the MAX-reduce hides), not compile
                # time, which compile_time_s already accounts
                mesh = self.mesh(("_barrier",))
                ones = jax.device_put(
                    jnp.ones((self.num_devices,), jnp.int32),
                    NamedSharding(mesh, P("_barrier")),
                )

                def _sum(x):
                    return shard_map_compat(
                        lambda v: jax.lax.psum(v, "_barrier"),
                        mesh=mesh,
                        in_specs=P("_barrier"),
                        out_specs=P(),
                    )(x)

                fn = jax.jit(_sum)
                fn(ones).block_until_ready()  # warm: compile not counted
                self._barrier_call = (fn, ones)
            fn, ones = self._barrier_call
            # dispatch outside the timed window: if a jax.clear_caches()
            # (signature-boundary isolation) dropped the executable, the
            # recompile happens during dispatch and must not count as
            # wait; the barrier WAIT is the device-completion block
            out = fn(ones)
            t0 = time.perf_counter()
            out.block_until_ready()
            # summed per row into the ``barrier_wait_s`` CSV column
            telemetry.record("barrier_wait_s", time.perf_counter() - t0)
        # two-sided exchange record: the barrier span is a clock-sync
        # exchange point (no rank exits before the last one enters), so
        # its enter/exit stamps feed BOTH the row skew fold and the
        # post-hoc world-timeline offset fit. The instant additionally
        # anchors this process's monotonic clock to the trace shard's
        # epoch timestamps (a no-op unless DDLB_TPU_TRACE is set).
        t_exit = time.monotonic()
        clocksync.record_span("runtime.barrier", t_enter, t_exit)
        telemetry.instant(
            "clocksync.exchange", cat="clocksync", mono_t=t_exit,
            site="runtime.barrier",
        )

    def __repr__(self) -> str:
        return (
            f"Runtime(process={self.process_id}/{self.num_processes}, "
            f"devices={self.num_devices}, platform={self.platform})"
        )


def as_auto_mesh(mesh):
    """Rebuild a mesh with all axes in ``Auto`` mode for GSPMD implicit
    propagation (JAX 0.9 defaults to Explicit sharding-in-types, which
    rejects mid-function ``with_sharding_constraint``); operands and jit
    shardings must then use this mesh consistently. Pre-0.5 JAX has no
    axis types at all — every mesh already propagates implicitly — so
    the mesh passes through untouched there (the version bridge the
    model-layer shard_map_compat migration rides)."""
    try:
        from jax.sharding import AxisType, Mesh
    except ImportError:
        return mesh

    return Mesh(
        mesh.devices,
        mesh.axis_names,
        axis_types=(AxisType.Auto,) * len(mesh.axis_names),
    )
